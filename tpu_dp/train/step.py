"""The compiled train/eval steps — the framework's hot loop.

The reference's hot loop (`/root/reference/cifar_example_ddp.py:94-107`,
SURVEY.md §3.2) is five eager calls per step with NCCL allreduces fired from
C++ autograd hooks during `loss.backward()`. Here the *entire* loop body is
one jitted XLA program:

    loss, grads = value_and_grad(xent ∘ model)(params, global_batch)
    params, opt = sgd(params, grads, lr(step))

with the global batch *sharded* over the ``data`` mesh axis and the state
*replicated*. Because the loss is a mean over the logical global batch, XLA's
partitioner (GSPMD) materializes the cross-chip gradient all-reduce inside
the compiled program — the same collective DDP runs from hooks, but fused,
scheduled alongside compute by the compiler, and overlap-optimized over ICI.
Donation reuses the state's device buffers across steps (no allocator
churn). Single-chip is the same program on a mesh of one.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from tpu_dp.parallel.sharding import (
    batch_sharding,
    replicated_sharding,
    scan_batch_sharding,
)
from tpu_dp.train.optim import Optimizer
from tpu_dp.train.schedule import Schedule
from tpu_dp.train.state import TrainState


def cross_entropy_loss(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    weight: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(Weighted) mean softmax cross-entropy from integer labels.

    Parity with `nn.CrossEntropyLoss()` (reduction='mean', raw logits in)
    (`/root/reference/cifar_example.py:63`). Computed in float32 regardless
    of the model's compute dtype (bf16-safe reduction). ``weight`` masks
    padded examples out of the mean (eval's final partial batch).
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(
        logits, labels[:, None].astype(jnp.int32), axis=-1
    )[:, 0]
    per_example = logz - true_logit
    if weight is None:
        return jnp.mean(per_example)
    return jnp.sum(per_example * weight) / jnp.maximum(jnp.sum(weight), 1.0)


def _to_varying(x: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """Cast a replication-invariant value to device-varying under shard_map.

    `jax.lax.pvary` is deprecated in jax 0.9 in favour of
    `jax.lax.pcast(..., to='varying')`; keep one call site so the next
    rename is a one-line change.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_name)
    # Pre-vma JAX: no replication typing, nothing to cast (the shard_map
    # below runs with check_rep=False, so AD never inserts implicit psums).
    return x


def _shard_map(f, mesh, in_specs, out_specs):
    """`jax.shard_map` across JAX versions.

    Older JAX only has `jax.experimental.shard_map.shard_map`; its
    replication-checking rewrite would insert the implicit grad psums the
    varying-params cast in `_to_varying` exists to avoid, so it runs
    unchecked there — the explicit collectives make every output replicated
    before it crosses the shard_map boundary either way.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _maybe_normalize(images: jnp.ndarray) -> jnp.ndarray:
    """Fused on-device normalize for uint8 batches (pipeline default).

    Same transform as `tpu_dp.data.cifar.normalize` (reference parity:
    ToTensor + Normalize(0.5, 0.5), `cifar_example.py:38-40`); XLA fuses the
    convert+scale into the consumer of the batch.
    """
    if images.dtype == jnp.uint8:
        from tpu_dp.data.cifar import normalize

        return normalize(images)  # works on traced arrays; one source of truth
    return images


def _apply_model(model, state: TrainState, images, train: bool):
    """Run the model, handling BatchNorm's mutable running stats."""
    if state.has_batch_stats:
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        if train:
            logits, mutated = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            return logits, mutated["batch_stats"]
        return model.apply(variables, images, train=False), state.batch_stats
    return model.apply({"params": state.params}, images, train=train), {}


def _forward_backward(model, loss_impl, state: TrainState, images, labels,
                      cast_params=None):
    """Shared fwd+bwd block: loss, grads, updated BN stats, correct count.

    Train batches are always full (drop_remainder enforced), so no weight
    mask on the training loss. Used by both step factories so the GSPMD and
    explicit-`shard_map` paths cannot drift apart.

    ``cast_params`` (per-leaf, applied *before* differentiation) is the
    explicit-collectives path's varying-cast hook: under shard_map's
    replication typing, differentiating a *varying* loss wrt *invariant*
    params would insert an implicit cross-shard psum (the cotangent of the
    invariant→varying broadcast) before the explicit collective — casting
    outside the diff'd function keeps AD local, per-shard grads out.
    """
    params0 = state.params
    if cast_params is not None:
        params0 = jax.tree_util.tree_map(cast_params, params0)

    def loss_fn(params):
        logits, new_batch_stats = _apply_model(
            model, state.replace(params=params), images, train=True
        )
        return loss_impl(logits, labels), (logits, new_batch_stats)

    (loss, (logits, new_batch_stats)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(params0)
    correct = jnp.sum(jnp.argmax(logits, axis=-1) == labels)
    return loss, grads, new_batch_stats, correct


def _apply_update(
    optimizer: Optimizer, schedule: Schedule, state: TrainState, grads,
    new_batch_stats, lr_scale=None, new_residuals=None,
):
    """Shared optimizer tail: LR lookup, update, next TrainState.

    ``lr_scale`` is the guardrail layer's LR ease-in knob (a replicated
    runtime scalar from ``guard_in``): after a rollback the policy ramps it
    from ``guard.lr_ease_start`` back to 1.0 so the replayed window does not
    re-trace the exact trajectory that diverged. None (the default, every
    non-sentinel program) leaves the schedule untouched — and the trace
    unchanged.

    ``new_residuals`` carries the int8 wire codec's updated error-feedback
    state out of the reduce hook (None — every non-quantized program —
    passes the state's residuals through untouched: {} for them, so the
    compiled HLO is unchanged).
    """
    lr = schedule(state.step)
    if lr_scale is not None:
        lr = lr * lr_scale
    new_params, new_opt_state = optimizer.update(
        grads, state.opt_state, state.params, lr
    )
    new_state = TrainState(
        step=state.step + 1,
        params=new_params,
        opt_state=new_opt_state,
        batch_stats=new_batch_stats,
        residuals=(state.residuals if new_residuals is None
                   else new_residuals),
    )
    return new_state, lr


def _select_loss_impl(use_pallas_xent: bool):
    """One source of truth for the loss implementation switch."""
    if use_pallas_xent:
        from tpu_dp.ops.xent import mean_softmax_xent

        return mean_softmax_xent
    return cross_entropy_loss


def default_guard_in():
    """The neutral ``guard_in`` pytree the sentinel-enabled steps take.

    A replicated input of four scalars (host-built numpy so constructing it
    never touches a device):

    - ``loss_cap`` — device-side skip threshold: a finite training loss
      above it is treated like a non-finite one (update not applied). The
      guard policy arms it from the trailing window's median/MAD under
      ``guard.action=skip``; +inf disarms.
    - ``lr_scale`` — multiplies the scheduled LR (rollback ease-in; 1.0 is
      exact identity, bitwise).
    - ``fault_step`` / ``fault_scale`` — the deterministic fault-injection
      seam (``TPU_DP_FAULT`` ``nan:``/``spike:`` specs, docs/RESILIENCE.md):
      at ``state.step == fault_step`` the loss and gradients are multiplied
      by ``fault_scale`` *inside the compiled program* (NaN for ``nan:``,
      a large finite scale for ``spike:``). ``fault_step=-1`` never fires,
      and the disarmed multiply-by-1.0 is bitwise identity.

    Feeding the same dtypes every call keeps the trace signature stable
    (one cache entry; the RecompileGuard stays silent).
    """
    import numpy as np

    return {
        "loss_cap": np.float32(np.inf),
        "lr_scale": np.float32(1.0),
        "fault_step": np.int32(-1),
        "fault_scale": np.float32(1.0),
    }


def guard_in_struct():
    """ShapeDtypeStruct twin of `default_guard_in` (AOT fingerprinting)."""
    return {
        "loss_cap": jax.ShapeDtypeStruct((), jnp.float32),
        "lr_scale": jax.ShapeDtypeStruct((), jnp.float32),
        "fault_step": jax.ShapeDtypeStruct((), jnp.int32),
        "fault_scale": jax.ShapeDtypeStruct((), jnp.float32),
    }


def _inject_guard_fault(step, loss, grads, guard_in):
    """The ``nan:``/``spike:`` injection seam, compiled into the step.

    Sits on the *pre-reduction* gradients so a rank-gated fault propagates
    to every replica through the gradient collective exactly like a real
    corrupted batch would (explicit-collectives paths; under GSPMD the
    partitioner may place the multiply after the inferred all-reduce, so
    rank-gated injection there stays rank-local — documented in
    docs/RESILIENCE.md). Disarmed (``fault_step=-1``) this is a
    multiply-by-1.0: bitwise identity.
    """
    fire = step == guard_in["fault_step"]
    factor = jnp.where(fire, guard_in["fault_scale"], jnp.float32(1.0))
    loss = loss * factor.astype(loss.dtype)
    grads = jax.tree_util.tree_map(
        lambda g: g * factor.astype(g.dtype), grads
    )
    return loss, grads


def _grad_health(grads, loss, health_reduce=None):
    """The on-device health summary: global grad-norm + finite-ness flag.

    ``sum(g²)`` in f32 over every leaf; a single NaN/Inf anywhere in the
    gradient tree makes the sum non-finite, so one scalar carries both the
    norm and the finite-ness signal. ``health_reduce`` closes the
    cross-replica gap on the sharded-update path (each replica holds a
    1/world gradient shard, so the local sum-of-squares is partial — one
    extra *scalar* psum over the data axis, the only collective the
    sentinel ever adds; replicated/GSPMD paths compute on already-reduced
    gradients and add none).
    """
    sumsq = jnp.zeros((), jnp.float32)
    for g in jax.tree_util.tree_leaves(grads):
        sumsq = sumsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
    if health_reduce is not None:
        sumsq = health_reduce(sumsq)
    finite = jnp.isfinite(loss.astype(jnp.float32)) & jnp.isfinite(sumsq)
    return jnp.sqrt(sumsq), finite


def _sentinel_tail(optimizer, schedule, state, grads, new_batch_stats,
                   loss, correct, count, guard_in, health_reduce,
                   opt_pred_cast=None, new_residuals=None,
                   extra_metrics=None):
    """The sentinel step tail: health summary → guarded update → metrics.

    The update is computed unconditionally and then *selected against*: a
    step whose loss/gradients are non-finite, or whose loss exceeds the
    armed ``loss_cap``, emits the ORIGINAL state — params, optimizer
    state, BatchNorm statistics and the step counter all unchanged, as if
    the batch was never seen (the quarantine contract: the final params of
    a run that skipped batch K are bitwise those of a run that never saw
    it). The decision is computed from globally-reduced values, so every
    replica selects identically — no divergence, no extra sync.

    Metrics grow the health fields the guard policy consumes (one host
    fetch per window, at the existing fence boundary): ``loss_raw`` /
    ``grad_norm`` (unmasked), ``applied`` (0 = quarantined). ``loss`` and
    ``correct`` are masked to zero on skipped steps so the epoch
    accumulators never ingest a NaN.
    """
    if guard_in is None:
        guard_in = default_guard_in()
    with jax.named_scope("tpu_dp.sentinel"):
        gnorm, finite = _grad_health(grads, loss, health_reduce)
        applied = finite & (loss.astype(jnp.float32) <= guard_in["loss_cap"])
    with jax.named_scope("tpu_dp.update"):
        new_state, lr = _apply_update(
            optimizer, schedule, state, grads, new_batch_stats,
            lr_scale=guard_in["lr_scale"], new_residuals=new_residuals,
        )
        # ``opt_pred_cast`` (sharded update only): the opt-state leaves
        # are device-varying 1/world shards under shard_map's replication
        # typing, so the invariant skip predicate is cast varying for that
        # subtree (`_to_varying`; a no-op on pre-vma JAX and everywhere
        # else the whole state is replicated). The int8 codec's residuals
        # share the varying predicate: a quarantined batch's quantization
        # error must be forgotten WITH the batch, or the next step's error
        # feedback would re-inject a slice of the poisoned gradient.
        opt_pred = applied if opt_pred_cast is None else opt_pred_cast(applied)
        new_state = TrainState(
            step=jnp.where(applied, new_state.step, state.step),
            params=jax.tree_util.tree_map(
                lambda n, o: jnp.where(applied, n, o),
                new_state.params, state.params),
            opt_state=jax.tree_util.tree_map(
                lambda n, o: jnp.where(opt_pred, n, o),
                new_state.opt_state, state.opt_state),
            batch_stats=jax.tree_util.tree_map(
                lambda n, o: jnp.where(applied, n, o),
                new_state.batch_stats, state.batch_stats),
            residuals=jax.tree_util.tree_map(
                lambda n, o: jnp.where(opt_pred, n, o),
                new_state.residuals, state.residuals),
        )
    metrics = {
        "loss": jnp.where(applied, loss, jnp.zeros_like(loss)),
        "correct": jnp.where(applied, correct, jnp.zeros_like(correct)),
        "count": count,
        "lr": lr,
        "loss_raw": loss,
        "grad_norm": gnorm,
        "applied": applied.astype(jnp.int32),
    }
    if extra_metrics:
        metrics.update(extra_metrics)
    return new_state, metrics


def _make_step_body(model, optimizer, schedule, loss_impl, augment_fn,
                    reduce_fn=None, cast_params=None, sentinel=False,
                    health_reduce=None, opt_pred_cast=None):
    """The single-microbatch step body shared by `make_train_step`
    (accum_steps=1) and `make_multi_step`'s scan — one source of truth for
    normalize → augment → fwd/bwd → [cross-replica reduce] → update →
    metrics, so the host-loop and device-loop paths cannot drift apart.

    ``reduce_fn(grads, loss, correct, count, batch_stats, residuals)`` is
    the explicit-collectives hook: the GSPMD path passes None (the
    partitioner infers the gradient all-reduce from shardings), the
    `shard_map` path injects the typed collective wrappers between the
    per-shard grads and the optimizer update — the one placement
    `tpu_dp.analysis` verifies. It returns the reduced values plus the
    (possibly updated) error-feedback residuals and an extra-metrics dict
    ({} everywhere but the int8 wire codec, whose overflow/clip counts
    ride the metrics stream).

    ``sentinel=True`` (the guardrail layer, docs/RESILIENCE.md
    "Guardrails") adds the on-device health summary + guarded update
    (`_sentinel_tail`) and the ``guard_in`` third argument; off (the
    default) the body — and its compiled HLO — is bit-for-bit the program
    it always was.
    """

    def body(state: TrainState, batch, guard_in=None):
        # jax.named_scope: names land in HLO op metadata, so device-side
        # profiles (jax.profiler XPlane / Perfetto) attribute time to the
        # training phase instead of to anonymous fusions. Metadata only —
        # the compiled collective schedule (dplint DP304 fingerprint) is
        # unchanged.
        with jax.named_scope("tpu_dp.input"):
            images, labels = _maybe_normalize(batch["image"]), batch["label"]
            if augment_fn is not None:
                # Keyed by the global step: compiled into the program,
                # deterministic, identical on every replica.
                images = augment_fn(state.step, images)
        with jax.named_scope("tpu_dp.fwd_bwd"):
            loss, grads, new_batch_stats, correct = _forward_backward(
                model, loss_impl, state, images, labels,
                cast_params=cast_params
            )
        count = jnp.asarray(labels.shape[0], jnp.int32)
        if sentinel:
            gi = guard_in if guard_in is not None else default_guard_in()
            loss, grads = _inject_guard_fault(state.step, loss, grads, gi)
        new_residuals, extra = None, {}
        if reduce_fn is not None:
            with jax.named_scope("tpu_dp.grad_reduce"):
                (grads, loss, correct, count, new_batch_stats,
                 new_residuals, extra) = reduce_fn(
                    grads, loss, correct, count, new_batch_stats,
                    state.residuals,
                )
        if sentinel:
            return _sentinel_tail(
                optimizer, schedule, state, grads, new_batch_stats,
                loss, correct, count, guard_in, health_reduce,
                opt_pred_cast=opt_pred_cast, new_residuals=new_residuals,
                extra_metrics=extra,
            )
        with jax.named_scope("tpu_dp.update"):
            new_state, lr = _apply_update(
                optimizer, schedule, state, grads, new_batch_stats,
                new_residuals=new_residuals,
            )
        metrics = {
            "loss": loss,
            "correct": correct,
            "count": count,
            "lr": lr,
        }
        metrics.update(extra)
        return new_state, metrics

    return body


def _make_accum_body(
    model, optimizer, schedule, loss_impl, augment_fn, accum_steps,
    reduce_fn=None, cast_params=None, sentinel=False, health_reduce=None,
    opt_pred_cast=None,
):
    """The gradient-accumulation step body: one optimizer update from
    ``accum_steps`` sequential microbatches.

    Batch leaves carry a leading (accum_steps,) axis (replicated; the
    microbatch dim is the sharded one). ``lax.scan`` runs the microbatches
    sequentially, accumulating grads on-device — how a logical global batch
    larger than HBM (e.g. BASELINE config 5's 4096) runs on few chips.
    Shared by `make_train_step` (one dispatch per update) and
    `make_multi_step` (scan-of-scan: a window of accumulated updates in one
    program), so the two paths cannot drift apart.
    """

    def body(state: TrainState, batch, guard_in=None):
        # Same named_scope annotations as `_make_step_body` (HLO metadata
        # for device-side trace attribution; schedule-neutral).
        with jax.named_scope("tpu_dp.input"):
            images, labels = _maybe_normalize(batch["image"]), batch["label"]
            if augment_fn is not None:
                # On-device augmentation keyed by the global step and the
                # microbatch index: compiled into the step, deterministic,
                # identical on every replica.
                images = jax.vmap(
                    lambda i, im: augment_fn(state.step * accum_steps + i, im)
                )(jnp.arange(accum_steps), images)

        def micro(carry, mb):
            grads_acc, batch_stats, loss_acc, correct_acc = carry
            mstate = state.replace(batch_stats=batch_stats)
            with jax.named_scope("tpu_dp.fwd_bwd"):
                loss, grads, new_bs, correct = _forward_backward(
                    model, loss_impl, mstate, mb["image"], mb["label"],
                    cast_params=cast_params,
                )
            grads_acc = jax.tree_util.tree_map(
                jnp.add, grads_acc, grads
            )
            return (grads_acc, new_bs, loss_acc + loss,
                    correct_acc + correct), None

        init = (
            jax.tree_util.tree_map(jnp.zeros_like, state.params),
            state.batch_stats,
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.int32),
        )
        (grads, new_batch_stats, loss_sum, correct), _ = jax.lax.scan(
            micro, init, {"image": images, "label": labels}
        )
        grads = jax.tree_util.tree_map(
            lambda g: g / accum_steps, grads
        )
        loss = loss_sum / accum_steps
        count = jnp.asarray(labels.shape[0] * labels.shape[1], jnp.int32)

        # The fault seam sits on the accumulated (whole-update) gradients,
        # like the reduce hook: one injected fault means one poisoned
        # optimizer update, never a per-microbatch spray.
        if sentinel:
            gi = guard_in if guard_in is not None else default_guard_in()
            loss, grads = _inject_guard_fault(state.step, loss, grads, gi)

        # The reduce hook sits AFTER the microbatch scan and the 1/accum
        # rescale: exactly one cross-replica reduction per optimizer update,
        # never one per microbatch (`tpu_dp.analysis` DP202 verifies this)
        # — and so the int8 codec quantizes (and its residual updates) once
        # per optimizer update too.
        new_residuals, extra = None, {}
        if reduce_fn is not None:
            with jax.named_scope("tpu_dp.grad_reduce"):
                (grads, loss, correct, count, new_batch_stats,
                 new_residuals, extra) = reduce_fn(
                    grads, loss, correct, count, new_batch_stats,
                    state.residuals,
                )

        if sentinel:
            return _sentinel_tail(
                optimizer, schedule, state, grads, new_batch_stats,
                loss, correct, count, guard_in, health_reduce,
                opt_pred_cast=opt_pred_cast, new_residuals=new_residuals,
                extra_metrics=extra,
            )
        with jax.named_scope("tpu_dp.update"):
            new_state, lr = _apply_update(
                optimizer, schedule, state, grads, new_batch_stats,
                new_residuals=new_residuals,
            )
        metrics = {
            "loss": loss,
            "correct": correct,
            "count": count,
            "lr": lr,
        }
        metrics.update(extra)
        return new_state, metrics

    return body


def _select_body(model, optimizer, schedule, loss_impl, augment_fn,
                 accum_steps, reduce_fn=None, cast_params=None,
                 sentinel=False, health_reduce=None, opt_pred_cast=None):
    """One source of truth for the per-update body: plain step at
    accum_steps == 1, gradient-accumulation body otherwise. Used by
    `make_train_step`, `make_multi_step`, and (via `make_local_step`) the
    explicit-collectives `shard_map` path, so all step programs share the
    exact same body."""
    if accum_steps == 1:
        return _make_step_body(model, optimizer, schedule, loss_impl,
                               augment_fn, reduce_fn=reduce_fn,
                               cast_params=cast_params, sentinel=sentinel,
                               health_reduce=health_reduce,
                               opt_pred_cast=opt_pred_cast)
    return _make_accum_body(model, optimizer, schedule, loss_impl,
                            augment_fn, accum_steps, reduce_fn=reduce_fn,
                            cast_params=cast_params, sentinel=sentinel,
                            health_reduce=health_reduce,
                            opt_pred_cast=opt_pred_cast)


def make_train_step(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    schedule: Schedule,
    use_pallas_xent: bool = False,
    accum_steps: int = 1,
    augment_fn: Callable | None = None,
    sentinel: bool = False,
) -> Callable:
    """Build the jitted DP train step for this model/optimizer/mesh.

    Returns ``step(state, batch) -> (new_state, metrics)`` where ``batch``
    is the device-placed global batch (leading dim sharded over ``data``)
    and metrics are replicated scalars: mean loss, correct-prediction count,
    and example count — the per-step statistics the reference prints
    (`cifar_example.py:83-87`) plus what its synced eval metric accumulates
    (`cifar_example_ddp.py:133`).

    ``sentinel=True`` (guard.enabled, docs/RESILIENCE.md "Guardrails")
    compiles the on-device health summary + guarded update into the
    program: the signature becomes ``step(state, batch, guard_in)``
    (`default_guard_in` — replicated scalars, not donated) and metrics
    gain ``loss_raw`` / ``grad_norm`` / ``applied``. Off, the factory —
    and the compiled HLO — is exactly the pre-guardrails program (the
    DP304 fingerprint is digest-identical).
    """
    # The GSPMD path is replicated-update only (the sharded update needs
    # explicit collectives — `make_train_step_shard_map`); reject a
    # sharded-layout optimizer at the factory boundary.
    _check_update_sharding("replicated", optimizer)
    repl = replicated_sharding(mesh)
    batch_sh = batch_sharding(mesh)
    loss_impl = _select_loss_impl(use_pallas_xent)

    # `batch_sh` is a pytree-prefix: every batch leaf (image, label, and
    # the optional weight mask) shards on its leading dim — or, with
    # accumulation, on the microbatch dim after the scan axis.
    step = _select_body(model, optimizer, schedule, loss_impl, augment_fn,
                        accum_steps, sentinel=sentinel)
    in_batch_sh = batch_sh if accum_steps == 1 else scan_batch_sharding(mesh)
    in_sh = (repl, in_batch_sh) + ((repl,) if sentinel else ())
    return jax.jit(
        step,
        in_shardings=in_sh,
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )


def make_multi_step(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    schedule: Schedule,
    num_steps: int,
    use_pallas_xent: bool = False,
    augment_fn: Callable | None = None,
    accum_steps: int = 1,
    update_sharding: str = "replicated",
    collective_dtype: str | None = None,
    quant_block_size: int | None = None,
    quant_error_feedback: bool = True,
    bucket_mb: float = 0.0,
    sentinel: bool = False,
) -> Callable:
    """Device-side training loop: ``num_steps`` train steps in ONE program.

    ``lax.scan`` over the same step body `make_train_step` compiles, fed by a
    device-resident pool of batches with a leading (num_steps,) axis. One
    dispatch executes the whole window, so host→device round-trips (launch
    latency, relay RTT in tunneled setups) amortize across the window — the
    reference's eager loop pays them every step
    (`/root/reference/cifar_example_ddp.py:94-107`). Semantically identical
    to calling the single step ``num_steps`` times (equivalence-tested);
    metrics come back stacked per step.

    Returns ``loop(state, batches) -> (new_state, stacked_metrics)`` where
    every ``batches`` leaf has shape (pool, global_batch, ...). When
    ``pool == num_steps`` the scan consumes the pool directly; a smaller
    pool is cycled modularly *inside* the program (device-side gather per
    step), so HBM cost stays constant in ``num_steps`` — e.g. a benchmark
    can run a 30-step window over 4 staged batches without 30 copies.

    With ``accum_steps > 1`` the scanned body is the gradient-accumulation
    step (scan-of-scan): batch leaves gain a second leading axis,
    (pool, accum_steps, microbatch, ...), and each of the ``num_steps``
    window elements performs one accumulated optimizer update — BASELINE
    config 5's global-batch-4096 recipe running windowed on a small mesh,
    where both amortizations (dispatch RTT and HBM) are needed at once.

    ``update_sharding="sharded"`` runs the window over the explicit
    sharded-weight-update body (`make_local_step` — reduce-scatter →
    1/world update → params all-gather inside every scanned step, opt state
    permanently sharded over ``data``); ``optimizer`` must then be a
    `train.optim.ShardedUpdate`, as for `make_train_step_shard_map`.

    ``sentinel=True`` scans the sentinel body: the loop signature becomes
    ``loop(state, batches, guard_in)`` with ONE replicated ``guard_in``
    shared by every step of the window (the policy's cap/ease values are
    per-window by construction — the host only observes window
    boundaries). A window step that trips the guard emits the unchanged
    carry, so the remaining scanned steps continue from the pre-fault
    state exactly like the per-step path.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS, data_axis_size

    repl = replicated_sharding(mesh)
    loss_impl = _select_loss_impl(use_pallas_xent)

    if update_sharding == "sharded":
        body = make_local_step(
            model, optimizer, schedule, use_pallas_xent=use_pallas_xent,
            accum_steps=accum_steps, augment_fn=augment_fn,
            world=data_axis_size(mesh), axis_name=DATA_AXIS,
            update_sharding=update_sharding,
            collective_dtype=collective_dtype,
            quant_block_size=quant_block_size,
            quant_error_feedback=quant_error_feedback,
            bucket_mb=bucket_mb,
            sentinel=sentinel,
        )
    else:
        _check_update_sharding(update_sharding, optimizer)
        _refuse_replicated_bucketing(bucket_mb)
        body = _select_body(model, optimizer, schedule, loss_impl,
                            augment_fn, accum_steps, sentinel=sentinel)

    def loop(state: TrainState, batches, guard_in=None):
        step_body = body if guard_in is None else (
            lambda st, mb: body(st, mb, guard_in)
        )
        pool = jax.tree_util.tree_leaves(batches)[0].shape[0]
        if pool == num_steps:
            return jax.lax.scan(step_body, state, batches, length=num_steps)

        def indexed_body(st, i):
            mb = jax.tree_util.tree_map(
                lambda x: jax.lax.dynamic_index_in_dim(
                    x, i % pool, keepdims=False
                ),
                batches,
            )
            return step_body(st, mb)

        return jax.lax.scan(
            indexed_body, state, jnp.arange(num_steps, dtype=jnp.int32)
        )

    # Scan axis (and, with accumulation, the microbatch-stack axis) in
    # front, batch dim sharded over data.
    prefix_dims = 1 if accum_steps == 1 else 2
    in_batch_sh = scan_batch_sharding(mesh, prefix_dims=prefix_dims)
    state_sh = _state_shardings(mesh, update_sharding)
    run = loop
    if update_sharding == "sharded":
        # The explicit-collectives window: the whole scan runs per-shard
        # under shard_map, each scanned step performing the reduce-scatter /
        # sharded-update / all-gather sequence of `make_local_step`.
        batch_spec = P(*([None] * prefix_dims), DATA_AXIS)
        run = _shard_map(
            loop,
            mesh=mesh,
            in_specs=(_state_specs(update_sharding), batch_spec)
            + ((P(),) if sentinel else ()),
            out_specs=(_state_specs(update_sharding), P()),
        )
    return jax.jit(
        run,
        in_shardings=(state_sh, in_batch_sh) + ((repl,) if sentinel else ()),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def make_multi_step_resident(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    schedule: Schedule,
    num_steps: int,
    use_pallas_xent: bool = False,
    augment_fn: Callable | None = None,
    accum_steps: int = 1,
    update_sharding: str = "replicated",
    collective_dtype: str | None = None,
    quant_block_size: int | None = None,
    quant_error_feedback: bool = True,
    bucket_mb: float = 0.0,
    sentinel: bool = False,
) -> Callable:
    """Windowed training loop fed by a device-resident dataset + indices.

    The end-to-end feed redesign (VERDICT r4 next-steps #3): instead of the
    host gathering and shipping ~MBs of batch per step (the reference's
    DataLoader feed, `/root/reference/cifar_example.py:46-52`), the whole
    train set is staged in HBM once (CIFAR-10: 150 MB uint8) and each window
    dispatch carries only int32 *indices* — (num_steps, [accum,] batch),
    ~KBs. The compiled program gathers each step's batch on-device from the
    replicated dataset (the gather partitions trivially: indices are
    sharded over ``data``, the operand is replicated, so every device
    gathers exactly its shard's examples), then runs the same shared step
    body as `make_multi_step` — normalize/augment/fwd/bwd/update all
    unchanged and trajectory-identical (equivalence-tested).

    Returns ``loop(state, data, idx) -> (new_state, stacked_metrics)``:
    ``data`` leaves are (N, ...) device-resident (replicated; uint8 images
    fine — normalization is in-body), ``idx`` is int32 with the window axis
    in front. Only ``state`` is donated — ``data`` must survive the call.

    ``update_sharding="sharded"`` composes the resident feed with the
    sharded weight update: the indices shard over ``data`` (each replica
    gathers only its shard's examples from the replicated dataset) and the
    scanned body is the explicit reduce-scatter / 1/world-update /
    all-gather step of `make_local_step`.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS, data_axis_size

    repl = replicated_sharding(mesh)
    loss_impl = _select_loss_impl(use_pallas_xent)
    if update_sharding == "sharded":
        body = make_local_step(
            model, optimizer, schedule, use_pallas_xent=use_pallas_xent,
            accum_steps=accum_steps, augment_fn=augment_fn,
            world=data_axis_size(mesh), axis_name=DATA_AXIS,
            update_sharding=update_sharding,
            collective_dtype=collective_dtype,
            quant_block_size=quant_block_size,
            quant_error_feedback=quant_error_feedback,
            bucket_mb=bucket_mb,
            sentinel=sentinel,
        )
    else:
        _check_update_sharding(update_sharding, optimizer)
        _refuse_replicated_bucketing(bucket_mb)
        body = _select_body(model, optimizer, schedule, loss_impl,
                            augment_fn, accum_steps, sentinel=sentinel)

    def loop(state: TrainState, data, idx, guard_in=None):
        step_body = body if guard_in is None else (
            lambda st, mb: body(st, mb, guard_in)
        )

        def indexed_body(st, idx_step):
            mb = jax.tree_util.tree_map(lambda x: x[idx_step], data)
            return step_body(st, mb)

        # length pins the window size: a mis-shaped idx errors at trace
        # time instead of silently running a different number of steps.
        return jax.lax.scan(indexed_body, state, idx, length=num_steps)

    prefix_dims = 1 if accum_steps == 1 else 2
    idx_sh = scan_batch_sharding(mesh, prefix_dims=prefix_dims)
    state_sh = _state_shardings(mesh, update_sharding)
    run = loop
    if update_sharding == "sharded":
        idx_spec = P(*([None] * prefix_dims), DATA_AXIS)
        run = _shard_map(
            loop,
            mesh=mesh,
            in_specs=(_state_specs(update_sharding), P(), idx_spec)
            + ((P(),) if sentinel else ()),
            out_specs=(_state_specs(update_sharding), P()),
        )
    return jax.jit(
        run,
        in_shardings=(state_sh, repl, idx_sh) + ((repl,) if sentinel else ()),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


UPDATE_SHARDING_MODES = ("replicated", "sharded")


def _refuse_replicated_bucketing(bucket_mb: float) -> None:
    """Bucketing restructures the explicit reduce-scatter schedule; the
    replicated GSPMD path has no explicit exchange to bucket. Refused at
    every factory boundary — a silently-dropped `bucket_mb` would leave
    the caller believing the overlap schedule is armed."""
    if bucket_mb and float(bucket_mb) > 0:
        raise ValueError(
            "bucket_mb applies to the sharded update's reduce-scatter; "
            "pass update_sharding='sharded'"
        )


def _check_update_sharding(update_sharding: str, optimizer) -> None:
    """Fail fast on a mode/optimizer mismatch.

    The sharded layout is a *contract* between three parties — the reduce
    hook (flat grad shards out), the optimizer (`ShardedUpdate`: shard-
    shaped state, param-shard slicing, params all-gather), and the state
    created from that optimizer's `init`. A plain optimizer in sharded mode
    (or vice versa) would trace to shape errors deep inside the update;
    diagnose it at the factory boundary instead.
    """
    if update_sharding not in UPDATE_SHARDING_MODES:
        raise ValueError(
            f"update_sharding must be one of {UPDATE_SHARDING_MODES}, "
            f"got {update_sharding!r}"
        )
    is_sharded_opt = getattr(optimizer, "is_sharded_update", False)
    if update_sharding == "sharded" and not is_sharded_opt:
        raise ValueError(
            "update_sharding='sharded' requires a ShardedUpdate optimizer "
            "(train.optim.shard_optimizer) so the TrainState's opt_state "
            "was initialized in the sharded layout"
        )
    if update_sharding == "replicated" and is_sharded_opt:
        raise ValueError(
            "replicated update with a ShardedUpdate optimizer: the opt "
            "state layouts are incompatible; pass the inner optimizer"
        )


def _parse_wire_codec(collective_dtype: str | None,
                      quant_block_size: int | None = None,
                      quant_error_feedback: bool = True):
    """`train.collective_dtype` → wire codec for the gradient collective.

    The cast-only knob of PR 4 grown into a pluggable codec seam
    (`tpu_dp.parallel.quant.make_wire_codec`): None/"f32" keeps the leaf
    dtype on the wire, "bf16" returns the cast codec, "int8" the
    blockwise-absmax-scaled codec with error feedback — which is the one
    that needs the residual state threaded through `TrainState`.
    """
    from tpu_dp.parallel import quant

    return quant.make_wire_codec(
        collective_dtype,
        block_size=(quant.DEFAULT_BLOCK_SIZE if quant_block_size is None
                    else quant_block_size),
        error_feedback=quant_error_feedback,
    )


def _state_specs(update_sharding: str):
    """PartitionSpec pytree-prefix for a TrainState under ``update_sharding``.

    Replicated mode: everything P() (one spec, prefix-matched). Sharded
    mode: opt_state leaves are flat 1-D arrays laid out over the data axis
    — P(DATA_AXIS) — while step/params/batch_stats stay replicated. The
    returned TrainState-of-specs is a pytree prefix (each field's spec
    broadcasts over that subtree), valid for shard_map in/out_specs and,
    mapped through NamedSharding, for jit in/out_shardings.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS

    if update_sharding != "sharded":
        return P()
    # Residuals (int8 wire codec) are flat-sharded like the opt state:
    # f32[world, qpad] leaves with dim 0 over the data axis — each replica
    # holds its own pending-rounding-error row. {} when the codec is off,
    # where the prefix spec binds zero leaves.
    return TrainState(step=P(), params=P(), opt_state=P(DATA_AXIS),
                      batch_stats=P(), residuals=P(DATA_AXIS))


def _state_shardings(mesh: Mesh, update_sharding: str):
    """NamedSharding pytree-prefix for a TrainState (jit in/out_shardings):
    the device-placement twin of `_state_specs`."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS

    repl = replicated_sharding(mesh)
    if update_sharding != "sharded":
        return repl
    return TrainState(
        step=repl, params=repl,
        opt_state=NamedSharding(mesh, P(DATA_AXIS)),
        batch_stats=repl,
        residuals=NamedSharding(mesh, P(DATA_AXIS)),
    )


def make_local_step(
    model,
    optimizer: Optimizer,
    schedule: Schedule,
    use_pallas_xent: bool = False,
    accum_steps: int = 1,
    augment_fn: Callable | None = None,
    world: int = 1,
    axis_name: str | None = None,
    cast_params: bool = True,
    update_sharding: str = "replicated",
    collective_dtype: str | None = None,
    quant_block_size: int | None = None,
    quant_error_feedback: bool = True,
    bucket_mb: float = 0.0,
    sentinel: bool = False,
) -> Callable:
    """The per-shard step program with *explicit* collectives, unjitted.

    This is the SPMD program each device runs under
    `make_train_step_shard_map`: the shared step body (`_select_body` — the
    same normalize → augment → fwd/bwd → update the GSPMD path compiles)
    with the cross-replica reduction written out between the per-shard
    grads and the optimizer update — pmean(grads) / pmean(loss) /
    psum(correct) over the ``data`` axis via the typed wrappers in
    `tpu_dp.parallel.collectives`, a line-for-line statement of what DDP's
    C++ reducer fires from backward hooks.

    ``update_sharding="sharded"`` swaps the gradient pmean for the
    cross-replica sharded weight update (Xu et al., PAPERS.md): the reduce
    hook runs `collectives.psum_scatter` instead — each replica receives
    only the mean of its 1/world flat shard of every gradient leaf — and
    ``optimizer`` must be a `train.optim.ShardedUpdate`, whose update slices
    the matching parameter shards locally, steps 1/world of the state, and
    all-gathers the updated params. Same one-reduction-per-update invariant
    (`reduce_scatter` counts as the data-axis reduction for DP201/DP202);
    the compiled schedule becomes one reduce-scatter group + one all-gather
    group instead of one all-reduce group (DP301's second legal schedule).
    ``collective_dtype`` compresses the reduce-scatter wire format,
    EQuARX-style — off (None/"") reduces in the leaf dtype, "bf16" casts
    the payload, "int8" routes quantizable leaves through the blockwise-
    scaled codec (`collectives.psum_scatter_quant`): quantize once (with
    the ``TrainState.residuals`` error feedback, unless
    ``quant_error_feedback=False`` — the ablation seam), ONE int8
    all-to-all + f32 scales on the wire, dequantize-and-sum once; DP301's
    third legal schedule. ``quant_block_size`` sets the scaling-block
    length (`train.quant_block_size`; None = 256), and the step's metrics
    gain replicated ``quant_overflow``/``quant_clip`` block counts.

    Exposed as a factory (rather than a closure inside the shard_map
    wrapper) so `tpu_dp.analysis` can trace the *real shipped program* on
    abstract values and verify the reduction invariant — every gradient
    leaf reduced over the data axis exactly once per optimizer update,
    including under gradient accumulation (`accum_steps > 1`, where the
    reduction must sit after the microbatch scan, not inside it).

    ``bucket_mb > 0`` (`train.bucket_mb`, docs/PERF.md "Overlapped
    collectives") issues the gradient exchange as K size-targeted bucket
    reductions in reverse production order instead of one monolithic
    reduce-scatter — `collectives.psum_scatter_bucketed` (f32/bf16 wire)
    or `psum_scatter_quant_bucketed` (int8, per-bucket error-feedback
    residuals) — with `optimization_barrier` issue-order hints so XLA's
    latency-hiding scheduler can overlap each bucket's wire time with the
    remaining backward compute. Sharded mode only (the overlap schedule
    IS the decomposed exchange); DP301 verifies the K-bucket schedule
    covers the union of gradient leaves exactly once.

    ``cast_params=False`` skips the varying-cast of the params (a no-op on
    pre-vma JAX anyway); the analyzer uses it to trace outside a real
    `shard_map` scope.
    """
    from tpu_dp.parallel import bucketing, collectives, quant
    from tpu_dp.parallel.dist import DATA_AXIS

    if axis_name is None:
        axis_name = DATA_AXIS
    _check_update_sharding(update_sharding, optimizer)
    codec = _parse_wire_codec(collective_dtype, quant_block_size,
                              quant_error_feedback)
    if codec is not None and update_sharding != "sharded":
        # Only the sharded reduce-scatter reads the wire codec; accepting
        # it here would silently run full-precision pmean instead.
        raise ValueError(
            "collective_dtype applies to the sharded update's "
            "reduce-scatter; pass update_sharding='sharded'"
        )
    bucket_bytes = bucketing.parse_bucket_mb(bucket_mb)
    if update_sharding != "sharded":
        _refuse_replicated_bucketing(bucket_mb)

    loss_impl = _select_loss_impl(use_pallas_xent)

    def reduce_fn(grads, loss, correct, count, batch_stats, residuals):
        # The explicit DDP reduction: grad mean over the data axis, exactly
        # once, after any gradient-accumulation scan. Replicated mode
        # all-reduces the full leaves; sharded mode reduce-scatters, each
        # replica keeping only the shard its optimizer slice will consume —
        # through the int8 wire codec when configured (quantize once →
        # int8 all-to-all → dequantize once; residuals carry the error
        # feedback across steps), and as K bucketed reductions in reverse
        # production order when `bucket_mb` arms the overlap schedule.
        extra = {}
        if isinstance(codec, quant.Int8BlockCodec):
            if bucket_bytes:
                grads, residuals, stats = (
                    collectives.psum_scatter_quant_bucketed(
                        grads, residuals, axis_name, world=world, mean=True,
                        block_size=codec.block_size,
                        error_feedback=codec.error_feedback,
                        bucket_bytes=bucket_bytes,
                    ))
            else:
                grads, residuals, stats = collectives.psum_scatter_quant(
                    grads, residuals, axis_name, world=world, mean=True,
                    block_size=codec.block_size,
                    error_feedback=codec.error_feedback,
                )
            # Codec-health counts are rank-local (each replica quantizes
            # its own contribution): two scalar psums make them replicated
            # metrics — declared in the analyzer's metric-reduction budget
            # for the int8 programs, like loss/correct.
            extra = {
                "quant_overflow": collectives.psum(
                    stats["overflow"], axis_name),
                "quant_clip": collectives.psum(stats["clip"], axis_name),
            }
        elif bucket_bytes:
            grads = collectives.psum_scatter_bucketed(
                grads, axis_name, world=world, mean=True,
                dtype=codec.dtype if codec is not None else None,
                bucket_bytes=bucket_bytes,
            )
        elif update_sharding == "sharded":
            grads = collectives.psum_scatter(
                grads, axis_name, world=world, mean=True,
                dtype=codec.dtype if codec is not None else None,
            )
        else:
            grads = collectives.pmean(grads, axis_name)
        loss = collectives.pmean(loss, axis_name)
        correct = collectives.psum(correct, axis_name)
        count = count * world
        if getattr(model, "axis_name", None) is None and batch_stats:
            # Unsynced BN model: average per-shard running stats so state
            # leaves shard_map replicated. Models built with
            # axis_name=DATA_AXIS already synced in-forward — skip the
            # redundant per-step all-reduce over the stats tree.
            batch_stats = collectives.pmean(batch_stats, axis_name)
        return grads, loss, correct, count, batch_stats, residuals, extra

    # Mark the replicated params as device-varying before differentiating.
    # Under shard_map's replication typing, grads of a *varying* loss wrt
    # *invariant* params would get an implicit cross-shard psum inserted
    # by AD (the cotangent of the invariant→varying broadcast) — i.e.
    # globally-summed grads before our explicit collective, which would
    # overscale the update by the world size. Casting params to
    # *varying* keeps AD local: per-shard grads out, exactly what DDP's
    # reducer sees pre-allreduce.
    cast = (lambda p: _to_varying(p, axis_name)) if cast_params else None
    # The sentinel's cross-replica gap on the sharded path: each replica
    # holds a 1/world gradient shard, so the health sum-of-squares needs
    # one scalar psum (the ONLY collective the sentinel adds — the
    # replicated path computes it on already-pmean'ed grads), and the
    # skip select over the varying opt-state shards needs a varying
    # predicate under replication typing.
    health_reduce = None
    opt_pred_cast = None
    if sentinel and update_sharding == "sharded":
        health_reduce = lambda s: collectives.psum(s, axis_name)  # noqa: E731
        if cast_params:
            opt_pred_cast = lambda p: _to_varying(p, axis_name)  # noqa: E731
    return _select_body(model, optimizer, schedule, loss_impl, augment_fn,
                        accum_steps, reduce_fn=reduce_fn, cast_params=cast,
                        sentinel=sentinel, health_reduce=health_reduce,
                        opt_pred_cast=opt_pred_cast)


def make_train_step_shard_map(
    model,
    optimizer: Optimizer,
    mesh: Mesh,
    schedule: Schedule,
    use_pallas_xent: bool = False,
    accum_steps: int = 1,
    augment_fn: Callable | None = None,
    update_sharding: str = "replicated",
    collective_dtype: str | None = None,
    quant_block_size: int | None = None,
    quant_error_feedback: bool = True,
    bucket_mb: float = 0.0,
    sentinel: bool = False,
) -> Callable:
    """Explicit-collectives variant of the DP train step (`shard_map`).

    Where `make_train_step` lets GSPMD *infer* the gradient all-reduce from
    sharding annotations, this path writes the distributed program per-shard,
    with the collectives explicit (`make_local_step`): each device computes
    loss/grads over its local shard of the global batch, then pmeans the
    gradients over the ``data`` mesh axis (ICI) — a line-for-line statement
    of what DDP's C++ reducer does from backward hooks
    (`/root/reference/cifar_example_ddp.py:83`), but inside one compiled
    program. Both paths are equivalence-tested against each other; this one
    is also the extension point for hand-scheduled comms (e.g. overlapping
    grad reduction with remaining backward compute). Composes with gradient
    accumulation: batch leaves gain a leading replicated (accum_steps,)
    axis, the microbatch dim is the sharded one.

    ``update_sharding="sharded"`` is that extension point exercised: the
    gradient pmean becomes reduce-scatter → 1/world optimizer update →
    params all-gather (`make_local_step` docs; Xu et al., PAPERS.md), with
    ``optimizer`` a `train.optim.ShardedUpdate` and the TrainState's
    opt_state living sharded over ``data`` (in/out specs P(DATA_AXIS) —
    per-replica optimizer memory ~1/world). ``collective_dtype="bf16"``
    additionally compresses the reduce-scatter wire format (EQuARX-style).

    BatchNorm models must be constructed with ``axis_name=DATA_AXIS`` so
    batch statistics sync across shards (the `shard_map` analogue of the
    global-batch stats GSPMD computes automatically — sync-BN semantics).
    """
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS, data_axis_size

    repl = replicated_sharding(mesh)
    repl_spec = P()
    state_spec = _state_specs(update_sharding)
    state_sh = _state_shardings(mesh, update_sharding)
    if accum_steps == 1:
        batch_sh = batch_sharding(mesh)
        batch_spec = P(DATA_AXIS)
    else:
        batch_sh = scan_batch_sharding(mesh)
        batch_spec = P(None, DATA_AXIS)

    local_step = make_local_step(
        model, optimizer, schedule, use_pallas_xent=use_pallas_xent,
        accum_steps=accum_steps, augment_fn=augment_fn,
        world=data_axis_size(mesh), axis_name=DATA_AXIS,
        update_sharding=update_sharding, collective_dtype=collective_dtype,
        quant_block_size=quant_block_size,
        quant_error_feedback=quant_error_feedback,
        bucket_mb=bucket_mb,
        sentinel=sentinel,
    )

    # Replication checking stays ON: an output that is rank-varying (a
    # forgotten pmean/psum on a new metric) is a trace-time error instead of
    # a silent wrong answer from device 0's shard.
    sharded = _shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, batch_spec) + ((repl_spec,) if sentinel else ()),
        out_specs=(state_spec, repl_spec),
    )
    return jax.jit(
        sharded,
        in_shardings=(state_sh, batch_sh) + ((repl,) if sentinel else ()),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )


def _infer_forward(model, state: TrainState, batch):
    """Shared inference forward: normalize → model(train=False) → logits/preds.

    One source of truth for the two inference consumers — `make_eval_step`
    (training-time accuracy) and `make_serve_step` (the serving subsystem,
    `tpu_dp/serve/`) — so the serve path can never drift from the forward
    the eval metrics were measured on. Uses running statistics for
    BatchNorm models; ``state`` only needs params/batch_stats populated
    (serve passes a TrainState with an empty opt_state).
    """
    images = _maybe_normalize(batch["image"])
    logits, _ = _apply_model(model, state, images, train=False)
    predictions = jnp.argmax(logits, axis=-1)
    return logits, predictions


def make_eval_step(model, mesh: Mesh,
                   update_sharding: str = "replicated") -> Callable:
    """Build the jitted eval step: global (correct, count) per batch.

    ``update_sharding`` must match the TrainState's layout: with the
    sharded weight update the opt_state leaves arrive sharded over ``data``
    (the eval computation never touches them, but jit checks every input's
    declared sharding against the committed buffers).

    Parity with the reference's synced eval
    (`cifar_example_ddp.py:124-136`): torchmetrics allreduces
    correct/total state on every update (`dist_sync_on_step=True`). Here each
    batch's counts are computed over the *sharded global* batch, so the
    cross-chip reduction is inside the compiled step and the returned scalars
    are already exact global values — same semantics, one fused collective.
    Uses running statistics for BatchNorm models (`train=False`); the
    reference never calls `.eval()` (`cifar_example_ddp.py:130` — moot for
    its BN-free `Net`, divergence documented per SURVEY.md §3.4).
    """
    repl = replicated_sharding(mesh)
    batch_sh = batch_sharding(mesh)
    state_sh = _state_shardings(mesh, update_sharding)

    def step(state: TrainState, batch):
        labels = batch["label"]
        weight = batch.get("weight")
        logits, predictions = _infer_forward(model, state, batch)
        if weight is None:
            correct = jnp.sum(predictions == labels)
            count = jnp.asarray(labels.shape[0], jnp.int32)
        else:
            correct = jnp.sum((predictions == labels) * weight).astype(jnp.int32)
            count = jnp.sum(weight).astype(jnp.int32)
        return {
            "loss": cross_entropy_loss(logits, labels, weight),
            "correct": correct,
            "count": count,
        }

    return jax.jit(
        step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=repl,
    )


def init_serve_stats(num_classes: int):
    """Device-resident serving statistics threaded through every serve step.

    ``served`` counts examples actually served (padding excluded via the
    batch's weight mask) and ``class_counts`` is the per-class prediction
    histogram — the device-side ground truth `tpu_dp.serve` cross-checks
    its host-side request counters against. This pytree is the serve
    step's *donated* argument: like the train state, it is consumed and
    re-emitted every call, so XLA aliases the buffers in place (dplint
    DP303 verifies the aliasing for the serve programs too) and the
    dispatch loop never churns the allocator.
    """
    return {
        "served": jnp.zeros((), jnp.int32),
        "class_counts": jnp.zeros((int(num_classes),), jnp.int32),
    }


def make_serve_step(model, mesh: Mesh, batch_size: int) -> Callable:
    """Compiled donated-buffer inference forward for ONE padded bucket size.

    The serving hot path (`tpu_dp/serve/engine.py`) keeps the training
    stack's compiled-program discipline: every batch the dynamic batcher
    forms is padded to a fixed bucket size from a ladder, and each bucket
    gets exactly one program built by this factory — fixed shapes, stats
    donation, a fingerprinted collective schedule (registered in dplint's
    Level-3 artifact) — so after one warmup call per bucket the
    RecompileGuard must observe zero retraces.

    Returns ``step(stats, state, batch) -> (new_stats, out)`` where:

    - ``stats`` is `init_serve_stats`'s pytree, **donated** (argnum 0 —
      the leading flattened leaves, which is what DP303's prefix check
      verifies); ``new_stats`` aliases its buffers;
    - ``state`` is a `TrainState` whose params/batch_stats are populated
      (opt_state may be empty — serving never materializes it; see
      `checkpoint.load_params_only`), replicated and NOT donated: it is
      reused by every call of every bucket program;
    - ``batch`` is ``{"image": [B, H, W, C], "weight": f32[B]}`` with
      ``weight`` masking padded rows out of the stats (1.0 = real
      example), and ``out`` is ``{"prediction": s32[B],
      "confidence": f32[B]}`` (top-1 class and its softmax probability).

    Replica fan-out comes from the data mesh for free: buckets divisible
    by the data-axis size shard the batch (and the per-example outputs)
    over ``data`` — each replica runs B/world examples and the only
    collectives are the two stats reductions (one scalar, one [C]-vector
    all-reduce, full-mesh, add — the schedule DP301 holds serve programs
    to). Smaller buckets run replicated (every device computes the whole
    batch — duplicated work is cheaper than a resharding collective at
    those sizes), compiling to zero collectives.
    """
    repl = replicated_sharding(mesh)
    from tpu_dp.parallel.dist import data_axis_size

    if batch_size < 1:
        raise ValueError(f"batch_size must be positive, got {batch_size}")
    sharded = batch_size % data_axis_size(mesh) == 0
    batch_sh = batch_sharding(mesh) if sharded else repl

    def step(stats, state: TrainState, batch):
        logits, predictions = _infer_forward(model, state, batch)
        weight = batch["weight"]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        confidence = jnp.max(probs, axis=-1)
        one_hot = jax.nn.one_hot(
            predictions, logits.shape[-1], dtype=jnp.float32
        )
        new_stats = {
            "served": stats["served"]
            + jnp.sum(weight).astype(jnp.int32),
            "class_counts": stats["class_counts"]
            + jnp.sum(one_hot * weight[:, None], axis=0).astype(jnp.int32),
        }
        out = {
            "prediction": predictions.astype(jnp.int32),
            "confidence": confidence,
        }
        return new_stats, out

    return jax.jit(
        step,
        in_shardings=(repl, repl, batch_sh),
        out_shardings=(repl, batch_sh),
        donate_argnums=(0,),
    )
