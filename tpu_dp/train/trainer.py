"""Trainer: epochs, logging, eval, checkpoint — the reference's `main()`.

One code path from one chip to a full slice (mesh shape is the only
variable), replacing the reference's forked `cifar_example.py` /
`cifar_example_ddp.py` pair. Reproduces the observable behavior of
`/root/reference/cifar_example_ddp.py:90-136`: per-epoch `set_epoch`
reshuffle (`:92`), running-loss print every `log_every` steps in the
reference's exact format (`:111-114`, but process-0-gated and with a correct
remainder divisor), end-of-training weights export (`:118-119`), and a
synced-accuracy eval (`:124-136`) — plus what the reference lacks: resume,
throughput metering, and profiler hooks (SURVEY.md §5).

Hot-loop discipline: the Python loop only *dispatches* compiled steps and
accumulates the returned replicated scalars with on-device adds — it never
blocks on a device→host transfer except at log boundaries and epoch ends, so
host dispatch runs ahead of device execution and the input pipeline's
prefetch overlaps (unlike the reference, whose `loss.item()` syncs every
step, `cifar_example.py:83`).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from tpu_dp import checkpoint as ckpt_lib
from tpu_dp.config import Config
from tpu_dp.data.cifar import load_dataset
from tpu_dp.data.pipeline import DataPipeline
from tpu_dp.models import build_model
from tpu_dp.parallel import dist
from tpu_dp.train.optim import SGD
from tpu_dp.train.schedule import make_schedule
from tpu_dp.obs.counters import counters as _obs_counters
from tpu_dp.train.state import create_train_state
from tpu_dp.train.step import make_eval_step, make_train_step
from tpu_dp.utils import (
    StepProfiler,
    ThroughputMeter,
    log0,
    parse_profile_steps,
    print0,
    profile_trace,
)


def _iso_ts(epoch_seconds: float) -> str:
    """ISO-8601 UTC stamp for metrics records (millisecond resolution)."""
    from datetime import datetime, timezone

    return datetime.fromtimestamp(
        epoch_seconds, timezone.utc
    ).isoformat(timespec="milliseconds")


class Trainer:
    def __init__(self, cfg: Config, mesh=None):
        self.cfg = cfg
        self.ctx = dist.initialize(
            cfg.parallel.coordinator_address,
            cfg.parallel.num_processes,
            cfg.parallel.process_id,
        )
        self.mesh = mesh if mesh is not None else dist.data_mesh(
            num_devices=cfg.parallel.num_devices
        )
        self.num_devices = int(self.mesh.devices.size)
        log0("topology: %s", json.dumps(dist.describe(self.mesh)))

        self._load_data(cfg)

        # The dataset determines the number of classes; an explicit config
        # value must agree (a silently mis-sized head clamps labels inside
        # the compiled loss and trains garbage with no error).
        num_classes = self.train_ds.num_classes
        if cfg.model.num_classes is not None and (
            cfg.model.num_classes != num_classes
        ):
            raise ValueError(
                f"model.num_classes={cfg.model.num_classes} conflicts with "
                f"dataset {self.train_ds.name!r} ({num_classes} classes)"
            )

        import jax.numpy as jnp  # local: keep module import light

        dtype = jnp.bfloat16 if cfg.model.bf16 else jnp.float32
        from tpu_dp.models import parse_fused_stages

        # Cross-replica sharded weight update (docs/PERF.md). Validated
        # before model construction because the sharded path runs the
        # explicit-collectives `shard_map` program, where BatchNorm models
        # must sync their batch statistics in-forward (axis_name=DATA_AXIS
        # — sync-BN semantics, matching the global-batch stats the GSPMD
        # path computes automatically).
        us = cfg.train.update_sharding
        if us not in ("replicated", "sharded"):
            raise ValueError(
                f"train.update_sharding must be replicated|sharded, "
                f"got {us!r}"
            )
        if cfg.train.collective_dtype and us != "sharded":
            raise ValueError(
                "train.collective_dtype applies to the sharded update's "
                "reduce-scatter; set train.update_sharding=sharded"
            )
        self.update_sharding = us

        model_kwargs = dict(
            num_classes=num_classes, dtype=dtype,
            fused_stages=parse_fused_stages(cfg.model.fused_stages),
            fused_block_b=cfg.model.fused_block_b,
            fused_bwd=cfg.model.fused_bwd,
        )
        from tpu_dp.models import BATCHNORM_MODELS

        if us == "sharded" and cfg.model.name.lower() in BATCHNORM_MODELS:
            model_kwargs["axis_name"] = dist.DATA_AXIS
        self.model = build_model(cfg.model.name, **model_kwargs)
        # Sync-BN models need the data axis bound even at init; the
        # axis-free twin has the identical parameter tree and initializes
        # anywhere (same trick as tpu_dp.analysis.gradsync).
        self._init_model = self.model
        if "axis_name" in model_kwargs:
            self._init_model = build_model(
                cfg.model.name,
                **{k: v for k, v in model_kwargs.items()
                   if k != "axis_name"})

        self.train_pipe = DataPipeline(
            self.train_ds, cfg.data.batch_size, self.mesh,
            shuffle=cfg.data.shuffle, seed=cfg.train.seed,
            drop_remainder=cfg.data.drop_remainder, prefetch=cfg.data.prefetch,
            accum_steps=cfg.optim.grad_accum_steps,
        )
        self.test_pipe = DataPipeline(
            self.test_ds, cfg.data.batch_size, self.mesh,
            shuffle=False, seed=cfg.train.seed,
            drop_remainder=False, prefetch=cfg.data.prefetch,
        )

        steps_per_epoch = len(self.train_pipe)
        total_steps = steps_per_epoch * cfg.train.epochs
        self.optimizer = SGD(
            cfg.optim.momentum,
            cfg.optim.weight_decay,
            decay_exclude_bias_and_norm=cfg.optim.decay_exclude_bias_and_norm,
        )
        # Sharded mode wraps the optimizer so its state initializes — and
        # persists — sharded over the data axis; the train step then routes
        # through the explicit-collectives factory that reduce-scatters
        # grads and all-gathers updated params. The replicated default
        # keeps the GSPMD path.
        if us == "sharded":
            from tpu_dp.train.optim import shard_optimizer

            self.optimizer = shard_optimizer(
                self.optimizer, dist.data_axis_size(self.mesh)
            )
        self.schedule = make_schedule(
            cfg.optim.schedule, cfg.optim.lr, total_steps,
            int(cfg.optim.warmup_epochs * steps_per_epoch), cfg.optim.final_lr,
        )
        augment_fn = None
        if cfg.data.augment:
            from tpu_dp.data.augment import make_augment_fn

            augment_fn = make_augment_fn(cfg.train.seed + 1)
        self._augment_fn = augment_fn
        # RecompileGuard (dplint DP305's runtime half): any post-warmup
        # growth of a step's trace cache is a silent recompile — a
        # step-time cliff this surfaces instead of swallowing. The eval
        # step is deliberately unguarded: its final partial batch
        # legitimately compiles a second variant.
        guard_mode = cfg.train.recompile_guard
        if guard_mode not in ("off", "warn", "raise"):
            raise ValueError(
                f"train.recompile_guard must be off|warn|raise, "
                f"got {guard_mode!r}"
            )
        self._guard = None if guard_mode == "off" else guard_mode
        if us == "sharded":
            from tpu_dp.train.step import make_train_step_shard_map

            self.train_step = self._guarded(
                "train_step", make_train_step_shard_map(
                    self.model, self.optimizer, self.mesh, self.schedule,
                    use_pallas_xent=cfg.train.pallas_xent,
                    accum_steps=cfg.optim.grad_accum_steps,
                    augment_fn=augment_fn,
                    update_sharding=us,
                    collective_dtype=cfg.train.collective_dtype or None,
                ))
        else:
            self.train_step = self._guarded("train_step", make_train_step(
                self.model, self.optimizer, self.mesh, self.schedule,
                use_pallas_xent=cfg.train.pallas_xent,
                accum_steps=cfg.optim.grad_accum_steps,
                augment_fn=augment_fn,
            ))
        self.eval_step = make_eval_step(self.model, self.mesh,
                                        update_sharding=us)
        spc = int(cfg.train.steps_per_call)
        if spc < 0:
            raise ValueError(
                f"train.steps_per_call must be >= 0 (0 = auto), got {spc}"
            )
        if spc == 0:
            # Auto: windowed dispatch whenever the pipeline shape allows.
            # 24 steps/window matches the longrun recipe — big enough to
            # amortize a high-RTT dispatch, small enough to keep the
            # log cadence and HBM batch staging reasonable.
            spc = min(24, steps_per_epoch) if cfg.data.drop_remainder else 1
        self.steps_per_call = max(1, spc)
        if self.steps_per_call > 1 and not cfg.data.drop_remainder:
            raise ValueError(
                "train.steps_per_call > 1 requires data.drop_remainder=true"
            )
        self.multi_step = None
        if self.steps_per_call > 1:
            from tpu_dp.train.step import make_multi_step

            # Composes with gradient accumulation (scan-of-scan): each
            # window element is one accumulated optimizer update, so
            # BASELINE config 5 (global batch 4096) runs windowed on a
            # small mesh — both the dispatch-RTT and the HBM amortization
            # at once.
            self.multi_step = self._guarded("multi_step", make_multi_step(
                self.model, self.optimizer, self.mesh, self.schedule,
                num_steps=self.steps_per_call,
                use_pallas_xent=cfg.train.pallas_xent,
                augment_fn=augment_fn,
                accum_steps=cfg.optim.grad_accum_steps,
                update_sharding=us,
                collective_dtype=cfg.train.collective_dtype or None,
            ))

        # Device-resident feed (VERDICT r4 next-steps #3): stage the train
        # set in HBM once; per-window dispatch ships only indices. The
        # trajectory is identical to the streaming path (same sampler
        # order, same step body — equivalence-tested); what changes is the
        # host work per step: ~KB of int32 instead of a ~MB gather+copy.
        # Staging is lazy (`resident_train` property): eval-only or tooling
        # constructions never pay the host→HBM transfer (ADVICE r5).
        self._resident_train = None
        self._resident_loops: dict[int, Any] = {}
        mode = cfg.data.device_resident
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"data.device_resident must be auto|on|off, got {mode!r}"
            )
        if mode == "on" and not cfg.data.drop_remainder:
            raise ValueError(
                "data.device_resident=on requires data.drop_remainder=true"
            )
        if mode == "on":
            ds_bytes = self.train_pipe.dataset_bytes()
            if ds_bytes > cfg.data.resident_max_bytes:
                # Forced on is explicit user intent — warn with the numbers
                # (instead of the opaque allocator error staging would hit
                # on a dataset that genuinely exceeds HBM) and proceed.
                log0(
                    "warning: data.device_resident=on stages %d bytes, over "
                    "data.resident_max_bytes=%d — staging may exhaust "
                    "device memory; raise the budget or use auto",
                    ds_bytes, cfg.data.resident_max_bytes,
                )
        self._resident_enabled = mode == "on" or (
            mode == "auto"
            and cfg.data.drop_remainder
            and self.train_pipe.dataset_bytes() <= cfg.data.resident_max_bytes
        )

        rng = jax.random.PRNGKey(cfg.train.seed)
        sample = np.zeros((1, 32, 32, 3), np.float32)
        self.state = create_train_state(self._init_model, rng, sample,
                                        self.optimizer)
        self.start_epoch = 0
        self.start_step = 0  # step within start_epoch (mid-epoch resume)
        self.meter = ThroughputMeter(warmup_steps=2)

        self.ckpt_mgr = ckpt_lib.CheckpointManager(
            cfg.train.ckpt_dir, keep=cfg.train.ckpt_keep,
            async_save=cfg.train.ckpt_async,
        )

        # Resilience (tpu_dp/resilience/, docs/RESILIENCE.md): async
        # step-cadence snapshots, SIGTERM/SIGINT preemption hook, and
        # deterministic fault injection for the test suite. The snapshot
        # manager always exists — with snapshot_every_steps=0 the cadence
        # never fires, but the preemption hook's final snapshot still has
        # somewhere to land.
        from tpu_dp.resilience import (
            FaultInjector,
            PreemptionHandler,
            SnapshotManager,
        )

        res = cfg.resilience
        self.snapshot_dir = res.snapshot_dir or str(
            Path(cfg.train.ckpt_dir) / "snapshots"
        )
        self.snap_mgr = SnapshotManager(
            self.snapshot_dir, every_steps=res.snapshot_every_steps,
            keep=res.snapshot_keep,
        )
        self.preempt = PreemptionHandler() if res.handle_signals else None
        self.fault = FaultInjector.from_spec(
            res.fault, rank=self.ctx.process_index
        )
        if cfg.train.resume:
            self._maybe_resume()
        # Host-side mirror of state.step: the snapshot cadence and fault
        # steps key off it without a per-window device sync.
        self._host_step = int(self.state.step)
        self._metrics_file = None  # lazily opened by _log_metrics (rank 0)
        self._hb_write_failed = False  # one-shot heartbeat-failure warning

        # Telemetry (tpu_dp/obs/, docs/OBSERVABILITY.md). Everything below
        # is None at obs=off — the hot loop then takes the untelemetered
        # path (one is-None check per window; benched within noise).
        if cfg.train.obs not in ("off", "basic", "full"):
            raise ValueError(
                f"train.obs must be off|basic|full, got {cfg.train.obs!r}"
            )
        self.obs_mode = cfg.train.obs
        self.obs_dir = Path(
            cfg.obs.run_dir or Path(cfg.train.ckpt_dir) / "obs"
        )
        self.spans = None
        self.heartbeat = None
        self.health = None
        if self.obs_mode != "off":
            from tpu_dp.obs import HealthMonitor, HeartbeatWriter, SpanRecorder

            self.spans = SpanRecorder(capacity=cfg.obs.span_capacity)
            if cfg.obs.heartbeat_every_steps > 0:
                # Every rank appends to its own heartbeat file — per-rank
                # host IO is the protocol, not a rank gate.
                self.heartbeat = HeartbeatWriter(
                    self.obs_dir, rank=self.ctx.process_index,
                    every_steps=cfg.obs.heartbeat_every_steps,
                )
            if self.heartbeat is not None and self.ctx.process_index == 0:  # dplint: allow(DP101) host-only monitor
                self.health = HealthMonitor(
                    self.obs_dir, world=self.ctx.process_count,
                    straggler_factor=cfg.obs.straggler_factor,
                    stale_after_s=cfg.obs.stale_after_s,
                    min_step_ms=cfg.obs.min_step_ms,
                    on_flag=cfg.obs.on_straggler,
                )
        # Step-ranged profiling (train.profile_steps=START:END): trace only
        # the window under investigation instead of the whole run.
        profile_range = parse_profile_steps(cfg.train.profile_steps)
        self._step_profiler = None
        if profile_range is not None:
            self._step_profiler = StepProfiler(
                cfg.train.profile_dir, *profile_range
            )

        if cfg.train.verify_fingerprint:
            self._verify_step_fingerprint()

    def _guarded(self, name: str, step_fn):
        """Wrap a compiled step in a RecompileGuard (train.recompile_guard).

        warmup_calls=2: the first call consumes the host-staged
        (uncommitted) init state, every later call the donated
        device-resident output — that placement transition legitimately
        traces a second cache entry, so only growth past call 2 is a real
        retrace. Without drop_remainder the epoch's final partial batch
        (padded, with a weight leaf) legitimately compiles another variant
        every epoch, so guarding would cry wolf — steps run unguarded
        there, like the eval step. No logger override: retrace divergence
        is inherently per-rank, so the guard's own stderr report must fire
        on whichever rank retraced, not only on process 0.
        """
        if self._guard is None or not self.cfg.data.drop_remainder:
            return step_fn
        from tpu_dp.analysis.recompile import RecompileGuard

        return RecompileGuard(
            step_fn, name=name, on_retrace=self._guard, warmup_calls=2,
        )

    def _verify_step_fingerprint(self) -> None:
        """Cross-rank collective-schedule check at startup (dplint DP304).

        Every rank AOT-compiles the train step it is about to run, digests
        the ordered collective sequence + replica groups of the compiled
        module, and compares against rank 0's digest — a rank running a
        stale binary / different JAX build / diverged config fails here
        instead of deadlocking the slice at the first divergent collective.
        """
        import jax.numpy as jnp

        from tpu_dp.analysis.hlo import program_fingerprint

        cfg = self.cfg
        gb = cfg.data.batch_size * self.ctx.process_count
        accum = cfg.optim.grad_accum_steps
        prefix = (accum,) if accum > 1 else ()
        batch = {
            "image": jax.ShapeDtypeStruct(
                prefix + (gb, 32, 32, 3), jnp.uint8
            ),
            "label": jax.ShapeDtypeStruct(prefix + (gb,), jnp.int32),
        }
        digest = program_fingerprint(self.train_step, (self.state, batch))
        dist.verify_collective_fingerprint(digest, tag="train_step")
        log0("collective-schedule fingerprint (train_step): %s", digest[:16])

    def _load_data(self, cfg: Config) -> None:
        """Process 0 materializes the dataset first; the rest then read it.

        Fixes the reference's download race — every rank extracting into the
        shared `./data` dir concurrently (`cifar_example_ddp.py:67-68,73-74`,
        SURVEY.md §5 "Race detection").
        """

        def _load():
            train = load_dataset(
                cfg.data.dataset, cfg.data.root, train=True,
                allow_synthetic=cfg.data.allow_synthetic,
                synthetic_num_examples=cfg.data.synthetic_train_size,
                seed=cfg.train.seed,
            )
            test = load_dataset(
                cfg.data.dataset, cfg.data.root, train=False,
                allow_synthetic=cfg.data.allow_synthetic,
                synthetic_num_examples=cfg.data.synthetic_test_size,
                seed=cfg.train.seed,
            )
            return train, test

        if self.ctx.process_count == 1:
            self.train_ds, self.test_ds = _load()
            return
        from jax.experimental import multihost_utils

        # Host-only IO stagger: rank 0 downloads, the barrier sits OUTSIDE
        # both gates so every rank reaches it.
        if self.ctx.process_index == 0:  # dplint: allow(DP101)
            self.train_ds, self.test_ds = _load()
        multihost_utils.sync_global_devices("tpu_dp_data_materialized")
        if self.ctx.process_index != 0:  # dplint: allow(DP101)
            self.train_ds, self.test_ds = _load()

    def _resume_position(self, meta: dict) -> tuple[int, int]:
        """(start_epoch, start_step) a restored state's meta encodes.

        Epoch checkpoints record the *finished* epoch → resume at the next
        one, step 0. Snapshots record the mid-epoch position → resume the
        same epoch and fast-forward the sampler by ``steps_done`` (no batch
        replayed, none skipped). A snapshot taken at the exact epoch end
        normalizes to (epoch+1, 0).
        """
        if meta.get("kind") == "snapshot":
            epoch = int(meta.get("epoch", 0))
            step = int(meta.get("steps_done", 0))
            spe = len(self.train_pipe)
            if spe and step >= spe:
                return epoch + 1, 0
            return epoch, step
        return int(meta.get("epoch", -1)) + 1, 0

    def _maybe_resume(self) -> None:
        """Resume from the newest checkpoint OR snapshot, agreed across
        processes.

        Checkpoints/snapshots are written by process 0 only; on a pod each
        host has its own disk, so the resume decision and the restored
        state must come from process 0 (otherwise replicas desync: some
        resume, some start fresh). The newest complete save wins across
        both layouts (`tpu_dp.resilience.find_latest`), so a run killed
        mid-epoch resumes from its last step snapshot, not the last epoch
        boundary.
        """
        cfg = self.cfg
        from tpu_dp.resilience import find_latest

        found = find_latest(cfg.train.ckpt_dir, self.snapshot_dir)
        resume_dir = found[0] if found is not None else None
        exists = resume_dir is not None
        if self.ctx.process_count == 1:
            if not exists:
                return
            self.state, meta = ckpt_lib.load_checkpoint(resume_dir, self.state)
            self.start_epoch, self.start_step = self._resume_position(meta)
        else:
            from jax.experimental import multihost_utils

            exists0 = bool(
                int(multihost_utils.broadcast_one_to_all(np.int32(exists)))
            )
            if not exists0:
                return
            # Host-only checkpoint read; the broadcasts below are outside
            # the gate, reached by every rank.
            if self.ctx.process_index == 0:  # dplint: allow(DP101)
                state, meta = ckpt_lib.load_checkpoint(resume_dir, self.state)
                epoch, step = self._resume_position(meta)
                pos = np.asarray([epoch, step], np.int32)
            else:
                state, pos = self.state, np.zeros(2, np.int32)
            host_state = jax.tree_util.tree_map(np.asarray, state)
            self.state = multihost_utils.broadcast_one_to_all(host_state)
            pos = multihost_utils.broadcast_one_to_all(pos)
            self.start_epoch, self.start_step = int(pos[0]), int(pos[1])
        log0("resumed from %s at epoch %d step-in-epoch %d (global step %d)",
             resume_dir, self.start_epoch, self.start_step,
             int(self.state.step))

    @property
    def resident_train(self):
        """The device-resident train set, staged on first access (or None).

        Lazy so a Trainer built for eval/tooling never pays the host→HBM
        transfer (ADVICE r5); `train_epoch` touches it on its first window.
        """
        if self._resident_enabled and self._resident_train is None:
            self._resident_train = self.train_pipe.resident_data()
        return self._resident_train

    @property
    def global_batch_size(self) -> int:
        """Logical per-step batch: per-process batch × processes (the
        reference's batch-4-per-rank × world accounting, SURVEY.md §2A)."""
        return (self.cfg.data.batch_size * self.ctx.process_count
                * self.cfg.optim.grad_accum_steps)

    def _resident_loop(self, n: int):
        """Compiled resident window program for window size ``n`` (cached;
        an epoch uses at most two sizes: steps_per_call and 1)."""
        loop = self._resident_loops.get(n)
        if loop is None:
            from tpu_dp.train.step import make_multi_step_resident

            loop = self._guarded(f"resident_loop[w{n}]", make_multi_step_resident(
                self.model, self.optimizer, self.mesh, self.schedule,
                num_steps=n, use_pallas_xent=self.cfg.train.pallas_xent,
                augment_fn=self._augment_fn,
                accum_steps=self.cfg.optim.grad_accum_steps,
                update_sharding=self.update_sharding,
                collective_dtype=self.cfg.train.collective_dtype or None,
            ))
            self._resident_loops[n] = loop
        return loop

    def train_epoch(self, epoch: int, start_step: int = 0) -> dict[str, float]:
        """One epoch of training; ``start_step`` resumes it mid-way.

        ``start_step > 0`` (a snapshot resume) fast-forwards the sampler:
        the epoch's first ``start_step`` batches were already consumed by
        the run being resumed, so iteration starts at exactly the next one
        — no batch replayed, none skipped.
        """
        cfg = self.cfg
        self.train_pipe.set_epoch(epoch)  # `cifar_example_ddp.py:92` parity
        gbs = self.global_batch_size
        run_loss, run_steps = None, 0  # device-side running-loss accumulator
        ep_loss = ep_correct = None
        ep_steps, ep_count = 0, 0
        i = start_step - 1
        done = start_step  # steps of this epoch completed (snapshot meta)
        if self.resident_train is not None:
            items = self.train_pipe.index_windows(
                self.steps_per_call, skip_steps=start_step)
        else:
            items = self.train_pipe.windows(
                self.steps_per_call, skip_steps=start_step)
        def _unstack(stacked, n):
            # Lazy per-step views over the window's stacked metrics — still
            # no host sync outside log boundaries.
            return tuple(
                {k: v[j] for k, v in stacked.items()} for j in range(n)
            )

        # Telemetry (train.obs != off): span timestamps bracket the loop's
        # phases — t0→t1 data_wait, t1→t2 h2d (full only: block on the
        # placed batch), t2→t3 dispatch, t3→t4 device (full only: a scalar
        # fetch, the `ThroughputMeter.mark()` fence discipline — the only
        # obs mode that adds a host sync, which is why it is opt-in).
        spans = self.spans
        obs_full = self.obs_mode == "full"
        t_boundary = time.perf_counter()  # heartbeat boundary-to-boundary clock
        hb_steps = 0  # steps since the last accepted heartbeat
        it = iter(items)
        while True:
            if spans is not None:
                # ts_wall is the step's wall-clock START — stamped before
                # next(), so the data_wait slice occupies its real place
                # on the exported timeline instead of shifting every
                # step's slices right by its own data_wait.
                ts_wall = time.time()
                t0 = time.perf_counter()
            try:
                n, item = next(it)
            except StopIteration:
                break
            if self._step_profiler is not None:
                # BEFORE dispatch: the window about to run is steps
                # [_host_step + 1, _host_step + n] — arming at the
                # post-window boundary would trace the window after the
                # requested range (and miss in-window ranges entirely).
                self._step_profiler.on_window_start(self._host_step + 1, n)
            if spans is not None:
                t1 = time.perf_counter()
                t2 = t1
                if obs_full:
                    jax.block_until_ready(item)
                    t2 = time.perf_counter()
            if self.resident_train is not None:
                # Indices in, stacked metrics out — the dataset never
                # re-crosses the host→device link.
                self.state, stacked = self._resident_loop(n)(
                    self.state, self.resident_train, item
                )
                window = _unstack(stacked, n)
            elif n == 1:
                self.state, m = self.train_step(self.state, item)
                window = (m,)
            else:
                # One dispatch, n optimizer steps (device-side scanned loop).
                self.state, stacked = self.multi_step(self.state, item)
                window = _unstack(stacked, n)
            if spans is not None:
                t3 = time.perf_counter()
                t4 = t3
                if obs_full:
                    float(window[-1]["loss"])  # scalar fetch: honest fence
                    t4 = self.meter.mark()     # one fence, two consumers
                    _obs_counters.gauge(
                        "throughput.images_per_sec",
                        round(self.meter.images_per_sec, 1),
                    )
                    from tpu_dp.obs import update_device_memory_gauges

                    update_device_memory_gauges()
                # Basic mode OMITS h2d/device rather than recording 0.0:
                # absence means "not measured" — a fake zero would render
                # as "device took 0 ms" in rollups and the Perfetto trace
                # (same principle as the absent memory gauges).
                window_spans = {
                    "data_wait": (t1 - t0) * 1e3,
                    "dispatch": (t3 - t2) * 1e3,
                }
                if obs_full:
                    window_spans["h2d"] = (t2 - t1) * 1e3
                    window_spans["device"] = (t4 - t3) * 1e3
                new_recs = spans.record_window(
                    self._host_step + 1, n, window_spans, ts=ts_wall,
                )
                if obs_full:
                    # Per-step metrics.jsonl records (schema 2): spans plus
                    # a counter snapshot, one line per optimizer step.
                    snap = _obs_counters.snapshot()
                    for r in new_recs:
                        self._log_metrics({
                            "step": r["step"],
                            "ts": _iso_ts(r["ts"]),
                            "spans": {k: round(v, 3)
                                      for k, v in r["spans"].items()},
                            "counters": snap,
                        })
            for m in window:
                i += 1
                # On-device async adds; no host sync inside the loop.
                run_loss = (
                    m["loss"] if run_loss is None else run_loss + m["loss"]
                )
                run_steps += 1
                ep_loss = m["loss"] if ep_loss is None else ep_loss + m["loss"]
                ep_correct = (
                    m["correct"] if ep_correct is None
                    else ep_correct + m["correct"]
                )
                ep_steps += 1
                ep_count += gbs
                self.meter.step(gbs)
                if i % cfg.train.log_every == cfg.train.log_every - 1:
                    # Reference print format (`cifar_example.py:85-86`); the
                    # float() here is the only sync per log interval.
                    print0("[%d, %5d] loss: %.3f"
                           % (epoch + 1, i + 1, float(run_loss) / run_steps))
                    run_loss, run_steps = None, 0
                    if self.health is not None:
                        # Rank 0 reads every rank's heartbeat file at the
                        # log cadence (already a sync boundary): stragglers
                        # and stale/hung ranks get named while the run is
                        # still up, not in the postmortem.
                        self.health.report(self.health.check())
            # Resilience hooks, once per dispatched window (the host-side
            # step boundary): async snapshot on cadence, then fault
            # injection (tests), then the preemption flag check.
            done += n
            self._host_step += n
            if self.snap_mgr.due(self._host_step):
                # Meta (a full Config.to_dict) is built only when a snapshot
                # actually fires — not on every window of the host hot loop.
                self.snap_mgr.snapshot(
                    self.state, self._host_step, self._snapshot_meta(epoch, done)
                )
            if self.fault is not None:
                self.fault.on_step(self._host_step)
            if self.heartbeat is not None:
                # Boundary-to-boundary wall time per step since the last
                # accepted beat, AFTER the fault hook so an injected delay
                # is attributed to the step it fired at. Host-clock
                # honesty: without fences (basic mode) this is a dispatch
                # rate; sustained, backpressure makes it track the device
                # rate.
                now = time.perf_counter()
                hb_steps += n
                try:
                    accepted = self.heartbeat.beat(
                        self._host_step, (now - t_boundary) / hb_steps * 1e3
                    )
                except OSError:
                    # Best-effort telemetry on a shared filesystem where
                    # transient errors (NFS blip, quota) are routine — a
                    # failed beat must never abort training. Logged once;
                    # the monitor sees the gap as staleness.
                    if not self._hb_write_failed:
                        self._hb_write_failed = True
                        log0("heartbeat write failed (suppressing further "
                             "warnings)", exc_info=True)
                    accepted = False
                if accepted:
                    t_boundary, hb_steps = now, 0
            if self._step_profiler is not None:
                self._step_profiler.on_step(self._host_step)
            if self.preempt is not None and self.preempt.requested:
                self._preempt_exit(epoch, done)
        stats = {
            "loss": float(ep_loss) / max(1, ep_steps) if ep_steps else 0.0,
            "accuracy": float(ep_correct) / ep_count if ep_count else 0.0,
        }
        if start_step:
            # A resumed epoch's accumulators cover only its post-resume
            # tail; label the record so loss curves explain their own
            # discontinuity instead of faking full-epoch coverage.
            stats["resumed_at_step"] = start_step
        self.meter.mark()  # fence: epoch stats fetched, device drained
        return stats

    def _snapshot_meta(self, epoch: int, steps_done: int) -> dict[str, Any]:
        """Snapshot metadata: the mid-epoch resume position + provenance."""
        return {
            "kind": "snapshot",
            "epoch": epoch,
            "steps_done": steps_done,
            "config": self.cfg.to_dict(),
            "seed": self.cfg.train.seed,
        }

    def _preempt_exit(self, epoch: int, steps_done: int) -> None:
        """The preemption contract: final snapshot → barrier → exit 143.

        The snapshot is joined (not just dispatched) before the barrier, so
        by the time any rank exits, rank 0's final state is committed and
        an auto-restart (`--resume=auto`) loses zero steps.
        """
        from tpu_dp.resilience import PreemptedError

        log0("preemption: taking final snapshot at epoch %d step %d "
             "(global step %d)", epoch, steps_done, self._host_step)
        self.snap_mgr.snapshot(
            self.state, self._host_step, self._snapshot_meta(epoch, steps_done)
        )
        self.snap_mgr.wait()
        try:
            res = self.cfg.resilience
            dist.fault_tolerant_barrier(
                self.mesh, retries=res.max_retries,
                base_delay=res.retry_base_delay_s,
            )
        except Exception:
            # A half-dead slice must not block the survivors' clean exit —
            # the snapshot is already committed.
            log0("preemption barrier failed; exiting anyway", exc_info=True)
        raise PreemptedError(
            f"preempted at epoch {epoch}, step-in-epoch {steps_done} "
            f"(global step {self._host_step}); snapshot committed to "
            f"{self.snapshot_dir}"
        )

    @property
    def metrics_path(self) -> Path:
        """The metrics.jsonl sink (train.metrics_path, defaulting to the
        historical <ckpt_dir>/metrics.jsonl)."""
        return Path(
            self.cfg.train.metrics_path
            or Path(self.cfg.train.ckpt_dir) / "metrics.jsonl"
        )

    def _log_metrics(self, record: dict) -> None:
        """Append a schema-2 JSON line to the metrics sink (process 0 only).

        Structured observability the reference lacks (its only records are
        stdout prints, SURVEY.md §5 "Metrics / logging"). Every record is
        stamped with a wall-clock ``ts`` (ISO-8601 UTC), the global
        optimizer ``step``, and ``schema: 2`` — the previous schema's
        records (implicitly v1) carried none of the three, so two runs'
        logs could not even be aligned in time. Caller-provided fields win
        (per-step span records carry their own measured ts/step).
        """
        if self.ctx.process_index != 0:  # dplint: allow(DP101) host-only IO
            return
        rec = {"ts": _iso_ts(time.time()), "step": self._host_step,
               "schema": 2}
        rec.update(record)
        if self._metrics_file is None or self._metrics_file.closed:
            # Opened once and held (append + flush per record): obs=full
            # writes one record per optimizer step, and a per-record
            # open/close on a shared filesystem would land in the very
            # step times being recorded. Closed in fit()'s finally;
            # post-fit records (the eval line) transparently reopen.
            path = self.metrics_path
            path.parent.mkdir(parents=True, exist_ok=True)
            self._metrics_file = open(path, "a")
        self._metrics_file.write(json.dumps(rec) + "\n")
        self._metrics_file.flush()

    def evaluate(self) -> dict[str, float]:
        """Global test accuracy/loss with ONE device→host fetch.

        The per-batch sums stay device-resident (each `+` is an async
        dispatch, never a sync) — on a high-RTT transport a per-batch
        `int(...)`/`float(...)` would make eval dispatch-bound, the exact
        host-sync pattern the train loop avoids.
        """
        correct = count = loss_sum = None
        for batch in self.test_pipe:
            m = self.eval_step(self.state, batch)
            batch_loss_sum = m["loss"] * m["count"]  # mean → sum, on device
            if correct is None:
                correct, count = m["correct"], m["count"]
                loss_sum = batch_loss_sum
            else:
                correct = correct + m["correct"]
                count = count + m["count"]
                loss_sum = loss_sum + batch_loss_sum
        if count is None:
            return {"accuracy": 0.0, "loss": 0.0}
        correct, count, loss_sum = jax.device_get((correct, count, loss_sum))
        n = max(int(count), 1)
        return {"accuracy": float(correct) / n, "loss": float(loss_sum) / n}

    def export_trace(self) -> Path | None:
        """Write the Perfetto/Chrome trace JSON for this rank's spans.

        Rank 0 only (one artifact per run dir; per-rank traces would need
        per-rank paths — `obs.export.merge_traces` exists for offline
        fan-in). Returns the path, or None when obs is off / not rank 0.
        """
        if self.spans is None:
            return None
        if self.ctx.process_index != 0:  # dplint: allow(DP101) host-only IO
            return None
        from tpu_dp.obs import export_perfetto

        path = Path(
            self.cfg.obs.perfetto_path
            or self.obs_dir / "trace.perfetto.json"
        )
        out = export_perfetto(
            path, self.spans.records(), rank=self.ctx.process_index,
            counter_points=[
                {"ts": time.time(), "counters": _obs_counters.snapshot()}
            ],
        )
        log0("perfetto trace: %s (%d step records) — open in "
             "chrome://tracing or ui.perfetto.dev", out, len(self.spans))
        return out

    def obs_summary(self) -> dict[str, Any] | None:
        """Span rollup + counter snapshot for end-of-run summaries
        (train.py's JSON line); None when obs is off."""
        if self.spans is None:
            return None
        return {
            "mode": self.obs_mode,
            "spans_ms": self.spans.rollup(),
            "counters": _obs_counters.snapshot(),
        }

    def fit(self) -> dict[str, Any]:
        cfg = self.cfg
        log0(
            "training %s on %s: %d device(s), %d process(es), "
            "global batch %d (%d/process), %d epochs",
            cfg.model.name, self.train_ds.name, self.num_devices,
            self.ctx.process_count, self.global_batch_size,
            cfg.data.batch_size, cfg.train.epochs,
        )
        t0 = time.perf_counter()
        history = []
        try:
            if self.preempt is not None:
                self.preempt.install()
            # Step-ranged profiling replaces the whole-run trace: both at
            # once would nest jax.profiler sessions (an error) and the
            # ranged trace exists precisely to avoid the whole-run one.
            whole_run_profile = (
                None if self._step_profiler is not None
                else cfg.train.profile_dir
            )
            with profile_trace(whole_run_profile):
                for epoch in range(self.start_epoch, cfg.train.epochs):
                    start_step = (
                        self.start_step if epoch == self.start_epoch else 0
                    )
                    stats = self.train_epoch(epoch, start_step=start_step)
                    history.append(stats)
                    log0("epoch %d: train loss %.4f acc %.4f (%.1f img/s)",
                         epoch + 1, stats["loss"], stats["accuracy"],
                         self.meter.images_per_sec)
                    epoch_rec = {"epoch": epoch + 1, **stats,
                                 "images_per_sec":
                                     round(self.meter.images_per_sec, 1)}
                    if self.spans is not None:
                        # Epoch rollup: span percentiles over the ring +
                        # the counter registry — the at-a-glance record
                        # (per-step records are obs=full only).
                        _obs_counters.gauge(
                            "throughput.images_per_sec",
                            round(self.meter.images_per_sec, 1),
                        )
                        from tpu_dp.obs import update_device_memory_gauges

                        update_device_memory_gauges()
                        epoch_rec["spans"] = self.spans.rollup()
                        epoch_rec["counters"] = _obs_counters.snapshot()
                    self._log_metrics(epoch_rec)
                    self.ckpt_mgr.save(
                        self.state,
                        {"epoch": epoch, "config": cfg.to_dict(),
                         "seed": cfg.train.seed},
                    )
                    every = cfg.train.eval_every_epochs
                    if every and (epoch + 1) % every == 0:
                        ev = self.evaluate()
                        log0("epoch %d: eval loss %.4f acc %.4f",
                             epoch + 1, ev["loss"], ev["accuracy"])
                    if self.health is not None:
                        # End-of-epoch health pass: a rank that went quiet
                        # mid-epoch is flagged here even when log_every
                        # never fired.
                        self.health.report(self.health.check())
                    # A signal that lands between epochs (or during eval)
                    # still gets the snapshot-and-exit-143 contract.
                    if self.preempt is not None and self.preempt.requested:
                        self._preempt_exit(epoch + 1, 0)
        finally:
            # Join any in-flight async write even when training aborts —
            # the freshest checkpoint is exactly what a crash-restart needs.
            # If an exception is already propagating, a checkpoint failure
            # must not replace it: log and let the original surface. On a
            # clean run, a failed final write must raise (a checkpoint that
            # silently failed to persist is worse than a crash).
            import sys

            propagating = sys.exc_info()[0] is not None
            try:
                self.ckpt_mgr.close()
            except RuntimeError:
                if not propagating:
                    raise
                log0("checkpoint write failed during abort (original "
                     "exception propagates)", exc_info=True)
            try:
                self.snap_mgr.close()
            except RuntimeError:
                if not propagating:
                    raise
                log0("snapshot write failed during abort (original "
                     "exception propagates)", exc_info=True)
            if self.preempt is not None:
                self.preempt.uninstall()
            # Telemetry teardown runs on EVERY exit path: a crashed or
            # preempted run is exactly when the trace matters. Each step
            # is guarded separately — a failed profiler flush (disk full,
            # deleted trace dir) must neither mask the original exception
            # nor rob the Perfetto export behind it.
            if self._step_profiler is not None:
                try:
                    self._step_profiler.close()
                except Exception:
                    log0("step-profiler close failed", exc_info=True)
            if self.heartbeat is not None:
                try:
                    self.heartbeat.close()
                except Exception:
                    log0("heartbeat close failed", exc_info=True)
            if self.spans is not None and len(self.spans):
                try:
                    self.export_trace()
                except Exception:
                    log0("perfetto export failed", exc_info=True)
            if self._metrics_file is not None:
                try:
                    self._metrics_file.close()
                except OSError:
                    log0("metrics sink close failed", exc_info=True)
        print0("Finished Training")  # `cifar_example.py:90` parity
        wall = time.perf_counter() - t0

        # End-of-training weights export (`cifar_example.py:92-93` analogue).
        ckpt_lib.save_params(f"{cfg.train.ckpt_dir}/final_params.msgpack",
                             self.state.params)

        result: dict[str, Any] = {
            "history": history,
            "wall_time_s": wall,
            "images_per_sec": self.meter.images_per_sec,
        }
        if cfg.train.eval_at_end:
            eval_stats = self.evaluate()
            result["eval"] = eval_stats
            self._log_metrics({"eval": eval_stats})
            # Reference integer-percent print (`cifar_example.py:111-112`).
            print0("Accuracy of the network on the %d test images: %d %%"
                   % (len(self.test_ds), int(100 * eval_stats["accuracy"])))
        return result
