"""Trainer: epochs, logging, eval, checkpoint — the reference's `main()`.

One code path from one chip to a full slice (mesh shape is the only
variable), replacing the reference's forked `cifar_example.py` /
`cifar_example_ddp.py` pair. Reproduces the observable behavior of
`/root/reference/cifar_example_ddp.py:90-136`: per-epoch `set_epoch`
reshuffle (`:92`), running-loss print every `log_every` steps in the
reference's exact format (`:111-114`, but process-0-gated and with a correct
remainder divisor), end-of-training weights export (`:118-119`), and a
synced-accuracy eval (`:124-136`) — plus what the reference lacks: resume,
throughput metering, and profiler hooks (SURVEY.md §5).

Hot-loop discipline: the Python loop only *dispatches* compiled steps and
accumulates the returned replicated scalars with on-device adds — it never
blocks on a device→host transfer except at log boundaries and epoch ends, so
host dispatch runs ahead of device execution and the input pipeline's
prefetch overlaps (unlike the reference, whose `loss.item()` syncs every
step, `cifar_example.py:83`).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

import jax
import numpy as np

from tpu_dp import checkpoint as ckpt_lib
from tpu_dp.config import Config
from tpu_dp.data.cifar import load_dataset
from tpu_dp.data.pipeline import DataPipeline
from tpu_dp.models import build_model
from tpu_dp.parallel import dist
from tpu_dp.train.optim import SGD
from tpu_dp.train.schedule import make_schedule
from tpu_dp.obs.counters import counters as _obs_counters
from tpu_dp.train.state import create_train_state
from tpu_dp.train.step import make_eval_step, make_train_step
from tpu_dp.utils import (
    StepProfiler,
    ThroughputMeter,
    log0,
    parse_profile_steps,
    print0,
    profile_trace,
)


def _iso_ts(epoch_seconds: float) -> str:
    """ISO-8601 UTC stamp for metrics records (millisecond resolution)."""
    from datetime import datetime, timezone

    return datetime.fromtimestamp(
        epoch_seconds, timezone.utc
    ).isoformat(timespec="milliseconds")


class _RegroupSignal(Exception):
    """Raised out of `train_epoch` by a survivor when a quiesce completed:
    the mesh must shrink before the next step (`Trainer._execute_regroup`).
    Internal control flow — never escapes `fit()`."""

    def __init__(self, epoch: int, done: int, plan):
        super().__init__(f"elastic regroup at epoch {epoch} step {done}")
        self.epoch = int(epoch)
        self.done = int(done)
        self.plan = plan


class _GuardRollback(Exception):
    """Raised out of `train_epoch` by the guard hook when the divergence
    policy escalates to rollback: rewind to the newest complete (and
    non-quarantined) save before the next step
    (`Trainer._execute_guard_rollback`). Internal control flow — never
    escapes `fit()`."""

    def __init__(self, epoch: int, done: int, trigger):
        super().__init__(
            f"guard rollback at epoch {epoch} step {done}: {trigger.reason}"
        )
        self.epoch = int(epoch)
        self.done = int(done)
        self.trigger = trigger


def _elastic_fatal_errors() -> tuple[type[BaseException], ...]:
    """Exception types that mean "a peer is gone" in elastic mode:
    a wedged/failed collective (XLA runtime) or an exhausted resilient
    ring (`PeerFailedError`) — the rollback-regroup triggers."""
    from tpu_dp.resilience import PeerFailedError

    errs: list[type[BaseException]] = [PeerFailedError]
    try:
        from jax._src.lib import xla_extension

        errs.append(xla_extension.XlaRuntimeError)
    except Exception:  # jaxlib layout drift: JaxRuntimeError still covers it
        pass
    try:
        errs.append(jax.errors.JaxRuntimeError)
    except AttributeError:
        pass
    return tuple(errs)


class Trainer:
    def __init__(self, cfg: Config, mesh=None):
        self.cfg = cfg
        # Elastic grow (docs/RESILIENCE.md "Grow"): before any classic
        # bootstrap, a starting process may instead JOIN a live run it
        # discovers through the membership ledger — the relaunched-after-
        # preemption path (`resilience.elastic_join`). The handshake
        # (fenced join request → admission → re-initialize into the grown
        # mesh) runs first because it replaces the bootstrap entirely:
        # the joiner's world and dense rank exist only once the members
        # admit it.
        self._join = None
        if cfg.resilience.elastic and mesh is None:
            from tpu_dp.resilience.elastic import maybe_join

            # Knowable-locally config errors must fail BEFORE the join
            # handshake: past confirm_join_ready, a dying joiner bills
            # the incumbents a whole quiesce + bootstrap timeout +
            # fallback regroup. (Deeper, dataset-dependent validation
            # still runs post-join; a joiner failing THERE costs the
            # fleet one bounded aborted grow — documented trade.)
            if not cfg.data.drop_remainder:
                raise ValueError(
                    "resilience.elastic requires data.drop_remainder=true "
                    "(the mid-epoch re-split carries no weight masks)"
                )
            self._join = maybe_join(cfg)
        if self._join is not None:
            self.ctx = self._join.ctx
        else:
            self.ctx = dist.initialize(
                cfg.parallel.coordinator_address,
                cfg.parallel.num_processes,
                cfg.parallel.process_id,
                elastic=cfg.resilience.elastic,
            )
        if mesh is not None and cfg.resilience.elastic:
            raise ValueError(
                "resilience.elastic cannot rebuild a caller-injected mesh "
                "after a regroup; pass parallel.num_devices instead"
            )
        self.mesh = mesh if mesh is not None else dist.data_mesh(
            num_devices=cfg.parallel.num_devices
        )
        self.num_devices = int(self.mesh.devices.size)
        # A parallel.num_devices restriction is remembered per process so
        # a regroup can rebuild the same per-process device footprint at
        # the new world (the restriction names a GLOBAL count for the
        # launch world; the global count shrinks with it).
        self._devices_per_process = (
            self.num_devices // max(1, self.ctx.process_count)
            if cfg.parallel.num_devices is not None else None
        )
        log0("topology: %s", json.dumps(dist.describe(self.mesh)))

        self._load_data(cfg)

        # The dataset determines the number of classes; an explicit config
        # value must agree (a silently mis-sized head clamps labels inside
        # the compiled loss and trains garbage with no error).
        num_classes = self.train_ds.num_classes
        if cfg.model.num_classes is not None and (
            cfg.model.num_classes != num_classes
        ):
            raise ValueError(
                f"model.num_classes={cfg.model.num_classes} conflicts with "
                f"dataset {self.train_ds.name!r} ({num_classes} classes)"
            )

        import jax.numpy as jnp  # local: keep module import light

        dtype = jnp.bfloat16 if cfg.model.bf16 else jnp.float32
        from tpu_dp.models import parse_fused_stages

        # Cross-replica sharded weight update (docs/PERF.md). Validated
        # before model construction because the sharded path runs the
        # explicit-collectives `shard_map` program, where BatchNorm models
        # must sync their batch statistics in-forward (axis_name=DATA_AXIS
        # — sync-BN semantics, matching the global-batch stats the GSPMD
        # path computes automatically).
        us = cfg.train.update_sharding
        if us not in ("replicated", "sharded"):
            raise ValueError(
                f"train.update_sharding must be replicated|sharded, "
                f"got {us!r}"
            )
        if cfg.train.collective_dtype and us != "sharded":
            raise ValueError(
                "train.collective_dtype applies to the sharded update's "
                "reduce-scatter; set train.update_sharding=sharded"
            )
        self.update_sharding = us
        # Quantized collectives (train.collective_dtype=int8, docs/PERF.md
        # "Quantized collectives"): the step factories route quantizable
        # gradient leaves through the blockwise int8 wire codec, and the
        # TrainState carries per-replica error-feedback residuals
        # (initialized by `_with_residuals`, resharded by load_checkpoint).
        self._quant_enabled = cfg.train.collective_dtype in ("int8", "i8")
        if int(cfg.train.quant_block_size) < 1:
            raise ValueError(
                f"train.quant_block_size must be >= 1, got "
                f"{cfg.train.quant_block_size}"
            )
        # Bucketed overlap-scheduled collectives (train.bucket_mb,
        # docs/PERF.md "Overlapped collectives"): parsed once here so a
        # bad value fails at config time, threaded into every step
        # factory, the residual init, and the commprof wire report.
        from tpu_dp.parallel import bucketing

        self._bucket_bytes = bucketing.parse_bucket_mb(cfg.train.bucket_mb)
        if self._bucket_bytes and us != "sharded":
            raise ValueError(
                "train.bucket_mb applies to the sharded update's "
                "reduce-scatter; set train.update_sharding=sharded"
            )
        self._quant_pub_step = -1  # last window whose codec stats published
        # Coupled-knob guard (docs/TUNE.md "Coupled knobs"): the SAME rule
        # the tune search space and dplint DP105 apply — a hand-set config
        # gets the identical warning a tuner-proposed one would.
        from tpu_dp.config import coupling_warning

        coupled = coupling_warning(cfg.train.bucket_mb,
                                   cfg.train.quant_block_size,
                                   cfg.train.collective_dtype)
        if coupled:
            log0("config warning: %s", coupled)
        # A tuned profile (train.profile, set by --profile) is only valid
        # for the (workload, mesh geometry, backend) it was searched on —
        # re-check against the LIVE topology: parse_cli validated the file
        # but could not see the mesh. Typed refusal, never silent drift.
        if cfg.train.profile:
            import jax

            from tpu_dp.tune.profile import check_key, load_profile

            check_key(load_profile(cfg.train.profile),
                      workload=cfg.model.name,
                      devices=self.num_devices,
                      backend=jax.default_backend(),
                      where="this Trainer")
            log0("profile: %s (key ok: %s x%d on %s)",
                 cfg.train.profile, cfg.model.name, self.num_devices,
                 jax.default_backend())

        model_kwargs = dict(
            num_classes=num_classes, dtype=dtype,
            fused_stages=parse_fused_stages(cfg.model.fused_stages),
            fused_block_b=cfg.model.fused_block_b,
            fused_bwd=cfg.model.fused_bwd,
        )
        from tpu_dp.models import BATCHNORM_MODELS

        if us == "sharded" and cfg.model.name.lower() in BATCHNORM_MODELS:
            model_kwargs["axis_name"] = dist.DATA_AXIS
        self.model = build_model(cfg.model.name, **model_kwargs)
        # Sync-BN models need the data axis bound even at init; the
        # axis-free twin has the identical parameter tree and initializes
        # anywhere (same trick as tpu_dp.analysis.gradsync).
        self._init_model = self.model
        if "axis_name" in model_kwargs:
            self._init_model = build_model(
                cfg.model.name,
                **{k: v for k, v in model_kwargs.items()
                   if k != "axis_name"})

        augment_fn = None
        if cfg.data.augment:
            from tpu_dp.data.augment import make_augment_fn

            augment_fn = make_augment_fn(cfg.train.seed + 1)
        self._augment_fn = augment_fn
        # RecompileGuard (dplint DP305's runtime half): any post-warmup
        # growth of a step's trace cache is a silent recompile — a
        # step-time cliff this surfaces instead of swallowing. The eval
        # step is deliberately unguarded: its final partial batch
        # legitimately compiles a second variant.
        guard_mode = cfg.train.recompile_guard
        if guard_mode not in ("off", "warn", "raise"):
            raise ValueError(
                f"train.recompile_guard must be off|warn|raise, "
                f"got {guard_mode!r}"
            )
        self._guard = None if guard_mode == "off" else guard_mode
        if int(cfg.train.steps_per_call) < 0:
            raise ValueError(
                f"train.steps_per_call must be >= 0 (0 = auto), "
                f"got {int(cfg.train.steps_per_call)}"
            )
        mode = cfg.data.device_resident
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"data.device_resident must be auto|on|off, got {mode!r}"
            )
        if mode == "on" and not cfg.data.drop_remainder:
            raise ValueError(
                "data.device_resident=on requires data.drop_remainder=true"
            )
        if int(cfg.train.steps_per_call) > 1 and not cfg.data.drop_remainder:
            raise ValueError(
                "train.steps_per_call > 1 requires data.drop_remainder=true"
            )
        if cfg.resilience.elastic and not cfg.data.drop_remainder:
            raise ValueError(
                "resilience.elastic requires data.drop_remainder=true "
                "(the mid-epoch re-split carries no weight masks)"
            )
        # Training guardrails (tpu_dp/resilience/guard.py,
        # docs/RESILIENCE.md "Guardrails"): guard.enabled compiles the
        # sentinel into every train-step program (on-device health summary
        # + guarded update) and registers the GuardHook policy engine.
        self.guard_enabled = bool(cfg.guard.enabled)

        # Everything world-dependent — pipelines, optimizer layout,
        # compiled programs, resident staging — is built by the two
        # builders below so an elastic regroup (`_execute_regroup`) can
        # rebuild it against the shrunk mesh; `__init__` holds only the
        # run-once validation and construction.
        self._build_pipelines()
        if mode == "on":
            ds_bytes = self.train_pipe.dataset_bytes()
            if ds_bytes > cfg.data.resident_max_bytes:
                # Forced on is explicit user intent — warn with the numbers
                # (instead of the opaque allocator error staging would hit
                # on a dataset that genuinely exceeds HBM) and proceed.
                log0(
                    "warning: data.device_resident=on stages %d bytes, over "
                    "data.resident_max_bytes=%d — staging may exhaust "
                    "device memory; raise the budget or use auto",
                    ds_bytes, cfg.data.resident_max_bytes,
                )
        self._build_training()

        self.state = self._fresh_state()
        self.start_epoch = 0
        self.start_step = 0  # step within start_epoch (mid-epoch resume)
        self.meter = ThroughputMeter(warmup_steps=2)

        self.ckpt_mgr = ckpt_lib.CheckpointManager(
            cfg.train.ckpt_dir, keep=cfg.train.ckpt_keep,
            async_save=cfg.train.ckpt_async,
        )

        # Resilience (tpu_dp/resilience/, docs/RESILIENCE.md): async
        # step-cadence snapshots, SIGTERM/SIGINT preemption hook, and
        # deterministic fault injection for the test suite. The snapshot
        # manager always exists — with snapshot_every_steps=0 the cadence
        # never fires, but the preemption hook's final snapshot still has
        # somewhere to land.
        from tpu_dp.resilience import (
            FaultInjector,
            PreemptionHandler,
            SnapshotManager,
        )

        res = cfg.resilience
        # The unified shared-filesystem IO retry budget: the membership
        # ledger AND checkpoint/snapshot writes derive their backoff
        # schedule from this one knob (tpu_dp/resilience/retry.py).
        from tpu_dp.resilience.retry import configure_io_retry

        configure_io_retry(res.io_retry_s)
        self.snapshot_dir = res.snapshot_dir or str(
            Path(cfg.train.ckpt_dir) / "snapshots"
        )
        self.snap_mgr = SnapshotManager(
            self.snapshot_dir, every_steps=res.snapshot_every_steps,
            keep=res.snapshot_keep, async_save=cfg.train.ckpt_async,
        )
        self.preempt = PreemptionHandler() if res.handle_signals else None
        self.fault = FaultInjector.from_spec(
            res.fault, rank=self.ctx.process_index
        )
        if self.fault is not None and not self.guard_enabled:
            seam = [k for k in self.fault.kinds() if k in ("nan", "spike")]
            if seam:
                # The nan/spike injection seam is compiled into the
                # sentinel step; without the sentinel the fault would
                # silently never fire — the worst property a
                # deterministic injector can have.
                raise ValueError(
                    f"TPU_DP_FAULT {seam[0]!r} requires guard.enabled=true "
                    f"(the injection seam lives in the sentinel-enabled "
                    f"step program)"
                )
        # Elastic world size (tpu_dp/resilience/elastic.py): this rank's
        # stable id is its process index at generation start; dense ranks
        # are reassigned per membership epoch, sids never. A JOINER's
        # stable id is the seat its admission granted — its dense rank at
        # the grown epoch is whatever sorted-sid order assigns.
        self.stable_rank = (
            self._join.coordinator.sid if self._join is not None
            else self.ctx.process_index
        )
        self.elastic = None
        self._epoch_lineage: list[list[int]] = []  # [world, steps] segments
        self._elastic_tail: Any = None
        self._quiesce_plan = None
        self._q_flavor = "graceful"
        if cfg.train.resume and self._join is None:
            self._maybe_resume()
        elif cfg.train.resume:
            log0("elastic join: ignoring --resume — a joiner's state comes "
                 "from the admitted membership record's snapshot, never "
                 "its stale local disk")
        # Host-side mirror of state.step: the snapshot cadence and fault
        # steps key off it without a per-window device sync.
        self._host_step = int(self.state.step)
        if res.elastic and self._join is not None:
            # The admission handshake already attached this process to the
            # live generation; adopt the record's resume truth (state,
            # step clock, re-split lineage) instead of minting anything.
            self.elastic = self._join.coordinator
            self._adopt_join_resume(self._join.record)
        elif res.elastic:
            import uuid

            from tpu_dp.resilience import ElasticCoordinator

            # The generation key combines state every rank already agrees
            # on (resumed global step + launch world) with a launch-unique
            # token minted over the coordination KV store — a restarted
            # incarnation gets a fresh ledger directory even when it
            # resumes from the very same step.
            nonce = dist.agree_token(
                "elastic_gen", lambda: uuid.uuid4().hex[:8],
                timeout_s=res.regroup_timeout_s,
            )
            self.elastic = ElasticCoordinator(
                res.membership_dir or str(
                    Path(cfg.train.ckpt_dir) / "membership"
                ),
                generation=(
                    f"gen_{self._host_step:010d}_w{self.ctx.process_count}"
                    f"_{nonce}"
                ),
                sid=self.stable_rank,
                world=self.ctx.process_count,
                coordinator_address=self.ctx.coordinator_address,
                regroup_timeout_s=res.regroup_timeout_s,
                poll_every_steps=res.elastic_poll_every_steps,
                coordinator_host=res.elastic_coordinator_host,
                min_world=res.elastic_min_world,
                max_world=res.elastic_max_world,
            )
        self._metrics_file = None  # lazily opened by _log_metrics (rank 0)
        self._hb_write_failed = False  # one-shot heartbeat-failure warning

        # Telemetry (tpu_dp/obs/, docs/OBSERVABILITY.md). Everything below
        # is None at obs=off — the hot loop then takes the untelemetered
        # path (one is-None check per window; benched within noise).
        if cfg.train.obs not in ("off", "basic", "full"):
            raise ValueError(
                f"train.obs must be off|basic|full, got {cfg.train.obs!r}"
            )
        self.obs_mode = cfg.train.obs
        self.obs_dir = Path(
            cfg.obs.run_dir or Path(cfg.train.ckpt_dir) / "obs"
        )
        self.spans = None
        self.heartbeat = None
        self.health = None
        if self.obs_mode != "off":
            from tpu_dp.obs import HealthMonitor, HeartbeatWriter, SpanRecorder

            self.spans = SpanRecorder(capacity=cfg.obs.span_capacity)
            if cfg.obs.heartbeat_every_steps > 0 and self._join is None:
                # Every rank appends to its own heartbeat file — per-rank
                # host IO is the protocol, not a rank gate. A JOINER never
                # writes into the launch obs root: its dense rank's
                # filename there belongs to a me-epoch-0 seat it never
                # held (`_complete_join` homes it into obs/me<E>/).
                self.heartbeat = HeartbeatWriter(
                    self.obs_dir, rank=self.ctx.process_index,
                    every_steps=cfg.obs.heartbeat_every_steps,
                )
            if self.heartbeat is not None and self.ctx.process_index == 0:  # dplint: allow(DP101) host-only monitor
                self.health = HealthMonitor(
                    self.obs_dir, world=self.ctx.process_count,
                    straggler_factor=cfg.obs.straggler_factor,
                    stale_after_s=cfg.obs.stale_after_s,
                    min_step_ms=cfg.obs.min_step_ms,
                    on_flag=cfg.obs.on_straggler,
                )
        # Live efficiency accounting (tpu_dp/obs/costs.py): rolling MFU /
        # goodput / step-time gauges per dispatched window, computed from
        # the per-program cost registry (`_register_program_costs`). The
        # peak-FLOPs denominator comes from the device kind (override:
        # obs.peak_flops_override); unknown kinds publish no MFU rather
        # than a wrong one.
        self._eff = None
        self._last_efficiency: dict | None = None
        if self.obs_mode != "off":
            from tpu_dp.obs.costs import EfficiencyMeter
            from tpu_dp.obs.costs import peak_flops as _peak_flops

            peak = cfg.obs.peak_flops_override or None
            if peak is None:
                try:
                    peak = _peak_flops(jax.devices()[0].device_kind)
                except Exception:
                    peak = None
            self._eff = EfficiencyMeter(peak=peak,
                                        capacity=cfg.obs.span_capacity)
        # Flight recorder (tpu_dp/obs/flightrec.py): the always-on black
        # box, independent of train.obs — crash forensics must not require
        # live telemetry. The dump filename uses the STABLE launch rank so
        # an elastic regroup's dense-rank reassignment can never make two
        # processes overwrite each other's dump; the dump dir stays the
        # launch obs root for the same reason (obsctl globs it).
        self.flightrec = None
        from tpu_dp.obs import flightrec as _flightrec

        if cfg.obs.flightrec_capacity <= 0:
            # "Disabled" must mean disabled: the subsystems' module-level
            # record() calls become no-ops, not silent in-memory growth.
            _flightrec.recorder.disable()
        else:
            self.flightrec = _flightrec.recorder.configure(
                rank=self.stable_rank, dump_dir=self.obs_dir,
                capacity=cfg.obs.flightrec_capacity,
                fresh=True,  # a new Trainer is a new run's black box
                # A rejoined incarnation's dump must coexist with its
                # predecessor's departure dump (same stable rank): the
                # membership epoch it was admitted at tags the filename.
                tag=(f"me{self._join.record.epoch:04d}"
                     if self._join is not None else ""),
                run={
                    "model": cfg.model.name,
                    "world": self.ctx.process_count,
                    "devices": self.num_devices,
                    "global_batch": self.global_batch_size,
                    "elastic": bool(cfg.resilience.elastic),
                    "guard": self.guard_enabled,
                    "joined": self._join is not None,
                },
            )
        self._prom_failed = False  # one-shot prom-write failure warning
        # Step-ranged profiling (train.profile_steps=START:END): trace only
        # the window under investigation instead of the whole run.
        profile_range = parse_profile_steps(cfg.train.profile_steps)
        self._step_profiler = None
        if profile_range is not None:
            self._step_profiler = StepProfiler(
                cfg.train.profile_dir, *profile_range
            )
        # In-run comm/compute attribution (tpu_dp/obs/commprof.py,
        # docs/OBSERVABILITY.md "Comm/compute attribution"): capture
        # windows over obs.comm_profile_steps, auto-parsed into the
        # obs.comm_ms / obs.exposed_comm_ms / obs.overlap_frac gauges, a
        # comm_profile metrics event, and <obs dir>/comm_report.json —
        # with the trace-vs-static reconciliation against the DP304
        # fingerprint schedule.
        self._comm_profiler = None
        self._build_comm_profiler()

        # Guardrail run state: the rollback generation stamps every
        # metrics/quarantine record written after a rewind (post-hoc
        # tooling must never double-count replayed steps), and the evict
        # flag is the SDC audit's "this rank is corrupt — leave" handoff
        # to the elastic boundary.
        self._rollback_gen = 0
        self._guard_evict = False
        self._sdc_suspect_active = False  # suppresses snapshots (hooks.py)

        # Per-program FLOP costs for the live MFU gauges (and bench's
        # single source of truth) — registered after state creation so the
        # optional measured path can AOT-compile the real step.
        self._register_program_costs()

        # The step-lifecycle hook registry (tpu_dp/train/hooks.py): every
        # cross-cutting subsystem — guardrails, snapshots, fault injection,
        # heartbeats, profiling, the elastic/preemption boundary —
        # registers here instead of splicing into the hot loop.
        self._build_hooks()

        if self._join is not None:
            # The joiner's half of the regroup epilogue — observers homed
            # into the me-epoch, then the SAME verify + barrier sequence
            # the incumbents run at the tail of `_execute_regroup`, so the
            # grown mesh's first collectives are exactly matched.
            self._complete_join(self._join.record)
        elif cfg.train.verify_fingerprint:
            self._verify_step_fingerprint()

    def _adopt_join_resume(self, record) -> None:
        """Install the admitted membership record's resume truth.

        The joiner's state comes from the grow quiesce's final snapshot
        (the record's ``resume.snapshot_dir``) through the resharding
        `load_checkpoint` path — NEVER from this process's own disk,
        which belongs to a retired incarnation and may be arbitrarily
        stale. Step clock, consumption lineage, and the re-split tail all
        follow the record, exactly like a surviving incumbent's.
        """
        resume = dict(record.resume or {})
        snap = resume.get("snapshot_dir")
        if snap:
            try:
                self.state, _ = ckpt_lib.load_checkpoint(Path(snap),
                                                         self.state)
            except ckpt_lib.CorruptCheckpointError as e:
                # The agreed snapshot IS the joiner's only legal state
                # source (its own disk is a retired incarnation's) — a
                # corrupt one is a typed admission abort, never a silent
                # restore of different bytes than the incumbents hold.
                # The incumbents' bounded bootstrap timeout then re-forms
                # the world without us (`establish_fallback`).
                from tpu_dp.resilience import ElasticError

                raise ElasticError(
                    f"elastic join: admitted snapshot {snap} failed "
                    f"checksum verification — aborting the join ({e})"
                ) from e
            self.state = self._place_state(self.state)
        else:
            # Nothing on disk at the agreed resume point: the run itself
            # restarted from scratch at this epoch; the joiner does too.
            log0("elastic join: admitted record carries no snapshot — "
                 "starting from init like the incumbents")
        self._host_step = int(resume.get("global_step", 0))
        self._quant_pub_step = self._host_step
        epoch = int(resume.get("epoch", 0))
        lineage = resume.get("lineage") or []
        if lineage:
            has_tail = self._set_elastic_tail(epoch, lineage)
            self.start_epoch, self.start_step = (
                (epoch, 0) if has_tail else (epoch + 1, 0)
            )
        else:
            self.start_epoch = epoch
            self.start_step = int(resume.get("steps_done", 0))
        log0("elastic join: adopted resume — epoch %d step %d (global "
             "step %d, membership epoch %d, world %d)",
             self.start_epoch, self.start_step, self._host_step,
             record.epoch, record.world)

    def _complete_join(self, record) -> None:
        """Mirror of `_execute_regroup`'s epilogue on the joiner side."""
        from tpu_dp.obs import flightrec

        # The joiner's own act, in ITS ring — "elastic_join", the grow
        # twin of the leaver's "elastic_departure"; the membership record
        # tells the corresponding "rank_joined" (like "eviction"), so the
        # timeline never double-tells one admission under one kind.
        flightrec.record("elastic_join", step=self._host_step,
                         sid=self.stable_rank,
                         membership_epoch=record.epoch, world=record.world,
                         rank=self.ctx.process_index)
        self._rebuild_observers(record)
        if self._guard_hook is not None:
            # Fresh audit baseline at the adopted step: nothing older
            # than the admission can be this incarnation's clean point.
            self._guard_hook.on_regroup()
        if self.cfg.resilience.elastic_verify_fingerprint:
            self._verify_step_fingerprint(
                tag=f"train_step@me{record.epoch}w{record.world}"
            )
        dist.membership_barrier(
            "regroup_ready", record.epoch,
            timeout_s=self.cfg.resilience.regroup_timeout_s,
        )
        log0("elastic join: membership epoch %d live — joined at world "
             "%d as dense rank %d (stable id %d)",
             record.epoch, record.world, self.ctx.process_index,
             self.stable_rank)

    def _with_residuals(self, state):
        """Attach zero-initialized error-feedback residuals when the int8
        wire codec is on (`train.collective_dtype=int8`); identity — and
        an unchanged pytree — everywhere else."""
        if not self._quant_enabled:
            return state
        from tpu_dp.parallel import quant

        return state.replace(residuals=quant.init_residuals(
            state.params, dist.data_axis_size(self.mesh),
            self.cfg.train.quant_block_size,
            bucket_bytes=self._bucket_bytes,
        ))

    def _fresh_state(self) -> Any:
        """A from-scratch TrainState for the CURRENT topology/optimizer
        layout (+ codec residuals) — init, guard-rollback-to-nothing, and
        regroup reload targets all build states through here so none can
        forget a layout-bearing field."""
        rng = jax.random.PRNGKey(self.cfg.train.seed)
        sample = np.zeros((1, 32, 32, 3), np.float32)
        return self._with_residuals(create_train_state(
            self._init_model, rng, sample, self.optimizer
        ))

    def _publish_quant_counters(self, window, first_step: int) -> None:
        """Publish the int8 codec's health counts for one window.

        ``quant.overflow`` (non-finite blocks entering the codec) and
        ``quant.clip_blocks`` (rail-crowded blocks) accumulate into the
        counter registry, so schema-3 metrics records and `obsctl diff`
        carry them (docs/OBSERVABILITY.md). The values are already in the
        window's metrics — the fetch rides an EXISTING fence (the guard
        hook's health fetch, or obs=full's per-window scalar fetch); this
        method never adds a host sync of its own, which is why obs=basic
        guard-off runs publish nothing. The ``first_step`` marker dedupes
        the two call sites when both fences are live.
        """
        if not self._quant_enabled or first_step <= self._quant_pub_step:
            return
        self._quant_pub_step = first_step
        overflow = clip = 0
        for m in window:
            if "quant_overflow" not in m:
                return
            overflow += int(np.asarray(m["quant_overflow"]))
            clip += int(np.asarray(m["quant_clip"]))
        # inc(0) still creates the counter: a clean run stamps an explicit
        # quant.overflow=0 into its records — "0 overflows observed" is a
        # statement, absence is not.
        _obs_counters.inc("quant.overflow", overflow)
        _obs_counters.inc("quant.clip_blocks", clip)

    def _guarded(self, name: str, step_fn):
        """Wrap a compiled step in a RecompileGuard (train.recompile_guard).

        warmup_calls=2: the first call consumes the host-staged
        (uncommitted) init state, every later call the donated
        device-resident output — that placement transition legitimately
        traces a second cache entry, so only growth past call 2 is a real
        retrace. Without drop_remainder the epoch's final partial batch
        (padded, with a weight leaf) legitimately compiles another variant
        every epoch, so guarding would cry wolf — steps run unguarded
        there, like the eval step. No logger override: retrace divergence
        is inherently per-rank, so the guard's own stderr report must fire
        on whichever rank retraced, not only on process 0.
        """
        if self._guard is None or not self.cfg.data.drop_remainder:
            return step_fn
        from tpu_dp.analysis.recompile import RecompileGuard

        return RecompileGuard(
            step_fn, name=name, on_retrace=self._guard, warmup_calls=2,
        )

    def _build_pipelines(self) -> None:
        """(Re)build the input pipelines for the current mesh/topology.

        Called at construction and again by `_execute_regroup` after the
        mesh shrank — `DataPipeline` bakes the process count into its
        sampler and the mesh into its placement specs.
        """
        cfg = self.cfg
        self.train_pipe = DataPipeline(
            self.train_ds, cfg.data.batch_size, self.mesh,
            shuffle=cfg.data.shuffle, seed=cfg.train.seed,
            drop_remainder=cfg.data.drop_remainder, prefetch=cfg.data.prefetch,
            accum_steps=cfg.optim.grad_accum_steps,
            sync_placement=cfg.data.sync_placement,
        )
        self.test_pipe = DataPipeline(
            self.test_ds, cfg.data.batch_size, self.mesh,
            shuffle=False, seed=cfg.train.seed,
            drop_remainder=False, prefetch=cfg.data.prefetch,
            sync_placement=cfg.data.sync_placement,
        )

    def _build_training(self) -> None:
        """(Re)build optimizer layout + compiled programs for the mesh.

        World-sensitive throughout: the sharded optimizer pads its flat
        shards to the data-axis size, the step factories bake the mesh
        into their shardings, the auto window size keys off steps/epoch,
        and the resident-feed budget decision is per-topology. After a
        regroup everything here is stale and rebuilt; `load_checkpoint`
        reshards the persisted optimizer state onto the new layout.
        """
        cfg = self.cfg
        us = self.update_sharding
        augment_fn = self._augment_fn
        steps_per_epoch = len(self.train_pipe)
        total_steps = steps_per_epoch * cfg.train.epochs
        self.optimizer = SGD(
            cfg.optim.momentum,
            cfg.optim.weight_decay,
            decay_exclude_bias_and_norm=cfg.optim.decay_exclude_bias_and_norm,
        )
        # Sharded mode wraps the optimizer so its state initializes — and
        # persists — sharded over the data axis; the train step then routes
        # through the explicit-collectives factory that reduce-scatters
        # grads and all-gathers updated params. The replicated default
        # keeps the GSPMD path.
        if us == "sharded":
            from tpu_dp.train.optim import shard_optimizer

            self.optimizer = shard_optimizer(
                self.optimizer, dist.data_axis_size(self.mesh)
            )
        self.schedule = make_schedule(
            cfg.optim.schedule, cfg.optim.lr, total_steps,
            int(cfg.optim.warmup_epochs * steps_per_epoch), cfg.optim.final_lr,
        )
        if us == "sharded":
            from tpu_dp.train.step import make_train_step_shard_map

            self.train_step = self._guarded(
                "train_step", make_train_step_shard_map(
                    self.model, self.optimizer, self.mesh, self.schedule,
                    use_pallas_xent=cfg.train.pallas_xent,
                    accum_steps=cfg.optim.grad_accum_steps,
                    augment_fn=augment_fn,
                    update_sharding=us,
                    collective_dtype=cfg.train.collective_dtype or None,
                    quant_block_size=cfg.train.quant_block_size,
                    bucket_mb=cfg.train.bucket_mb,
                    sentinel=self.guard_enabled,
                ))
        else:
            self.train_step = self._guarded("train_step", make_train_step(
                self.model, self.optimizer, self.mesh, self.schedule,
                use_pallas_xent=cfg.train.pallas_xent,
                accum_steps=cfg.optim.grad_accum_steps,
                augment_fn=augment_fn,
                sentinel=self.guard_enabled,
            ))
        self.eval_step = make_eval_step(self.model, self.mesh,
                                        update_sharding=us)
        spc = int(cfg.train.steps_per_call)
        if spc == 0:
            # Auto: windowed dispatch whenever the pipeline shape allows.
            # 24 steps/window matches the longrun recipe — big enough to
            # amortize a high-RTT dispatch, small enough to keep the
            # log cadence and HBM batch staging reasonable.
            spc = min(24, steps_per_epoch) if cfg.data.drop_remainder else 1
        self.steps_per_call = max(1, spc)
        self.multi_step = None
        if self.steps_per_call > 1:
            from tpu_dp.train.step import make_multi_step

            # Composes with gradient accumulation (scan-of-scan): each
            # window element is one accumulated optimizer update, so
            # BASELINE config 5 (global batch 4096) runs windowed on a
            # small mesh — both the dispatch-RTT and the HBM amortization
            # at once.
            self.multi_step = self._guarded("multi_step", make_multi_step(
                self.model, self.optimizer, self.mesh, self.schedule,
                num_steps=self.steps_per_call,
                use_pallas_xent=cfg.train.pallas_xent,
                augment_fn=augment_fn,
                accum_steps=cfg.optim.grad_accum_steps,
                update_sharding=us,
                collective_dtype=cfg.train.collective_dtype or None,
                quant_block_size=cfg.train.quant_block_size,
                bucket_mb=cfg.train.bucket_mb,
                sentinel=self.guard_enabled,
            ))

        # Device-resident feed (VERDICT r4 next-steps #3): stage the train
        # set in HBM once; per-window dispatch ships only indices. The
        # trajectory is identical to the streaming path (same sampler
        # order, same step body — equivalence-tested); what changes is the
        # host work per step: ~KB of int32 instead of a ~MB gather+copy.
        # Staging is lazy (`resident_train` property): eval-only or tooling
        # constructions never pay the host→HBM transfer (ADVICE r5).
        self._resident_train = None
        self._resident_loops: dict[int, Any] = {}
        mode = cfg.data.device_resident
        self._resident_enabled = mode == "on" or (
            mode == "auto"
            and cfg.data.drop_remainder
            and self.train_pipe.dataset_bytes() <= cfg.data.resident_max_bytes
        )

    def _build_hooks(self) -> None:
        """Register the step-lifecycle hooks, in load-bearing order.

        Guard first (a triggering window must not be snapshotted before
        its rollback picks a target), snapshot cadence, fault injection
        (a kill at step K lands after the step-K snapshot — the
        kill/resume contract), heartbeats (injected delays attribute to
        the step they fired at), profiling, and the elastic/preemption
        boundary last (it raises on a transition). Hooks whose subsystem
        is off no-op per call, so the registry survives a regroup's
        observer rebuild without being rebuilt itself.
        """
        from tpu_dp.train.hooks import (
            BoundaryHook,
            CommProfilerHook,
            FaultHook,
            GuardHook,
            HeartbeatHook,
            ProfilerHook,
            SnapshotHook,
        )

        from tpu_dp.train.hooks import FlightRecorderHook

        self._guard_hook = GuardHook(self) if self.guard_enabled else None
        hooks: list = []
        if self.flightrec is not None:
            # FIRST, before anything that can raise: the black box must
            # record the very boundary a guard halt / regroup / preempt
            # is about to raise out of — later hooks in a sweep are
            # skipped after a raise, and the fatal window is exactly the
            # one the postmortem needs. (The guard-before-snapshot
            # invariant below is untouched: this hook snapshots nothing.)
            hooks.append(FlightRecorderHook(self))
        if self._guard_hook is not None:
            hooks.append(self._guard_hook)
        hooks += [SnapshotHook(self), FaultHook(self), HeartbeatHook(self),
                  ProfilerHook(self), CommProfilerHook(self),
                  BoundaryHook(self)]
        self._hooks = hooks

    @property
    def quarantine_path(self) -> Path:
        """The quarantine.jsonl sink (guard.quarantine_path, defaulting to
        <ckpt_dir>/quarantine.jsonl; the --guard CI lane archives it)."""
        return Path(
            self.cfg.guard.quarantine_path
            or Path(self.cfg.train.ckpt_dir) / "quarantine.jsonl"
        )

    def _ckpt_write_error(self, err: BaseException) -> None:
        """Degrade one failed epoch-checkpoint/export write: loud in the
        counters, the log and the black box — never fatal to the run
        (the snapshot cadence and older epoch saves still cover resume;
        docs/RESILIENCE.md "Storage faults")."""
        from tpu_dp.obs import flightrec

        _obs_counters.inc("ckpt.write_errors")
        flightrec.record("ckpt_write_error", step=self._host_step,
                         error=str(err)[:300])
        log0("epoch-checkpoint write failed (%s) — training continues; "
             "resume falls back to the newest earlier complete save", err)

    def _take_snapshot(self, epoch: int, steps_done: int,
                       wait: bool = False) -> bool:
        """One snapshot + the ``on_snapshot`` hook sweep (cadence,
        preemption final, and elastic quiesce final all route here so
        every registered hook sees every committed snapshot).

        Returns False when the write DEGRADED (disk full/flaky — already
        logged + counted by the snapshot manager): the hooks never see a
        snapshot that did not commit, and callers whose protocol depends
        on the commit (quiesce/preempt finals) get the honest verdict.
        With ``wait=True`` an async failure surfaces here as False too.
        """
        meta = self._snapshot_meta(epoch, steps_done)
        out = self.snap_mgr.snapshot(self.state, self._host_step, meta)
        if out is None:
            return False
        if wait:
            try:
                self.snap_mgr.wait()
            except (RuntimeError, OSError) as e:
                self.snap_mgr._record_write_error(self._host_step, e)
                return False
        for hook in self._hooks:
            hook.on_snapshot(epoch, steps_done, self._host_step, meta)
        return True

    def _inject_sdc(self, plan) -> None:
        """Apply an ``sdc:`` fault: flip one HIGH bit of the matching
        params leaves on THIS rank's replica (testing only).

        The honest simulation of silent data corruption: the local copy of
        a logically-replicated parameter silently diverges — no error, no
        NaN, just a replica whose forward pass (and gradient contribution)
        is wrong from here on. The flipped bit is the top exponent bit
        (bit 30 for f32), not a low mantissa bit: a low-bit flip of a
        zero-initialized leaf makes a denormal the very next (identical
        across replicas) update arithmetically absorbs, leaving nothing
        for the audit to catch — whereas the cross-replica delta of a
        high-bit flip survives identical additive updates exactly.
        ``leaf=`` globs over the "/"-joined leaf paths; default corrupts
        the first leaf.
        """
        import fnmatch

        from tpu_dp.resilience.guard import leaf_paths

        paths = leaf_paths(self.state.params)
        targets = (
            [p for p in paths if fnmatch.fnmatch(p, plan.leaf)]
            if plan.leaf else paths[:1]
        )
        if not targets:
            raise ValueError(
                f"sdc fault leaf={plan.leaf!r} matches no params leaf; "
                f"available: {paths[:8]}..."
            )
        log0("fault injection: sdc bit-flip on rank %d at step %d "
             "(leaves %s)", self.ctx.process_index, self._host_step, targets)
        flat, treedef = jax.tree_util.tree_flatten(self.state.params)
        new_flat = []
        for path, leaf in zip(paths, flat):
            if path in targets:
                host = np.asarray(leaf).copy()
                width = host.dtype.itemsize
                view = host.reshape(-1).view(
                    {1: np.uint8, 2: np.uint16, 4: np.uint32,
                     8: np.uint64}[width]
                )
                view[0] ^= np.asarray(1 << (8 * width - 2), view.dtype)
                # STRICTLY process-local rebuild: place the mutated host
                # copy onto each addressable device and reassemble the
                # logical array from the single-device pieces. A plain
                # `device_put(host, global_sharding)` can dispatch mesh
                # work the OTHER ranks never dispatch, desyncing the
                # collective stream — the injected "corruption" would then
                # crash the job instead of silently poisoning it, which is
                # the opposite of what SDC does.
                pieces = [
                    jax.device_put(host[s.index], s.device)
                    for s in leaf.addressable_shards
                ]
                leaf = jax.make_array_from_single_device_arrays(
                    leaf.shape, leaf.sharding, pieces
                )
            new_flat.append(leaf)
        self.state = self.state.replace(
            params=jax.tree_util.tree_unflatten(treedef, new_flat)
        )

    def _quarantine_saves_after(self, clean_step: int, reason: str) -> None:
        """Mark every complete save newer than ``clean_step`` untrusted
        (rank 0 — the save writer — only; `find_candidates` then skips
        them, so no rollback or ``--resume=auto`` lands on a save that may
        carry the corruption)."""
        from tpu_dp.resilience import find_candidates, quarantine_save_dir

        for source, step in find_candidates(
            self.cfg.train.ckpt_dir, self.snapshot_dir
        ):
            if step > int(clean_step):
                quarantine_save_dir(source, reason)
                log0("guard: quarantined save %s (step %d > last clean "
                     "audit %d)", source, step, clean_step)

    def _verify_step_fingerprint(self, tag: str = "train_step") -> None:
        """Cross-rank collective-schedule check at startup (dplint DP304).

        Every rank AOT-compiles the train step it is about to run, digests
        the ordered collective sequence + replica groups of the compiled
        module, and compares against rank 0's digest — a rank running a
        stale binary / different JAX build / diverged config fails here
        instead of deadlocking the slice at the first divergent collective.
        """
        from tpu_dp.analysis.hlo import program_fingerprint

        digest = program_fingerprint(self.train_step,
                                     self._step_arg_structs())
        dist.verify_collective_fingerprint(digest, tag=tag)
        log0("collective-schedule fingerprint (%s): %s", tag, digest[:16])

    def _step_arg_structs(self):
        """Abstract (state, batch[, guard_in]) args of the shipped per-step
        program — shared by the DP304 fingerprint check and the
        cost-analysis FLOPs measurement."""
        import jax.numpy as jnp

        cfg = self.cfg
        gb = cfg.data.batch_size * self.ctx.process_count
        accum = cfg.optim.grad_accum_steps
        prefix = (accum,) if accum > 1 else ()
        batch = {
            "image": jax.ShapeDtypeStruct(
                prefix + (gb, 32, 32, 3), jnp.uint8
            ),
            "label": jax.ShapeDtypeStruct(prefix + (gb,), jnp.int32),
        }
        args = (self.state, batch)
        if self.guard_enabled:
            from tpu_dp.train.step import guard_in_struct

            args = args + (guard_in_struct(),)
        return args

    def _register_program_costs(self) -> None:
        """Stamp this topology's per-step program cost into the registry.

        One optimizer step costs the same FLOPs whether it is dispatched
        per-step, windowed (`multi_step`) or resident, so one entry is
        registered under "train_step" and aliased to the other tags the
        hot loop routes through. Source is the analytic per-model
        estimate (`tpu_dp.obs.costs`); ``obs.measure_flops=true`` upgrades
        it to XLA's cost analysis of the real compiled step — the exact
        resolution order bench.py uses, now shared
        (docs/OBSERVABILITY.md "Efficiency accounting").
        """
        from tpu_dp.obs import costs

        per_chip = self.global_batch_size / max(1, self.num_devices)
        model = self.cfg.model.name
        cost = costs.registry.register_analytic("train_step", model,
                                                per_chip)
        if self.cfg.obs.measure_flops and self.obs_mode != "off":
            try:
                lowered = self.train_step.lower(*self._step_arg_structs())
                step_flops = costs.cost_analysis_flops(lowered.compile())
            except Exception:
                log0("obs.measure_flops: cost-analysis compile failed; "
                     "keeping the analytic estimate", exc_info=True)
                step_flops = None
            if step_flops:
                resolved, source, check = costs.resolve_flops_per_step(
                    None, step_flops, 1, per_chip,
                    costs.train_flops_per_image(model),
                )
                cost = costs.registry.register("train_step", resolved,
                                               source=source, check=check)
                log0("obs: measured step cost %.3g FLOPs/step/chip "
                     "(%s, check=%s)", resolved, source, check)
        if cost is not None:
            # The world-keyed alias records which mesh shape this cost
            # belongs to — after an elastic regroup the registry carries
            # one tag per world the run passed through, so post-hoc MFU
            # questions ("was the shrunk mesh efficient?") resolve per
            # shape instead of against whatever topology ended the run.
            for tag in ("multi_step", f"multi_step[w{self.steps_per_call}]",
                        f"train_step@w{dist.data_axis_size(self.mesh)}"):
                costs.registry.alias(tag, "train_step")
            from tpu_dp.obs.counters import counters as _c

            _c.gauge("obs.flops_per_step_per_chip",
                     cost.flops_per_step_per_chip)

    def _build_comm_profiler(self) -> None:
        """Construct the comm-attribution capture driver (rank 0 only).

        Mutually exclusive with the whole-run trace and the plain
        step-ranged profiler — `jax.profiler` sessions cannot nest, and
        the comm window exists precisely to replace an undirected trace.
        The reconciliation's expected schedule is the per-step train
        program's static collective schedule (a scanned multi-step
        window's loop body compiles the identical schedule, counted
        once); resident-feed windows dispatch a different program, so
        reconciliation is disabled there rather than wrong.
        """
        from tpu_dp.obs.commprof import (
            CommProfiler,
            parse_comm_profile_steps,
        )

        cfg = self.cfg
        spec = parse_comm_profile_steps(cfg.obs.comm_profile_steps)
        if spec is None:
            return
        if cfg.train.profile_steps or cfg.train.profile_dir:
            raise ValueError(
                "obs.comm_profile_steps cannot combine with "
                "train.profile_steps/train.profile_dir — jax.profiler "
                "sessions cannot nest, and the comm window replaces the "
                "undirected trace"
            )
        if self.ctx.process_index != 0:  # dplint: allow(DP101) host-only profiler
            return
        trace_dir = cfg.obs.comm_profile_dir or str(
            self.obs_dir / "commprof"
        )
        local_devices = [d for d in self.mesh.devices.flat
                         if d.process_index == self.ctx.process_index]
        expected_fn = None
        if not self._resident_enabled:
            # Precomputed EAGERLY (one AOT compile at startup, like
            # verify_fingerprint): resolving it lazily at the first
            # window boundary would bill seconds of compile time to that
            # step's data_wait span and crater its goodput record.
            from tpu_dp.obs.commprof import expected_schedule

            try:
                expected = expected_schedule(self.train_step,
                                             self._step_arg_structs())
                expected_fn = lambda: expected  # noqa: E731
            except Exception:
                log0("comm profile: expected-schedule compile failed; "
                     "reconciliation disabled", exc_info=True)
        else:
            log0("comm profile: device-resident feed active — the "
                 "fingerprint reconciliation is disabled (the resident "
                 "window is a different program); counts/time still "
                 "publish")
        wire_report = None
        if self.update_sharding == "sharded":
            from tpu_dp.parallel import quant

            wire_report = quant.wire_report(
                self.state.params, dist.data_axis_size(self.mesh),
                cfg.train.quant_block_size,
                bucket_bytes=self._bucket_bytes,
            )
        from tpu_dp.obs import chips

        try:
            ici = chips.ici_gbs(jax.devices()[0].device_kind)
        except Exception:
            ici = None
        self._comm_profiler = CommProfiler(
            trace_dir, spec,
            devices=len(local_devices) or 1,
            world=dist.data_axis_size(self.mesh),
            expected_fn=expected_fn,
            wire_report=wire_report,
            wire_dtype=cfg.train.collective_dtype or "",
            ici_gbs=ici,
            publish=self._publish_comm_report,
        )
        log0("comm profile: windows %r -> %s", cfg.obs.comm_profile_steps,
             trace_dir)

    def _publish_comm_report(self, report: dict, start: int, end: int,
                             trace_dir: str) -> None:
        """One captured window's breakdown -> metrics event + report file.

        The gauges were already set by the CommProfiler (they ride the
        next records' counter snapshots and the promfile); this stamps
        the schema-3 ``comm_profile`` event and rewrites
        ``<obs dir>/comm_report.json`` (newest window wins — the file is
        a gauge, the metrics stream the history).
        """
        from tpu_dp.obs.commprof import write_comm_report

        recon = report.get("reconciliation") or {}
        self._log_metrics({
            "event": "comm_profile",
            "start_step": start,
            "end_step": end,
            "comm_ms": report["comm_ms"],
            "exposed_comm_ms": report["exposed_comm_ms"],
            "overlap_frac": report["overlap_frac"],
            "compute_ms": report["compute_ms"],
            "reconciled": recon.get("ok"),
            "by_kind": {k: v["per_step"]
                        for k, v in report["by_kind"].items()},
            "trace_dir": trace_dir,
        })
        write_comm_report(self.obs_dir / "comm_report.json", report)
        self._write_prom()
        log0("comm profile [%d, %d): comm %.3f ms/step (exposed %.3f, "
             "overlap %s), compute %.3f ms/step%s — %s",
             start, end, report["comm_ms"], report["exposed_comm_ms"],
             report["overlap_frac"], report["compute_ms"],
             "" if not recon else (
                 ", schedule reconciled" if recon.get("ok")
                 else ", RECONCILIATION MISMATCH"),
             trace_dir)

    def _write_prom(self) -> None:
        """Atomically rewrite the Prometheus textfile (obs.prom_path).

        Multi-process runs suffix the stable rank so every rank's file
        can coexist in one scraped directory; failures warn once and
        never abort training (same contract as heartbeat writes).
        """
        path = self.cfg.obs.prom_path
        if not path:
            return
        from tpu_dp.obs.promfile import write_promfile

        out = Path(path)
        if self.ctx.process_count > 1:
            out = out.with_name(out.name + f".r{self.stable_rank}")
        try:
            write_promfile(out, labels={"rank": str(self.ctx.process_index)})
        except OSError:
            if not self._prom_failed:
                self._prom_failed = True
                log0("prometheus textfile write failed (suppressing "
                     "further warnings)", exc_info=True)

    def _load_data(self, cfg: Config) -> None:
        """Process 0 materializes the dataset first; the rest then read it.

        Fixes the reference's download race — every rank extracting into the
        shared `./data` dir concurrently (`cifar_example_ddp.py:67-68,73-74`,
        SURVEY.md §5 "Race detection").
        """

        def _load():
            train = load_dataset(
                cfg.data.dataset, cfg.data.root, train=True,
                allow_synthetic=cfg.data.allow_synthetic,
                synthetic_num_examples=cfg.data.synthetic_train_size,
                seed=cfg.train.seed,
            )
            test = load_dataset(
                cfg.data.dataset, cfg.data.root, train=False,
                allow_synthetic=cfg.data.allow_synthetic,
                synthetic_num_examples=cfg.data.synthetic_test_size,
                seed=cfg.train.seed,
            )
            return train, test

        if self.ctx.process_count == 1 or self._join is not None:
            # A joiner must not run the materialization barrier: the
            # incumbents are mid-regroup (they will next meet it at the
            # DP304 verify / regroup_ready barrier, not here), and the
            # dataset already materialized at the original launch — the
            # shared filesystem elastic requires makes it readable now.
            self.train_ds, self.test_ds = _load()
            return
        from jax.experimental import multihost_utils

        # Host-only IO stagger: rank 0 downloads, the barrier sits OUTSIDE
        # both gates so every rank reaches it.
        if self.ctx.process_index == 0:  # dplint: allow(DP101)
            self.train_ds, self.test_ds = _load()
        multihost_utils.sync_global_devices("tpu_dp_data_materialized")
        if self.ctx.process_index != 0:  # dplint: allow(DP101)
            self.train_ds, self.test_ds = _load()

    def _segment_steps(self, done: int) -> int:
        """Steps of the CURRENT world's segment out of ``done`` cumulative
        epoch steps (the part not covered by `_epoch_lineage`)."""
        return int(done) - sum(int(s) for _, s in self._epoch_lineage)

    def _membership_meta(self, epoch: int, steps_done: int) -> dict | None:
        """Membership stamp for checkpoint/snapshot manifests (elastic).

        ``lineage`` describes the interrupted epoch's full consumption —
        prior segments plus the in-flight one — so any later reader
        (a rollback regroup, a fresh incarnation resuming into the tail)
        can reconstruct the exact remaining sample set from
        ``(seed, epoch, lineage)`` via `elastic_resplit`.
        """
        if self.elastic is None:
            return None
        rec = self.elastic.record
        return {
            "epoch": rec.epoch,
            "world": self.ctx.process_count,
            "members": list(rec.members),
            "lineage": [list(map(int, seg)) for seg in self._epoch_lineage]
            + [[self.ctx.process_count, self._segment_steps(steps_done)]],
        }

    def _set_elastic_tail(self, epoch: int, lineage, skip: int = 0) -> bool:
        """Install the re-split remainder of an interrupted epoch.

        Returns False when the lineage already covers the whole epoch
        (nothing remains for this world — the caller advances to the next
        epoch). ``skip`` fast-forwards within the tail (resuming a run
        that had already progressed past the re-split point).
        """
        from tpu_dp.data.sampler import ElasticTailSampler, elastic_resplit

        cfg = self.cfg
        lineage = [list(map(int, seg)) for seg in lineage]
        per_step = cfg.data.batch_size * cfg.optim.grad_accum_steps
        idx = elastic_resplit(
            len(self.train_ds), cfg.data.shuffle, cfg.train.seed, epoch,
            per_step, lineage,
            self.ctx.process_count, self.ctx.process_index,
        )
        steps = len(idx) // per_step
        if steps - int(skip) <= 0:
            # The lineage already covers the whole epoch: the caller
            # advances to the NEXT epoch, whose consumption history is
            # empty — keeping the old lineage installed would poison every
            # later snapshot manifest with negative segment counts.
            self._elastic_tail = None
            self._epoch_lineage = []
            return False
        self._epoch_lineage = lineage
        pipe = DataPipeline(
            self.train_ds, cfg.data.batch_size, self.mesh,
            shuffle=cfg.data.shuffle, seed=cfg.train.seed,
            drop_remainder=True, prefetch=cfg.data.prefetch,
            accum_steps=cfg.optim.grad_accum_steps,
            sampler=ElasticTailSampler(idx, epoch),
            sync_placement=cfg.data.sync_placement,
        )
        from types import SimpleNamespace

        self._elastic_tail = SimpleNamespace(
            epoch=int(epoch), pipe=pipe,
            base=sum(s for _, s in lineage), skip=int(skip),
        )
        log0(
            "elastic: epoch %d re-split over world %d — %d prior step(s) "
            "across %s, %d step(s) remain (resuming %d in)",
            epoch, self.ctx.process_count, self._elastic_tail.base,
            lineage, steps, skip,
        )
        return True

    def _resume_position(self, meta: dict) -> tuple[int, int]:
        """(start_epoch, start_step) a restored state's meta encodes.

        Epoch checkpoints record the *finished* epoch → resume at the next
        one, step 0. Snapshots record the mid-epoch position → resume the
        same epoch and fast-forward the sampler by ``steps_done`` (no batch
        replayed, none skipped). A snapshot taken at the exact epoch end
        normalizes to (epoch+1, 0).
        """
        if meta.get("kind") == "snapshot":
            epoch = int(meta.get("epoch", 0))
            step = int(meta.get("steps_done", 0))
            spe = len(self.train_pipe)
            if spe and step >= spe:
                return epoch + 1, 0
            return epoch, step
        return int(meta.get("epoch", -1)) + 1, 0

    def _maybe_resume(self) -> None:
        """Resume from the newest checkpoint OR snapshot, agreed across
        processes.

        Checkpoints/snapshots are written by process 0 only; on a pod each
        host has its own disk, so the resume decision and the restored
        state must come from process 0 (otherwise replicas desync: some
        resume, some start fresh). The newest complete save wins across
        both layouts, through the self-healing `resume_latest` loop — a
        torn or checksum-corrupt best candidate (the torn:/bitrot: chaos
        signature: a rank killed right after its snapshot committed, the
        disk having lied about the commit) is marked and the next-older
        complete save restores instead; the auto-restart must not die on
        the very artifact the crash mangled. A tree where EVERY candidate
        is unreadable degrades to a fresh start — the documented
        ``--resume=auto`` semantics ("continue when a usable save exists,
        start fresh otherwise"), loudly.
        """
        cfg = self.cfg
        from tpu_dp.resilience import resume_latest

        resume_dir = None
        if self.ctx.process_count == 1:
            try:
                self.state, meta, resume_dir = resume_latest(
                    self.state, cfg.train.ckpt_dir, self.snapshot_dir
                )
            except FileNotFoundError:
                return
            except RuntimeError:
                log0("resume: every candidate unreadable — starting "
                     "fresh (auto-resume semantics)", exc_info=True)
                return
            self.start_epoch, self.start_step = self._resume_position(meta)
        else:
            from jax.experimental import multihost_utils

            # Host-only checkpoint read; the broadcasts below are outside
            # the gate, reached by every rank.
            loaded, state = False, self.state
            pos = np.zeros(2, np.int32)
            if self.ctx.process_index == 0:  # dplint: allow(DP101)
                try:
                    state, meta, resume_dir = resume_latest(
                        self.state, cfg.train.ckpt_dir, self.snapshot_dir
                    )
                    pos = np.asarray(self._resume_position(meta), np.int32)
                    loaded = True
                except FileNotFoundError:
                    pass
                except RuntimeError:
                    log0("resume: every candidate unreadable — starting "
                         "fresh (auto-resume semantics)", exc_info=True)
            loaded0 = bool(
                int(multihost_utils.broadcast_one_to_all(np.int32(loaded)))
            )
            if not loaded0:
                return
            host_state = jax.tree_util.tree_map(np.asarray, state)
            self.state = multihost_utils.broadcast_one_to_all(host_state)
            pos = multihost_utils.broadcast_one_to_all(pos)
            self.start_epoch, self.start_step = int(pos[0]), int(pos[1])
            # Non-writer ranks take rank 0's LITERAL pick, not a local
            # re-derivation: a candidate rank 0 skipped as transiently
            # unreadable leaves no quarantine marker behind, so a local
            # `find_latest` could land on a different dir and install a
            # different membership-lineage tail (replayed/dropped
            # samples, cross-rank desync).
            buf = np.zeros(4096, np.uint8)
            if self.ctx.process_index == 0:  # dplint: allow(DP101)
                if resume_dir is not None:
                    raw = str(resume_dir).encode()[:4096]
                    buf[:len(raw)] = np.frombuffer(raw, np.uint8)
            raw = multihost_utils.broadcast_one_to_all(buf)
            raw = np.asarray(raw, np.uint8).tobytes().rstrip(b"\x00")
            if self.ctx.process_index != 0:  # dplint: allow(DP101)
                resume_dir = Path(raw.decode()) if raw else None
        if self.cfg.resilience.elastic:
            self._maybe_resume_into_tail(resume_dir)
        log0("resumed from %s at epoch %d step-in-epoch %d (global step %d)",
             resume_dir, self.start_epoch, self.start_step,
             int(self.state.step))

    def _maybe_resume_into_tail(self, resume_dir) -> None:
        """Honor a snapshot's membership lineage on a full restart.

        A snapshot taken after a mid-epoch regroup describes an epoch
        consumed across *several* world sizes; the plain
        `_resume_position` skip (one world, one stride) would replay and
        drop samples. Every rank reads the manifest itself — elastic runs
        require the checkpoint tree on a shared filesystem — and installs
        the re-split tail for whatever world this incarnation launched
        with (which may differ from the world that wrote the snapshot).
        """
        if resume_dir is None:
            # This rank's local view lacked the checkpoint rank 0 found —
            # a shared-filesystem violation elastic cannot survive later
            # anyway, but resume itself already restored via broadcast.
            log0("elastic: resume source not visible on this rank's "
                 "filesystem; lineage resume unavailable")
            return
        try:
            meta = json.loads((Path(resume_dir) / "meta.json").read_text())
        except (OSError, ValueError):
            return
        lineage = (meta.get("membership") or {}).get("lineage") or []
        if meta.get("kind") != "snapshot" or not lineage:
            return
        world = self.ctx.process_count
        if len(lineage) == 1 and int(lineage[0][0]) == world:
            return  # single-world epoch: the standard skip path is exact
        epoch = int(meta.get("epoch", 0))
        if int(lineage[-1][0]) == world:
            # The last segment ran at this very world: its re-split tail is
            # this incarnation's stream too — skip what it already did.
            prior, skip = lineage[:-1], int(lineage[-1][1])
        else:
            prior, skip = lineage, 0
        if self._set_elastic_tail(epoch, prior, skip=skip):
            self.start_epoch, self.start_step = epoch, 0
        else:
            self.start_epoch, self.start_step = epoch + 1, 0

    @property
    def resident_train(self):
        """The device-resident train set, staged on first access (or None).

        Lazy so a Trainer built for eval/tooling never pays the host→HBM
        transfer (ADVICE r5); `train_epoch` touches it on its first window.
        """
        if self._resident_enabled and self._resident_train is None:
            self._resident_train = self.train_pipe.resident_data()
        return self._resident_train

    @property
    def global_batch_size(self) -> int:
        """Logical per-step batch: per-process batch × processes (the
        reference's batch-4-per-rank × world accounting, SURVEY.md §2A)."""
        return (self.cfg.data.batch_size * self.ctx.process_count
                * self.cfg.optim.grad_accum_steps)

    def _resident_loop(self, n: int):
        """Compiled resident window program for window size ``n`` (cached;
        an epoch uses at most two sizes: steps_per_call and 1)."""
        loop = self._resident_loops.get(n)
        if loop is None:
            from tpu_dp.train.step import make_multi_step_resident

            from tpu_dp.obs import costs as _costs

            _costs.registry.alias(f"resident_loop[w{n}]", "train_step")
            loop = self._guarded(f"resident_loop[w{n}]", make_multi_step_resident(
                self.model, self.optimizer, self.mesh, self.schedule,
                num_steps=n, use_pallas_xent=self.cfg.train.pallas_xent,
                augment_fn=self._augment_fn,
                accum_steps=self.cfg.optim.grad_accum_steps,
                update_sharding=self.update_sharding,
                collective_dtype=self.cfg.train.collective_dtype or None,
                quant_block_size=self.cfg.train.quant_block_size,
                bucket_mb=self.cfg.train.bucket_mb,
                sentinel=self.guard_enabled,
            ))
            self._resident_loops[n] = loop
        return loop

    def train_epoch(self, epoch: int, start_step: int = 0) -> dict[str, float]:
        """One epoch of training; ``start_step`` resumes it mid-way.

        ``start_step > 0`` (a snapshot resume) fast-forwards the sampler:
        the epoch's first ``start_step`` batches were already consumed by
        the run being resumed, so iteration starts at exactly the next one
        — no batch replayed, none skipped.
        """
        cfg = self.cfg
        # Elastic tail: after a mid-epoch regroup (or a restart into one),
        # the interrupted epoch's remaining samples come from the re-split
        # pipe; `done` stays epoch-cumulative across the world change so
        # snapshot metadata and the quiesce protocol keep one step clock.
        tail = self._elastic_tail
        if tail is not None and tail.epoch != epoch:
            tail = None
        pipe = tail.pipe if tail is not None else self.train_pipe
        base = tail.base if tail is not None else 0
        if tail is not None:
            start_step = tail.skip
        pipe.set_epoch(epoch)  # `cifar_example_ddp.py:92` parity
        gbs = self.global_batch_size
        run_loss, run_steps = None, 0  # device-side running-loss accumulator
        ep_loss = ep_correct = None
        ep_steps, ep_count = 0, 0
        i = start_step - 1
        done = base + start_step  # epoch steps completed (snapshot meta)
        self._epoch_done = done
        if self.resident_train is not None:
            items = pipe.index_windows(
                self.steps_per_call, skip_steps=start_step)
        else:
            items = pipe.windows(
                self.steps_per_call, skip_steps=start_step)
        def _unstack(stacked, n):
            # Lazy per-step views over the window's stacked metrics — still
            # no host sync outside log boundaries.
            return tuple(
                {k: v[j] for k, v in stacked.items()} for j in range(n)
            )

        # Telemetry (train.obs != off): span timestamps bracket the loop's
        # phases — t0→t1 data_wait, t1→t2 h2d (full only: block on the
        # placed batch), t2→t3 dispatch, t3→t4 device (full only: a scalar
        # fetch, the `ThroughputMeter.mark()` fence discipline — the only
        # obs mode that adds a host sync, which is why it is opt-in).
        spans = self.spans
        obs_full = self.obs_mode == "full"
        from tpu_dp.train.hooks import StepEvent

        for hook in self._hooks:
            hook.on_epoch_start(epoch)
        it = iter(items)
        while True:
            if spans is not None:
                # ts_wall is the step's wall-clock START — stamped before
                # next(), so the data_wait slice occupies its real place
                # on the exported timeline instead of shifting every
                # step's slices right by its own data_wait.
                ts_wall = time.time()
                t0 = time.perf_counter()
            try:
                n, item = next(it)
            except StopIteration:
                break
            for hook in self._hooks:
                hook.on_window_start(self._host_step + 1, n)
            # The sentinel's replicated input (guard on only): armed loss
            # cap, LR ease-in scale, and the nan/spike injection seam.
            guard_args = ()
            if self._guard_hook is not None:
                guard_args = (
                    self._guard_hook.guard_in(self._host_step + 1, n),
                )
            if spans is not None:
                t1 = time.perf_counter()
                t2 = t1
                if obs_full:
                    jax.block_until_ready(item)
                    t2 = time.perf_counter()
            if self.resident_train is not None:
                # Indices in, stacked metrics out — the dataset never
                # re-crosses the host→device link.
                self.state, stacked = self._resident_loop(n)(
                    self.state, self.resident_train, item, *guard_args
                )
                window = _unstack(stacked, n)
            elif n == 1:
                self.state, m = self.train_step(self.state, item,
                                                *guard_args)
                window = (m,)
            else:
                # One dispatch, n optimizer steps (device-side scanned loop).
                self.state, stacked = self.multi_step(self.state, item,
                                                      *guard_args)
                window = _unstack(stacked, n)
            if spans is not None:
                t3 = time.perf_counter()
                t4 = t3
                if obs_full:
                    float(window[-1]["loss"])  # scalar fetch: honest fence
                    t4 = self.meter.mark()     # one fence, two consumers
                    _obs_counters.gauge(
                        "throughput.images_per_sec",
                        round(self.meter.images_per_sec, 1),
                    )
                    from tpu_dp.obs import update_device_memory_gauges

                    update_device_memory_gauges()
                # Basic mode OMITS h2d/device rather than recording 0.0:
                # absence means "not measured" — a fake zero would render
                # as "device took 0 ms" in rollups and the Perfetto trace
                # (same principle as the absent memory gauges).
                window_spans = {
                    "data_wait": (t1 - t0) * 1e3,
                    "dispatch": (t3 - t2) * 1e3,
                }
                if obs_full:
                    window_spans["h2d"] = (t2 - t1) * 1e3
                    window_spans["device"] = (t4 - t3) * 1e3
                new_recs = spans.record_window(
                    self._host_step + 1, n, window_spans, ts=ts_wall,
                    gen=self._rollback_gen,
                )
                eff = None
                if self._eff is not None:
                    # Live efficiency gauges, per dispatched window: MFU
                    # from the cost registry (absent when the program's
                    # cost or the chip's peak is unknown — never a wrong
                    # number), goodput = 1 − data_wait/window. Window wall
                    # time is boundary-to-boundary: at obs=full it ends on
                    # the device fence (honest device time); at basic it
                    # is a dispatch rate (documented in OBSERVABILITY.md).
                    if self.resident_train is not None:
                        tag = f"resident_loop[w{n}]"
                    else:
                        tag = "train_step" if n == 1 else "multi_step"
                    wall_ms = ((t4 if obs_full else t3) - t0) * 1e3
                    eff = self._eff.observe(
                        tag, n, wall_ms, window_spans["data_wait"]
                    )
                    self._last_efficiency = eff
                    _obs_counters.gauge("obs.step_time_ms",
                                        eff["step_time_ms"])
                    _obs_counters.gauge("obs.goodput", eff["goodput"])
                    if "mfu" in eff:
                        _obs_counters.gauge("obs.mfu", eff["mfu"])
                if obs_full:
                    # Per-step metrics.jsonl records (schema 3): spans,
                    # the window's efficiency gauges, and a counter
                    # snapshot — one line per optimizer step. The int8
                    # codec's overflow/clip counts publish first (riding
                    # this block's existing fence) so the same window's
                    # records carry them.
                    self._publish_quant_counters(window,
                                                 self._host_step + 1)
                    snap = _obs_counters.snapshot()
                    for r in new_recs:
                        rec = {
                            "step": r["step"],
                            "ts": _iso_ts(r["ts"]),
                            "spans": {k: round(v, 3)
                                      for k, v in r["spans"].items()},
                            "counters": snap,
                        }
                        if eff is not None:
                            rec["goodput"] = eff["goodput"]
                            if "mfu" in eff:
                                rec["mfu"] = eff["mfu"]
                        self._log_metrics(rec)
            for m in window:
                i += 1
                # On-device async adds; no host sync inside the loop.
                run_loss = (
                    m["loss"] if run_loss is None else run_loss + m["loss"]
                )
                run_steps += 1
                ep_loss = m["loss"] if ep_loss is None else ep_loss + m["loss"]
                ep_correct = (
                    m["correct"] if ep_correct is None
                    else ep_correct + m["correct"]
                )
                ep_steps += 1
                ep_count += gbs
                self.meter.step(gbs)
                if i % cfg.train.log_every == cfg.train.log_every - 1:
                    # Reference print format (`cifar_example.py:85-86`); the
                    # float() here is the only sync per log interval.
                    print0("[%d, %5d] loss: %.3f"
                           % (epoch + 1, i + 1, float(run_loss) / run_steps))
                    run_loss, run_steps = None, 0
                    if self.health is not None:
                        # Rank 0 reads every rank's heartbeat file at the
                        # log cadence (already a sync boundary): stragglers
                        # and stale/hung ranks get named while the run is
                        # still up, not in the postmortem. The hang-dump
                        # sentinel goes out BEFORE report() — on_flag=raise
                        # must not abort past the request that makes every
                        # still-stepping rank preserve its black box.
                        issues = self.health.check()
                        if self.flightrec is not None:
                            # Aimed at the dir the recorders POLL (the
                            # launch obs root) — after a regroup the
                            # monitor's own run dir is the re-homed
                            # me<E> dir nobody stats.
                            self.health.request_dump(
                                issues, dump_dir=self.flightrec.dump_dir)
                        self.health.report(issues)
                        self._suspect_from_health(issues)
                    self._write_prom()
            # The step-lifecycle hook sweep, once per dispatched window
            # (the host-side step boundary): guardrails, snapshot cadence,
            # fault injection, heartbeats, profiling, and the
            # elastic/preemption boundary, in the registered order
            # (`_build_hooks` — ordering is load-bearing). A hook may
            # raise the loop's control-flow exceptions (_RegroupSignal,
            # _GuardRollback, PreemptedError, DivergedError).
            done += n
            self._host_step += n
            self._epoch_done = done  # regroup attribution (fit's handler)
            ev = StepEvent(epoch=epoch, done=done, n=n, window=window)
            for hook in self._hooks:
                hook.on_step_end(ev)
        stats = {
            "loss": float(ep_loss) / max(1, ep_steps) if ep_steps else 0.0,
            "accuracy": float(ep_correct) / ep_count if ep_count else 0.0,
        }
        if start_step or base:
            # A resumed (or regrouped) epoch's accumulators cover only its
            # post-resume tail; label the record so loss curves explain
            # their own discontinuity instead of faking full-epoch coverage.
            stats["resumed_at_step"] = base + start_step
        self.meter.mark()  # fence: epoch stats fetched, device drained
        return stats

    def _snapshot_meta(self, epoch: int, steps_done: int) -> dict[str, Any]:
        """Snapshot metadata: the mid-epoch resume position + provenance.

        Elastic runs add the membership stamp — epoch, world, members and
        the interrupted epoch's consumption lineage — so a rollback
        regroup or a fresh incarnation can reconstruct the exact remaining
        sample set (`_membership_meta`).
        """
        meta = {
            "kind": "snapshot",
            "epoch": epoch,
            "steps_done": steps_done,
            "config": self.cfg.to_dict(),
            "seed": self.cfg.train.seed,
        }
        if self._rollback_gen:
            # A post-rollback save identifies its generation, so forensic
            # tooling can align it with the tombstoned metrics/quarantine
            # records of the pass it replaced.
            meta["rollback_generation"] = self._rollback_gen
        membership = self._membership_meta(epoch, steps_done)
        if membership is not None:
            meta["membership"] = membership
        return meta

    def _preempt_exit(self, epoch: int, steps_done: int) -> None:
        """The preemption contract: final snapshot → barrier → exit 143.

        The snapshot is joined (not just dispatched) before the barrier, so
        by the time any rank exits, rank 0's final state is committed and
        an auto-restart (`--resume=auto`) loses zero steps.
        """
        from tpu_dp.obs import flightrec
        from tpu_dp.resilience import PreemptedError

        flightrec.record("preempt_exit", step=self._host_step, epoch=epoch,
                         done=steps_done)
        log0("preemption: taking final snapshot at epoch %d step %d "
             "(global step %d)", epoch, steps_done, self._host_step)
        if not self._take_snapshot(epoch, steps_done, wait=True):
            # Degrade, still honor the 143 contract: the final write
            # failed (full/flaky disk — counted + in the black box), so
            # the auto-restart resumes from the newest EARLIER complete
            # save instead; dying with a disk error here would just turn
            # a bounded work loss into a supervisor-visible failure.
            log0("preemption: final snapshot FAILED — resume will fall "
                 "back to the newest earlier complete save")
        try:
            res = self.cfg.resilience
            dist.fault_tolerant_barrier(
                self.mesh, retries=res.max_retries,
                base_delay=res.retry_base_delay_s,
            )
        except Exception:
            # A half-dead slice must not block the survivors' clean exit —
            # the snapshot is already committed.
            log0("preemption barrier failed; exiting anyway", exc_info=True)
        raise PreemptedError(
            f"preempted at epoch {epoch}, step-in-epoch {steps_done} "
            f"(global step {self._host_step}); snapshot committed to "
            f"{self.snapshot_dir}"
        )

    # -- elastic world size (tpu_dp/resilience/elastic.py) ---------------

    def _suspect_from_health(self, issues) -> None:
        """Fold rank-0's hang detection into the membership ledger.

        A stale/missing heartbeat is the "peers observe it" detection path
        (docs/RESILIENCE.md failure matrix): rank 0 publishes the suspect,
        every member's next boundary poll sees it and joins a rollback
        quiesce. Stragglers are slow, not dead — never suspected.
        """
        if self.elastic is None:
            return
        for issue in issues:
            if issue.kind in ("stale", "missing"):
                self.elastic.mark_suspect(issue.rank, issue.describe())

    def _leave_requested(self) -> bool:
        """This rank was told to go: SIGTERM (elastic semantics), the
        ``leave:`` fault injection, or the SDC audit named it corrupt
        (`GuardHook._sdc_audit` — a replica holding divergent params must
        leave before it poisons another gradient reduction)."""
        return (
            (self.preempt is not None and self.preempt.requested)
            or (self.fault is not None and self.fault.leave_requested)
            or self._guard_evict
        )

    def _elastic_boundary(self, epoch: int, done: int) -> None:
        """Window-boundary elastic hook: detect, converge, hand over.

        Detection is one rate-limited ledger glob (plus the local leave
        flags). A triggered transition then converges WITHOUT stalling:
        this rank refreshes its check-in at every boundary and keeps
        stepping (a stopped member would wedge every peer's in-flight
        collective) until the published plan's stop threshold — the first
        boundary at or past it is the same global position on every member
        (identical boundary sequences). There rank 0 commits the final
        snapshot, the ledger barrier closes, and control leaves
        `train_epoch` — as `PreemptedError` on a departing rank,
        `_RegroupSignal` on a survivor.
        """
        plan = self._quiesce_plan
        if plan is None:
            el = self.elastic
            leaving = self._leave_requested()
            if not el.quiescing:
                trigger = el.poll(self._host_step, leave_requested=leaving)
                if trigger is None:
                    return
                log0("elastic: regroup trigger %r at epoch %d step %d "
                     "(global step %d)", trigger, epoch, done,
                     self._host_step)
                from tpu_dp.obs import flightrec

                flightrec.record("elastic_trigger", step=self._host_step,
                                 trigger=str(trigger), leaving=leaving)
                # Rollback flavor: a suspected-dead peer, or an SDC
                # eviction (the corrupt rank leaves AND everyone resumes
                # from a pre-corruption save — a graceful final snapshot
                # would persist the very state the audit condemned).
                self._q_flavor = (
                    "rollback" if trigger == "suspect" or self._guard_evict
                    else "graceful"
                )
            plan = el.quiesce_step(
                epoch, self._host_step, leaving=leaving,
                flavor=self._q_flavor, window=self.steps_per_call,
            )
            if plan is None:
                return  # keep stepping; the next boundary re-converges
            self._quiesce_plan = plan
        # A rollback plan finishes immediately only when members DEPARTED
        # (the mesh is already broken — further steps are impossible);
        # a live-membered rollback (SDC eviction) converges at the common
        # stop threshold like a graceful one — stopping this rank early
        # would wedge every still-stepping peer's in-flight collective.
        if (plan.flavor == "rollback" and plan.departed) \
                or self._host_step >= plan.stop_step:
            self._finish_quiesce(epoch, done, plan)

    def _finish_quiesce(self, epoch: int, done: int, plan) -> None:
        """The quiesce epilogue: final snapshot, barrier, hand-off."""
        from tpu_dp.resilience import ElasticError, PreemptedError

        if (plan.flavor == "rollback" and not plan.departed
                and not plan.leavers):
            # Symmetric twin of `_elastic_rollback`'s no-shrink guard: a
            # rollback plan in which every member is alive and staying
            # means some rank reported a NON-membership failure (OOM, a
            # bug). The reporting rank re-raises its original error; every
            # other member must fail fast too — regrouping to the full
            # original world would only hang in bootstrap waiting for the
            # rank that is busy dying.
            self._quiesce_plan = None
            raise ElasticError(
                f"rollback quiesce e{plan.epoch} carries no membership "
                f"change — a peer reported a non-membership failure "
                f"(see its log); refusing to regroup the same world"
            )

        if plan.flavor in ("graceful", "grow"):
            # The final snapshot at the agreed step — the regroup's resume
            # point, so the world change replays and drops nothing (for a
            # grow it is also the JOINER's state source). Joined (not just
            # dispatched) before the barrier ack, like the preemption
            # contract's. A failure here (a peer died between the plan and
            # the stop step, poisoning the device state this fetch
            # materializes) must not kill the regroup: the leader's
            # pre-publish validation sees the missing snapshot and falls
            # back to a rollback resume.
            try:
                committed = self._take_snapshot(epoch, done, wait=True)
            except Exception:
                committed = False
                log0("elastic: final snapshot fetch at step %d failed",
                     self._host_step, exc_info=True)
            if not committed:
                log0("elastic: final snapshot at step %d did not commit — "
                     "the regroup will resume from the newest complete one",
                     self._host_step)
        self.elastic.ack_and_await_quiesced(plan)
        self._quiesce_plan = None
        if self.elastic.sid in plan.leavers:
            self.elastic.confirm_left(done)
            _obs_counters.inc("elastic.departures")
            from tpu_dp.obs import flightrec

            flightrec.record("elastic_departure", step=self._host_step,
                             epoch=epoch, done=done, flavor=plan.flavor,
                             membership_epoch=plan.epoch)
            raise PreemptedError(
                f"elastic departure at epoch {epoch}, step-in-epoch {done} "
                f"(global step {self._host_step}); membership epoch "
                f"{plan.epoch} forms with {len(plan.survivors)} survivor(s)"
            )
        raise _RegroupSignal(epoch, done, plan)

    def _elastic_rollback(self, epoch: int, err: BaseException) -> None:
        """A collective died under us (peer gone, no goodbye): check in
        with rollback flavor — no further steps are possible on this mesh
        — and hand over to the regroup. Raises; never returns."""
        done = self._epoch_done
        log0("elastic: collective failure at epoch %d step %d (%s) — "
             "entering rollback regroup", epoch, done, err)
        if self._quiesce_plan is None:
            self._quiesce_plan = self.elastic.quiesce_blocking(
                epoch, self._host_step, leaving=False, flavor="rollback",
                window=self.steps_per_call,
            )
        elif self._quiesce_plan.flavor == "grow":
            # A member died while a GROW plan was already adopted. The
            # plan is immutable for this epoch (exclusive-create) and its
            # survivor set — every incumbent plus the joiner — now
            # contains a dead rank, so neither the grown bootstrap nor a
            # rollback re-form of that exact set can ever rendezvous
            # (and the bootstrap failure mode is a LOG(FATAL), not an
            # error). The explicit answer (docs/RESILIENCE.md failure
            # matrix): fail fast and typed; the supervisor's full-world
            # restart — which resumes from the newest snapshot at any
            # world — is the recovery.
            from tpu_dp.resilience import ElasticError

            plan_epoch = self._quiesce_plan.epoch
            self._quiesce_plan = None
            raise ElasticError(
                f"member failure while grow plan e{plan_epoch} was in "
                f"flight ({err}); the planned membership (incumbents + "
                f"joiner) is unsatisfiable with a dead member — restart "
                f"the world"
            ) from err
        elif self._quiesce_plan.flavor == "graceful":
            # A graceful plan was adopted, then the mesh died under it
            # (e.g. the announced leaver was hard-killed before the stop
            # step). The graceful epilogue's premises are gone — this
            # rank's state is mid-failed-window and the common stop step
            # is unreachable — so it downgrades locally to rollback
            # semantics (no final snapshot; resume from the newest
            # complete one). The published record stays canonical: the new
            # leader validates the graceful snapshot before publishing and
            # falls back to a rollback resume when it never landed.
            import dataclasses

            self._quiesce_plan = dataclasses.replace(
                self._quiesce_plan, flavor="rollback"
            )
        plan = self._quiesce_plan
        if not plan.departed and not plan.leavers:
            # Every member is alive and staying: the failure is NOT a
            # membership event (OOM, a bug, a transient local error) and
            # shrinking would change nothing — surface the original error
            # instead of regrouping in a loop on the same world.
            self._quiesce_plan = None
            raise err
        self._finish_quiesce(epoch, done, plan)

    def _rollback_resume(self) -> dict:
        """The rollback resume payload: newest complete readable save.

        Computed by the new leader (every survivor computes it, only the
        leader's lands in the record): the newest complete snapshot or
        epoch checkpoint, its manifest supplying the epoch position and
        consumption lineage. With nothing on disk the job restarts from
        scratch — still on the surviving world, still without an operator.
        """
        from tpu_dp.resilience import find_candidates

        for source, step in find_candidates(
            self.cfg.train.ckpt_dir, self.snapshot_dir
        ):
            try:
                meta = json.loads((source / "meta.json").read_text())
            except (OSError, ValueError):
                log0("elastic rollback: %s has unreadable meta; skipping",
                     source)
                continue
            if meta.get("kind") == "snapshot":
                lineage = (meta.get("membership") or {}).get("lineage") or []
                return {
                    "epoch": int(meta.get("epoch", 0)),
                    "steps_done": int(meta.get("steps_done", 0)),
                    "lineage": lineage,
                    "global_step": int(meta.get("global_step", max(step, 0))),
                    "snapshot_dir": str(source),
                }
            return {  # epoch checkpoint: clean next-epoch start
                "epoch": int(meta.get("epoch", -1)) + 1,
                "steps_done": 0, "lineage": [],
                "global_step": max(step, 0), "snapshot_dir": str(source),
            }
        return {"epoch": 0, "steps_done": 0, "lineage": [],
                "global_step": 0, "snapshot_dir": None}

    def _load_rollback_state(self, resume: dict, target
                             ) -> tuple[Any, dict]:
        """Restore ``resume["snapshot_dir"]`` with the self-healing
        corrupt-candidate fallback (docs/RESILIENCE.md "Storage faults").

        A candidate that fails its checksum manifest is MARKED corrupt
        (the same quarantine marker the SDC audit drops — `find_candidates`
        then skips it forever, on every rank) and the resume payload is
        recomputed over the remaining candidates. Deterministic across
        survivors: everyone reads the same shared tree, refuses the same
        bytes, and lands on the same next-older save. Returns
        ``(state_or_None, resume)`` — None state means no usable candidate
        survived (the caller starts fresh, like an empty disk).
        """
        from tpu_dp.resilience import quarantine_save_dir

        while resume.get("snapshot_dir"):
            source = Path(resume["snapshot_dir"])
            try:
                state, _ = ckpt_lib.load_checkpoint(source, target)
                return state, resume
            except ckpt_lib.CorruptCheckpointError as e:
                _obs_counters.inc("ckpt.corrupt_candidates")
                quarantine_save_dir(source, f"checksum refusal: {e}")
                from tpu_dp.obs import flightrec

                flightrec.record("ckpt_corrupt_fallback",
                                 step=self._host_step, dir=str(source),
                                 leaves=list(e.leaves)[:8])
                log0("rollback restore: %s failed checksum verification "
                     "(%s) — marked corrupt, falling back to the "
                     "next-older complete candidate", source, e)
                resume = self._rollback_resume()
        return None, resume

    def _execute_guard_rollback(self, sig: _GuardRollback) -> tuple[int, int]:
        """Rewind to the newest complete, non-quarantined save and replay.

        The guard's auto-rollback (guard.action=rollback): every rank
        reaches the identical decision at the identical boundary (the
        policy consumes replicated values), so the rewind needs no
        coordination beyond agreeing on the resume source — local
        `_rollback_resume` where the checkpoint tree is shared (elastic /
        single process), rank-0-decides + broadcast otherwise (each host
        has its own disk; only rank 0's saves exist). Returns the
        ``(epoch, start_step)`` to continue from; the rolled-back steps'
        records are tombstoned and every later record carries the bumped
        ``rollback_generation``.
        """
        from_step = self._host_step
        hook = self._guard_hook
        # Budget check first: past max_rollbacks without progress this
        # raises DivergedError — a deterministic divergence replays
        # identically and rolling back into it forever is a livelock.
        hook.policy.on_rollback()
        if self.fault is not None:
            # The guard hook raises before the fault hook's disarm runs at
            # this boundary; without this, the replay would re-arm the
            # injected nan/spike seam and re-poison the very step being
            # rewound — an injected fault fires once per run, period.
            self.fault.disarm_device(from_step)
        log0("guard: rolling back from step %d — %s", from_step,
             sig.trigger.reason)
        if self.elastic is not None or self.ctx.process_count == 1:
            state, resume = self._load_rollback_state(
                self._rollback_resume(), self.state
            )
            if state is not None:
                self.state = self._place_state(state)
            else:
                self.state = self._fresh_state()
        else:
            from jax.experimental import multihost_utils

            # Non-elastic multi-process: no shared-filesystem requirement,
            # so the resume decision AND the restored state come from the
            # save writer (rank 0), like `_maybe_resume`.
            if self.ctx.process_index == 0:  # dplint: allow(DP101)
                state, resume = self._load_rollback_state(
                    self._rollback_resume(), self.state
                )
                if state is None:
                    state = self._fresh_state()
                pos = np.asarray([resume["epoch"], resume["steps_done"],
                                  resume["global_step"]], np.int32)
            else:
                state, pos = self.state, np.zeros(3, np.int32)
            host_state = jax.tree_util.tree_map(np.asarray, state)
            self.state = self._place_state(
                multihost_utils.broadcast_one_to_all(host_state)
            )
            pos = multihost_utils.broadcast_one_to_all(pos)
            resume = {"epoch": int(pos[0]), "steps_done": int(pos[1]),
                      "global_step": int(pos[2]), "lineage": []}
        self._host_step = int(resume.get("global_step", 0))
        self._epoch_done = int(resume.get("steps_done", 0))

        epoch = int(resume.get("epoch", 0))
        lineage = resume.get("lineage") or []
        if lineage:
            # The save predates (or spans) an elastic re-split: reinstall
            # the interrupted epoch's tail exactly like a regroup resume.
            has_tail = self._set_elastic_tail(epoch, lineage)
            position = (epoch, 0) if has_tail else (epoch + 1, 0)
        else:
            self._epoch_lineage = []
            self._elastic_tail = None
            position = (epoch, int(resume.get("steps_done", 0)))

        # Rewind bookkeeping: the generation bump + tombstone make the
        # rolled-back records identifiable (metrics sink, quarantine log,
        # heartbeats), and the cadence markers re-arm below the old
        # high-water step so the replay is snapshotted/beaten too.
        self._rollback_gen += 1
        # Same rewind contract as the snapshot/heartbeat/audit markers: the
        # publish marker must drop below the replay window, or the replayed
        # steps' codec overflow/clip counts — exactly the corruption signal
        # that may have caused this rollback — would be silently dropped.
        self._quant_pub_step = self._host_step
        if self.ctx.process_index == 0:  # dplint: allow(DP101) host-only IO
            hook.log.tombstone(
                from_step=from_step, to_step=self._host_step,
                reason=sig.trigger.reason,
            )
        hook.log.generation = self._rollback_gen
        if self.heartbeat is not None:
            self.heartbeat.rewind(self._host_step)
        self.snap_mgr.rewind(self._host_step)
        hook.on_rollback_rewind(self._host_step)
        if self.elastic is not None:
            # Same rewind contract for the ledger-poll cadence: its
            # crossing marker would otherwise sit at the pre-rollback
            # high-water step and suppress peer/suspect detection for the
            # whole replay window.
            self.elastic.rewind_poll(self._host_step)
        hook.arm_lr_ease(self._host_step)
        _obs_counters.inc("guard.rollbacks")
        from tpu_dp.obs import flightrec

        flightrec.record("guard_rollback", step=self._host_step,
                         from_step=from_step, to_step=self._host_step,
                         gen=self._rollback_gen,
                         reason=sig.trigger.reason)
        if self.spans is not None:
            self.spans.record_window(
                self._host_step, 1,
                {"guard_rollback": 0.0},
                gen=self._rollback_gen,
            )
        self._log_metrics({
            "event": "guard_rollback",
            "from_step": from_step,
            "to_step": self._host_step,
            "trigger": sig.trigger.reason,
            "resume_epoch": position[0],
            "resume_step": position[1],
        })
        log0("guard: rolled back %d step(s) — resuming at epoch %d step %d "
             "(global step %d, generation %d)",
             from_step - self._host_step, position[0], position[1],
             self._host_step, self._rollback_gen)
        return position

    def _execute_regroup(self, sig: _RegroupSignal) -> tuple[int, int]:
        """Re-form the mesh — shrink to the survivors or GROW to admit a
        joiner — and continue the run.

        The tentpole sequence (docs/RESILIENCE.md "Elastic world size"):
        publish/adopt the new membership record → abandon the old
        distributed context and re-`initialize` at the new world →
        rebuild pipelines and compiled programs against the re-formed
        mesh → reload the agreed state through the resharding
        `load_checkpoint` → re-split the interrupted epoch over the new
        world → re-verify the DP304 collective fingerprint — all before
        the first post-regroup step. Returns the ``(epoch, start_step)``
        to continue from. A grow whose joiner dies mid-handshake falls
        back to re-forming at world N from the same snapshot (bounded by
        the bootstrap timeout; no work lost, no rollback).
        """
        t0 = time.perf_counter()
        plan = sig.plan
        cfg = self.cfg
        if plan.flavor in ("graceful", "grow"):
            snap_dir = Path(self.snapshot_dir) / f"step_{self._host_step:010d}"
            resume = {
                "epoch": sig.epoch,
                "steps_done": sig.done,
                "lineage": [list(map(int, seg))
                            for seg in self._epoch_lineage]
                + [[self.ctx.process_count, self._segment_steps(sig.done)]],
                "global_step": self._host_step,
                "snapshot_dir": str(snap_dir),
            }
            if (self.elastic.sid == min(plan.incumbents or plan.survivors)
                    and not (snap_dir / "state.msgpack").exists()):
                # The final snapshot never landed (the writer died inside
                # its grace window): the new leader validates BEFORE
                # publishing, so every survivor follows one canonical
                # fallback instead of racing the filesystem.
                log0("elastic: final snapshot %s missing — falling back to "
                     "rollback resume", snap_dir)
                resume = self._rollback_resume()
        else:
            resume = self._rollback_resume()
        record = self.elastic.establish(plan, resume)
        if record.joined:
            # The grow gate: commit to the grown bootstrap only for
            # joiners that are demonstrably alive NOW. A coordination
            # connect with an absent party is not a catchable failure —
            # the client LOG(FATAL)s on rendezvous timeout — so "is the
            # joiner coming?" is answered on the ledger first: each
            # admitted joiner signals join_ready immediately before its
            # own connect; one that never signals within the bounded wait
            # is presumed dead mid-handshake and the incumbents re-form
            # at world N from the same snapshot (no wedge, no rollback).
            # ONE decider: the incumbent leader runs the wait and
            # publishes the verdict; everyone else follows the ledger —
            # per-incumbent timers would split the camps on a joiner that
            # signals inside the timers' skew window.
            from tpu_dp.resilience import ElasticError

            joined_sids = [int(j["sid"]) for j in record.joined]
            incumbents = [m for m in record.members
                          if m not in joined_sids]
            if self.elastic.sid == min(incumbents):
                missing = self.elastic.ledger.await_join_ready(
                    record.epoch, joined_sids,
                    timeout_s=cfg.resilience.regroup_timeout_s,
                )
                self.elastic.ledger.publish_grow_verdict(
                    record.epoch, commit=not missing,
                    reason=("" if not missing else
                            f"no join_ready from {missing}"),
                )
                commit = not missing
            else:
                verdict = self.elastic.ledger.await_grow_verdict(
                    record.epoch,
                    timeout_s=2 * cfg.resilience.regroup_timeout_s,
                )
                if verdict is None:
                    raise ElasticError(
                        f"grow e{record.epoch}: no verdict from the "
                        f"incumbent leader within "
                        f"{2 * cfg.resilience.regroup_timeout_s:.0f}s "
                        f"(leader died mid-grow)"
                    )
                commit = bool(verdict.get("commit"))
            if not commit:
                log0("elastic: admitted joiner(s) never signalled ready "
                     "within %.0fs — aborting the grow, re-forming at "
                     "world %d", cfg.resilience.regroup_timeout_s,
                     record.world - len(record.joined))
                record = self.elastic.establish_fallback(
                    record, reason="join handshake timeout (grow aborted)"
                )
        resume = record.resume  # the leader's payload is canonical
        old_world = self.ctx.process_count
        old_rank = self.ctx.process_index

        # Teardown of the old world: drop every reference into the old
        # backend (resident dataset, compiled loops, live state — the
        # agreed state is about to be reloaded from disk), then abandon
        # the old distributed context (graveyard semantics, see
        # `dist.abandon_distributed`) and bootstrap the new epoch's.
        self._resident_train = None
        self._resident_loops = {}
        self._elastic_tail = None
        self.state = None
        if self._comm_profiler is not None:
            # Stop an armed capture BEFORE the mesh it is tracing is torn
            # down; the driver itself is topology-bound (expected
            # schedule, wire report, local-device normalization) and is
            # rebuilt against the new mesh once the state is reloaded.
            self._comm_profiler.close()
            self._comm_profiler = None
        if self.heartbeat is not None:
            self.heartbeat.close()
        try:
            self.ctx = self.elastic.reinitialize(record)
        except Exception:
            if not record.joined:
                raise
            # The admitted joiner never completed the handshake (crashed
            # between its request and the coordination connect): every
            # incumbent's bootstrap timed out symmetrically. Re-form at
            # world N from the SAME resume payload — the grow quiesce's
            # snapshot — so the aborted grow costs the bounded timeout
            # and nothing else (no wedge, no rollback).
            log0("elastic: grow bootstrap at world %d failed — joiner "
                 "presumed dead mid-handshake; re-forming at world %d",
                 record.world, record.world - len(record.joined),
                 exc_info=True)
            record = self.elastic.establish_fallback(
                record, reason="join handshake timeout (grow aborted)"
            )
            resume = record.resume
            self.ctx = self.elastic.reinitialize(record)
        self.mesh = dist.data_mesh(
            num_devices=(
                self._devices_per_process * self.ctx.process_count
                if self._devices_per_process is not None else None
            )
        )
        self.num_devices = int(self.mesh.devices.size)
        self._build_pipelines()
        self._build_training()

        # Reload through the resharding path: the target carries the NEW
        # world's optimizer layout; `load_checkpoint` relays the saved
        # opt state onto it value-preserving (docs/PERF.md). A corrupt
        # agreed snapshot (checksum refusal) self-heals onto the
        # next-older complete candidate — every survivor reads the same
        # shared tree, refuses the same bytes, and recomputes the same
        # fallback resume, so the regroup stays in lockstep.
        target = self._fresh_state()
        state, resume = self._load_rollback_state(resume, target)
        if state is not None:
            # The restore yields host numpy; place it under the step's own
            # shardings (a numpy leaf behind a cross-process sharding is
            # rejected at dispatch, and the sharded-update opt state must
            # land distributed, not replicated).
            self.state = self._place_state(state)
        else:
            self.state = target  # nothing on disk: restart from init
        self._host_step = int(resume.get("global_step", 0))
        # The codec-stats publish marker rewinds with the step clock (a
        # rollback-flavor regroup replays below the old high-water mark).
        self._quant_pub_step = self._host_step
        # Program costs are per-topology (per-chip batch changed with the
        # world): re-register so post-regroup MFU/goodput gauges divide by
        # THIS mesh's cost, and the world-keyed alias tags the new shape.
        self._register_program_costs()
        # Comm-attribution driver re-keyed to this topology: the grown or
        # shrunk program's collective schedule, THIS world's wire report,
        # and the new local device count (the state is already reloaded,
        # so the wire report sees the real params).
        self._build_comm_profiler()

        # Re-split the interrupted epoch over the survivors: every
        # remaining sample visited exactly once (graceful), or the
        # rollback point's remainder re-run on the new world.
        epoch = int(resume.get("epoch", 0))
        lineage = resume.get("lineage") or []
        if lineage:
            has_tail = self._set_elastic_tail(epoch, lineage)
            position = (epoch, 0) if has_tail else (epoch + 1, 0)
        else:
            self._epoch_lineage = []
            position = (epoch, int(resume.get("steps_done", 0)))

        # Telemetry re-homing: heartbeat files are per-rank-per-epoch (a
        # reassigned dense rank must not append into another rank's
        # stream), the monitor follows the new world/leader.
        self._rebuild_observers(record)
        # Guardrail re-homing: the compiled checksum and the audit
        # baseline are topology-bound; the eviction flag (if this rank
        # survived an SDC regroup it was not the suspect) resets.
        self._guard_evict = False
        self._sdc_suspect_active = False
        if self._guard_hook is not None:
            self._guard_hook.on_regroup()

        # DP304 on the re-formed mesh, before the first post-regroup step:
        # a member about to run a different collective schedule fails
        # here, not as a deadlock at step one. The tag is keyed by BOTH
        # the membership epoch and the new world size, so the fingerprint
        # artifact names which mesh shape each verification covered.
        if cfg.resilience.elastic_verify_fingerprint:
            self._verify_step_fingerprint(
                tag=f"train_step@me{record.epoch}w{record.world}"
            )
        dist.membership_barrier(
            "regroup_ready", record.epoch,
            timeout_s=cfg.resilience.regroup_timeout_s,
        )

        dt = time.perf_counter() - t0
        joined = [int(j["sid"]) for j in record.joined]
        _obs_counters.inc("elastic.regroups")
        _obs_counters.inc("elastic.lost_ranks",
                          max(0, old_world - record.world))
        _obs_counters.inc("elastic.joined_ranks",
                          max(0, record.world - old_world))
        _obs_counters.inc("elastic.regroup_s", dt)
        from tpu_dp.obs import flightrec

        flightrec.record(
            "elastic_regroup", step=self._host_step,
            membership_epoch=record.epoch, flavor=plan.flavor,
            world=record.world,
            departed=[d.get("sid") for d in record.departed],
            joined=joined,
            regroup_s=round(dt, 3),
        )
        if joined:
            # The grow gets its own marker next to the generic regroup:
            # "capacity came back" is the signal operators grep for.
            flightrec.record(
                "elastic_grow", step=self._host_step,
                membership_epoch=record.epoch, world=record.world,
                joined=joined,
            )
        if self.spans is not None:
            self.spans.record_window(
                self._host_step, 1, {"elastic_regroup": dt * 1e3},
                gen=self._rollback_gen,
            )
        self._log_metrics({
            "event": "elastic_regroup",
            "membership_epoch": record.epoch,
            "flavor": plan.flavor,
            "world": record.world,
            "departed": [d["sid"] for d in record.departed],
            "joined": joined,
            "resume_epoch": position[0],
            "resume_step": position[1] or (
                self._elastic_tail.base if self._elastic_tail else 0
            ),
            "regroup_s": round(dt, 3),
        })
        if joined:
            self._log_metrics({
                "event": "elastic_grow",
                "membership_epoch": record.epoch,
                "world": record.world,
                "joined": joined,
            })
        log0(
            "elastic: membership epoch %d live — world %d→%d (rank %d→%d), "
            "%s resume at epoch %d step %d, regroup took %.2fs",
            record.epoch, old_world, record.world, old_rank,
            self.ctx.process_index, plan.flavor, position[0],
            (self._elastic_tail.base if self._elastic_tail else position[1]),
            dt,
        )
        return position

    def _place_state(self, state):
        """Device-place a host-restored TrainState under the current
        mesh + update-sharding layout (`train/step._state_shardings`)."""
        from tpu_dp.train.state import TrainState
        from tpu_dp.train.step import _state_shardings

        sh = _state_shardings(self.mesh, self.update_sharding)
        if isinstance(sh, TrainState):
            sh = TrainState(
                step=sh.step,
                params=jax.tree_util.tree_map(
                    lambda _: sh.params, state.params),
                opt_state=jax.tree_util.tree_map(
                    lambda _: sh.opt_state, state.opt_state),
                batch_stats=jax.tree_util.tree_map(
                    lambda _: sh.batch_stats, state.batch_stats),
                residuals=jax.tree_util.tree_map(
                    lambda _: sh.residuals, state.residuals),
            )
        else:
            sh = jax.tree_util.tree_map(lambda _: sh, state)
        return jax.device_put(state, sh)

    def _rebuild_observers(self, record) -> None:
        """Re-home heartbeats/health for a new membership epoch."""
        if self.obs_mode == "off":
            return
        from tpu_dp.obs import HealthMonitor, HeartbeatWriter

        run_dir = self.obs_dir / f"me{record.epoch:04d}"
        self.heartbeat = None
        self.health = None
        if self.cfg.obs.heartbeat_every_steps > 0:
            self.heartbeat = HeartbeatWriter(
                run_dir, rank=self.ctx.process_index,
                every_steps=self.cfg.obs.heartbeat_every_steps,
                me=record.epoch,
            )
        if self.heartbeat is not None and self.ctx.process_index == 0:  # dplint: allow(DP101) host-only monitor
            self.health = HealthMonitor(
                run_dir, world=self.ctx.process_count,
                straggler_factor=self.cfg.obs.straggler_factor,
                stale_after_s=self.cfg.obs.stale_after_s,
                min_step_ms=self.cfg.obs.min_step_ms,
                on_flag=self.cfg.obs.on_straggler,
            )
            # A freshly admitted joiner has no heartbeat history; this
            # monitor is constructed AT the admission, so its own startup
            # grace (`HealthMonitor._start`) is exactly the joiner's
            # admission grace — no per-rank bookkeeping needed here.
            # `HealthMonitor.admit` exists for monitors that OUTLIVE an
            # admission (out-of-band watchers over a growing world).
        if self._metrics_file is not None and self.ctx.process_index != 0:  # dplint: allow(DP101) host-only IO
            # A demoted rank 0 keeps the sink closed; the new rank 0's
            # `_log_metrics` appends to the same shared-filesystem file.
            try:
                self._metrics_file.close()
            except OSError:
                pass

    @property
    def metrics_path(self) -> Path:
        """The metrics.jsonl sink (train.metrics_path, defaulting to the
        historical <ckpt_dir>/metrics.jsonl)."""
        return Path(
            self.cfg.train.metrics_path
            or Path(self.cfg.train.ckpt_dir) / "metrics.jsonl"
        )

    def _log_metrics(self, record: dict) -> None:
        """Append a schema-3 JSON line to the metrics sink (process 0 only).

        Structured observability the reference lacks (its only records are
        stdout prints, SURVEY.md §5 "Metrics / logging"). Every record is
        stamped with a wall-clock ``ts`` (ISO-8601 UTC), the global
        optimizer ``step``, and ``schema: 3`` — schema 2 added the three
        stamps (v1 records carried none, so two runs' logs could not even
        be aligned in time); schema 3 adds the live efficiency fields
        (``mfu``/``goodput`` on per-step records, the ``efficiency``
        rollup on epoch records). Caller-provided fields win (per-step
        span records carry their own measured ts/step).
        """
        if self.ctx.process_index != 0:  # dplint: allow(DP101) host-only IO
            return
        rec = {"ts": _iso_ts(time.time()), "step": self._host_step,
               "schema": 3}
        if self._rollback_gen:
            # Rewind guard: post-rollback records name their generation so
            # consumers can drop the tombstoned (replayed-over) steps
            # instead of double-counting them (docs/OBSERVABILITY.md).
            rec["rollback_generation"] = self._rollback_gen
        if self.elastic is not None:
            # Every record carries the membership epoch, so a metrics
            # stream that spans a shrink explains its own discontinuities
            # (throughput, steps/epoch) without cross-referencing logs.
            rec["membership_epoch"] = self.elastic.record.epoch
        rec.update(record)
        if self._metrics_file is None or self._metrics_file.closed:
            # Opened once and held (append + flush per record): obs=full
            # writes one record per optimizer step, and a per-record
            # open/close on a shared filesystem would land in the very
            # step times being recorded. Closed in fit()'s finally;
            # post-fit records (the eval line) transparently reopen.
            path = self.metrics_path
            path.parent.mkdir(parents=True, exist_ok=True)
            self._metrics_file = open(path, "a")
        self._metrics_file.write(json.dumps(rec) + "\n")
        self._metrics_file.flush()

    def evaluate(self) -> dict[str, float]:
        """Global test accuracy/loss with ONE device→host fetch.

        The per-batch sums stay device-resident (each `+` is an async
        dispatch, never a sync) — on a high-RTT transport a per-batch
        `int(...)`/`float(...)` would make eval dispatch-bound, the exact
        host-sync pattern the train loop avoids.
        """
        correct = count = loss_sum = None
        for batch in self.test_pipe:
            m = self.eval_step(self.state, batch)
            batch_loss_sum = m["loss"] * m["count"]  # mean → sum, on device
            if correct is None:
                correct, count = m["correct"], m["count"]
                loss_sum = batch_loss_sum
            else:
                correct = correct + m["correct"]
                count = count + m["count"]
                loss_sum = loss_sum + batch_loss_sum
        if count is None:
            return {"accuracy": 0.0, "loss": 0.0}
        correct, count, loss_sum = jax.device_get((correct, count, loss_sum))
        n = max(int(count), 1)
        return {"accuracy": float(correct) / n, "loss": float(loss_sum) / n}

    def export_trace(self) -> Path | None:
        """Write the Perfetto/Chrome trace JSON for this rank's spans.

        Rank 0 only (one artifact per run dir; per-rank traces would need
        per-rank paths — `obs.export.merge_traces` exists for offline
        fan-in). Returns the path, or None when obs is off / not rank 0.
        """
        if self.spans is None:
            return None
        if self.ctx.process_index != 0:  # dplint: allow(DP101) host-only IO
            return None
        from tpu_dp.obs import export_perfetto

        path = Path(
            self.cfg.obs.perfetto_path
            or self.obs_dir / "trace.perfetto.json"
        )
        out = export_perfetto(
            path, self.spans.records(), rank=self.ctx.process_index,
            counter_points=[
                {"ts": time.time(), "counters": _obs_counters.snapshot()}
            ],
        )
        log0("perfetto trace: %s (%d step records) — open in "
             "chrome://tracing or ui.perfetto.dev", out, len(self.spans))
        return out

    def obs_summary(self) -> dict[str, Any] | None:
        """Span rollup + counter snapshot for end-of-run summaries
        (train.py's JSON line); None when obs is off."""
        if self.spans is None:
            return None
        out = {
            "mode": self.obs_mode,
            "spans_ms": self.spans.rollup(),
            "counters": _obs_counters.snapshot(),
        }
        if self._eff is not None:
            eff = self._eff.rollup()
            if eff is not None:
                out["efficiency"] = eff
        cp = self._comm_profiler
        if cp is not None and cp.last_report is not None:
            r = cp.last_report
            out["comm"] = {
                "windows": cp.reports,
                "comm_ms": r["comm_ms"],
                "exposed_comm_ms": r["exposed_comm_ms"],
                "overlap_frac": r["overlap_frac"],
                "reconciled": (r.get("reconciliation") or {}).get("ok"),
            }
        return out

    def fit(self) -> dict[str, Any]:
        cfg = self.cfg
        log0(
            "training %s on %s: %d device(s), %d process(es), "
            "global batch %d (%d/process), %d epochs",
            cfg.model.name, self.train_ds.name, self.num_devices,
            self.ctx.process_count, self.global_batch_size,
            cfg.data.batch_size, cfg.train.epochs,
        )
        t0 = time.perf_counter()
        history = []
        try:
            if self.preempt is not None:
                self.preempt.install()
            # Step-ranged profiling replaces the whole-run trace: both at
            # once would nest jax.profiler sessions (an error) and the
            # ranged trace exists precisely to avoid the whole-run one.
            whole_run_profile = (
                None if self._step_profiler is not None
                else cfg.train.profile_dir
            )
            with profile_trace(whole_run_profile):
                # Peer-death signatures that trigger a rollback regroup in
                # elastic mode (empty tuple otherwise: nothing is caught).
                fatal = (_elastic_fatal_errors()
                         if self.elastic is not None else ())
                epoch, start_step = self.start_epoch, self.start_step
                while epoch < cfg.train.epochs:
                    try:
                        stats = self.train_epoch(epoch, start_step=start_step)
                    except _RegroupSignal as sig:
                        # A survivor of a completed quiesce: shrink the
                        # mesh and continue — the regroup-aware fit loop.
                        epoch, start_step = self._execute_regroup(sig)
                        continue
                    except _GuardRollback as sig:
                        # The guard policy condemned the trajectory:
                        # rewind to the newest trusted save and replay
                        # (may raise DivergedError past the budget).
                        epoch, start_step = self._execute_guard_rollback(sig)
                        continue
                    except fatal as e:
                        try:
                            self._elastic_rollback(epoch, e)
                        except _RegroupSignal as sig:
                            epoch, start_step = self._execute_regroup(sig)
                        continue
                    history.append(stats)
                    log0("epoch %d: train loss %.4f acc %.4f (%.1f img/s)",
                         epoch + 1, stats["loss"], stats["accuracy"],
                         self.meter.images_per_sec)
                    epoch_rec = {"epoch": epoch + 1, **stats,
                                 "images_per_sec":
                                     round(self.meter.images_per_sec, 1)}
                    if self.spans is not None:
                        # Epoch rollup: span percentiles over the ring +
                        # the counter registry — the at-a-glance record
                        # (per-step records are obs=full only).
                        _obs_counters.gauge(
                            "throughput.images_per_sec",
                            round(self.meter.images_per_sec, 1),
                        )
                        from tpu_dp.obs import update_device_memory_gauges

                        update_device_memory_gauges()
                        epoch_rec["spans"] = self.spans.rollup()
                        if self._eff is not None:
                            # The window-level MFU/goodput/step-time
                            # rollup obsctl diff reads back post-hoc.
                            eff_roll = self._eff.rollup()
                            if eff_roll is not None:
                                epoch_rec["efficiency"] = eff_roll
                        epoch_rec["counters"] = _obs_counters.snapshot()
                    self._log_metrics(epoch_rec)
                    self._write_prom()
                    ckpt_meta = {"epoch": epoch, "config": cfg.to_dict(),
                                 "seed": cfg.train.seed}
                    if self.elastic is not None:
                        # Manifest stamp: which membership epoch/world
                        # finished this dataset epoch (no lineage — an
                        # epoch checkpoint resumes at a clean epoch start).
                        rec = self.elastic.record
                        ckpt_meta["membership"] = {
                            "epoch": rec.epoch,
                            "world": self.ctx.process_count,
                            "members": list(rec.members),
                        }
                    try:
                        self.ckpt_mgr.save(self.state, ckpt_meta)
                    except (RuntimeError, OSError) as e:
                        # Same degrade contract as the snapshot cadence
                        # (docs/RESILIENCE.md "Storage faults"): a full
                        # disk costs durability, loudly — never the run.
                        self._ckpt_write_error(e)
                    every = cfg.train.eval_every_epochs
                    if every and (epoch + 1) % every == 0:
                        ev = self.evaluate()
                        log0("epoch %d: eval loss %.4f acc %.4f",
                             epoch + 1, ev["loss"], ev["accuracy"])
                    if self.health is not None:
                        # End-of-epoch health pass: a rank that went quiet
                        # mid-epoch is flagged here even when log_every
                        # never fired (hang-dump sentinel first, as at the
                        # log boundary).
                        issues = self.health.check()
                        if self.flightrec is not None:
                            self.health.request_dump(
                                issues, dump_dir=self.flightrec.dump_dir)
                        self.health.report(issues)
                        self._suspect_from_health(issues)
                    # A signal that lands between epochs (or during eval)
                    # still gets the snapshot-and-exit-143 contract; in
                    # elastic mode the next epoch's first boundary runs
                    # the single-rank departure protocol instead.
                    if (self.elastic is None and self.preempt is not None
                            and self.preempt.requested):
                        self._preempt_exit(epoch + 1, 0)
                    # The epoch is fully consumed: its re-split tail and
                    # consumption lineage are history.
                    self._elastic_tail = None
                    self._epoch_lineage = []
                    epoch += 1
                    start_step = 0
        finally:
            # Join any in-flight async write even when training aborts —
            # the freshest checkpoint is exactly what a crash-restart
            # needs. A write failure surfacing here DEGRADES (counted +
            # logged + in the black box): it must neither mask a
            # propagating training error nor turn a completed run into a
            # disk-error exit (docs/RESILIENCE.md "Storage faults").
            import sys

            try:
                self.ckpt_mgr.close()
            except (RuntimeError, OSError) as e:
                # Degrade (counted, logged, in the black box): the run's
                # training outcome is already decided here, and replacing
                # it — or a propagating error — with a disk error would
                # turn "lost the LAST epoch checkpoint, resume falls back
                # one save" into a supervisor-visible job failure.
                self._ckpt_write_error(e)
            try:
                self.snap_mgr.close()
            except (RuntimeError, OSError):
                log0("snapshot write failed during teardown (degraded)",
                     exc_info=True)
            if self.preempt is not None:
                self.preempt.uninstall()
            # The black box, FIRST among the telemetry teardown: every
            # exit path out of fit() — clean, PreemptedError (SIGTERM via
            # the handler's boundary raise), DivergedError,
            # PeerFailedError, HealthError, any unhandled exception —
            # leaves flightrec_r<rank>.json, and it must land before any
            # later teardown step can fail and rob it. dump() never
            # raises (it logs); the reason names the in-flight exception
            # so obsctl's timeline shows WHY the rank exited.
            if self.flightrec is not None:
                exc = sys.exc_info()
                reason = "clean" if exc[0] is None else (
                    f"{exc[0].__name__}: {exc[1]}"[:500]
                )
                self.flightrec.record("exit", step=self._host_step,
                                      reason=reason)
                self.flightrec.dump(reason=reason)
            self._write_prom()
            # Telemetry teardown runs on EVERY exit path: a crashed or
            # preempted run is exactly when the trace matters. Each step
            # is guarded separately — a failed profiler flush (disk full,
            # deleted trace dir) must neither mask the original exception
            # nor rob the Perfetto export behind it.
            if self._step_profiler is not None:
                try:
                    self._step_profiler.close()
                except Exception:
                    log0("step-profiler close failed", exc_info=True)
            if self.heartbeat is not None:
                try:
                    self.heartbeat.close()
                except Exception:
                    log0("heartbeat close failed", exc_info=True)
            if self.spans is not None and len(self.spans):
                try:
                    self.export_trace()
                except Exception:
                    log0("perfetto export failed", exc_info=True)
            if self._metrics_file is not None:
                try:
                    self._metrics_file.close()
                except OSError:
                    log0("metrics sink close failed", exc_info=True)
            for hook in self._hooks:
                try:
                    hook.close()
                except Exception:
                    log0("step hook close failed", exc_info=True)
            if self.elastic is not None:
                # Every elastic exit path — leaver, survivor, crash — pins
                # the live coordination objects so interpreter teardown
                # can't abort a peer mid-exit (see `dist.park_distributed`).
                dist.park_distributed()
        print0("Finished Training")  # `cifar_example.py:90` parity
        wall = time.perf_counter() - t0

        # End-of-training weights export (`cifar_example.py:92-93` analogue).
        try:
            ckpt_lib.save_params(
                f"{cfg.train.ckpt_dir}/final_params.msgpack",
                self.state.params)
        except OSError as e:
            self._ckpt_write_error(e)

        result: dict[str, Any] = {
            "history": history,
            "wall_time_s": wall,
            "images_per_sec": self.meter.images_per_sec,
        }
        if cfg.train.eval_at_end:
            eval_stats = self.evaluate()
            result["eval"] = eval_stats
            self._log_metrics({"eval": eval_stats})
            # Reference integer-percent print (`cifar_example.py:111-112`).
            print0("Accuracy of the network on the %d test images: %d %%"
                   % (len(self.test_ds), int(100 * eval_stats["accuracy"])))
        return result


def run_elastic(cfg: Config) -> tuple[Trainer, dict[str, Any]]:
    """Drive `Trainer.fit` with the ``relaunch:`` fault's in-process rejoin.

    The deterministic twin of "the preempted rank comes back"
    (docs/RESILIENCE.md "Fault-injection spec"): a fired
    ``relaunch:step=K,rank=R`` departs exactly like ``leave:`` — the full
    single-rank elastic-departure protocol, survivors shrink to world N−1
    — but instead of surfacing the `PreemptedError` this driver builds a
    JOIN-mode Trainer in the same OS process (ledger discovery, fenced
    join request, admission, state restore from the agreed snapshot) and
    keeps training to completion at the regrown world. Every other
    `PreemptedError` propagates unchanged (train.py's exit-143 contract),
    as does a departure on a non-elastic run. One rejoin per call: a
    REAL preemption of the rejoined incarnation exits 143 like any other.
    """
    from tpu_dp.resilience import PreemptedError

    tr = Trainer(cfg)
    rejoined = False
    while True:
        try:
            return tr, tr.fit()
        except PreemptedError:
            fault = tr.fault
            if rejoined or not (
                fault is not None and fault.fired_kind("relaunch")
            ):
                raise
            rejoined = True
            log0("relaunch fault: departed at global step %d — rejoining "
                 "the run in-process", tr._host_step)
            import copy

            cfg2 = copy.deepcopy(cfg)
            cfg2.resilience.fault = ""
            cfg2.resilience.elastic_join = "always"
            cfg2.train.resume = False
            tr = Trainer(cfg2)
            if tr.fault is not None and tr.fault.has_kind("relaunch"):
                # A TPU_DP_FAULT env spec survives into the rejoined
                # incarnation (cfg2 cleared only the config field); the
                # plan already fired once this process — mark it spent so
                # the rejoined rank does not immediately leave again.
                tr.fault.spend("relaunch")
