"""Learning-rate schedules.

The reference has none — lr is a hardcoded constant 0.001
(`/root/reference/cifar_example.py:64`), with no warmup and no scaling with
world size (SURVEY.md §2A "Optimizer config"). BASELINE.json config 5 adds
"cosine LR at global batch 4096", so cosine-with-linear-warmup is provided as
a jit-traceable function of the step counter (pure jnp — schedules change no
compiled code, the lr is just a traced scalar).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def constant_lr(lr: float) -> Schedule:
    """The reference's schedule: lr forever (`cifar_example.py:64`)."""
    return lambda step: jnp.asarray(lr, jnp.float32)


def cosine_lr(
    base_lr: float,
    total_steps: int,
    warmup_steps: int = 0,
    final_lr: float = 0.0,
) -> Schedule:
    """Linear warmup 0→base over `warmup_steps`, cosine decay to `final_lr`."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = base_lr * step / jnp.maximum(1.0, warmup_steps)
        decay_steps = jnp.maximum(1.0, total_steps - warmup_steps)
        t = jnp.clip((step - warmup_steps) / decay_steps, 0.0, 1.0)
        cos = final_lr + 0.5 * (base_lr - final_lr) * (1.0 + jnp.cos(jnp.pi * t))
        return jnp.where(step < warmup_steps, warm, cos).astype(jnp.float32)

    return schedule


def make_schedule(
    name: str,
    base_lr: float,
    total_steps: int = 0,
    warmup_steps: int = 0,
    final_lr: float = 0.0,
) -> Schedule:
    if name == "constant":
        return constant_lr(base_lr)
    if name == "cosine":
        return cosine_lr(base_lr, total_steps, warmup_steps, final_lr)
    raise ValueError(f"unknown schedule {name!r}")
