"""Training loop layer: state, optimizer, schedules, compiled steps, Trainer.

TPU-native replacement of the reference's L4 layer
(`/root/reference/cifar_example.py:66-87`, `cifar_example_ddp.py:90-114`):
the eager zero_grad/forward/backward/step loop with DDP hook-based gradient
allreduce becomes ONE compiled XLA program per step — forward, backward,
cross-chip gradient mean, and the SGD update fused and scheduled together.
"""

from tpu_dp.train.optim import SGD, Optimizer, ShardedUpdate, shard_optimizer
from tpu_dp.train.schedule import constant_lr, cosine_lr, make_schedule
from tpu_dp.train.state import TrainState, create_train_state
from tpu_dp.train.step import (
    cross_entropy_loss,
    make_eval_step,
    make_local_step,
    make_multi_step,
    make_train_step,
    make_train_step_shard_map,
)
from tpu_dp.train.trainer import Trainer

__all__ = [
    "SGD",
    "Optimizer",
    "ShardedUpdate",
    "Trainer",
    "TrainState",
    "shard_optimizer",
    "constant_lr",
    "cosine_lr",
    "create_train_state",
    "cross_entropy_loss",
    "make_eval_step",
    "make_local_step",
    "make_multi_step",
    "make_schedule",
    "make_train_step",
    "make_train_step_shard_map",
]
