"""Train state: the complete pytree the compiled step transforms.

Bundles what the reference scatters across mutable Python objects —
`net.parameters()` (implicit in the module), SGD momentum buffers (inside
`optim.SGD`, `/root/reference/cifar_example.py:64`), and the step counter
(the loop index `i`, `cifar_example.py:69`) — into one immutable pytree, so
`state' = step(state, batch)` is a pure function XLA can compile and shard.
Checkpointing the whole training run (SURVEY.md §5 checkpoint gap) is then
just serializing this pytree.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray  # scalar int32
    params: Any
    opt_state: Any
    batch_stats: Any  # {} for models without BatchNorm (e.g. `Net`)
    # Error-feedback residuals of the int8 wire codec
    # (`train.collective_dtype=int8`; tpu_dp/parallel/quant.py): per
    # quantized leaf, each replica's pending rounding error —
    # f32[world, quant_padded_size], flat-sharded over the data axis like
    # the opt state. {} (zero leaves) everywhere the codec is off, so
    # every pre-existing program's pytree is unchanged.
    residuals: Any = flax.struct.field(default_factory=dict)

    @property
    def has_batch_stats(self) -> bool:
        return bool(self.batch_stats)


def create_train_state(
    model,
    rng: jax.Array,
    sample_input,
    optimizer,
) -> TrainState:
    """Initialize params (+ batch stats) and optimizer slots.

    Parameter init is deterministic in `rng` on every process, which gives
    the replica-consistent start DDP gets from its wrap-time parameter
    broadcast (`cifar_example_ddp.py:83`) — no broadcast needed when all
    replicas compute the same init.
    """
    variables = model.init(rng, sample_input, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        batch_stats=batch_stats,
    )
