"""SGD with momentum — exact update-rule parity with the reference.

The reference uses `optim.SGD(lr=0.001, momentum=0.9)` with no weight decay,
no dampening, no Nesterov (`/root/reference/cifar_example.py:64`,
`cifar_example_ddp.py:86`). Torch's update rule (which differs from the
classical velocity form) is:

    buf ← momentum·buf + grad          (buf starts as grad on step 0)
    p   ← p − lr·buf

Implemented here as a pure pytree transform (buffers zero-initialized:
momentum·0 + grad == grad on step 0, identical trajectory). Weight decay, when
enabled for the ResNet presets, is torch-style decoupled-from-schedule L2:
grad ← grad + wd·p before the momentum accumulation.

The learning rate is a traced scalar input, so LR schedules (BASELINE.json
config 5's cosine) change no compiled code.

`ShardedUpdate` wraps any such pytree optimizer into the cross-replica
*sharded* weight update of Xu et al. (PAPERS.md, `train.update_sharding=
sharded`): the step hands it reduce-scattered gradient shards, it slices the
matching 1/world parameter shards locally, runs the wrapped update on 1/world
of every leaf, and all-gathers only the updated parameters — optimizer state
(momentum, and any future slots) lives permanently sharded over the data
axis, cutting its per-replica memory to ~1/world and the update FLOPs with
it.
"""

from __future__ import annotations

from typing import Any, Protocol

import jax
import jax.numpy as jnp


class Optimizer(Protocol):
    def init(self, params) -> Any: ...
    def update(self, grads, opt_state, params, lr) -> tuple[Any, Any]: ...


def _is_no_decay_leaf(path) -> bool:
    """True for leaves conventionally excluded from weight decay: biases and
    normalization scales (BatchNorm parameters are named scale/bias in Flax;
    Dense/Conv biases are named bias). Matches the common high-accuracy
    ResNet recipe; torch's SGD decays everything, which stays the default."""
    last = path[-1]
    name = getattr(last, "key", getattr(last, "name", str(last)))
    return name in ("bias", "scale")


class SGD:
    """Torch-semantics SGD(momentum) as a stateless pytree transform."""

    def __init__(
        self,
        momentum: float = 0.9,
        weight_decay: float = 0.0,
        decay_exclude_bias_and_norm: bool = False,
    ):
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.decay_exclude_bias_and_norm = decay_exclude_bias_and_norm

    def init(self, params):
        if self.momentum == 0.0:
            return ()
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def update(self, grads, opt_state, params, lr):
        """Returns (new_params, new_opt_state)."""
        if self.weight_decay:
            if self.decay_exclude_bias_and_norm:
                grads = jax.tree_util.tree_map_with_path(
                    lambda path, g, p: g
                    if _is_no_decay_leaf(path)
                    else g + self.weight_decay * p,
                    grads,
                    params,
                )
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, p: g + self.weight_decay * p, grads, params
                )
        if self.momentum == 0.0:
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads
            )
            return new_params, opt_state
        new_buf = jax.tree_util.tree_map(
            lambda b, g: self.momentum * b + g, opt_state, grads
        )
        new_params = jax.tree_util.tree_map(
            lambda p, b: p - lr * b, params, new_buf
        )
        return new_params, new_buf


class ShardedUpdate:
    """Cross-replica sharded weight update over ``axis_name`` (Xu et al.).

    Wraps a pytree optimizer so the update runs on 1/world of every leaf:

        grad shards (from `collectives.psum_scatter`, flat 1-D)
          + param shards (local `collectives.shard_slice`, no comms)
          → inner.update on the shards
          → `collectives.all_gather` of the updated params only.

    Contract with the step factories (`train.step`): the gradients handed to
    ``update`` are *already* reduce-scattered flat shards — the reduce hook
    in `make_local_step(update_sharding="sharded")` produced them — while
    ``params`` are the full replicated leaves. ``opt_state`` is created by
    this class's ``init`` and is permanently shard-laid-out: each leaf is
    flat 1-D of `padded_size(n, world)` elements globally, sharded over the
    data axis (per-replica view inside `shard_map`: `shard_size(n, world)`
    elements — ~1/world of the replicated layout's memory).

    Weight decay and the decay-exclusion mask live in the wrapped optimizer
    and work unchanged: the shard trees preserve the param tree structure
    (`tree_map_with_path` sees the same key paths), and decay's
    ``g + wd·p`` is elementwise, so shard-wise == full-tensor.
    """

    is_sharded_update = True  # step-factory handshake (duck-typed)

    def __init__(self, inner: "Optimizer", world: int,
                 axis_name: str | None = None):
        from tpu_dp.parallel.dist import DATA_AXIS

        if world < 1:
            raise ValueError(f"world must be >= 1, got {world}")
        self.inner = inner
        self.world = int(world)
        self.axis_name = DATA_AXIS if axis_name is None else axis_name

    def init(self, params):
        """Shard-laid-out optimizer state: global view, host-side.

        Each inner-state leaf becomes flat 1-D of `padded_size(n, world)`
        zeros; jit's ``in_shardings`` (P over the data axis) slices it to
        `shard_size(n, world)` per replica. Runs on host (no axis bound), so
        it builds the *global* layout the per-shard program's out_specs
        stitch back together.
        """
        from tpu_dp.parallel.collectives import padded_size

        inner_state = self.inner.init(params)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros((padded_size(s.size, self.world),), s.dtype),
            inner_state,
        )

    def local_view(self, opt_state):
        """Per-replica slice of a global-layout ``opt_state`` (leaf[:n/w]).

        What one replica sees inside `shard_map` — used by the analyzers to
        trace the per-shard program outside a real shard_map scope, and by
        tests asserting the ~1/world memory claim.
        """
        return jax.tree_util.tree_map(
            lambda s: s[: s.size // self.world], opt_state
        )

    def update(self, grad_shards, opt_state, params, lr):
        """Per-shard update; returns (full new_params, sharded new state)."""
        from tpu_dp.parallel import collectives

        param_shards = collectives.shard_slice(
            params, self.axis_name, world=self.world
        )
        new_param_shards, new_opt_state = self.inner.update(
            grad_shards, opt_state, param_shards, lr
        )
        new_params = collectives.all_gather(
            new_param_shards, params, self.axis_name
        )
        return new_params, new_opt_state


def shard_optimizer(optimizer: "Optimizer", world: int,
                    axis_name: str | None = None) -> ShardedUpdate:
    """`ShardedUpdate` over ``optimizer``. World 1 is the same code path
    with degenerate (1-replica) collectives — one layout everywhere, so a
    sharded checkpoint written on one topology restores on any other."""
    return ShardedUpdate(optimizer, world, axis_name)
