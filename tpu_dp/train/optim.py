"""SGD with momentum — exact update-rule parity with the reference.

The reference uses `optim.SGD(lr=0.001, momentum=0.9)` with no weight decay,
no dampening, no Nesterov (`/root/reference/cifar_example.py:64`,
`cifar_example_ddp.py:86`). Torch's update rule (which differs from the
classical velocity form) is:

    buf ← momentum·buf + grad          (buf starts as grad on step 0)
    p   ← p − lr·buf

Implemented here as a pure pytree transform (buffers zero-initialized:
momentum·0 + grad == grad on step 0, identical trajectory). Weight decay, when
enabled for the ResNet presets, is torch-style decoupled-from-schedule L2:
grad ← grad + wd·p before the momentum accumulation.

The learning rate is a traced scalar input, so LR schedules (BASELINE.json
config 5's cosine) change no compiled code.
"""

from __future__ import annotations

from typing import Any, Protocol

import jax
import jax.numpy as jnp


class Optimizer(Protocol):
    def init(self, params) -> Any: ...
    def update(self, grads, opt_state, params, lr) -> tuple[Any, Any]: ...


def _is_no_decay_leaf(path) -> bool:
    """True for leaves conventionally excluded from weight decay: biases and
    normalization scales (BatchNorm parameters are named scale/bias in Flax;
    Dense/Conv biases are named bias). Matches the common high-accuracy
    ResNet recipe; torch's SGD decays everything, which stays the default."""
    last = path[-1]
    name = getattr(last, "key", getattr(last, "name", str(last)))
    return name in ("bias", "scale")


class SGD:
    """Torch-semantics SGD(momentum) as a stateless pytree transform."""

    def __init__(
        self,
        momentum: float = 0.9,
        weight_decay: float = 0.0,
        decay_exclude_bias_and_norm: bool = False,
    ):
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.decay_exclude_bias_and_norm = decay_exclude_bias_and_norm

    def init(self, params):
        if self.momentum == 0.0:
            return ()
        return jax.tree_util.tree_map(jnp.zeros_like, params)

    def update(self, grads, opt_state, params, lr):
        """Returns (new_params, new_opt_state)."""
        if self.weight_decay:
            if self.decay_exclude_bias_and_norm:
                grads = jax.tree_util.tree_map_with_path(
                    lambda path, g, p: g
                    if _is_no_decay_leaf(path)
                    else g + self.weight_decay * p,
                    grads,
                    params,
                )
            else:
                grads = jax.tree_util.tree_map(
                    lambda g, p: g + self.weight_decay * p, grads, params
                )
        if self.momentum == 0.0:
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads
            )
            return new_params, opt_state
        new_buf = jax.tree_util.tree_map(
            lambda b, g: self.momentum * b + g, opt_state, grads
        )
        new_params = jax.tree_util.tree_map(
            lambda p, b: p - lr * b, params, new_buf
        )
        return new_params, new_buf
