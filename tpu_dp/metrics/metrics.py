"""Accuracy and Mean metric accumulators with exact global semantics.

The reference's distributed metric is
`torchmetrics.Accuracy(dist_sync_on_step=True)`
(`/root/reference/cifar_example_ddp.py:124`): every `.update()` all-reduces
correct/total counts across ranks and `.compute()` yields the global top-1
(SURVEY.md §3.4 — and notes the per-step sync is wasteful by design).

TPU-native: the compiled train/eval steps already return *globally exact*
(correct, count) scalars — the cross-chip reduction over the sharded batch is
part of the XLA program — so the host-side accumulator below just sums
Python/NumPy scalars. That gives `dist_sync_on_step=True` accuracy semantics
with zero extra collectives per step, and exact weighted loss means (fixing
the reference's running-loss ÷2000-regardless-of-remainder quirk,
`cifar_example.py:86`, SURVEY.md §2A quirks — the parity-print path
reproduces the reference's formatting separately in the Trainer).
"""

from __future__ import annotations


class Accuracy:
    """Global top-1 accuracy from per-step (correct, count) scalars."""

    def __init__(self):
        self.correct = 0
        self.count = 0

    def update(self, correct, count) -> None:
        self.correct += int(correct)
        self.count += int(count)

    def compute(self) -> float:
        return self.correct / max(1, self.count)

    def reset(self) -> None:
        self.correct = 0
        self.count = 0


class Mean:
    """Weighted running mean (e.g. loss over examples)."""

    def __init__(self):
        self.total = 0.0
        self.count = 0

    def update(self, value, weight=1) -> None:
        self.total += float(value) * int(weight)
        self.count += int(weight)

    def compute(self) -> float:
        return self.total / max(1, self.count)

    def reset(self) -> None:
        self.total = 0.0
        self.count = 0
