"""Cross-replica-exact metrics.

Parity layer for `torchmetrics.Accuracy(dist_sync_on_step=True)`
(`/root/reference/cifar_example_ddp.py:124,133,136`) and the running-loss
meter (`cifar_example.py:83-87`).
"""

from tpu_dp.metrics.metrics import Accuracy, Mean

__all__ = ["Accuracy", "Mean"]
