"""Replica fan-out: N serving replicas behind one admission queue.

The self-healing serving tier (docs/SERVING.md "Replica fan-out"): a
`ServeCluster` splits the host's devices into ``replicas`` disjoint
meshes, runs one `ServeReplica` dispatch worker per mesh, and fronts them
all with a single SLO-class-aware `RequestQueue`. Routing is pull-based —
an idle replica takes the next batch, so load balance is emergent and a
slow replica naturally takes less — with the router owning every policy
decision the replicas themselves must not make:

- **health** — per-replica health derives from the same heartbeat files
  the trainer's `HealthMonitor` watches (`<run_dir>/obs/heartbeat_r<sid>`,
  one beat per dispatched batch): a replica whose heartbeat has gone
  stale *while it holds an in-flight batch* is *quarantined* — the
  router stops feeding it — and restored the moment it beats again
  (slow ≠ dead; its in-flight batch completes normally, so the books
  stay exact). Without a ``run_dir`` the same rule runs off the
  in-process in-flight clock.
- **failover with exactly-once accounting** — a replica whose dispatch
  *raises* is dead: its in-flight requests are re-queued onto a survivor
  (``serve.failover.retried``; admission is never re-counted) up to
  ``max_retries``, then shed with the typed reason ``replica_failed``.
  The claim guard on `RequestHandle` makes a double-resolution race
  structurally impossible, so the caller-vs-counter audit holds exactly
  through the failure.
- **elastic drain/rejoin** — `drain(sid)` (or SIGTERM via
  `install_sigterm_drain`, or an injected ``leave:`` fault) means
  drain-then-leave: the replica stops pulling, finishes its in-flight
  batch, and its departure is published as a serving-flavored membership
  epoch (`tpu_dp.resilience.elastic.ServeMembership` — the PR 7 ledger
  format, so ``obsctl timeline`` reconstructs it). Survivors absorb its
  share of the queue. `rejoin(sid)` restarts the worker on its still-
  compiled programs and still-resident weights — no restart, no
  recompile, no reload.
- **hot model swap** — `swap_model` / `swap_from_checkpoint` parks a new
  weight version on every replica; each applies it between batches, so
  zero requests are dropped and every response is stamped with the
  version that served it (flightrec ``model_swap``).

The cluster quacks like an `InferenceEngine` where it matters —
``submit`` / ``report`` / ``device_stats`` / ``queue`` / ``_counters`` —
so the load generator and its exactness audit drive both unchanged.
"""

from __future__ import annotations

import json
import signal
import threading
import time

import numpy as np

from tpu_dp.obs.counters import Counters, counters as _global_counters
from tpu_dp.obs.spans import SpanRecorder
from tpu_dp.serve.batcher import BucketLadder
from tpu_dp.serve.engine import (
    _load_swap_checkpoint, _resolve_checkpoint, register_serve_costs,
)
from tpu_dp.serve.queue import (
    SHED_CLOSED,
    SHED_REPLICA_FAILED,
    RequestHandle,
    RequestQueue,
    shed_counted,
)
from tpu_dp.serve.replica import LatencyBook, ServeReplica


class ServeCluster:
    """N `ServeReplica`s over disjoint device subsets, one shared queue."""

    def __init__(
        self,
        model,
        params,
        batch_stats=None,
        replicas: int = 2,
        devices=None,
        buckets=None,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        slo_ms: float = 50.0,
        shed_headroom_ms: float = 0.0,
        image_shape: tuple[int, int, int] = (32, 32, 3),
        image_dtype=np.uint8,
        num_classes: int | None = None,
        run_dir: str | None = None,
        span_capacity: int = 4096,
        on_retrace: str = "raise",
        fault: str = "",
        registry: Counters | None = None,
        model_name: str = "",
        flops_per_image: float | None = None,
        peak_flops: float | None = None,
        stale_after_s: float = 2.0,
        max_retries: int = 1,
        health_every_s: float = 0.05,
        class_slo_ms: dict[int, float] | None = None,
    ):
        import jax

        from tpu_dp.parallel import dist

        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        devices = list(jax.devices() if devices is None else devices)
        if len(devices) < replicas:
            raise ValueError(
                f"{replicas} replicas need at least {replicas} devices, "
                f"have {len(devices)}"
            )
        per = len(devices) // replicas  # trailing remainder devices unused
        self.model = model
        self.n_replicas = int(replicas)
        self.ladder = BucketLadder(
            buckets if buckets is not None else BucketLadder().buckets
        )
        self.slo_ms = float(slo_ms)
        self.class_slo_ms = dict(class_slo_ms or {})
        self.stale_after_s = float(stale_after_s)
        self.max_retries = int(max_retries)
        self.health_every_s = float(health_every_s)
        self._counters = _global_counters if registry is None else registry
        self.queue = RequestQueue(
            max_depth=max_queue,
            default_slo_ms=slo_ms,
            shed_headroom_ms=shed_headroom_ms,
            image_shape=image_shape,
            image_dtype=image_dtype,
            max_request=self.ladder.max_batch,
            registry=self._counters,
        )
        self.recorder = SpanRecorder(capacity=span_capacity)
        self.latency_book = LatencyBook(capacity=span_capacity)
        self._books_lock = threading.Lock()
        self._policy_lock = threading.Lock()  # failover/drain transitions
        self.model_version = 1
        self._errors: list[tuple[int, BaseException]] = []

        self.run_dir = None
        self.obs_dir = None
        self.membership = None
        self._monitor = None
        if run_dir:
            from pathlib import Path

            from tpu_dp.obs.health import HealthMonitor
            from tpu_dp.resilience.elastic import ServeMembership

            self.run_dir = Path(run_dir)
            self.obs_dir = self.run_dir / "obs"
            self.obs_dir.mkdir(parents=True, exist_ok=True)
            self.membership = ServeMembership(self.run_dir / "membership")
            self.membership.initial(range(self.n_replicas))
            self._monitor = HealthMonitor(
                self.obs_dir, world=self.n_replicas,
                stale_after_s=self.stale_after_s,
            )

        bucket_flops = register_serve_costs(
            self.ladder, max(1, per),
            model_name=model_name, flops_per_image=flops_per_image,
        )
        self.replicas: list[ServeReplica] = []
        for sid in range(self.n_replicas):
            hb = None
            if self.obs_dir is not None:
                from tpu_dp.obs.health import HeartbeatWriter

                hb = HeartbeatWriter(self.obs_dir, rank=sid)
            mesh = dist.data_mesh(devices=devices[sid * per:(sid + 1) * per])
            self.replicas.append(ServeReplica(
                sid=sid,
                model=model,
                params=params,
                batch_stats=batch_stats,
                mesh=mesh,
                ladder=self.ladder,
                queue=self.queue,
                recorder=self.recorder,
                latency_book=self.latency_book,
                books_lock=self._books_lock,
                max_wait_ms=max_wait_ms,
                num_classes=num_classes,
                on_retrace=on_retrace,
                fault=fault,
                hb=hb,
                router=self,
                model_version=self.model_version,
                peak_flops=peak_flops,
                bucket_flops=bucket_flops,
                registry=self._counters,
            ))
        self.num_classes = self.replicas[0].num_classes
        self.world = per * self.n_replicas

        # Fleet-stream registration (tpu_dp/obs/fleet.py): the health
        # loop appends one router record + per-replica records per tick
        # so a fleet aggregator can derive queue depth / attainment /
        # replica status across the tier from the files alone. Append
        # handles are opened once (one writer per file, like heartbeats).
        self._router_stream = None
        self._replica_streams: dict[int, object] = {}
        if self.obs_dir is not None:
            self._router_stream = open(
                self.obs_dir / "serve_router.jsonl", "a", encoding="utf-8")
            for sid in range(self.n_replicas):
                self._replica_streams[sid] = open(
                    self.obs_dir / f"replica_r{sid:05d}.jsonl", "a",
                    encoding="utf-8")

        self._health_thread: threading.Thread | None = None
        self._health_stop = threading.Event()
        self._sigterm_drain: list[int | None] = []  # set by signal handler
        self._prev_sigterm = None

    # -- router policy (called from replica threads) ---------------------

    def may_dispatch(self, sid: int) -> bool:
        """The feed gate: quarantined/draining replicas pull nothing."""
        r = self.replicas[sid]
        return not r.quarantined and not r.draining

    def begin_drain(self, sid: int, reason: str) -> None:
        """Ask ``sid`` to drain-then-leave (SIGTERM / ``leave:`` fault /
        operator). Idempotent; the departure is published when the
        replica actually leaves (`on_replica_drained`)."""
        r = self.replicas[sid]
        if not r.draining and r.status == "running":
            from tpu_dp.obs import flightrec

            flightrec.record("replica_drain_begin", replica=sid,
                             reason=reason)
            r.request_drain(reason)

    def on_replica_drained(self, sid: int, reason: str) -> None:
        """A draining replica finished its in-flight batch and left."""
        from tpu_dp.obs import flightrec

        with self._policy_lock:
            flightrec.record("replica_drain", replica=sid, reason=reason)
            if self.membership is not None:
                self.membership.depart(sid, reason or "preempted (graceful)")
            self._publish_live_gauge()
            self._maybe_flush_orphaned_queue(reason=SHED_CLOSED)

    def on_replica_error(self, sid: int, exc: BaseException,
                         pending: list) -> None:
        """Failover: retry a dead replica's in-flight on a survivor, or
        shed it typed — every request accounted, none double-served."""
        from tpu_dp.obs import flightrec

        with self._policy_lock:
            self._errors.append((sid, exc))
            flightrec.record("replica_failed", replica=sid,
                             error=f"{type(exc).__name__}: {exc}")
            if self.membership is not None:
                self.membership.depart(
                    sid, f"replica_failed: {type(exc).__name__}"
                )
            # A draining replica is not a survivor: it will never pull
            # again, so requeuing onto it would convert a replica failure
            # into a mislabelled `closed` shed at drain completion.
            # Quarantined replicas DO count — wedged is recoverable.
            survivors = any(
                r.sid != sid and r.status == "running" and not r.draining
                for r in self.replicas
            )
            retry = []
            for req in pending:
                if req.handle.done():
                    continue
                if survivors and req.retries < self.max_retries:
                    req.retries += 1
                    retry.append(req)
                else:
                    shed_counted(self._counters, req.handle,
                                 SHED_REPLICA_FAILED)
            if retry:
                self._counters.inc("serve.failover.retried", len(retry))
                self.queue.requeue(retry)
            self._publish_live_gauge()
            self._maybe_flush_orphaned_queue(reason=SHED_REPLICA_FAILED)

    def _publish_live_gauge(self) -> None:
        live = sum(1 for r in self.replicas if r.status == "running")
        self._counters.gauge("serve.replicas_live", live)

    def _maybe_flush_orphaned_queue(self, reason: str) -> None:
        """Nobody left to serve: close and shed everything typed —
        callers are unblocked, never abandoned (``replica_failed`` when
        the last replica died, ``closed`` when it drained away). A
        still-draining replica does not stay the flush: it pulls nothing
        more by definition."""
        if any(r.status == "running" and not r.draining
               for r in self.replicas):
            return
        self.queue.close()
        reqs, _ = self.queue.collect(self.ladder.max_batch * 10**6)
        for req in reqs:
            shed_counted(self._counters, req.handle, reason)

    # -- health loop -----------------------------------------------------

    def _stale_sids(self) -> set[int]:
        """Replica sids whose heartbeat machinery calls them stale/missing
        right now (file-based when run_dir is set, else the in-process
        in-flight clock — same threshold either way)."""
        if self._monitor is not None:
            try:
                return {
                    i.rank for i in self._monitor.check()
                    if i.kind in ("stale", "missing")
                }
            except Exception:
                return set()
        now = time.monotonic()
        out = set()
        for r in self.replicas:
            age = r.inflight_age(now)
            if age is not None and age > self.stale_after_s:
                out.add(r.sid)
        return out

    def health_tick(self) -> None:
        """One router health pass: quarantine wedged replicas, restore
        recovered ones, honor a SIGTERM drain request.

        Quarantine requires BOTH a stale heartbeat AND an in-flight batch
        older than the threshold: an *idle* replica beats only per batch,
        so its file goes quiet between bursts — quiet-and-empty is
        healthy, quiet-while-holding-work is wedged.
        """
        from tpu_dp.obs import flightrec

        while self._sigterm_drain:
            sid = self._sigterm_drain.pop()
            if sid is None:
                self.queue.close()  # graceful whole-tier drain
            else:
                self.begin_drain(int(sid), reason="preempted (SIGTERM)")
        stale = self._stale_sids()
        for r in self.replicas:
            if r.status != "running":
                continue
            age = r.inflight_age()
            wedged = (
                r.sid in stale and age is not None
                and age > self.stale_after_s
            )
            if wedged and not r.quarantined:
                r.quarantined = True
                self._counters.inc("serve.replica_quarantine_events")
                self._counters.gauge(f"serve.replica_health.{r.sid}", 0)
                flightrec.record(
                    "replica_quarantined", replica=r.sid,
                    inflight_s=round(age, 3),
                )
            elif r.quarantined and r.inflight_age() is None:
                r.quarantined = False
                self._counters.gauge(f"serve.replica_health.{r.sid}", 1)
                flightrec.record("replica_restored", replica=r.sid)
        self._publish_fleet_streams()

    def _publish_fleet_streams(self) -> None:
        """Append one router record + per-replica records for the fleet
        aggregator (`tpu_dp.obs.fleet.discover_streams` finds the files).

        Every failure is swallowed into ``fleet.publish_errors``: this
        runs on the health loop, which must keep quarantining wedged
        replicas even when the obs filesystem is full.

        Replica fields are read lock-free (GIL-atomic attribute loads),
        NEVER via ``r.snapshot()``: a wedged replica holds its ``_lock``
        across the device sync — the exact state this loop exists to
        detect — so contending on it here would stall the tick past the
        quarantine window. ``_books_lock`` is safe: its holds are brief
        post-sync bookkeeping, never spanning a device call."""
        if self._router_stream is None:
            return
        try:
            now = time.time()
            with self._books_lock:
                classes = self.latency_book.rollup(
                    self.class_slo_ms, self.slo_ms)
            live = sum(1 for r in self.replicas
                       if r.status == "running" and not r.quarantined)
            rec = {"kind": "router", "ts": now,
                   "queue_depth": len(self.queue),
                   "replicas_live": live, "classes": classes}
            self._router_stream.write(json.dumps(rec) + "\n")
            self._router_stream.flush()
            for r in self.replicas:
                f = self._replica_streams.get(r.sid)
                if f is None:
                    continue
                rep = {"kind": "replica", "sid": r.sid, "ts": now,
                       "status": r.status,
                       "batches": r._batch_index,
                       "quarantined": r.quarantined,
                       "model_version": r.model_version}
                f.write(json.dumps(rep) + "\n")
                f.flush()
        except Exception:
            self._counters.inc("fleet.publish_errors")

    def _health_loop(self) -> None:
        while not self._health_stop.wait(self.health_every_s):
            self.health_tick()

    # -- signals ---------------------------------------------------------

    def install_sigterm_drain(self, sid: int | None = None) -> None:
        """SIGTERM → drain-then-leave for replica ``sid`` (None: the whole
        tier stops admitting and drains out). The handler only records
        the request — the health loop acts on it, because a signal
        handler must never take the queue lock the interrupted thread
        might hold. Restore with `restore_sigterm`."""
        def _handler(signum, frame):
            from tpu_dp.obs import flightrec

            flightrec.record("preempt_signal", signum=int(signum),
                             scope="serve",
                             replica=-1 if sid is None else int(sid))
            self._counters.inc("preempt.signals")
            self._sigterm_drain.append(sid)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)

    def restore_sigterm(self) -> None:
        if self._prev_sigterm is not None:
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None

    # -- elastic membership (operator edge) ------------------------------

    def drain(self, sid: int, reason: str = "preempted (graceful)") -> None:
        """Drain-then-leave for replica ``sid`` (non-blocking)."""
        self.begin_drain(sid, reason)

    def rejoin(self, sid: int) -> None:
        """Bring a drained replica back into the feed set — on its
        still-compiled programs and still-resident weights, so the first
        post-rejoin batch is an ordinary dispatch, not a restart."""
        from tpu_dp.obs import flightrec

        r = self.replicas[sid]
        if r.status not in ("left", "stopped"):
            raise RuntimeError(
                f"replica {sid} is {r.status}; only a drained replica "
                f"rejoins (a dead one lost its donated stats buffers)"
            )
        # Status flips to "left" a few instructions before the old worker
        # thread actually returns — join it, or start() races it.
        r.join(timeout=10.0)
        with self._policy_lock:
            # A swap published while the replica was away still applies:
            # the pending state survives in the replica and is swapped in
            # before its first post-rejoin batch.
            r.quarantined = False
            r.start()
            if self.membership is not None:
                self.membership.rejoin(sid)
            flightrec.record("replica_rejoin", replica=sid)
            self._publish_live_gauge()
            self._counters.gauge(f"serve.replica_health.{sid}", 1)

    # -- hot swap --------------------------------------------------------

    def swap_model(self, params, batch_stats=None,
                   version: int | None = None) -> int:
        """Park a new weight version on every replica (left ones
        included — a rejoiner must serve the current version); each
        applies it between batches. Zero dropped requests; responses
        stamped with the serving version."""
        from tpu_dp.obs import flightrec

        self.model_version = (self.model_version + 1
                              if version is None else int(version))
        for r in self.replicas:
            r.set_pending_state(params, batch_stats, self.model_version)
        self._counters.gauge("serve.model_version", self.model_version)
        flightrec.record("model_swap", version=self.model_version,
                         replica=-1, scope="cluster")
        return self.model_version

    def swap_from_checkpoint(self, ckpt_dir,
                             version: int | None = None) -> int:
        """`swap_model` from a training checkpoint (params-only load)."""
        params, batch_stats, _ = _load_swap_checkpoint(
            ckpt_dir, self.model, self.queue.image_shape
        )
        return self.swap_model(params, batch_stats, version=version)

    # -- lifecycle -------------------------------------------------------

    def start(self, warmup: bool = True) -> "ServeCluster":
        """Warm every replica's bucket programs, launch the workers and
        the health loop."""
        for r in self.replicas:
            if warmup:
                r.warmup()
            r.start()
        self._publish_live_gauge()
        for r in self.replicas:
            self._counters.gauge(f"serve.replica_health.{r.sid}", 1)
        self._health_stop.clear()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="tpu_dp-serve-health", daemon=True
        )
        self._health_thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admission; drain (default) or abandon; join everything.

        Raises only when the WHOLE tier failed (every replica dead) —
        individual replica deaths were already failed over, accounted
        with typed sheds, and are reported in `report()['replicas']` /
        ``replica_errors``.
        """
        self.queue.close()
        if not drain:
            for r in self.replicas:
                r.stop_now()
        for r in self.replicas:
            r.join()
        if not drain:
            reqs, _ = self.queue.collect(self.ladder.max_batch * 10**6)
            for req in reqs:
                shed_counted(self._counters, req.handle, SHED_CLOSED)
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join()
            self._health_thread = None
        self.restore_sigterm()
        for r in self.replicas:
            if r._hb is not None:
                r._hb.close()
        for f in ([self._router_stream] if self._router_stream else []) + \
                list(self._replica_streams.values()):
            try:
                f.close()
            except OSError:
                pass
        self._router_stream = None
        self._replica_streams = {}
        if self._errors and not any(
            r.status in ("running", "stopped", "left") for r in self.replicas
        ):
            raise RuntimeError(
                f"all {self.n_replicas} serve replicas failed"
            ) from self._errors[-1][1]

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- producer API ----------------------------------------------------

    def submit(self, images, slo_ms: float | None = None,
               slo_class: int = 0) -> RequestHandle:
        """Enqueue one request (see `RequestQueue.submit`); may shed."""
        if slo_ms is None:
            slo_ms = self.class_slo_ms.get(int(slo_class))
        return self.queue.submit(images, slo_ms=slo_ms, slo_class=slo_class)

    # -- reporting -------------------------------------------------------

    @property
    def retraces(self) -> int:
        return sum(r.retraces for r in self.replicas)

    def guard_stats(self) -> list[dict]:
        return [
            dict(g, replica=r.sid)
            for r in self.replicas for g in r.guard_stats()
        ]

    def device_stats(self) -> dict:
        """Cluster device-side ground truth: per-replica donated stats,
        summed. ``served`` counts every real image exactly once ACROSS
        replicas — the zero-double-serve audit is this sum against the
        caller's books."""
        per = {r.sid: r.device_stats() for r in self.replicas}
        counts = [0] * self.num_classes
        for stats in per.values():
            for i, c in enumerate(stats.get("class_counts") or ()):
                counts[i] += c
        return {
            "served": sum(s["served"] for s in per.values()),
            "class_counts": counts,
            "per_replica": per,
            "unreadable": sorted(
                sid for sid, s in per.items() if s.get("unreadable")
            ),
        }

    def report(self) -> dict:
        """The engine report shape plus the fan-out story: per-replica
        status/batches, per-class attainment, membership epoch, versions."""
        from tpu_dp.serve.replica import serve_report_core

        out = serve_report_core(
            self.recorder, self.latency_book, self._books_lock,
            self.class_slo_ms, self.slo_ms, self._counters,
        )
        replicas = {str(r.sid): r.snapshot() for r in self.replicas}
        buckets: dict[int, int] = {}
        for r in replicas.values():
            for b, n in r["bucket_counts"].items():
                buckets[b] = buckets.get(b, 0) + n
        out.update({
            "batches": sum(r["batches"] for r in replicas.values()),
            "bucket_counts": dict(sorted(buckets.items())),
            "retraces": self.retraces,
            "guards": self.guard_stats(),
            "device_stats": self.device_stats(),
            "replicas": replicas,
            "replica_errors": [
                {"sid": sid, "error": f"{type(e).__name__}: {e}"}
                for sid, e in self._errors
            ],
            "membership_epoch": (
                self.membership.current().epoch
                if self.membership is not None else None
            ),
            "model_version": self.model_version,
            "world": self.world,
        })
        return out

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_serve_config(cls, model, params, serve_cfg, **kwargs):
        """Build from a `tpu_dp.config.ServeConfig` section."""
        from tpu_dp.config import parse_class_slo_ms
        from tpu_dp.serve.batcher import parse_buckets

        return cls(
            model, params,
            replicas=serve_cfg.replicas,
            buckets=parse_buckets(serve_cfg.buckets),
            max_wait_ms=serve_cfg.max_wait_ms,
            max_queue=serve_cfg.max_queue,
            slo_ms=serve_cfg.slo_ms,
            shed_headroom_ms=serve_cfg.shed_headroom_ms,
            run_dir=serve_cfg.run_dir or None,
            stale_after_s=serve_cfg.stale_after_s,
            max_retries=serve_cfg.max_retries,
            class_slo_ms=parse_class_slo_ms(serve_cfg.class_slo_ms),
            **kwargs,
        )

    @classmethod
    def from_checkpoint(cls, ckpt_dir, model=None, **kwargs):
        """Serve a training checkpoint across replicas, params-only."""
        model, params, batch_stats, name = _resolve_checkpoint(
            ckpt_dir, model, kwargs.get("image_shape", (32, 32, 3))
        )
        if name:
            kwargs.setdefault("model_name", name)
        return cls(model, params, batch_stats=batch_stats, **kwargs)
