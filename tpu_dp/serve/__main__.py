"""`python -m tpu_dp.serve` — the synthetic-load serving smoke + chaos
scenario driver.

Drives a freshly-initialized (or checkpointed) model through the full
serve pipeline on the current backend — on CPU it forces the 8-virtual-
device mesh, the same harness the tests use — and prints the audited
report JSON. With ``--replicas N`` the run goes through the self-healing
tier (`ServeCluster`): N replicas over disjoint device subsets, failover,
elastic drain/rejoin, hot swap and SLO classes, all scriptable mid-load:

    --fault "delay:step=3,ms=500,rank=0;leave:step=5,rank=1"
    --drain-at 40:1 --rejoin-at 160:1 --swap-at 120
    --class-mix 0.6,0.4 --class-slo-ms 250,800 --floors 0:0.9
    --run-dir DIR        # heartbeats + membership ledger + flightrec dump
                         # → `obsctl timeline DIR` rebuilds the story

SIGTERM during the run means drain-then-leave for ``--sigterm-drains SID``
(default: the whole tier stops admitting and drains out — typed `closed`
sheds, never dropped requests).

Exit code is the verdict:

- 0: every request accounted for, loadgen ground truth == serve counters
  exactly (per class included), zero post-warmup retraces, and every
  ``--floors`` class met its attainment floor;
- 1: the run completed but the audit failed (inconsistent books, a
  retrace, or a class below its floor — a serving-robustness regression);
- 2: usage error.

`tools/run_tier1.sh --serve` runs the single-replica smoke at 200
requests (artifacts/serve_report.json); ``--serve-elastic`` runs the
2-replica chaos matrix (artifacts/serve_elastic_report.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _parse_at_sid(spec: str, flag: str) -> tuple[int, int]:
    try:
        at, _, sid = spec.partition(":")
        return int(at), int(sid)
    except ValueError:
        raise ValueError(f"{flag} takes INDEX:SID, got {spec!r}") from None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dp.serve",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--pattern", default="poisson",
                    choices=["poisson", "burst", "diurnal"])
    ap.add_argument("--rate-rps", type=float, default=400.0)
    ap.add_argument("--burst", type=int, default=8)
    ap.add_argument("--sizes", default="1,2,3,4",
                    help="request image-count choices (mixed-size traffic)")
    ap.add_argument("--buckets", default="1,2,4,8,16,32",
                    help="padded batch-size ladder")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="per-request latency target (generous on CPU)")
    ap.add_argument("--model", default="net")
    ap.add_argument("--ckpt", default=None,
                    help="serve params from this checkpoint dir "
                         "(from_checkpoint, params-only) instead of a "
                         "fresh init")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here")
    # -- the self-healing tier (docs/SERVING.md "Replica fan-out") -------
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--run-dir", default=None,
                    help="serving artifact root: heartbeats, membership "
                         "ledger, flight-recorder dump (obsctl's input)")
    ap.add_argument("--fault", default="",
                    help="';'-separated deterministic fault specs, rank = "
                         "replica sid (e.g. 'delay:step=3,ms=500,rank=0;"
                         "leave:step=5,rank=1')")
    ap.add_argument("--stale-after-s", type=float, default=2.0)
    ap.add_argument("--max-retries", type=int, default=1)
    ap.add_argument("--class-mix", default=None,
                    help="SLO-class probability mix, class 0 first "
                         "(e.g. '0.6,0.3,0.1')")
    ap.add_argument("--class-slo-ms", default="",
                    help="per-class latency targets, class 0 first")
    ap.add_argument("--floors", default="",
                    help="per-class attainment floors 'cls:frac,...' — "
                         "exit 1 when missed")
    ap.add_argument("--swap-at", type=int, default=None,
                    help="hot-swap the model weights before this request "
                         "index (a fresh seed+1 init, or --swap-ckpt)")
    ap.add_argument("--swap-ckpt", default=None,
                    help="checkpoint dir the --swap-at swap loads "
                         "(params-only)")
    ap.add_argument("--drain-at", default=None, metavar="INDEX:SID",
                    help="drain-then-leave replica SID before request INDEX")
    ap.add_argument("--rejoin-at", default=None, metavar="INDEX:SID",
                    help="rejoin replica SID before request INDEX (waits "
                         "briefly for its drain to finish)")
    ap.add_argument("--sigterm-drains", type=int, default=None,
                    help="SIGTERM drains this replica sid instead of the "
                         "whole tier")
    ap.add_argument("--profile", default=None,
                    help="apply a tpu_dp.tune tuned.json: fills the "
                         "serving ladder knobs (--buckets, --max-wait-ms) "
                         "and the model (from the profile key's workload) "
                         "that were NOT given explicitly — explicit flags "
                         "win; a (workload, devices, backend) key mismatch "
                         "is a refusal (exit 2), never a silent fallback")
    args = ap.parse_args(argv)

    profile = None
    if args.profile is not None:
        from tpu_dp.tune.profile import ProfileError, load_profile

        try:
            profile = load_profile(args.profile)
        except ProfileError as e:
            print(f"serve: {e}", file=sys.stderr)
            return 2
        explicit = {a.split("=", 1)[0]
                    for a in (sys.argv[1:] if argv is None else argv)
                    if a.startswith("--")}
        knobs = profile["config"]
        if "--buckets" not in explicit and knobs.get("serve.buckets"):
            args.buckets = str(knobs["serve.buckets"])
        if "--max-wait-ms" not in explicit and "serve.max_wait_ms" in knobs:
            args.max_wait_ms = float(knobs["serve.max_wait_ms"])
        if "--model" not in explicit:
            args.model = str(profile["key"]["workload"])

    # Backend pinning BEFORE jax imports: the smoke must exercise the
    # multi-replica fan-out, so on CPU expose 8 virtual devices (the
    # tests' harness, tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    if profile is not None:
        # The ladder was tuned for a (workload, mesh, backend); serving a
        # different one under its numbers is the lie --profile refuses.
        from tpu_dp.tune.profile import ProfileMismatchError, check_key

        try:
            check_key(profile, workload=args.model,
                      devices=len(jax.devices()),
                      backend=jax.default_backend(),
                      where="this serve run")
        except ProfileMismatchError as e:
            print(f"serve: --profile {args.profile}: {e}", file=sys.stderr)
            return 2

    import numpy as np

    from tpu_dp.config import parse_class_floors, parse_class_slo_ms
    from tpu_dp.models import build_model
    from tpu_dp.serve import (
        InferenceEngine, ServeCluster, parse_buckets, run_load,
    )

    try:
        buckets = parse_buckets(args.buckets)
        sizes = tuple(int(s) for s in args.sizes.split(",") if s.strip())
        class_slo_ms = parse_class_slo_ms(args.class_slo_ms)
        floors = parse_class_floors(args.floors)
        class_mix = (
            None if args.class_mix is None
            else tuple(float(m) for m in args.class_mix.split(","))
        )
        drain_at = (None if args.drain_at is None
                    else _parse_at_sid(args.drain_at, "--drain-at"))
        rejoin_at = (None if args.rejoin_at is None
                     else _parse_at_sid(args.rejoin_at, "--rejoin-at"))
        if args.replicas < 1:
            raise ValueError(f"--replicas must be >= 1, got {args.replicas}")
        cluster_only = [
            name for name, val in (
                ("--drain-at", drain_at), ("--rejoin-at", rejoin_at),
                ("--run-dir", args.run_dir),
                ("--sigterm-drains", args.sigterm_drains),
            ) if val is not None
        ]
        if args.replicas == 1 and cluster_only:
            raise ValueError(
                f"{', '.join(cluster_only)} need --replicas >= 2"
            )
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2

    common = dict(
        buckets=buckets,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        slo_ms=args.slo_ms,
        class_slo_ms=class_slo_ms,
    )
    cluster_kw = dict(
        replicas=args.replicas,
        run_dir=args.run_dir,
        fault=args.fault,
        stale_after_s=args.stale_after_s,
        max_retries=args.max_retries,
    )
    multi = args.replicas > 1
    if args.ckpt:
        if multi:
            engine = ServeCluster.from_checkpoint(
                args.ckpt, **common, **cluster_kw
            )
        else:
            engine = InferenceEngine.from_checkpoint(
                args.ckpt, fault=args.fault, **common
            )
    else:
        model = build_model(args.model)
        variables = model.init(
            jax.random.PRNGKey(args.seed),
            np.zeros((1, 32, 32, 3), np.float32),
            train=False,
        )
        init_kw = dict(
            batch_stats=variables.get("batch_stats") or None,
            model_name=args.model,
        )
        if multi:
            engine = ServeCluster(model, variables["params"],
                                  **init_kw, **common, **cluster_kw)
        else:
            engine = InferenceEngine(model, variables["params"],
                                     fault=args.fault, **init_kw, **common)

    # The flight recorder + final dump are CLI-owned (not the cluster's):
    # a library embedder may share the process-wide recorder with a
    # trainer, and redirecting its dump dir behind their back would
    # misfile the trainer's black box.
    recorder = None
    if args.run_dir:
        from tpu_dp.obs import flightrec

        recorder = flightrec.recorder
        recorder.configure(
            rank=0, dump_dir=os.path.join(args.run_dir, "obs"), fresh=True,
            run={"kind": "serve", "replicas": args.replicas,
                 "model": args.model},
        )

    def _swap():
        if args.swap_ckpt:
            engine.swap_from_checkpoint(args.swap_ckpt)
            return
        fresh = build_model(args.model).init(
            jax.random.PRNGKey(args.seed + 1),
            np.zeros((1, 32, 32, 3), np.float32),
            train=False,
        )
        engine.swap_model(fresh["params"],
                          fresh.get("batch_stats") or None)

    def _rejoin(sid):
        # Wait briefly for the drain (scripted or fault-injected) to
        # land: rejoining a still-running replica is a scenario bug.
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if engine.replicas[sid].status in ("left", "stopped"):
                engine.rejoin(sid)
                return
            time.sleep(0.02)
        print(f"serve: replica {sid} never drained; rejoin skipped",
              file=sys.stderr)

    events = []
    if drain_at is not None:
        at, sid = drain_at
        events.append((at, f"drain:{sid}", lambda s=sid: engine.drain(s)))
    if rejoin_at is not None:
        at, sid = rejoin_at
        events.append((at, f"rejoin:{sid}", lambda s=sid: _rejoin(s)))
    if args.swap_at is not None:
        events.append((args.swap_at, "swap", _swap))

    if multi:
        engine.install_sigterm_drain(args.sigterm_drains)
    engine.start()
    try:
        report = run_load(
            engine,
            n_requests=args.requests,
            pattern=args.pattern,
            rate_rps=args.rate_rps,
            sizes=sizes,
            burst=args.burst,
            seed=args.seed,
            class_mix=class_mix,
            class_slo_ms=class_slo_ms,
            events=events,
        )
    finally:
        engine.stop()
        if recorder is not None:
            recorder.dump(reason="serve_exit")

    floor_misses = []
    for cls, floor in sorted(floors.items()):
        got = (report["classes"].get(str(cls)) or {}).get("attainment")
        if got is None or got < floor:
            floor_misses.append(
                {"class": cls, "floor": floor, "attainment": got}
            )
    ok = (report["consistent"] and report["retraces"] == 0
          and not floor_misses)
    report["verdict"] = {
        "ok": bool(ok),
        "consistent": report["consistent"],
        "retraces": report["retraces"],
        "floors": {str(c): f for c, f in sorted(floors.items())},
        "floor_misses": floor_misses,
    }

    payload = json.dumps(report, indent=2, sort_keys=True)
    print(payload)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")

    if not ok:
        print(
            f"serve: AUDIT FAILED — consistent={report['consistent']} "
            f"retraces={report['retraces']} floor_misses={floor_misses}",
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
