"""`python -m tpu_dp.serve` — the synthetic-load serving smoke.

Drives a freshly-initialized (or checkpointed) model through the full
serve pipeline on the current backend — on CPU it forces the 8-virtual-
device mesh, the same harness the tests use — and prints the audited
report JSON. Exit code is the verdict:

- 0: every request accounted for, loadgen ground truth == serve counters
  exactly, and zero post-warmup retraces;
- 1: the run completed but the audit failed (inconsistent books or a
  retrace — a serving-correctness regression);
- 2: usage error.

`tools/run_tier1.sh --serve` runs this at 200 requests and archives the
report as ``artifacts/serve_report.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dp.serve",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--pattern", default="poisson",
                    choices=["poisson", "burst"])
    ap.add_argument("--rate-rps", type=float, default=400.0)
    ap.add_argument("--burst", type=int, default=8)
    ap.add_argument("--sizes", default="1,2,3,4",
                    help="request image-count choices (mixed-size traffic)")
    ap.add_argument("--buckets", default="1,2,4,8,16,32",
                    help="padded batch-size ladder")
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--max-queue", type=int, default=256)
    ap.add_argument("--slo-ms", type=float, default=250.0,
                    help="per-request latency target (generous on CPU)")
    ap.add_argument("--model", default="net")
    ap.add_argument("--ckpt", default=None,
                    help="serve params from this checkpoint dir "
                         "(InferenceEngine.from_checkpoint) instead of a "
                         "fresh init")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=None,
                    help="also write the report JSON here")
    args = ap.parse_args(argv)

    # Backend pinning BEFORE jax imports: the smoke must exercise the
    # multi-replica fan-out, so on CPU expose 8 virtual devices (the
    # tests' harness, tests/conftest.py).
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from tpu_dp.models import build_model
    from tpu_dp.serve import InferenceEngine, parse_buckets, run_load

    try:
        buckets = parse_buckets(args.buckets)
        sizes = tuple(int(s) for s in args.sizes.split(",") if s.strip())
    except ValueError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 2

    common = dict(
        buckets=buckets,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        slo_ms=args.slo_ms,
    )
    if args.ckpt:
        engine = InferenceEngine.from_checkpoint(args.ckpt, **common)
    else:
        model = build_model(args.model)
        variables = model.init(
            jax.random.PRNGKey(args.seed),
            np.zeros((1, 32, 32, 3), np.float32),
            train=False,
        )
        engine = InferenceEngine(
            model, variables["params"],
            batch_stats=variables.get("batch_stats") or None,
            model_name=args.model,
            **common,
        )

    engine.start()
    try:
        report = run_load(
            engine,
            n_requests=args.requests,
            pattern=args.pattern,
            rate_rps=args.rate_rps,
            sizes=sizes,
            burst=args.burst,
            seed=args.seed,
        )
    finally:
        engine.stop()

    payload = json.dumps(report, indent=2, sort_keys=True)
    print(payload)
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(payload + "\n")

    ok = report["consistent"] and report["retraces"] == 0
    if not ok:
        print(
            f"serve: AUDIT FAILED — consistent={report['consistent']} "
            f"retraces={report['retraces']}",
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
