"""Shape-bucketed dynamic batching: coalesce requests into padded buckets.

The batching-vs-latency trade-off (the Gemma-on-TPU serving comparison,
PAPERS.md): bigger batches amortize dispatch and win throughput, but every
millisecond spent waiting for batch-mates is a millisecond of user-visible
latency. The batcher resolves it with two triggers — dispatch as soon as
the pending work fills the *largest* bucket (nothing to wait for), or when
the oldest pending request has waited ``max_wait_ms`` (no request pays
more than the cap to help its batch-mates).

The **bucket ladder** is the recompilation contract: every formed batch is
zero-padded up to a size from a fixed ascending ladder (1/2/4/…/max), so
the engine's per-bucket pre-compiled programs (`make_serve_step`) cover
every batch that can ever exist and the RecompileGuard stays silent — the
serving analogue of the fixed-shape discipline the training stack enforces
(docs/ANALYSIS.md DP305). Padded rows carry ``weight=0`` so they never
leak into results or the device-side stats.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from tpu_dp.serve.queue import Request, RequestQueue

#: the default ladder — powers of two up to 32 (ServeConfig.buckets)
DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def parse_buckets(spec: str) -> tuple[int, ...]:
    """Parse `ServeConfig.buckets`: comma-separated ascending sizes."""
    try:
        buckets = tuple(int(s) for s in spec.split(",") if s.strip())
    except ValueError:
        raise ValueError(
            f"buckets must be comma-separated integers, got {spec!r}"
        ) from None
    if not buckets:
        raise ValueError(f"buckets spec {spec!r} is empty")
    return buckets


class BucketLadder:
    """A fixed ascending ladder of padded batch sizes."""

    def __init__(self, buckets=DEFAULT_BUCKETS):
        buckets = tuple(int(b) for b in buckets)
        if not buckets:
            raise ValueError("bucket ladder must not be empty")
        if any(b < 1 for b in buckets):
            raise ValueError(f"bucket sizes must be positive: {buckets}")
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"bucket ladder must be strictly ascending: {buckets}"
            )
        self.buckets = buckets

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def pick(self, n: int) -> int:
        """Smallest bucket holding ``n`` images (n must fit the ladder)."""
        if n < 1:
            raise ValueError(f"cannot bucket {n} images")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"{n} images exceed the largest bucket {self.max_batch}"
        )


@dataclasses.dataclass
class FormedBatch:
    """One padded batch ready for dispatch, plus its form-time accounting."""

    requests: list[Request]     # FIFO order; slices index into images
    slices: list[slice]         # per-request row ranges within images
    expired: list[Request]      # shed at collect time (handles resolved)
    bucket: int                 # padded batch size (ladder element)
    valid: int                  # real (unpadded) image count
    images: np.ndarray          # (bucket, H, W, C), zero-padded
    weight: np.ndarray          # f32 (bucket,): 1.0 real, 0.0 padding
    formed: float               # perf_counter stamp when forming finished
    formed_ts: float            # wall-clock twin (obs records)
    form_ms: float              # time spent assembling/padding

    @property
    def occupancy(self) -> float:
        """Valid fraction of the padded batch — the efficiency the bucket
        ladder trades for shape stability (gauged as
        ``serve.batch_occupancy``)."""
        return self.valid / self.bucket if self.bucket else 0.0


class DynamicBatcher:
    """Single-consumer batch former over a `RequestQueue`."""

    def __init__(self, queue: RequestQueue, ladder: BucketLadder,
                 max_wait_ms: float = 5.0):
        self.queue = queue
        self.ladder = ladder
        self.max_wait_ms = float(max_wait_ms)

    def next_batch(self, timeout_s: float = 0.1) -> FormedBatch | str:
        """Block for the next dispatchable batch.

        Returns a `FormedBatch`, or ``"timeout"`` (nothing arrived —
        re-check your stop flag), or ``"closed"`` (queue closed and fully
        drained). A wake where every pending request had already expired
        returns a batch with ``requests=[]`` — the engine still consumes
        it for the expired handles' accounting.
        """
        why = self.queue.await_work(
            target_images=self.ladder.max_batch,
            max_wait_s=self.max_wait_ms / 1e3,
            timeout_s=timeout_s,
        )
        if why in ("timeout", "closed"):
            return why
        now = time.perf_counter()
        requests, expired = self.queue.collect(self.ladder.max_batch, now)
        return self.form(requests, expired, now)

    def form(self, requests: list[Request], expired: list[Request],
             now: float) -> FormedBatch:
        """Pad ``requests`` into their bucket (pure — unit-testable)."""
        t0 = time.perf_counter()
        valid = sum(r.n for r in requests)
        if not requests:
            return FormedBatch(
                requests=[], slices=[], expired=expired, bucket=0, valid=0,
                images=np.empty((0,) + self.queue.image_shape,
                                self.queue.image_dtype),
                weight=np.empty((0,), np.float32),
                formed=now, formed_ts=time.time(), form_ms=0.0,
            )
        bucket = self.ladder.pick(valid)
        images = np.zeros((bucket,) + self.queue.image_shape,
                          dtype=self.queue.image_dtype)
        weight = np.zeros((bucket,), np.float32)
        slices: list[slice] = []
        offset = 0
        for req in requests:
            sl = slice(offset, offset + req.n)
            images[sl] = req.images
            weight[sl] = 1.0
            slices.append(sl)
            offset += req.n
        form_ms = (time.perf_counter() - t0) * 1e3
        return FormedBatch(
            requests=requests, slices=slices, expired=expired,
            bucket=bucket, valid=valid, images=images, weight=weight,
            formed=time.perf_counter(), formed_ts=time.time(),
            form_ms=form_ms,
        )
