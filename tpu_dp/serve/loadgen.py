"""Synthetic load generation: Poisson/burst/diurnal arrivals + the
ground-truth audit that makes chaos testable.

Serving behavior under heavy traffic AND injected failure must be testable
on the CPU backend (the same 8-virtual-device trick the training tests
use), so the load generator is deterministic-seeded and keeps its own
books: every submit outcome (accepted / shed-with-reason) and every handle
resolution (completed / shed / deadline-missed, per SLO class, per model
version) is counted caller-side, then compared **exactly** against the
engine's `tpu_dp.obs` counters and the device-side donated stats. A
telemetry number that can drift from ground truth is worse than no number
— the audit is the test, and it must hold through replica failover, drain,
rejoin and hot swap (`tests/test_serve_elastic.py`,
`tools/run_tier1.sh --serve-elastic`).

Arrival patterns:

- ``poisson`` — exponential inter-arrival gaps at ``rate_rps`` (the
  classic open-loop model of independent user traffic);
- ``burst``   — groups of ``burst`` requests arriving back-to-back,
  separated by the idle gap that keeps the same average rate (the pattern
  that actually exercises queue-depth shedding and big buckets);
- ``diurnal`` — Poisson with the rate swept through one trough→peak→trough
  cycle across the run (peak = ``rate_rps``, trough = 25% of it) — the
  compressed day of traffic a serving tier must ramp across.

Requests are "mixed-size" (1..max(sizes) images, drawn from ``sizes``) and
optionally mixed-class (``class_mix``): the dynamic batcher's coalescing,
the queue's class-ordered dispatch, and lowest-class-first shedding all
see realistic variety. ``events`` injects scenario actions (hot swap,
drain, rejoin, SIGTERM) at exact request indices, so a chaos matrix is a
list of (index, label, callable) — deterministic where it matters.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_dp.serve.queue import ShedError

ARRIVAL_PATTERNS = ("poisson", "burst", "diurnal")

#: diurnal trough rate as a fraction of the peak ``rate_rps``.
DIURNAL_TROUGH = 0.25


def arrival_offsets(n: int, pattern: str, rate_rps: float, burst: int,
                    rng: np.random.Generator) -> np.ndarray:
    """Arrival times (seconds from start) for ``n`` requests."""
    if pattern not in ARRIVAL_PATTERNS:
        raise ValueError(
            f"pattern must be one of {ARRIVAL_PATTERNS}, got {pattern!r}"
        )
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    if n <= 0:
        return np.zeros((0,))
    if pattern == "poisson":
        gaps = rng.exponential(1.0 / rate_rps, size=n)
        gaps[0] = 0.0
        return np.cumsum(gaps)
    if pattern == "diurnal":
        # One trough→peak→trough cycle over the request sequence: the
        # i-th gap is drawn at the instantaneous rate of that phase of
        # the "day", so density ramps up to rate_rps mid-run and back.
        phase = np.sin(np.pi * (np.arange(n) + 0.5) / n) ** 2
        rates = rate_rps * (DIURNAL_TROUGH + (1.0 - DIURNAL_TROUGH) * phase)
        gaps = rng.exponential(1.0 / rates)
        gaps[0] = 0.0
        return np.cumsum(gaps)
    # burst: k back-to-back arrivals, then one gap sized to hold the rate.
    burst = max(1, int(burst))
    offsets = np.zeros(n)
    t = 0.0
    for i in range(n):
        if i and i % burst == 0:
            t += burst / rate_rps
        offsets[i] = t
    return offsets


def _empty_class_truth() -> dict:
    return {"submitted": 0, "accepted": 0, "completed": 0, "shed": 0,
            "deadline_missed": 0}


def run_load(
    engine,
    n_requests: int = 200,
    pattern: str = "poisson",
    rate_rps: float = 400.0,
    sizes=(1, 2, 3, 4),
    burst: int = 8,
    slo_ms: float | None = None,
    seed: int = 0,
    wait_timeout_s: float = 60.0,
    class_mix=None,
    class_slo_ms: dict[int, float] | None = None,
    events=None,
) -> dict:
    """Drive ``engine`` (an `InferenceEngine` OR a `ServeCluster`) with
    synthetic traffic; return the audited report.

    The engine must already be started. ``class_mix`` is an optional
    probability vector over SLO classes (class i with probability
    ``class_mix[i]``; default: everything class 0); ``class_slo_ms``
    overrides the per-class latency budget at submit. ``events`` is a
    list of ``(request_index, label, fn)``: ``fn()`` runs immediately
    before submitting that request — the scenario-matrix hook for hot
    swaps, drains, rejoins and signals (each firing is stamped into
    ``report["load"]["events"]``).

    Returns the engine's `report()` extended with the loadgen's
    ``ground_truth`` block and ``consistent`` — True iff the engine's
    serve counters match the caller-side books exactly (accepted,
    completed, shed total and per-reason, deadline_missed, AND each of
    those per SLO class) and the device-side served count across every
    replica equals the images actually served — zero dropped, zero
    double-served, through whatever the events/faults did to the tier.
    """
    rng = np.random.default_rng(seed)
    offsets = arrival_offsets(n_requests, pattern, rate_rps, burst, rng)
    sizes = tuple(int(s) for s in sizes)
    req_sizes = rng.choice(sizes, size=n_requests)
    if class_mix is not None:
        mix = np.asarray(list(class_mix), dtype=float)
        if mix.ndim != 1 or mix.size == 0 or (mix < 0).any() or \
                not np.isclose(mix.sum(), 1.0):
            raise ValueError(
                f"class_mix must be a probability vector, got {class_mix!r}"
            )
        req_classes = rng.choice(mix.size, size=n_requests, p=mix)
    else:
        req_classes = np.zeros(n_requests, dtype=int)
    class_slo_ms = dict(class_slo_ms or {})
    shape = engine.queue.image_shape
    dtype = engine.queue.image_dtype
    if np.issubdtype(dtype, np.integer):
        payloads = [
            rng.integers(0, 256, size=(k,) + shape).astype(dtype)
            for k in req_sizes
        ]
    else:
        payloads = [
            rng.standard_normal((k,) + shape).astype(dtype)
            for k in req_sizes
        ]
    fired_events = []
    events_at: dict[int, list] = {}
    for idx, label, fn in (events or ()):
        events_at.setdefault(int(idx), []).append((str(label), fn))

    before = {
        k: v for k, v in engine._counters.snapshot().items()
        if k.startswith("serve.")
    }
    served_before = engine.device_stats()["served"]

    handles = []
    truth = {
        "submitted": n_requests,
        "accepted": 0,
        "shed": 0,
        "shed_by_reason": {},
        "completed": 0,
        "deadline_missed": 0,
        "images_submitted": int(req_sizes.sum()),
        "images_served": 0,
        "by_class": {},
        "served_by_version": {},
    }
    by_class = truth["by_class"]
    t_start = time.perf_counter()
    for i in range(n_requests):
        for label, fn in events_at.get(i, ()):
            fired_events.append({
                "at_request": i, "label": label,
                "t_s": round(time.perf_counter() - t_start, 3),
            })
            fn()
        delay = t_start + float(offsets[i]) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        cls = int(req_classes[i])
        cb = by_class.setdefault(cls, _empty_class_truth())
        cb["submitted"] += 1
        budget = class_slo_ms.get(cls, slo_ms)
        try:
            handles.append(
                (i, engine.submit(payloads[i], slo_ms=budget, slo_class=cls))
            )
            truth["accepted"] += 1
            cb["accepted"] += 1
        except ShedError as e:
            truth["shed"] += 1
            cb["shed"] += 1
            truth["shed_by_reason"][e.reason] = (
                truth["shed_by_reason"].get(e.reason, 0) + 1
            )

    deadline = time.perf_counter() + wait_timeout_s
    unresolved = 0
    for i, h in handles:
        cb = by_class[int(req_classes[i])]
        if not h.wait(max(0.0, deadline - time.perf_counter())):
            unresolved += 1
            continue
        if h.ok:
            truth["completed"] += 1
            cb["completed"] += 1
            truth["images_served"] += h.n
            truth["deadline_missed"] += int(h.deadline_missed)
            cb["deadline_missed"] += int(h.deadline_missed)
            if h.model_version is not None:
                truth["served_by_version"][str(h.model_version)] = (
                    truth["served_by_version"].get(str(h.model_version), 0)
                    + 1
                )
        else:
            truth["shed"] += 1
            cb["shed"] += 1
            truth["shed_by_reason"][h.shed_reason] = (
                truth["shed_by_reason"].get(h.shed_reason, 0) + 1
            )
    # An ADMITTED request may be evicted by a later higher-class submit
    # (lowest-class-first queue_full shedding): it was counted accepted at
    # submit and resolves shed afterwards. Both sides of the audit see it
    # exactly once in each role, so the books still reconcile — but note
    # accepted != completed + shed as *disjoint outcomes*; the invariant
    # is submitted == completed + shed + unresolved.
    truth["unresolved"] = unresolved
    wall_s = time.perf_counter() - t_start

    report = engine.report()
    after = report["counters"]

    def delta(name: str) -> float:
        return after.get(name, 0.0) - before.get(name, 0.0)

    per_class_consistent = all(
        delta(f"serve.accepted.c{cls}") == cb["accepted"]
        and delta(f"serve.completed.c{cls}") == cb["completed"]
        and delta(f"serve.shed.c{cls}") == cb["shed"]
        and delta(f"serve.deadline_missed.c{cls}") == cb["deadline_missed"]
        for cls, cb in by_class.items()
    )
    consistent = (
        unresolved == 0
        and delta("serve.accepted") == truth["accepted"]
        and delta("serve.completed") == truth["completed"]
        and delta("serve.shed") == truth["shed"]
        and delta("serve.deadline_missed") == truth["deadline_missed"]
        and all(
            delta(f"serve.shed.{reason}") == count
            for reason, count in truth["shed_by_reason"].items()
        )
        and per_class_consistent
        and report["device_stats"]["served"] - served_before
        == truth["images_served"]
    )
    report["load"] = {
        "pattern": pattern,
        "rate_rps": rate_rps,
        "sizes": list(sizes),
        "burst": burst if pattern == "burst" else None,
        "class_mix": None if class_mix is None else [float(m) for m in mix],
        "seed": seed,
        "wall_s": round(wall_s, 3),
        "offered_rps": round(n_requests / wall_s, 1) if wall_s else None,
        "events": fired_events,
    }
    report["ground_truth"] = truth
    report["consistent"] = bool(consistent)
    return report
