"""Synthetic load generation: Poisson/burst arrivals + ground-truth audit.

Serving behavior under heavy traffic must be testable on the CPU backend
(the same 8-virtual-device trick the training tests use), so the load
generator is deterministic-seeded and keeps its own books: every submit
outcome (accepted / shed-with-reason) and every handle resolution
(completed / shed / deadline-missed) is counted caller-side, then compared
**exactly** against the engine's `tpu_dp.obs` counters. A telemetry number
that can drift from ground truth is worse than no number — the audit is
the test (`tests/test_serve.py`, `tools/run_tier1.sh --serve`).

Arrival patterns:

- ``poisson`` — exponential inter-arrival gaps at ``rate_rps`` (the
  classic open-loop model of independent user traffic);
- ``burst``   — groups of ``burst`` requests arriving back-to-back,
  separated by the idle gap that keeps the same average rate (the pattern
  that actually exercises queue-depth shedding and big buckets).

Requests are "mixed-size": each carries 1..max(sizes) images, drawn from
``sizes`` — so the dynamic batcher's coalescing and padding both see
realistic variety.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_dp.serve.engine import InferenceEngine
from tpu_dp.serve.queue import ShedError

ARRIVAL_PATTERNS = ("poisson", "burst")


def arrival_offsets(n: int, pattern: str, rate_rps: float, burst: int,
                    rng: np.random.Generator) -> np.ndarray:
    """Arrival times (seconds from start) for ``n`` requests."""
    if pattern not in ARRIVAL_PATTERNS:
        raise ValueError(
            f"pattern must be one of {ARRIVAL_PATTERNS}, got {pattern!r}"
        )
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be positive, got {rate_rps}")
    if n <= 0:
        return np.zeros((0,))
    if pattern == "poisson":
        gaps = rng.exponential(1.0 / rate_rps, size=n)
        gaps[0] = 0.0
        return np.cumsum(gaps)
    # burst: k back-to-back arrivals, then one gap sized to hold the rate.
    burst = max(1, int(burst))
    offsets = np.zeros(n)
    t = 0.0
    for i in range(n):
        if i and i % burst == 0:
            t += burst / rate_rps
        offsets[i] = t
    return offsets


def run_load(
    engine: InferenceEngine,
    n_requests: int = 200,
    pattern: str = "poisson",
    rate_rps: float = 400.0,
    sizes=(1, 2, 3, 4),
    burst: int = 8,
    slo_ms: float | None = None,
    seed: int = 0,
    wait_timeout_s: float = 60.0,
) -> dict:
    """Drive ``engine`` with synthetic traffic; return the audited report.

    The engine must already be started. Returns the engine's `report()`
    extended with the loadgen's ``ground_truth`` block and
    ``consistent`` — True iff the engine's serve counters match the
    caller-side books exactly (accepted, completed, shed total and
    per-reason, deadline_missed) AND the device-side served count matches
    the images actually served.
    """
    rng = np.random.default_rng(seed)
    offsets = arrival_offsets(n_requests, pattern, rate_rps, burst, rng)
    sizes = tuple(int(s) for s in sizes)
    req_sizes = rng.choice(sizes, size=n_requests)
    shape = engine.queue.image_shape
    dtype = engine.queue.image_dtype
    if np.issubdtype(dtype, np.integer):
        payloads = [
            rng.integers(0, 256, size=(k,) + shape).astype(dtype)
            for k in req_sizes
        ]
    else:
        payloads = [
            rng.standard_normal((k,) + shape).astype(dtype)
            for k in req_sizes
        ]

    before = {
        k: v for k, v in engine._counters.snapshot().items()
        if k.startswith("serve.")
    }
    served_before = engine.device_stats()["served"]

    handles = []
    truth = {
        "submitted": n_requests,
        "accepted": 0,
        "shed": 0,
        "shed_by_reason": {},
        "completed": 0,
        "deadline_missed": 0,
        "images_submitted": int(req_sizes.sum()),
        "images_served": 0,
    }
    t_start = time.perf_counter()
    for i in range(n_requests):
        delay = t_start + float(offsets[i]) - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            handles.append((i, engine.submit(payloads[i], slo_ms=slo_ms)))
            truth["accepted"] += 1
        except ShedError as e:
            truth["shed"] += 1
            truth["shed_by_reason"][e.reason] = (
                truth["shed_by_reason"].get(e.reason, 0) + 1
            )

    deadline = time.perf_counter() + wait_timeout_s
    unresolved = 0
    for i, h in handles:
        if not h.wait(max(0.0, deadline - time.perf_counter())):
            unresolved += 1
            continue
        if h.ok:
            truth["completed"] += 1
            truth["images_served"] += h.n
            truth["deadline_missed"] += int(h.deadline_missed)
        else:
            truth["shed"] += 1
            truth["shed_by_reason"][h.shed_reason] = (
                truth["shed_by_reason"].get(h.shed_reason, 0) + 1
            )
    truth["unresolved"] = unresolved
    wall_s = time.perf_counter() - t_start

    report = engine.report()
    after = report["counters"]

    def delta(name: str) -> float:
        return after.get(name, 0.0) - before.get(name, 0.0)

    consistent = (
        unresolved == 0
        and delta("serve.accepted") == truth["accepted"]
        and delta("serve.completed") == truth["completed"]
        and delta("serve.shed") == truth["shed"]
        and delta("serve.deadline_missed") == truth["deadline_missed"]
        and all(
            delta(f"serve.shed.{reason}") == count
            for reason, count in truth["shed_by_reason"].items()
        )
        and report["device_stats"]["served"] - served_before
        == truth["images_served"]
    )
    report["load"] = {
        "pattern": pattern,
        "rate_rps": rate_rps,
        "sizes": list(sizes),
        "burst": burst if pattern == "burst" else None,
        "seed": seed,
        "wall_s": round(wall_s, 3),
        "offered_rps": round(n_requests / wall_s, 1) if wall_s else None,
    }
    report["ground_truth"] = truth
    report["consistent"] = bool(consistent)
    return report
