"""In-process request queue: bounded depth, deadlines, shed-with-reason.

The admission edge of the serving pipeline (docs/SERVING.md). A request is
a small batch of images (1..max_batch — "mixed-size" traffic); the queue
holds it until the dynamic batcher coalesces pending requests into one
padded bucket. Backpressure is explicit and typed, never silent:

- **bounded depth** — a queue deeper than the engine can drain within the
  SLO only converts future deadline misses into memory; past ``max_depth``
  requests, `submit` sheds with reason ``queue_full``;
- **deadlines** — every request carries an absolute deadline (arrival +
  its SLO budget). A budget already below ``shed_headroom_ms`` at
  admission sheds immediately (reason ``deadline``: it cannot possibly be
  served in time, so rejecting it now is cheaper for everyone than
  serving it late), and a request that expires while queued is shed at
  batch-collect time with the same reason;
- **shed accounting** — every admission and shed increments the
  process-wide `tpu_dp.obs` counters (``serve.accepted``, ``serve.shed``,
  ``serve.shed.<reason>``), which the load generator's ground truth must
  match *exactly* (`tests/test_serve.py`).

Thread-safe: producers call `submit` from any thread; the engine's
dispatch thread is the single consumer of `collect`/`await_work`.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import numpy as np

from tpu_dp.obs.counters import Counters, counters as _global_counters

#: shed reasons (the `ShedError.reason` / `RequestHandle.shed_reason` values)
SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline"
SHED_CLOSED = "closed"


class ShedError(RuntimeError):
    """A request was rejected at admission; ``reason`` says why."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass
class Request:
    """One queued inference request: ``n`` images + its deadline."""

    req_id: int
    images: np.ndarray          # (n, H, W, C), host-side
    arrival: float              # time.perf_counter() — the latency clock
    arrival_ts: float           # time.time() — the obs wall-clock stamp
    deadline: float             # perf_counter seconds; absolute
    handle: "RequestHandle"

    @property
    def n(self) -> int:
        return int(self.images.shape[0])


class RequestHandle:
    """The caller's half of a request: blocks until served or shed.

    Resolved exactly once by the engine (or by the queue, for requests
    shed while queued). ``predictions``/``confidence`` are per-image
    (shape ``(n,)``); ``shed_reason`` is None on success.
    """

    def __init__(self, req_id: int, n: int):
        self.req_id = int(req_id)
        self.n = int(n)
        self._done = threading.Event()
        self.predictions: np.ndarray | None = None
        self.confidence: np.ndarray | None = None
        self.shed_reason: str | None = None
        self.latency_ms: float | None = None
        self.deadline_missed: bool = False
        self.spans: dict[str, float] = {}

    @property
    def ok(self) -> bool:
        return self._done.is_set() and self.shed_reason is None

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until resolved; False on timeout."""
        return self._done.wait(timeout)

    # -- engine-side resolution (exactly once) --------------------------

    def _resolve(self, predictions, confidence, latency_ms,
                 deadline_missed, spans) -> None:
        self.predictions = predictions
        self.confidence = confidence
        self.latency_ms = float(latency_ms)
        self.deadline_missed = bool(deadline_missed)
        self.spans = dict(spans)
        self._done.set()

    def _shed(self, reason: str) -> None:
        self.shed_reason = reason
        self._done.set()


class RequestQueue:
    """Bounded FIFO of pending requests with deadline-aware collection."""

    def __init__(
        self,
        max_depth: int = 256,
        default_slo_ms: float = 50.0,
        shed_headroom_ms: float = 0.0,
        image_shape: tuple[int, int, int] = (32, 32, 3),
        image_dtype=np.uint8,
        max_request: int | None = None,
        registry: Counters | None = None,
    ):
        if max_depth < 1:
            raise ValueError(f"max_depth must be positive, got {max_depth}")
        self.max_depth = int(max_depth)
        self.default_slo_ms = float(default_slo_ms)
        self.shed_headroom_ms = float(shed_headroom_ms)
        self.image_shape = tuple(image_shape)
        # One dtype per queue: the per-bucket programs are compiled for a
        # fixed input signature, and a request smuggling a different dtype
        # into a bucket would be a silent retrace (the exact cliff the
        # ladder exists to prevent).
        self.image_dtype = np.dtype(image_dtype)
        # A request larger than the biggest bucket could never be batched
        # and would wedge the FIFO head forever — a caller error, rejected
        # at submit (ValueError, not a shed: it is not a load condition).
        self.max_request = None if max_request is None else int(max_request)
        self._counters = _global_counters if registry is None else registry
        self._dq: deque[Request] = deque()
        self._cond = threading.Condition()
        self._images = 0          # total images pending (cheap occupancy)
        self._next_id = 0
        self._closed = False

    # -- producer side ---------------------------------------------------

    def submit(self, images: np.ndarray, slo_ms: float | None = None,
               now: float | None = None) -> RequestHandle:
        """Enqueue one request; raises `ShedError` when load-shed.

        ``images`` is ``(n, H, W, C)`` (a single ``(H, W, C)`` image is
        promoted to n=1). ``slo_ms`` is this request's latency budget
        (default: the queue's); the deadline is ``now + slo_ms``.
        """
        images = np.asarray(images)
        if images.shape == self.image_shape:
            images = images[None]
        if images.ndim != 4 or images.shape[1:] != self.image_shape:
            raise ValueError(
                f"request images must be (n, {', '.join(map(str, self.image_shape))}), "
                f"got {images.shape}"
            )
        if images.dtype != self.image_dtype:
            raise ValueError(
                f"request images must be {self.image_dtype}, got "
                f"{images.dtype} (the bucket programs compile for one "
                f"fixed input dtype)"
            )
        if self.max_request is not None and images.shape[0] > self.max_request:
            raise ValueError(
                f"request carries {images.shape[0]} images, above the "
                f"largest batch bucket ({self.max_request}); split it"
            )
        budget_ms = self.default_slo_ms if slo_ms is None else float(slo_ms)
        now = time.perf_counter() if now is None else float(now)
        with self._cond:
            if self._closed:
                raise ShedError(SHED_CLOSED, "queue is closed")
            handle = RequestHandle(self._next_id, int(images.shape[0]))
            self._next_id += 1
            if len(self._dq) >= self.max_depth:
                self._counters.inc("serve.shed")
                self._counters.inc(f"serve.shed.{SHED_QUEUE_FULL}")
                handle._shed(SHED_QUEUE_FULL)
                raise ShedError(
                    SHED_QUEUE_FULL,
                    f"queue depth {len(self._dq)} at max_depth "
                    f"{self.max_depth}; request {handle.req_id} shed",
                )
            if budget_ms < self.shed_headroom_ms:
                self._counters.inc("serve.shed")
                self._counters.inc(f"serve.shed.{SHED_DEADLINE}")
                handle._shed(SHED_DEADLINE)
                raise ShedError(
                    SHED_DEADLINE,
                    f"deadline budget {budget_ms:.1f}ms below shed headroom "
                    f"{self.shed_headroom_ms:.1f}ms; request {handle.req_id} "
                    f"shed at admission",
                )
            req = Request(
                req_id=handle.req_id,
                images=images,
                arrival=now,
                arrival_ts=time.time(),
                deadline=now + budget_ms / 1e3,
                handle=handle,
            )
            self._dq.append(req)
            self._images += req.n
            self._counters.inc("serve.accepted")
            self._cond.notify_all()
            return handle

    def close(self) -> None:
        """Stop admitting; queued requests still drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side (single dispatch thread) --------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def __len__(self) -> int:
        with self._cond:
            return len(self._dq)

    def pending_images(self) -> int:
        with self._cond:
            return self._images

    def await_work(self, target_images: int, max_wait_s: float,
                   timeout_s: float) -> str:
        """Block until a batch should form; returns why it should.

        - ``"fill"``    — pending images reached ``target_images`` (the
          ladder's max bucket: no point waiting longer);
        - ``"wait"``    — the oldest pending request aged past
          ``max_wait_s`` (or the queue is closed and draining): dispatch
          what we have;
        - ``"timeout"`` — no batch became *due* within ``timeout_s``
          (work may still be pending, just younger than ``max_wait_s`` —
          the dispatch loop's chance to check its stop flag before
          waiting again; returning "wait" here instead would silently
          cap the configured max_wait at the caller's poll interval);
        - ``"closed"``  — closed AND empty: the drain is complete.
        """
        end = time.perf_counter() + timeout_s
        with self._cond:
            while True:
                now = time.perf_counter()
                if self._dq:
                    if self._images >= target_images:
                        return "fill"
                    oldest = self._dq[0].arrival
                    if self._closed or now - oldest >= max_wait_s:
                        return "wait"
                    if now >= end:
                        return "timeout"
                    wake = min(end, oldest + max_wait_s)
                else:
                    if self._closed:
                        return "closed"
                    if now >= end:
                        return "timeout"
                    wake = end
                self._cond.wait(max(wake - now, 1e-4))

    def collect(self, max_images: int, now: float | None = None
                ) -> tuple[list[Request], list[Request]]:
        """Pop (batch, expired): FIFO requests up to ``max_images``.

        Expired requests (deadline already passed — serving them would
        only produce a late answer nobody is waiting for) are removed
        wherever they sit in the queue, shed with reason ``deadline``,
        and returned so the engine can resolve their handles. The batch
        is then the FIFO prefix whose cumulative image count fits
        ``max_images`` — a request is never split across batches.
        """
        now = time.perf_counter() if now is None else float(now)
        with self._cond:
            live: deque[Request] = deque()
            expired: list[Request] = []
            for req in self._dq:
                (expired if req.deadline <= now else live).append(req)
            batch: list[Request] = []
            total = 0
            while live and total + live[0].n <= max_images:
                req = live.popleft()
                batch.append(req)
                total += req.n
            self._dq = live
            self._images = sum(r.n for r in live)
            for req in expired:
                self._counters.inc("serve.shed")
                self._counters.inc(f"serve.shed.{SHED_DEADLINE}")
                req.handle._shed(SHED_DEADLINE)
            return batch, expired
