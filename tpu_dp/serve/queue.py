"""In-process request queue: bounded depth, deadlines, SLO classes,
shed-with-reason.

The admission edge of the serving pipeline (docs/SERVING.md). A request is
a small batch of images (1..max_batch — "mixed-size" traffic); the queue
holds it until a dynamic batcher coalesces pending requests into one
padded bucket. Backpressure is explicit and typed, never silent:

- **bounded depth** — a queue deeper than the engine can drain within the
  SLO only converts future deadline misses into memory; past ``max_depth``
  requests, `submit` sheds with reason ``queue_full`` — **lowest SLO class
  first**: when the incoming request outranks a queued one (smaller
  ``slo_class`` number), the youngest queued request of the *worst*
  represented class is evicted instead, so burst overload degrades the
  bronze tier before it ever touches gold;
- **deadlines** — every request carries an absolute deadline (arrival +
  its SLO budget). A budget already below ``shed_headroom_ms`` at
  admission sheds immediately (reason ``deadline``: it cannot possibly be
  served in time, so rejecting it now is cheaper for everyone than
  serving it late), and a request that expires while queued is shed at
  batch-collect time with the same reason;
- **closed** — `submit` after `close()` sheds ``closed`` synchronously at
  admission (counters included), so a caller racing shutdown gets an
  immediate typed answer instead of depending on the dispatch loop to
  notice it;
- **shed accounting** — every admission and shed increments the
  process-wide `tpu_dp.obs` counters (``serve.accepted``, ``serve.shed``,
  ``serve.shed.<reason>``, and the per-class twins
  ``serve.{accepted,completed,shed,deadline_missed}.c<k>``), which the
  load generator's ground truth must match *exactly*
  (`tests/test_serve.py`).

**SLO classes**: ``slo_class`` is a small non-negative integer priority, 0
highest ("gold"). Dispatch order is (class, arrival) — FIFO within a
class — and overload sheds the lowest class first (above). Classes are
accounting + ordering only; they never change *how* a request is served.

Thread-safe: producers call `submit` from any thread; replica dispatch
threads are concurrent consumers of `collect`/`await_work` (both take the
queue lock, so a formed batch is popped by exactly one consumer).
`requeue` is the failover edge: a dead replica's in-flight requests go
back in *without* re-counting admission, preserving the exactly-once
books (docs/SERVING.md "Failover").
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

import numpy as np

from tpu_dp.obs.counters import Counters, counters as _global_counters

#: shed reasons (the `ShedError.reason` / `RequestHandle.shed_reason` values)
SHED_QUEUE_FULL = "queue_full"
SHED_DEADLINE = "deadline"
SHED_CLOSED = "closed"
#: a dead/wedged replica's in-flight request that exhausted its failover
#: retries (tpu_dp/serve/router.py) — typed, never a silent drop.
SHED_REPLICA_FAILED = "replica_failed"


class ShedError(RuntimeError):
    """A request was rejected at admission; ``reason`` says why."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


@dataclasses.dataclass
class Request:
    """One queued inference request: ``n`` images + its deadline."""

    req_id: int
    images: np.ndarray          # (n, H, W, C), host-side
    arrival: float              # time.perf_counter() — the latency clock
    arrival_ts: float           # time.time() — the obs wall-clock stamp
    deadline: float             # perf_counter seconds; absolute
    handle: "RequestHandle"
    slo_class: int = 0          # priority class, 0 = highest ("gold")
    retries: int = 0            # failover re-admissions so far

    @property
    def n(self) -> int:
        return int(self.images.shape[0])


class RequestHandle:
    """The caller's half of a request: blocks until served or shed.

    Resolved exactly once — the `_claim` guard makes a second resolution
    attempt a no-op, which is what keeps failover honest: a request
    retried off a replica presumed dead can never be double-answered if
    the original resolver turns out to be merely slow.
    ``predictions``/``confidence`` are per-image (shape ``(n,)``);
    ``shed_reason`` is None on success. ``model_version`` stamps which
    weights served it (hot swap, docs/SERVING.md); ``served_by`` is the
    replica sid.
    """

    def __init__(self, req_id: int, n: int, slo_class: int = 0):
        self.req_id = int(req_id)
        self.n = int(n)
        self.slo_class = int(slo_class)
        self._done = threading.Event()
        self._claim_lock = threading.Lock()
        self._claimed = False
        self.predictions: np.ndarray | None = None
        self.confidence: np.ndarray | None = None
        self.shed_reason: str | None = None
        self.latency_ms: float | None = None
        self.deadline_missed: bool = False
        self.spans: dict[str, float] = {}
        self.model_version: int | None = None
        self.served_by: int | None = None

    @property
    def ok(self) -> bool:
        return self._done.is_set() and self.shed_reason is None

    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until resolved; False on timeout."""
        return self._done.wait(timeout)

    # -- engine-side resolution (exactly once) --------------------------

    def _claim(self) -> bool:
        """First resolver wins; every later attempt is discarded."""
        with self._claim_lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def _finish_resolve(self, predictions, confidence, latency_ms,
                        deadline_missed, spans) -> None:
        """Fill + wake an already-`_claim`ed handle (the replica claims
        the whole batch first, publishes counters, then finishes — a
        waiter that wakes must read books that already include it)."""
        self.predictions = predictions
        self.confidence = confidence
        self.latency_ms = float(latency_ms)
        self.deadline_missed = bool(deadline_missed)
        self.spans = dict(spans)
        self._done.set()

    def _resolve(self, predictions, confidence, latency_ms,
                 deadline_missed, spans) -> bool:
        if not self._claim():
            return False
        self._finish_resolve(predictions, confidence, latency_ms,
                             deadline_missed, spans)
        return True

    def _shed(self, reason: str) -> bool:
        if not self._claim():
            return False
        self.shed_reason = reason
        self._done.set()
        return True


def shed_counted(registry: Counters, handle: RequestHandle,
                 reason: str) -> bool:
    """Shed ``handle`` exactly once with exact books; False when it was
    already resolved (a lost failover race — nothing is counted twice).

    Counter order matters: the shed counters (total, per-reason, per-class)
    are published BEFORE the waiter wakes, so a caller whose handle just
    resolved always reads books that include it (the loadgen audit's
    invariant).
    """
    if not handle._claim():
        return False
    registry.inc("serve.shed")
    registry.inc(f"serve.shed.{reason}")
    registry.inc(f"serve.shed.c{handle.slo_class}")
    handle.shed_reason = reason
    handle._done.set()
    return True


class RequestQueue:
    """Bounded FIFO of pending requests with deadline-aware collection."""

    def __init__(
        self,
        max_depth: int = 256,
        default_slo_ms: float = 50.0,
        shed_headroom_ms: float = 0.0,
        image_shape: tuple[int, int, int] = (32, 32, 3),
        image_dtype=np.uint8,
        max_request: int | None = None,
        registry: Counters | None = None,
    ):
        if max_depth < 1:
            raise ValueError(f"max_depth must be positive, got {max_depth}")
        self.max_depth = int(max_depth)
        self.default_slo_ms = float(default_slo_ms)
        self.shed_headroom_ms = float(shed_headroom_ms)
        self.image_shape = tuple(image_shape)
        # One dtype per queue: the per-bucket programs are compiled for a
        # fixed input signature, and a request smuggling a different dtype
        # into a bucket would be a silent retrace (the exact cliff the
        # ladder exists to prevent).
        self.image_dtype = np.dtype(image_dtype)
        # A request larger than the biggest bucket could never be batched
        # and would wedge the FIFO head forever — a caller error, rejected
        # at submit (ValueError, not a shed: it is not a load condition).
        self.max_request = None if max_request is None else int(max_request)
        self._counters = _global_counters if registry is None else registry
        self._dq: deque[Request] = deque()
        self._cond = threading.Condition()
        self._images = 0          # total images pending (cheap occupancy)
        self._next_id = 0
        self._closed = False

    # -- producer side ---------------------------------------------------

    def submit(self, images: np.ndarray, slo_ms: float | None = None,
               now: float | None = None,
               slo_class: int = 0) -> RequestHandle:
        """Enqueue one request; raises `ShedError` when load-shed.

        ``images`` is ``(n, H, W, C)`` (a single ``(H, W, C)`` image is
        promoted to n=1). ``slo_ms`` is this request's latency budget
        (default: the queue's); the deadline is ``now + slo_ms``.
        ``slo_class`` is the request's priority class (0 = highest):
        dispatch prefers lower classes and overload sheds higher ones
        first (module docstring).
        """
        if slo_class < 0:
            raise ValueError(f"slo_class must be >= 0, got {slo_class}")
        images = np.asarray(images)
        if images.shape == self.image_shape:
            images = images[None]
        if images.ndim != 4 or images.shape[1:] != self.image_shape:
            raise ValueError(
                f"request images must be (n, {', '.join(map(str, self.image_shape))}), "
                f"got {images.shape}"
            )
        if images.dtype != self.image_dtype:
            raise ValueError(
                f"request images must be {self.image_dtype}, got "
                f"{images.dtype} (the bucket programs compile for one "
                f"fixed input dtype)"
            )
        if self.max_request is not None and images.shape[0] > self.max_request:
            raise ValueError(
                f"request carries {images.shape[0]} images, above the "
                f"largest batch bucket ({self.max_request}); split it"
            )
        budget_ms = self.default_slo_ms if slo_ms is None else float(slo_ms)
        now = time.perf_counter() if now is None else float(now)
        with self._cond:
            handle = RequestHandle(self._next_id, int(images.shape[0]),
                                   slo_class=slo_class)
            self._next_id += 1
            if self._closed:
                # Synchronous typed shed at admission: a caller racing
                # shutdown must not depend on a dispatch loop (possibly
                # already gone) to account for it — counters included, so
                # the loadgen audit stays exact through a close.
                shed_counted(self._counters, handle, SHED_CLOSED)
                raise ShedError(
                    SHED_CLOSED,
                    f"queue is closed; request {handle.req_id} shed",
                )
            # Headroom BEFORE the depth/eviction decision: a request that
            # cannot possibly be served in time must never evict a viable
            # queued request to make room for itself.
            if budget_ms < self.shed_headroom_ms:
                shed_counted(self._counters, handle, SHED_DEADLINE)
                raise ShedError(
                    SHED_DEADLINE,
                    f"deadline budget {budget_ms:.1f}ms below shed headroom "
                    f"{self.shed_headroom_ms:.1f}ms; request {handle.req_id} "
                    f"shed at admission",
                )
            if len(self._dq) >= self.max_depth:
                victim = self._full_queue_victim(slo_class)
                if victim is None:
                    shed_counted(self._counters, handle, SHED_QUEUE_FULL)
                    raise ShedError(
                        SHED_QUEUE_FULL,
                        f"queue depth {len(self._dq)} at max_depth "
                        f"{self.max_depth}; request {handle.req_id} shed",
                    )
                # Shed lowest class first: the incoming request outranks
                # the victim, which is evicted (typed, counted) to make
                # room — burst overload eats the bronze tier before gold.
                self._dq.remove(victim)
                self._images -= victim.n
                shed_counted(self._counters, victim.handle, SHED_QUEUE_FULL)
            req = Request(
                req_id=handle.req_id,
                images=images,
                arrival=now,
                arrival_ts=time.time(),
                deadline=now + budget_ms / 1e3,
                handle=handle,
                slo_class=int(slo_class),
            )
            self._dq.append(req)
            self._images += req.n
            self._counters.inc("serve.accepted")
            self._counters.inc(f"serve.accepted.c{req.slo_class}")
            self._cond.notify_all()
            return handle

    def _full_queue_victim(self, incoming_class: int) -> Request | None:
        """The queued request a full queue evicts for ``incoming_class``.

        The *youngest* request of the *worst* (numerically highest) class
        present, and only when that class is strictly worse than the
        incoming one — least invested work of the least important tier.
        None when the incoming request does not outrank anything (it is
        shed itself, exactly as before classes existed)."""
        worst: Request | None = None
        for req in self._dq:
            if req.slo_class <= incoming_class:
                continue
            if worst is None or req.slo_class > worst.slo_class or (
                req.slo_class == worst.slo_class
                and req.arrival >= worst.arrival
            ):
                worst = req
        return worst

    def requeue(self, requests: list[Request]) -> None:
        """Failover re-admission: a dead replica's in-flight requests go
        back to the queue head (original relative order, original arrival
        clocks and deadlines intact) WITHOUT re-counting admission — each
        was counted ``serve.accepted`` exactly once at submit, and the
        exactly-once audit depends on that staying true through a
        failover. Bypasses ``max_depth`` (these were already admitted)
        and works on a closed queue (a drain must still flush them)."""
        live = [r for r in requests if not r.handle.done()]
        if not live:
            return
        with self._cond:
            self._dq.extendleft(reversed(live))
            self._images += sum(r.n for r in live)
            self._cond.notify_all()

    def close(self) -> None:
        """Stop admitting; queued requests still drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side (single dispatch thread) --------------------------

    @property
    def closed(self) -> bool:
        return self._closed

    def __len__(self) -> int:
        with self._cond:
            return len(self._dq)

    def pending_images(self) -> int:
        with self._cond:
            return self._images

    def await_work(self, target_images: int, max_wait_s: float,
                   timeout_s: float) -> str:
        """Block until a batch should form; returns why it should.

        - ``"fill"``    — pending images reached ``target_images`` (the
          ladder's max bucket: no point waiting longer);
        - ``"wait"``    — the oldest pending request aged past
          ``max_wait_s`` (or the queue is closed and draining): dispatch
          what we have;
        - ``"timeout"`` — no batch became *due* within ``timeout_s``
          (work may still be pending, just younger than ``max_wait_s`` —
          the dispatch loop's chance to check its stop flag before
          waiting again; returning "wait" here instead would silently
          cap the configured max_wait at the caller's poll interval);
        - ``"closed"``  — closed AND empty: the drain is complete.
        """
        end = time.perf_counter() + timeout_s
        with self._cond:
            while True:
                now = time.perf_counter()
                if self._dq:
                    if self._images >= target_images:
                        return "fill"
                    oldest = self._dq[0].arrival
                    if self._closed or now - oldest >= max_wait_s:
                        return "wait"
                    if now >= end:
                        return "timeout"
                    wake = min(end, oldest + max_wait_s)
                else:
                    if self._closed:
                        return "closed"
                    if now >= end:
                        return "timeout"
                    wake = end
                self._cond.wait(max(wake - now, 1e-4))

    def collect(self, max_images: int, now: float | None = None
                ) -> tuple[list[Request], list[Request]]:
        """Pop (batch, expired): highest-class-first requests up to
        ``max_images``.

        Expired requests (deadline already passed — serving them would
        only produce a late answer nobody is waiting for) are removed
        wherever they sit in the queue, shed with reason ``deadline``,
        and returned so the engine can resolve their handles. The batch
        is then the (slo_class, arrival)-ordered prefix whose cumulative
        image count fits ``max_images`` — FIFO within a class (with one
        class, exactly the old FIFO), a request never split across
        batches, and the prefix stops at the first request that does not
        fit (no skip-ahead: a big gold request cannot be starved by small
        bronze ones slipping past it).
        """
        now = time.perf_counter() if now is None else float(now)
        with self._cond:
            live: list[Request] = []
            expired: list[Request] = []
            for req in self._dq:
                (expired if req.deadline <= now else live).append(req)
            ordered = sorted(live, key=lambda r: (r.slo_class, r.arrival))
            batch: list[Request] = []
            total = 0
            for req in ordered:
                if total + req.n > max_images:
                    break
                batch.append(req)
                total += req.n
            taken = {id(r) for r in batch}
            self._dq = deque(r for r in live if id(r) not in taken)
            self._images = sum(r.n for r in self._dq)
            for req in expired:
                shed_counted(self._counters, req.handle, SHED_DEADLINE)
            return batch, expired
