"""tpu_dp.serve — the self-healing serving tier: queue → dynamic batcher →
replicated compiled forwards (docs/SERVING.md).

The serving half of the "millions of users" north star (ROADMAP item 3),
built on the training stack's compiled-program discipline: requests enter
a bounded, deadline- and **SLO-class**-aware `RequestQueue`, a
`DynamicBatcher` coalesces them into zero-padded batches at fixed
**bucket** sizes (a ladder like 1/2/4/…/32, so every batch hits a
pre-compiled `make_serve_step` program and the RecompileGuard stays
silent), and either a single-replica `InferenceEngine` or a fan-out
`ServeCluster` of `ServeReplica` workers dispatches them — with
heartbeat-derived health routing, failover with exactly-once accounting
(`replica_failed` is a typed shed, never a silent drop), elastic
drain/rejoin through the PR 7 membership-ledger format, and versioned hot
weight swaps with zero dropped requests.

Per-request latency is measured with `tpu_dp.obs` spans
(queue_wait/batch_form/h2d/device/d2h), shed/SLO accounting lands in the
process-wide counter registry (with per-class twins), and the serve
programs are fingerprinted in dplint's Level-3 artifact alongside the
train steps.

``python -m tpu_dp.serve`` runs the synthetic-load CPU smoke
(`tools/run_tier1.sh --serve` archives its report; ``--serve-elastic``
runs the 2-replica chaos matrix).
"""

from tpu_dp.serve.batcher import (
    DEFAULT_BUCKETS,
    BucketLadder,
    DynamicBatcher,
    FormedBatch,
    parse_buckets,
)
from tpu_dp.serve.engine import SERVE_SPANS, InferenceEngine
from tpu_dp.serve.loadgen import ARRIVAL_PATTERNS, arrival_offsets, run_load
from tpu_dp.serve.queue import (
    SHED_CLOSED,
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    SHED_REPLICA_FAILED,
    Request,
    RequestHandle,
    RequestQueue,
    ShedError,
)
from tpu_dp.serve.replica import LatencyBook, ServeReplica
from tpu_dp.serve.router import ServeCluster

__all__ = [
    "ARRIVAL_PATTERNS",
    "BucketLadder",
    "DEFAULT_BUCKETS",
    "DynamicBatcher",
    "FormedBatch",
    "InferenceEngine",
    "LatencyBook",
    "Request",
    "RequestHandle",
    "RequestQueue",
    "SERVE_SPANS",
    "SHED_CLOSED",
    "SHED_DEADLINE",
    "SHED_QUEUE_FULL",
    "SHED_REPLICA_FAILED",
    "ServeCluster",
    "ServeReplica",
    "ShedError",
    "arrival_offsets",
    "parse_buckets",
    "run_load",
]
