"""tpu_dp.serve — batched inference: queue → dynamic batcher → compiled
forward (docs/SERVING.md).

The serving half of the "millions of users" north star (ROADMAP item 4),
built on the training stack's compiled-program discipline: requests enter
a bounded deadline-aware `RequestQueue`, a `DynamicBatcher` coalesces them
into zero-padded batches at fixed **bucket** sizes (a ladder like
1/2/4/…/32, so every batch hits a pre-compiled `make_serve_step` program
and the RecompileGuard stays silent), and an `InferenceEngine` dispatch
thread runs the donated-buffer forward across the data-mesh replicas.
Per-request latency is measured with `tpu_dp.obs` spans
(queue_wait/batch_form/h2d/device/d2h), shed/SLO accounting lands in the
process-wide counter registry, and the serve programs are fingerprinted in
dplint's Level-3 artifact alongside the train steps.

``python -m tpu_dp.serve`` runs the synthetic-load CPU smoke
(`tools/run_tier1.sh --serve` archives its report).
"""

from tpu_dp.serve.batcher import (
    DEFAULT_BUCKETS,
    BucketLadder,
    DynamicBatcher,
    FormedBatch,
    parse_buckets,
)
from tpu_dp.serve.engine import SERVE_SPANS, InferenceEngine
from tpu_dp.serve.loadgen import ARRIVAL_PATTERNS, arrival_offsets, run_load
from tpu_dp.serve.queue import (
    SHED_CLOSED,
    SHED_DEADLINE,
    SHED_QUEUE_FULL,
    Request,
    RequestHandle,
    RequestQueue,
    ShedError,
)

__all__ = [
    "ARRIVAL_PATTERNS",
    "BucketLadder",
    "DEFAULT_BUCKETS",
    "DynamicBatcher",
    "FormedBatch",
    "InferenceEngine",
    "Request",
    "RequestHandle",
    "RequestQueue",
    "SERVE_SPANS",
    "SHED_CLOSED",
    "SHED_DEADLINE",
    "SHED_QUEUE_FULL",
    "ShedError",
    "arrival_offsets",
    "parse_buckets",
    "run_load",
]
