"""The inference engine: per-bucket compiled forwards + the dispatch loop.

The serving half of the north star (ROADMAP item 4): the same compiled-
program discipline the trainer enforces — fixed shapes, donated state, a
fingerprinted collective schedule — applied to request traffic:

    submit() → RequestQueue → DynamicBatcher → per-bucket jitted
    `make_serve_step` → resolve handles

One dispatch thread drains the queue. Every bucket in the ladder gets its
own pre-compiled program (warmed up at `start`), wrapped in a
`RecompileGuard` with ``on_retrace="raise"`` by default: a retrace during
serving means a shape/dtype leaked past the batcher, and the engine treats
that as a bug, not a slow path. The params/batch_stats live in a
`TrainState` with an *empty* opt_state (`checkpoint.load_params_only` —
inference never materializes optimizer slots); the device-mesh replicas
give batch fan-out for free (see `make_serve_step`).

Telemetry (docs/OBSERVABILITY.md, docs/SERVING.md): per-request spans
``queue_wait / batch_form / h2d / device / d2h`` (+ ``total``) in a
`SpanRecorder`; counters ``serve.accepted / serve.shed[.reason] /
serve.completed / serve.deadline_missed / serve.batches`` and the
``serve.batch_occupancy`` gauge in the process-wide registry; per-batch
heartbeats via `HeartbeatWriter` when ``obs_dir`` is set, so a straggling
serve rank is attributable with the exact `HealthMonitor` tooling the
trainer uses. The deterministic fault injector (``TPU_DP_FAULT=delay:…``)
is consulted per batch inside the device span, so injected stragglers
surface in spans and heartbeats like real ones.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from tpu_dp.obs.counters import Counters, counters as _global_counters
from tpu_dp.obs.spans import SpanRecorder
from tpu_dp.serve.batcher import BucketLadder, DynamicBatcher, FormedBatch
from tpu_dp.serve.queue import SHED_CLOSED, RequestHandle, RequestQueue

#: per-request span names, in pipeline order (the serving analogue of
#: `tpu_dp.obs.spans.STEP_SPANS`).
SERVE_SPANS = ("queue_wait", "batch_form", "h2d", "device", "d2h")


class InferenceEngine:
    """Batched-inference engine over the data mesh (docs/SERVING.md)."""

    def __init__(
        self,
        model,
        params,
        batch_stats=None,
        mesh=None,
        buckets=None,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        slo_ms: float = 50.0,
        shed_headroom_ms: float = 0.0,
        image_shape: tuple[int, int, int] = (32, 32, 3),
        image_dtype=np.uint8,
        num_classes: int | None = None,
        obs_dir: str | None = None,
        span_capacity: int = 4096,
        on_retrace: str = "raise",
        fault: str = "",
        registry: Counters | None = None,
        model_name: str = "",
        flops_per_image: float | None = None,
        peak_flops: float | None = None,
    ):
        import jax

        from tpu_dp.parallel import dist
        from tpu_dp.parallel.sharding import (
            batch_sharding, replicated_sharding,
        )
        from tpu_dp.resilience.faultinject import FaultInjector
        from tpu_dp.train.state import TrainState

        self.model = model
        self.mesh = dist.data_mesh() if mesh is None else mesh
        self.ladder = BucketLadder(
            buckets if buckets is not None else BucketLadder().buckets
        )
        self.slo_ms = float(slo_ms)
        self._counters = _global_counters if registry is None else registry
        self.queue = RequestQueue(
            max_depth=max_queue,
            default_slo_ms=slo_ms,
            shed_headroom_ms=shed_headroom_ms,
            image_shape=image_shape,
            image_dtype=image_dtype,
            max_request=self.ladder.max_batch,
            registry=self._counters,
        )
        self.batcher = DynamicBatcher(self.queue, self.ladder,
                                      max_wait_ms=max_wait_ms)
        self.recorder = SpanRecorder(capacity=span_capacity)

        # Inference state: params (+ BN stats) only, replicated, never
        # donated. The empty opt_state is the point — serving a checkpoint
        # must not pay for (or even know about) optimizer slots.
        repl = replicated_sharding(self.mesh)
        state = TrainState(
            step=np.zeros((), np.int32),
            params=params,
            opt_state={},
            batch_stats=batch_stats or {},
        )
        self._state = jax.device_put(state, repl)
        if num_classes is None:
            from tpu_dp.train.step import _infer_forward

            probe = np.zeros((1,) + tuple(image_shape), np.dtype(image_dtype))
            shapes = jax.eval_shape(
                lambda s, b: _infer_forward(model, s, b),
                self._state, {"image": probe},
            )
            num_classes = int(shapes[0].shape[-1])
        self.num_classes = int(num_classes)

        from tpu_dp.train.step import init_serve_stats

        self._stats = jax.device_put(
            init_serve_stats(self.num_classes), repl
        )
        self._repl = repl
        self._batch_sharding = {
            b: (batch_sharding(self.mesh)
                if b % dist.data_axis_size(self.mesh) == 0 else repl)
            for b in self.ladder.buckets
        }
        self._programs: dict[int, object] = {}
        self._on_retrace = on_retrace
        self._fault = FaultInjector.from_spec(fault, rank=jax.process_index())
        self._hb = None
        if obs_dir:
            from tpu_dp.obs.health import HeartbeatWriter

            self._hb = HeartbeatWriter(obs_dir, rank=jax.process_index())
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._batch_index = 0
        self._bucket_counts: dict[int, int] = {}
        self._lock = threading.Lock()  # report() vs dispatch-thread state

        # Per-bucket device-utilization accounting from the SAME cost
        # registry the trainer's MFU gauges use (tpu_dp/obs/costs.py):
        # forward-only FLOPs per image (analytic, ~training/3) times the
        # bucket, per chip — world-divisible buckets shard the batch over
        # the mesh, sub-world buckets run replicated (every chip computes
        # the full bucket). Unknown models/chips publish nothing: absence
        # means "not measured", never a fake number.
        from tpu_dp.obs import costs as _costs

        if flops_per_image is None and model_name:
            flops_per_image = _costs.serve_flops_per_image(model_name)
        self._peak = peak_flops
        if self._peak is None:
            try:
                self._peak = _costs.peak_flops(
                    jax.devices()[0].device_kind
                )
            except Exception:
                self._peak = None
        if flops_per_image:
            world = dist.data_axis_size(self.mesh)
            for b in self.ladder.buckets:
                per_chip = (
                    float(flops_per_image) * b / world
                    if b % world == 0 else float(flops_per_image) * b
                )
                _costs.registry.register(
                    f"serve_step@b{b}", per_chip,
                    source="analytic", check="unverified",
                )

    # -- programs --------------------------------------------------------

    def _program(self, bucket: int):
        from tpu_dp.analysis.recompile import RecompileGuard
        from tpu_dp.train.step import make_serve_step

        prog = self._programs.get(bucket)
        if prog is None:
            prog = RecompileGuard(
                make_serve_step(self.model, self.mesh, bucket),
                name=f"serve_step@b{bucket}",
                warmup_calls=1,
                on_retrace=self._on_retrace,
            )
            self._programs[bucket] = prog
        return prog

    def warmup(self) -> dict[int, float]:
        """Compile + run every bucket program once; per-bucket wall ms.

        After this, the acceptance bar is ZERO retraces for the rest of
        the engine's life (`retraces` property; the guards raise by
        default). Warmup batches are all-padding (weight 0), so the
        device stats count nothing.
        """
        import jax

        times: dict[int, float] = {}
        for bucket in self.ladder.buckets:
            t0 = time.perf_counter()
            # Placed exactly like the live path (`_place_batch`): a warmup
            # call whose argument signature differs from production calls
            # would leave the real first request paying the compile.
            batch = self._place_batch(
                bucket,
                np.zeros((bucket,) + self.queue.image_shape,
                         self.queue.image_dtype),
                np.zeros((bucket,), np.float32),
            )
            self._stats, out = self._program(bucket)(
                self._stats, self._state, batch
            )
            jax.block_until_ready(out)
            times[bucket] = round((time.perf_counter() - t0) * 1e3, 2)
        return times

    @property
    def retraces(self) -> int:
        """Post-warmup retraces across every bucket program (must stay 0)."""
        return sum(g.retraces for g in self._programs.values())

    def guard_stats(self) -> list[dict]:
        return [g.stats() for _, g in sorted(self._programs.items())]

    # -- lifecycle -------------------------------------------------------

    def start(self, warmup: bool = True) -> "InferenceEngine":
        """Warm the bucket programs and launch the dispatch thread."""
        if self._thread is not None:
            raise RuntimeError("engine already started")
        if warmup:
            self.warmup()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="tpu_dp-serve-dispatch", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admission; drain (default) or abandon the queue; join.

        ``drain=False`` is the fast shutdown: the loop exits after at
        most the in-flight batch, and everything still pending is shed
        with reason ``closed`` — abandoned callers are unblocked, never
        left waiting. Re-raises a dispatch-thread failure — an engine
        that died mid-run must not report a clean shutdown.
        """
        self.queue.close()
        if not drain:
            self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if not drain:
            # Abandoned requests must not leave callers blocked forever.
            reqs, _ = self.queue.collect(self.ladder.max_batch * 10**6)
            for req in reqs:
                self._counters.inc("serve.shed")
                self._counters.inc(f"serve.shed.{SHED_CLOSED}")
                req.handle._shed(SHED_CLOSED)
        if self._hb is not None:
            self._hb.close()
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("serve dispatch thread failed") from err

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- producer API ----------------------------------------------------

    def submit(self, images, slo_ms: float | None = None) -> RequestHandle:
        """Enqueue one request (see `RequestQueue.submit`); may shed."""
        return self.queue.submit(images, slo_ms=slo_ms)

    def _place_batch(self, bucket: int, images: np.ndarray,
                     weight: np.ndarray):
        """Host batch → device, under the bucket's sharding (one path for
        warmup and live dispatch, so their jit signatures cannot differ)."""
        import jax

        sh = self._batch_sharding[bucket]
        return jax.device_put(
            {"image": images, "weight": weight},
            {"image": sh, "weight": sh},
        )

    # -- the dispatch loop ----------------------------------------------

    def _loop(self) -> None:
        batch = None
        try:
            while True:
                if self._stop.is_set():  # abandon mode: stop(drain=False)
                    return
                batch = self.batcher.next_batch(timeout_s=0.05)
                if batch == "closed":
                    return
                if batch == "timeout":
                    continue
                if self._stop.is_set():
                    # Abandon a batch formed while stopping — its popped
                    # requests go back through the shed-on-close path.
                    for req in batch.requests:
                        self._counters.inc("serve.shed")
                        self._counters.inc(f"serve.shed.{SHED_CLOSED}")
                        req.handle._shed(SHED_CLOSED)
                    return
                self._run_batch(batch)
                batch = None
        except BaseException as e:  # surfaced by stop()
            self._error = e
            # Neither the in-flight batch's requests (already popped) nor
            # anything still queued may wait forever on a dead loop.
            self.queue.close()
            pending = list(batch.requests) if isinstance(batch, FormedBatch) \
                else []
            reqs, _ = self.queue.collect(self.ladder.max_batch * 10**6)
            pending.extend(reqs)
            for req in pending:
                if not req.handle.done():
                    self._counters.inc("serve.shed")
                    self._counters.inc("serve.shed.engine_error")
                    req.handle._shed("engine_error")

    def _run_batch(self, batch: FormedBatch) -> None:
        import jax

        # Expired handles were resolved (shed) by the queue; nothing to
        # serve in an all-expired wake.
        if not batch.requests:
            return
        t0 = time.perf_counter()
        dev_batch = self._place_batch(batch.bucket, batch.images,
                                      batch.weight)
        jax.block_until_ready(dev_batch)
        t1 = time.perf_counter()
        with self._lock:
            # The donated stats buffer is consumed by the call below, so
            # report()/device_stats() must never read `self._stats` while
            # a dispatch is in flight — the lock brackets consumption and
            # reassignment as one atomic step.
            if self._fault is not None:
                # Deterministic straggler/kill injection, bracketed inside
                # the device span so an injected delay is attributed
                # exactly like a real slow device (tests/test_serve.py).
                self._fault.on_step(self._batch_index)
            self._stats, out = self._program(batch.bucket)(
                self._stats, self._state, dev_batch
            )
            jax.block_until_ready(out)
        t2 = time.perf_counter()
        predictions = np.asarray(out["prediction"])
        confidence = np.asarray(out["confidence"])
        t3 = time.perf_counter()

        h2d_ms = (t1 - t0) * 1e3
        device_ms = (t2 - t1) * 1e3
        d2h_ms = (t3 - t2) * 1e3
        resolutions = []
        missed = 0
        with self._lock:
            for req, sl in zip(batch.requests, batch.slices):
                latency_ms = (t3 - req.arrival) * 1e3
                deadline_missed = t3 > req.deadline
                missed += int(deadline_missed)
                spans = {
                    "queue_wait": max(
                        0.0,
                        (batch.formed - req.arrival) * 1e3 - batch.form_ms,
                    ),
                    "batch_form": batch.form_ms,
                    "h2d": h2d_ms,
                    "device": device_ms,
                    "d2h": d2h_ms,
                    "total": latency_ms,
                }
                self.recorder.record(req.req_id, spans, ts=req.arrival_ts)
                resolutions.append(
                    (req, sl, latency_ms, deadline_missed, spans)
                )
            self._bucket_counts[batch.bucket] = (
                self._bucket_counts.get(batch.bucket, 0) + 1
            )
            self._batch_index += 1
        # Publish counters BEFORE waking any waiter: a caller whose last
        # handle just resolved must read books that already include it
        # (the loadgen's exact-consistency audit depends on this order).
        self._counters.inc("serve.batches")
        self._counters.inc("serve.completed", len(batch.requests))
        if missed:
            self._counters.inc("serve.deadline_missed", missed)
        self._counters.gauge("serve.batch_occupancy", batch.occupancy)
        # Per-device HBM gauges from the dispatch loop — serving was the
        # one workload flying blind on device memory (the trainer already
        # publishes these per window). Backends without memory stats
        # publish nothing.
        from tpu_dp.obs.counters import update_device_memory_gauges

        update_device_memory_gauges(registry=self._counters)
        # Per-bucket device utilization from the shared cost registry:
        # the fraction of the chip's peak this dispatch's forward used.
        from tpu_dp.obs import costs as _costs
        from tpu_dp.obs import flightrec as _flightrec

        util = _costs.registry.utilization(
            f"serve_step@b{batch.bucket}", 1, device_ms / 1e3, self._peak
        )
        if util is not None:
            self._counters.gauge(f"serve.device_util.b{batch.bucket}",
                                 round(util, 4))
            self._counters.gauge("serve.device_util", round(util, 4))
        _flightrec.record(
            "serve_dispatch", bucket=batch.bucket,
            n=len(batch.requests), occupancy=batch.occupancy,
            device_ms=round(device_ms, 3), deadline_missed=missed,
        )
        if self._hb is not None:
            self._hb.beat(
                step=self._batch_index,
                step_ms=batch.form_ms + (t3 - t0) * 1e3,
            )
        for req, sl, latency_ms, deadline_missed, spans in resolutions:
            req.handle._resolve(
                predictions[sl].copy(), confidence[sl].copy(),
                latency_ms, deadline_missed, spans,
            )

    # -- reporting -------------------------------------------------------

    def device_stats(self) -> dict:
        """The donated stats pytree, fetched: device-side ground truth."""
        with self._lock:
            served = np.asarray(self._stats["served"])
            counts = np.asarray(self._stats["class_counts"])
        return {
            "served": int(served),
            "class_counts": [int(c) for c in counts],
        }

    def report(self) -> dict:
        """SLO attainment + latency percentiles + shed/bucket accounting.

        Both come from the per-request obs span records: each served
        request's ``total`` span is its end-to-end latency, and SLO
        attainment is the fraction of *completed* requests within
        ``slo_ms`` (shed requests are reported separately — a shed is an
        explicit rejection, not a silent miss). The recorder is a ring
        (``span_capacity`` requests), so on a long-lived engine these are
        the statistics of the most recent window — bounded memory by
        design, like the trainer's span ring.
        """
        from tpu_dp.obs.spans import percentile

        with self._lock:
            buckets = dict(sorted(self._bucket_counts.items()))
            n_batches = self._batch_index
            lat = sorted(
                rec["spans"]["total"] for rec in self.recorder.records()
            )
            # Under the same lock as record(): a rollup while the dispatch
            # thread appends would iterate a mutating deque.
            rollup = self.recorder.rollup()
        latency = None
        attainment = None
        if lat:
            latency = {
                "p50_ms": round(percentile(lat, 50), 3),
                "p95_ms": round(percentile(lat, 95), 3),
                "p99_ms": round(percentile(lat, 99), 3),
                "mean_ms": round(sum(lat) / len(lat), 3),
                "max_ms": round(lat[-1], 3),
                "n": len(lat),
            }
            attainment = round(
                sum(1 for v in lat if v <= self.slo_ms) / len(lat), 4
            )
        snap = self._counters.snapshot()
        return {
            "slo": {"target_ms": self.slo_ms, "attainment": attainment},
            "latency_ms": latency,
            "spans": {k: v for k, v in rollup.items() if k != "total"},
            "counters": {k: v for k, v in sorted(snap.items())
                         if k.startswith("serve.")},
            "batches": n_batches,
            "bucket_counts": buckets,
            "occupancy": snap.get("serve.batch_occupancy"),
            "device_util": snap.get("serve.device_util"),
            "retraces": self.retraces,
            "guards": self.guard_stats(),
            "device_stats": self.device_stats(),
            "world": int(self.mesh.devices.size),
        }

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_serve_config(cls, model, params, serve_cfg, **kwargs):
        """Build from a `tpu_dp.config.ServeConfig` section."""
        from tpu_dp.serve.batcher import parse_buckets

        return cls(
            model, params,
            buckets=parse_buckets(serve_cfg.buckets),
            max_wait_ms=serve_cfg.max_wait_ms,
            max_queue=serve_cfg.max_queue,
            slo_ms=serve_cfg.slo_ms,
            shed_headroom_ms=serve_cfg.shed_headroom_ms,
            obs_dir=serve_cfg.obs_dir or None,
            **kwargs,
        )

    @classmethod
    def from_checkpoint(cls, ckpt_dir, model=None, mesh=None, **kwargs):
        """Serve straight from a training checkpoint, params-only.

        ``ckpt_dir`` is either one ``step_*`` checkpoint directory or a
        `CheckpointManager` root (its newest complete checkpoint is
        used). The model is rebuilt from the checkpoint's recorded config
        when not passed. Optimizer state is never materialized
        (`checkpoint.load_params_only`), so a checkpoint written under
        any world size or ``train.update_sharding`` mode serves
        unchanged.
        """
        import json
        from pathlib import Path

        import jax

        from tpu_dp.checkpoint import CheckpointManager, load_params_only
        from tpu_dp.models import build_model

        ckpt_dir = Path(ckpt_dir)
        if not (ckpt_dir / "state.msgpack").exists():
            latest = CheckpointManager(ckpt_dir).latest_dir()
            if latest is None:
                raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
            ckpt_dir = latest
        meta_path = ckpt_dir / "meta.json"
        meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
        cfg = meta.get("config", {})
        if model is None:
            model_cfg = cfg.get("model", {})
            name = model_cfg.get("name", "net")
            num_classes = model_cfg.get("num_classes") or (
                100 if cfg.get("data", {}).get("dataset") == "cifar100"
                else 10
            )
            model = build_model(name, num_classes=num_classes)
            # The checkpoint names the model, so the per-bucket
            # device-utilization gauges come for free.
            kwargs.setdefault("model_name", name)
        image_shape = kwargs.get("image_shape", (32, 32, 3))
        variables = model.init(
            jax.random.PRNGKey(0),
            np.zeros((1,) + tuple(image_shape), np.float32),
            train=False,
        )
        params, batch_stats, _ = load_params_only(
            ckpt_dir,
            variables["params"],
            target_batch_stats=variables.get("batch_stats") or None,
        )
        return cls(model, params, batch_stats=batch_stats, mesh=mesh,
                   **kwargs)
