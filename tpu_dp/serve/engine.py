"""The single-replica inference engine: queue → batcher → one ServeReplica.

The serving entry point for one process serving one model on one mesh —
and, since the self-healing tier landed, a thin façade over the same
`tpu_dp.serve.replica.ServeReplica` core the multi-replica
`tpu_dp.serve.router.ServeCluster` fans out (docs/SERVING.md). The engine
owns the admission edge (a `RequestQueue` with SLO classes and typed
shedding) and the shared books (span recorder, per-class latency book);
the replica owns the per-bucket compiled programs, the dispatch thread,
heartbeats and fault injection. One code path serves both topologies, so
the single-engine tests pin the exact dispatch semantics every cluster
replica runs.

    submit() → RequestQueue → DynamicBatcher → per-bucket jitted
    `make_serve_step` → resolve handles

Every bucket in the ladder gets its own pre-compiled program (warmed up at
`start`), wrapped in a `RecompileGuard` with ``on_retrace="raise"`` by
default: a retrace during serving means a shape/dtype leaked past the
batcher, and the engine treats that as a bug, not a slow path. The
params/batch_stats live in a `TrainState` with an *empty* opt_state
(`checkpoint.load_params_only` — inference never materializes optimizer
slots); the device-mesh replicas give batch fan-out for free
(see `make_serve_step`). `swap_model` hot-swaps a new weight version
between batches — zero dropped requests, every response stamped with the
version that served it.

Telemetry (docs/OBSERVABILITY.md, docs/SERVING.md): per-request spans
``queue_wait / batch_form / h2d / device / d2h`` (+ ``total``) in a
`SpanRecorder`; counters ``serve.accepted / serve.shed[.reason] /
serve.completed / serve.deadline_missed / serve.batches`` (+ per-class
``.c<k>`` twins) and the ``serve.batch_occupancy`` gauge in the
process-wide registry; per-batch heartbeats via `HeartbeatWriter` when
``obs_dir`` is set, so a straggling serve rank is attributable with the
exact `HealthMonitor` tooling the trainer uses. The deterministic fault
injector (``TPU_DP_FAULT=delay:…``) is consulted per batch inside the
device span, so injected stragglers surface in spans and heartbeats like
real ones.
"""

from __future__ import annotations

import numpy as np

from tpu_dp.obs.counters import Counters, counters as _global_counters
from tpu_dp.obs.spans import SpanRecorder
from tpu_dp.serve.batcher import BucketLadder
from tpu_dp.serve.queue import (
    SHED_CLOSED, RequestHandle, RequestQueue, shed_counted,
)
from tpu_dp.serve.replica import SERVE_SPANS, LatencyBook, ServeReplica

__all__ = ["SERVE_SPANS", "InferenceEngine", "register_serve_costs"]


def register_serve_costs(ladder: BucketLadder, world: int,
                         model_name: str = "",
                         flops_per_image: float | None = None
                         ) -> dict[int, float]:
    """Per-bucket serve FLOPs: registered in the shared cost registry AND
    returned for the replicas' own utilization gauges.

    Forward-only FLOPs per image (analytic, ~training/3) times the
    bucket, per chip — world-divisible buckets shard the batch over the
    mesh, sub-world buckets run replicated (every chip computes the full
    bucket). Unknown models publish nothing: absence means "not
    measured", never a fake number. The returned dict (bucket → per-chip
    FLOPs) is what each replica computes its gauges from: the registry
    entry is introspection metadata, and two topologies with different
    per-replica worlds in one process (engine + cluster) must not
    corrupt each other's live gauges through the shared key.
    """
    from tpu_dp.obs import costs as _costs

    if flops_per_image is None and model_name:
        flops_per_image = _costs.serve_flops_per_image(model_name)
    if not flops_per_image:
        return {}
    out: dict[int, float] = {}
    for b in ladder.buckets:
        per_chip = (
            float(flops_per_image) * b / world
            if b % world == 0 else float(flops_per_image) * b
        )
        out[b] = per_chip
        _costs.registry.register(
            f"serve_step@b{b}", per_chip,
            source="analytic", check="unverified",
        )
    return out


class InferenceEngine:
    """Batched-inference engine over the data mesh (docs/SERVING.md)."""

    def __init__(
        self,
        model,
        params,
        batch_stats=None,
        mesh=None,
        buckets=None,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        slo_ms: float = 50.0,
        shed_headroom_ms: float = 0.0,
        image_shape: tuple[int, int, int] = (32, 32, 3),
        image_dtype=np.uint8,
        num_classes: int | None = None,
        obs_dir: str | None = None,
        span_capacity: int = 4096,
        on_retrace: str = "raise",
        fault: str = "",
        registry: Counters | None = None,
        model_name: str = "",
        flops_per_image: float | None = None,
        peak_flops: float | None = None,
        class_slo_ms: dict[int, float] | None = None,
        profile_dir: str = "",
        profile_batches: tuple[int, int] | None = None,
    ):
        import jax

        from tpu_dp.parallel import dist

        self.model = model
        self.mesh = dist.data_mesh() if mesh is None else mesh
        self.ladder = BucketLadder(
            buckets if buckets is not None else BucketLadder().buckets
        )
        self.slo_ms = float(slo_ms)
        self.class_slo_ms = dict(class_slo_ms or {})
        self._counters = _global_counters if registry is None else registry
        self.queue = RequestQueue(
            max_depth=max_queue,
            default_slo_ms=slo_ms,
            shed_headroom_ms=shed_headroom_ms,
            image_shape=image_shape,
            image_dtype=image_dtype,
            max_request=self.ladder.max_batch,
            registry=self._counters,
        )
        self.recorder = SpanRecorder(capacity=span_capacity)
        self.latency_book = LatencyBook(capacity=span_capacity)
        hb = None
        if obs_dir:
            from tpu_dp.obs.health import HeartbeatWriter

            hb = HeartbeatWriter(obs_dir, rank=jax.process_index())
        bucket_flops = register_serve_costs(
            self.ladder, dist.data_axis_size(self.mesh),
            model_name=model_name, flops_per_image=flops_per_image,
        )
        self.replica = ServeReplica(
            sid=0,
            model=model,
            params=params,
            batch_stats=batch_stats,
            mesh=self.mesh,
            ladder=self.ladder,
            queue=self.queue,
            recorder=self.recorder,
            latency_book=self.latency_book,
            max_wait_ms=max_wait_ms,
            num_classes=num_classes,
            on_retrace=on_retrace,
            fault=fault,
            fault_rank=jax.process_index(),
            hb=hb,
            router=None,
            peak_flops=peak_flops,
            bucket_flops=bucket_flops,
            registry=self._counters,
            profile_dir=profile_dir,
            profile_batches=profile_batches,
        )
        self.batcher = self.replica.batcher
        self.num_classes = self.replica.num_classes
        self._published_version = self.replica.model_version

    # -- replica delegation (the façade's seams) -------------------------

    @property
    def _programs(self) -> dict:
        return self.replica._programs

    @property
    def _stats(self):
        return self.replica._stats

    @property
    def _lock(self):
        return self.replica._lock

    @property
    def _hb(self):
        return self.replica._hb

    @property
    def model_version(self) -> int:
        return self.replica.model_version

    def warmup(self) -> dict[int, float]:
        """Compile + run every bucket program once; per-bucket wall ms
        (`ServeReplica.warmup`)."""
        return self.replica.warmup()

    @property
    def retraces(self) -> int:
        """Post-warmup retraces across every bucket program (must stay 0)."""
        return self.replica.retraces

    def guard_stats(self) -> list[dict]:
        return self.replica.guard_stats()

    def device_stats(self) -> dict:
        """The donated stats pytree, fetched: device-side ground truth."""
        return self.replica.device_stats()

    # -- hot swap --------------------------------------------------------

    def swap_model(self, params, batch_stats=None,
                   version: int | None = None) -> int:
        """Hot-swap the served weights in place, between batches.

        Zero dropped requests by construction: the dispatch loop applies
        the swap only at a batch boundary, and every response carries the
        ``model_version`` that actually served it. Returns the version
        now pending (applied before the next dispatched batch). Versions
        count PUBLISHED swaps, not applied ones: two swaps landing
        between the same pair of batches still get distinct stamps.
        """
        self._published_version = (self._published_version + 1
                                   if version is None else int(version))
        self.replica.set_pending_state(params, batch_stats,
                                       self._published_version)
        return self._published_version

    def swap_from_checkpoint(self, ckpt_dir,
                             version: int | None = None) -> int:
        """`swap_model` from a training checkpoint (params-only load —
        optimizer state and error-feedback residuals never materialize)."""
        params, batch_stats, _ = _load_swap_checkpoint(
            ckpt_dir, self.model, self.queue.image_shape
        )
        return self.swap_model(params, batch_stats, version=version)

    # -- lifecycle -------------------------------------------------------

    def start(self, warmup: bool = True) -> "InferenceEngine":
        """Warm the bucket programs and launch the dispatch thread."""
        if self.replica.status == "running":
            raise RuntimeError("engine already started")
        if warmup:
            self.replica.warmup()
        self.replica.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Close admission; drain (default) or abandon the queue; join.

        ``drain=False`` is the fast shutdown: the loop exits after at
        most the in-flight batch, and everything still pending is shed
        with reason ``closed`` — abandoned callers are unblocked, never
        left waiting. Re-raises a dispatch-thread failure — an engine
        that died mid-run must not report a clean shutdown.
        """
        self.queue.close()
        if not drain:
            self.replica.stop_now()
        self.replica.join()
        if not drain:
            # Abandoned requests must not leave callers blocked forever.
            reqs, _ = self.queue.collect(self.ladder.max_batch * 10**6)
            for req in reqs:
                shed_counted(self._counters, req.handle, SHED_CLOSED)
        if self.replica._hb is not None:
            self.replica._hb.close()
        err = self.replica.take_error()
        if err is not None:
            raise RuntimeError("serve dispatch thread failed") from err

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- producer API ----------------------------------------------------

    def submit(self, images, slo_ms: float | None = None,
               slo_class: int = 0) -> RequestHandle:
        """Enqueue one request (see `RequestQueue.submit`); may shed.

        ``slo_class`` picks the priority tier (0 = highest); its default
        latency budget comes from ``class_slo_ms`` when configured.
        """
        if slo_ms is None:
            slo_ms = self.class_slo_ms.get(int(slo_class))
        return self.queue.submit(images, slo_ms=slo_ms, slo_class=slo_class)

    # -- reporting -------------------------------------------------------

    def report(self) -> dict:
        """SLO attainment + latency percentiles + shed/bucket accounting.

        Both come from the per-request obs span records: each served
        request's ``total`` span is its end-to-end latency, and SLO
        attainment is the fraction of *completed* requests within
        ``slo_ms`` (shed requests are reported separately — a shed is an
        explicit rejection, not a silent miss). ``classes`` is the
        per-SLO-class twin (attainment vs each class's own target). The
        recorder is a ring (``span_capacity`` requests), so on a
        long-lived engine these are the statistics of the most recent
        window — bounded memory by design, like the trainer's span ring.
        """
        from tpu_dp.serve.replica import serve_report_core

        out = serve_report_core(
            self.recorder, self.latency_book, self.replica._books_lock,
            self.class_slo_ms, self.slo_ms, self._counters,
        )
        snap_replica = self.replica.snapshot()
        out.update({
            "batches": snap_replica["batches"],
            "bucket_counts": snap_replica["bucket_counts"],
            "retraces": self.retraces,
            "guards": self.guard_stats(),
            "device_stats": self.device_stats(),
            "model_version": self.replica.model_version,
            "world": int(self.mesh.devices.size),
        })
        return out

    # -- constructors ----------------------------------------------------

    @classmethod
    def from_serve_config(cls, model, params, serve_cfg, **kwargs):
        """Build from a `tpu_dp.config.ServeConfig` section."""
        from tpu_dp.config import parse_class_slo_ms
        from tpu_dp.serve.batcher import parse_buckets
        from tpu_dp.utils.profiling import parse_profile_steps

        profile_batches = parse_profile_steps(serve_cfg.profile_batches)
        if profile_batches is not None and not serve_cfg.profile_dir:
            raise ValueError(
                "serve.profile_batches needs serve.profile_dir for the "
                "trace output"
            )
        return cls(
            model, params,
            buckets=parse_buckets(serve_cfg.buckets),
            max_wait_ms=serve_cfg.max_wait_ms,
            max_queue=serve_cfg.max_queue,
            slo_ms=serve_cfg.slo_ms,
            shed_headroom_ms=serve_cfg.shed_headroom_ms,
            obs_dir=serve_cfg.obs_dir or None,
            class_slo_ms=parse_class_slo_ms(serve_cfg.class_slo_ms),
            profile_dir=serve_cfg.profile_dir,
            profile_batches=profile_batches,
            **kwargs,
        )

    @classmethod
    def from_checkpoint(cls, ckpt_dir, model=None, mesh=None, **kwargs):
        """Serve straight from a training checkpoint, params-only.

        ``ckpt_dir`` is either one ``step_*`` checkpoint directory or a
        `CheckpointManager` root (its newest complete checkpoint is
        used). The model is rebuilt from the checkpoint's recorded config
        when not passed. Optimizer state is never materialized — and a
        post-PR-10 int8-trained checkpoint's error-feedback residuals are
        dropped the same way (`checkpoint.load_params_only`) — so a
        checkpoint written under any world size, ``train.update_sharding``
        mode, or ``train.collective_dtype`` serves unchanged.
        """
        model, params, batch_stats, name = _resolve_checkpoint(
            ckpt_dir, model, kwargs.get("image_shape", (32, 32, 3))
        )
        if name:
            kwargs.setdefault("model_name", name)
        return cls(model, params, batch_stats=batch_stats, mesh=mesh,
                   **kwargs)


def _resolve_ckpt_dir(ckpt_dir):
    """One ``step_*`` checkpoint directory, or a `CheckpointManager` root
    resolved to its newest complete checkpoint — every serve-side loader
    (initial load AND hot swap) accepts both."""
    from pathlib import Path

    from tpu_dp.checkpoint import CheckpointManager

    ckpt_dir = Path(ckpt_dir)
    if not (ckpt_dir / "state.msgpack").exists():
        latest = CheckpointManager(ckpt_dir).latest_dir()
        if latest is None:
            raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
        ckpt_dir = latest
    return ckpt_dir


def _resolve_checkpoint(ckpt_dir, model, image_shape):
    """(model, params, batch_stats, model_name) from a training checkpoint
    dir or CheckpointManager root — the shared loader behind
    `InferenceEngine.from_checkpoint` and `ServeCluster.from_checkpoint`."""
    import json

    from tpu_dp.models import build_model

    ckpt_dir = _resolve_ckpt_dir(ckpt_dir)
    meta_path = ckpt_dir / "meta.json"
    meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
    cfg = meta.get("config", {})
    name = ""
    if model is None:
        model_cfg = cfg.get("model", {})
        name = model_cfg.get("name", "net")
        num_classes = model_cfg.get("num_classes") or (
            100 if cfg.get("data", {}).get("dataset") == "cifar100"
            else 10
        )
        model = build_model(name, num_classes=num_classes)
    params, batch_stats, _ = _load_swap_checkpoint(
        ckpt_dir, model, image_shape
    )
    return model, params, batch_stats, name


def _load_swap_checkpoint(ckpt_dir, model, image_shape):
    """Params-only restore against a fresh init of ``model`` (accepts a
    step dir or a CheckpointManager root, like `from_checkpoint`)."""
    import jax

    from tpu_dp.checkpoint import load_params_only

    variables = model.init(
        jax.random.PRNGKey(0),
        np.zeros((1,) + tuple(image_shape), np.float32),
        train=False,
    )
    return load_params_only(
        _resolve_ckpt_dir(ckpt_dir),
        variables["params"],
        target_batch_stats=variables.get("batch_stats") or None,
    )
