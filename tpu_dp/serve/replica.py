"""One serving replica: per-bucket compiled forwards + a dispatch worker.

The unit the self-healing serving tier is built from (docs/SERVING.md
"Replica fan-out"). A `ServeReplica` owns everything that is *per-replica*
— a device mesh (a subset of the host's devices under fan-out, the whole
mesh for a single-replica `InferenceEngine`), the per-bucket pre-compiled
`make_serve_step` programs behind RecompileGuards, the versioned inference
state, the donated device stats, a heartbeat writer, and its fault
injectors — and runs one dispatch thread that pulls padded batches from a
**shared** `RequestQueue`.

What it deliberately does NOT own: the queue (shared admission — the
router's, or the engine's), the span recorder and per-class latency book
(shared books: the audit is cluster-wide), and the health/failover policy.
A replica reports *facts* (heartbeats, in-flight age, errors); the router
(`tpu_dp/serve/router.py`) decides what they mean. With ``router=None``
the replica degrades to the original single-engine behavior: a dispatch
failure sheds everything ``engine_error`` and closes the queue, because
there is nobody to fail over to.

Lifecycle states (``status``): ``idle`` → ``running`` → one of
``stopped`` (queue drained/closed), ``left`` (drain-then-leave — elastic
departure; `start` again to rejoin without recompiling anything), or
``dead`` (dispatch raised; the router retried/shed its in-flight batch).

Hot swap: `set_pending_state` parks a new (device-placed) state + version;
the dispatch loop swaps it in **between batches** — never mid-batch, so
every response is stamped with exactly the ``model_version`` that computed
it and zero requests are dropped by an upgrade.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from tpu_dp.obs.counters import Counters, counters as _global_counters
from tpu_dp.obs.spans import SpanRecorder, percentile
from tpu_dp.serve.batcher import BucketLadder, DynamicBatcher, FormedBatch
from tpu_dp.serve.queue import (
    SHED_CLOSED,
    RequestQueue,
    shed_counted,
)

#: per-request span names, in pipeline order (the serving analogue of
#: `tpu_dp.obs.spans.STEP_SPANS`).
SERVE_SPANS = ("queue_wait", "batch_form", "h2d", "device", "d2h")

#: fault kinds consulted INSIDE the device span (they simulate a slow or
#: corrupt device) vs at the loop top (process/membership events).
_DEVICE_FAULT_KINDS = ("delay",)
_LOOP_FAULT_KINDS = ("leave", "preempt", "kill")


def parse_fault_specs(spec: str, rank: int):
    """';'-separated fault specs → one injector per plan for ``rank``.

    The single-spec grammar is `tpu_dp.resilience.faultinject`'s; the
    semicolon list exists because a chaos scenario poisons one replica
    with ``delay:`` while another gets ``leave:`` in the same run. Empty
    spec falls back to ``TPU_DP_FAULT`` (same as the single-spec path).
    """
    from tpu_dp.resilience.faultinject import FaultInjector, FaultPlan

    spec = spec or os.environ.get("TPU_DP_FAULT", "")
    out = []
    for part in spec.split(";"):
        plan = FaultPlan.parse(part.strip())
        if plan is not None:
            out.append(FaultInjector(plan, rank=rank))
    return out


class LatencyBook:
    """Shared per-SLO-class completed-request latencies (bounded rings).

    One per engine/cluster, appended by every replica under the shared
    books lock; `rollup` turns it into the per-class attainment block the
    serve report and ``obsctl diff`` gate on. Bounded like the span ring:
    long-lived servers report the statistics of the recent window.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lat: dict[int, deque] = {}

    def note(self, slo_class: int, latency_ms: float) -> None:
        dq = self._lat.get(int(slo_class))
        if dq is None:
            dq = self._lat.setdefault(
                int(slo_class), deque(maxlen=self.capacity)
            )
        dq.append(float(latency_ms))

    def classes(self) -> list[int]:
        return sorted(self._lat)

    def rollup(self, slo_ms_by_class: dict[int, float],
               default_slo_ms: float) -> dict[str, dict]:
        """Per-class latency percentiles + attainment vs the class target.

        Keys are stringified class ids (JSON-stable). ``attainment`` is
        the fraction of completed requests within the class's SLO —
        sheds are accounted separately (explicit rejection ≠ silent
        miss), exactly like the engine-level attainment.
        """
        out: dict[str, dict] = {}
        for cls in self.classes():
            lat = sorted(self._lat[cls])
            if not lat:
                continue
            target = float(slo_ms_by_class.get(cls, default_slo_ms))
            out[str(cls)] = {
                "slo_ms": target,
                "attainment": round(
                    sum(1 for v in lat if v <= target) / len(lat), 4
                ),
                "p50_ms": round(percentile(lat, 50), 3),
                "p95_ms": round(percentile(lat, 95), 3),
                "mean_ms": round(sum(lat) / len(lat), 3),
                "n": len(lat),
            }
        return out


def serve_report_core(recorder: SpanRecorder, latency_book: LatencyBook,
                      books_lock: threading.Lock,
                      class_slo_ms: dict[int, float], slo_ms: float,
                      registry: Counters) -> dict:
    """The report keys shared by `InferenceEngine` and `ServeCluster` —
    one rollup implementation, so the single-replica and fan-out reports
    cannot drift. Overall SLO attainment and latency percentiles come
    from the shared span ring, per-class attainment from the latency
    book, both read under the shared books lock (a rollup racing a
    dispatch thread's append would iterate a mutating deque)."""
    with books_lock:
        lat = sorted(
            rec["spans"]["total"] for rec in recorder.records()
        )
        rollup = recorder.rollup()
        classes = latency_book.rollup(class_slo_ms, slo_ms)
    latency = None
    attainment = None
    if lat:
        latency = {
            "p50_ms": round(percentile(lat, 50), 3),
            "p95_ms": round(percentile(lat, 95), 3),
            "p99_ms": round(percentile(lat, 99), 3),
            "mean_ms": round(sum(lat) / len(lat), 3),
            "max_ms": round(lat[-1], 3),
            "n": len(lat),
        }
        attainment = round(
            sum(1 for v in lat if v <= slo_ms) / len(lat), 4
        )
    snap = registry.snapshot()
    return {
        "slo": {"target_ms": slo_ms, "attainment": attainment},
        "latency_ms": latency,
        "spans": {k: v for k, v in rollup.items() if k != "total"},
        "classes": classes,
        "counters": {k: v for k, v in sorted(snap.items())
                     if k.startswith("serve.")},
        "occupancy": snap.get("serve.batch_occupancy"),
        "device_util": snap.get("serve.device_util"),
    }


class ServeReplica:
    """One replica's compiled programs + dispatch worker (module docstring).

    ``params``/``batch_stats`` are host (or any-layout) pytrees; the
    replica places them replicated over its own ``mesh``. ``queue``,
    ``recorder``, ``latency_book`` and ``books_lock`` are shared with the
    other replicas (and the report reader) — everything else is private.
    """

    def __init__(
        self,
        sid: int,
        model,
        params,
        mesh,
        ladder: BucketLadder,
        queue: RequestQueue,
        recorder: SpanRecorder,
        latency_book: LatencyBook,
        batch_stats=None,
        books_lock: threading.Lock | None = None,
        max_wait_ms: float = 5.0,
        num_classes: int | None = None,
        on_retrace: str = "raise",
        fault: str = "",
        fault_rank: int | None = None,
        hb=None,
        router=None,
        model_version: int = 1,
        peak_flops: float | None = None,
        bucket_flops: dict[int, float] | None = None,
        registry: Counters | None = None,
        profile_dir: str = "",
        profile_batches: tuple[int, int] | None = None,
    ):
        import jax

        from tpu_dp.parallel import dist
        from tpu_dp.parallel.sharding import (
            batch_sharding, replicated_sharding,
        )
        from tpu_dp.train.state import TrainState

        self.sid = int(sid)
        self.model = model
        self.mesh = mesh
        self.ladder = ladder
        self.queue = queue
        self.recorder = recorder
        self.latency_book = latency_book
        self.batcher = DynamicBatcher(queue, ladder, max_wait_ms=max_wait_ms)
        self.router = router
        self._counters = _global_counters if registry is None else registry
        self._on_retrace = on_retrace
        self._hb = hb
        self._faults = parse_fault_specs(
            fault, self.sid if fault_rank is None else int(fault_rank)
        )

        # Inference state: params (+ BN stats) only, replicated over THIS
        # replica's mesh, never donated. The empty opt_state is the point —
        # serving a checkpoint must not pay for (or know about) optimizer
        # slots, and a post-PR-10 checkpoint's error-feedback residuals
        # are equally training-only (`checkpoint.load_params_only`).
        self._repl = replicated_sharding(mesh)
        state = TrainState(
            step=np.zeros((), np.int32),
            params=params,
            opt_state={},
            batch_stats=batch_stats or {},
        )
        self._state = jax.device_put(state, self._repl)
        self.model_version = int(model_version)
        self._pending_state = None  # (device_state, version) hot-swap park

        if num_classes is None:
            from tpu_dp.train.step import _infer_forward

            probe = np.zeros((1,) + self.queue.image_shape,
                             self.queue.image_dtype)
            shapes = jax.eval_shape(
                lambda s, b: _infer_forward(model, s, b),
                self._state, {"image": probe},
            )
            num_classes = int(shapes[0].shape[-1])
        self.num_classes = int(num_classes)

        from tpu_dp.train.step import init_serve_stats

        self._stats = jax.device_put(
            init_serve_stats(self.num_classes), self._repl
        )
        self._batch_sharding = {
            b: (batch_sharding(mesh)
                if b % dist.data_axis_size(mesh) == 0 else self._repl)
            for b in ladder.buckets
        }
        self._programs: dict[int, object] = {}
        # Per-bucket per-chip FLOPs snapshot (engine.register_serve_costs):
        # utilization gauges compute from THIS replica's own numbers, so a
        # second topology registering the shared `serve_step@bN` cost-
        # registry keys with a different world cannot corrupt them.
        self._bucket_flops = dict(bucket_flops or {})
        self._peak = peak_flops
        if self._peak is None:
            try:
                from tpu_dp.obs import costs as _costs

                self._peak = _costs.peak_flops(
                    jax.devices()[0].device_kind
                )
            except Exception:
                self._peak = None

        # Batch-ranged serving capture (the training comm-profile
        # window's serving twin, docs/OBSERVABILITY.md): a StepProfiler
        # armed over *batch indices* — per-bucket device time becomes
        # xplane-inspectable (`python -m tpu_dp.obs.xplane`) exactly like
        # a training window, with the same flightrec
        # profile_start/profile_stop discoverability. Per-sid subdirs so
        # fan-out replicas' captures never collide.
        self._profiler = None
        if profile_dir and profile_batches is not None:
            from tpu_dp.utils.profiling import StepProfiler

            self._profiler = StepProfiler(
                os.path.join(profile_dir, f"r{self.sid}"),
                int(profile_batches[0]), int(profile_batches[1]),
                label=f"serve_r{self.sid}",
            )

        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._batch_index = 0
        self._bucket_counts: dict[int, int] = {}
        # The dispatch lock brackets donated-stats consumption and
        # reassignment as one atomic step (device_stats/report vs the
        # dispatch thread); the books lock guards the SHARED recorder +
        # latency book across replicas. For a single-replica engine both
        # default to the same object — exactly the old engine locking.
        self._lock = threading.Lock()
        self._books_lock = self._lock if books_lock is None else books_lock

        self.status = "idle"  # idle | running | stopped | left | dead
        self.draining = False
        self.drain_reason = ""
        self.quarantined = False
        self.inflight_since: float | None = None  # monotonic; device-held
        self.last_progress = time.monotonic()

    # -- programs --------------------------------------------------------

    def _program(self, bucket: int):
        from tpu_dp.analysis.recompile import RecompileGuard
        from tpu_dp.train.step import make_serve_step

        prog = self._programs.get(bucket)
        if prog is None:
            prog = RecompileGuard(
                make_serve_step(self.model, self.mesh, bucket),
                name=f"serve_step@b{bucket}",
                warmup_calls=1,
                on_retrace=self._on_retrace,
            )
            self._programs[bucket] = prog
        return prog

    def warmup(self) -> dict[int, float]:
        """Compile + run every bucket program once; per-bucket wall ms.

        After this, the acceptance bar is ZERO retraces for the rest of
        the replica's life (`retraces`; the guards raise by default) —
        including across drain/rejoin cycles, which reuse the compiled
        programs untouched. Warmup batches are all-padding (weight 0),
        so the device stats count nothing.
        """
        import jax

        times: dict[int, float] = {}
        for bucket in self.ladder.buckets:
            t0 = time.perf_counter()
            # Placed exactly like the live path (`_place_batch`): a warmup
            # call whose argument signature differs from production calls
            # would leave the real first request paying the compile.
            batch = self._place_batch(
                bucket,
                np.zeros((bucket,) + self.queue.image_shape,
                         self.queue.image_dtype),
                np.zeros((bucket,), np.float32),
            )
            self._stats, out = self._program(bucket)(
                self._stats, self._state, batch
            )
            jax.block_until_ready(out)
            times[bucket] = round((time.perf_counter() - t0) * 1e3, 2)
        return times

    @property
    def retraces(self) -> int:
        """Post-warmup retraces across every bucket program (must stay 0).

        Tolerates non-guard entries: the failover tests (and any chaos
        harness) replace bucket programs with raising stubs to simulate a
        dying replica — a dead replica's report must still render."""
        return sum(
            getattr(g, "retraces", 0) for g in self._programs.values()
        )

    def guard_stats(self) -> list[dict]:
        return [
            g.stats() for _, g in sorted(self._programs.items())
            if hasattr(g, "stats")
        ]

    # -- hot swap --------------------------------------------------------

    def set_pending_state(self, params, batch_stats, version: int) -> None:
        """Park a new model version; applied between batches (never mid-
        batch). ``params``/``batch_stats`` may be host arrays — placement
        onto this replica's mesh happens here, off the dispatch thread."""
        import jax

        from tpu_dp.train.state import TrainState

        state = jax.device_put(
            TrainState(
                step=np.zeros((), np.int32),
                params=params,
                opt_state={},
                batch_stats=batch_stats or {},
            ),
            self._repl,
        )
        with self._lock:
            self._pending_state = (state, int(version))

    def _apply_pending_swap(self) -> None:
        """Dispatch-thread only: swap in a parked version between batches."""
        with self._lock:
            pending, self._pending_state = self._pending_state, None
            if pending is None:
                return
            self._state, self.model_version = pending
        from tpu_dp.obs import flightrec as _flightrec

        self._counters.gauge("serve.model_version", self.model_version)
        _flightrec.record(
            "model_swap", replica=self.sid, version=self.model_version,
            step=self._batch_index,
        )

    # -- health facts ----------------------------------------------------

    def inflight_age(self, now: float | None = None) -> float | None:
        """Seconds the current batch has been held on device, or None."""
        since = self.inflight_since
        if since is None:
            return None
        return (time.monotonic() if now is None else now) - since

    def _touch(self) -> None:
        self.last_progress = time.monotonic()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ServeReplica":
        """Launch (or relaunch — rejoin) the dispatch thread.

        Rejoin is deliberately a plain `start`: programs, state and stats
        survive a drain, so a returning replica serves its first batch
        without a restart, a recompile, or a weight reload.
        """
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError(f"replica {self.sid} already running")
        self._stop.clear()
        self.draining = False
        self.drain_reason = ""
        self.status = "running"
        self._touch()
        self._thread = threading.Thread(
            target=self._loop,
            name=f"tpu_dp-serve-replica-{self.sid}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop_now(self) -> None:
        """Abandon mode: exit after at most the in-flight batch."""
        self._stop.set()

    def request_drain(self, reason: str) -> None:
        """Stop pulling new batches; finish the in-flight one; leave."""
        self.drain_reason = reason
        self.draining = True

    def join(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
            if not self._thread.is_alive():
                self._thread = None

    def take_error(self) -> BaseException | None:
        err, self._error = self._error, None
        return err

    # -- the dispatch loop ----------------------------------------------

    def _poll_loop_faults(self) -> None:
        """Fire loop-scoped fault plans (leave/preempt/kill) at batch
        boundaries; a fired ``leave`` becomes a drain request — the
        signal-free SIGTERM twin, per replica."""
        for inj in self._faults:
            if inj.plan.kind in _LOOP_FAULT_KINDS:
                inj.on_step(self._batch_index)
            if inj.leave_requested and not self.draining:
                inj.leave_requested = False
                if self.router is not None:
                    self.router.begin_drain(
                        self.sid, reason="preempted (leave)"
                    )
                else:
                    # Single-replica engine: nobody absorbs the queue, so
                    # a leave means "stop admitting, serve out the queue,
                    # exit" — close + drain, never abandoned callers.
                    self.queue.close()

    def _loop(self) -> None:
        batch = None
        try:
            # Run-forever service loop by design: lifetime is bounded by
            # the stop/drain flags checked first thing every turn (and
            # every sleep is a short backpressure nap), not by a
            # deadline — a serving replica has no natural timeout.
            # dplint: allow(DP402) flag-bounded service loop, no deadline
            while True:
                if self._stop.is_set():  # abandon mode: stop(drain=False)
                    self.status = "stopped"
                    return
                self._touch()
                self._poll_loop_faults()
                if self.draining:
                    # Drain-then-leave: the in-flight batch (if any) was
                    # finished by the previous iteration; new work goes to
                    # the survivors. The departure epoch is published
                    # BEFORE status flips to "left" — a rejoiner polling
                    # the status must find the departure already on the
                    # ledger, never rejoin-before-depart.
                    if self.router is not None:
                        self.router.on_replica_drained(
                            self.sid, self.drain_reason
                        )
                    self.status = "left"
                    return
                if self.router is not None and \
                        not self.router.may_dispatch(self.sid):
                    if self.queue.closed and len(self.queue) == 0:
                        # Quarantined through the shutdown drain: nothing
                        # left to be fed anyway — exit, don't wedge join().
                        self.status = "stopped"
                        return
                    time.sleep(0.02)
                    continue
                batch = self.batcher.next_batch(timeout_s=0.05)
                if batch == "closed":
                    self.status = "stopped"
                    return
                if batch == "timeout":
                    batch = None
                    continue
                if self._stop.is_set():
                    # Abandon a batch formed while stopping — its popped
                    # requests go back through the shed-on-close path.
                    for req in batch.requests:
                        shed_counted(self._counters, req.handle, SHED_CLOSED)
                    self.status = "stopped"
                    return
                self._apply_pending_swap()
                # _run_batch advances _batch_index; pin THIS batch's
                # 0-based index so the profiler range means what
                # `serve.profile_batches` documents ("START:END batch
                # indices", half-open — 0:1 captures exactly batch 0).
                bi = self._batch_index
                if self._profiler is not None:
                    # Arm BEFORE dispatch (the StepProfiler discipline).
                    self._profiler.on_window_start(bi, 1)
                self._run_batch(batch)
                if self._profiler is not None:
                    self._profiler.on_step(bi)
                batch = None
        except BaseException as e:
            self._error = e
            self.status = "dead"
            pending = [
                r for r in (batch.requests
                            if isinstance(batch, FormedBatch) else [])
                if not r.handle.done()
            ]
            if self.router is not None:
                # Failover: the router retries the in-flight batch on a
                # survivor or sheds it `replica_failed` — typed either way.
                self.router.on_replica_error(self.sid, e, pending)
            else:
                # Single-replica engine (surfaced by stop()): neither the
                # in-flight batch nor anything queued may wait forever on
                # a dead loop.
                self.queue.close()
                reqs, _ = self.queue.collect(self.ladder.max_batch * 10**6)
                for req in pending + reqs:
                    shed_counted(self._counters, req.handle, "engine_error")
        finally:
            # A capture window cut short by drain/stop/death still stops
            # the trace (the flightrec profile_stop event points at it).
            if self._profiler is not None:
                self._profiler.close()

    def _place_batch(self, bucket: int, images: np.ndarray,
                     weight: np.ndarray):
        """Host batch → device, under the bucket's sharding (one path for
        warmup and live dispatch, so their jit signatures cannot differ)."""
        import jax

        sh = self._batch_sharding[bucket]
        return jax.device_put(
            {"image": images, "weight": weight},
            {"image": sh, "weight": sh},
        )

    def _run_batch(self, batch: FormedBatch) -> None:
        # Expired handles were resolved (shed) by the queue; nothing to
        # serve in an all-expired wake.
        if not batch.requests:
            return
        self.inflight_since = time.monotonic()
        try:
            self._run_batch_inner(batch)
        finally:
            self.inflight_since = None
            self._touch()

    def _run_batch_inner(self, batch: FormedBatch) -> None:
        import jax

        t0 = time.perf_counter()
        dev_batch = self._place_batch(batch.bucket, batch.images,
                                      batch.weight)
        jax.block_until_ready(dev_batch)
        t1 = time.perf_counter()
        version = self.model_version
        with self._lock:
            # The donated stats buffer is consumed by the call below, so
            # report()/device_stats() must never read `self._stats` while
            # a dispatch is in flight — the lock brackets consumption and
            # reassignment as one atomic step.
            for inj in self._faults:
                if inj.plan.kind in _DEVICE_FAULT_KINDS:
                    # Deterministic straggler injection, bracketed inside
                    # the device span so an injected delay is attributed
                    # exactly like a real slow device (tests/test_serve.py)
                    # — and surfaces in this replica's heartbeat, which is
                    # what the router's staleness quarantine keys off.
                    inj.on_step(self._batch_index)
            self._stats, out = self._program(batch.bucket)(
                self._stats, self._state, dev_batch
            )
            # The device sync IS the protected operation: the lock must
            # span the donated buffer's consumption until `out` is
            # materialized, or a concurrent device_stats() reads a
            # consumed buffer. Holding it across the sync is the bracket,
            # not an accident.
            # dplint: allow(DP505) donated-buffer bracket spans the sync
            jax.block_until_ready(out)
        t2 = time.perf_counter()
        predictions = np.asarray(out["prediction"])
        confidence = np.asarray(out["confidence"])
        t3 = time.perf_counter()

        h2d_ms = (t1 - t0) * 1e3
        device_ms = (t2 - t1) * 1e3
        d2h_ms = (t3 - t2) * 1e3
        with self._lock:
            self._bucket_counts[batch.bucket] = (
                self._bucket_counts.get(batch.bucket, 0) + 1
            )
            self._batch_index += 1
        # Per-device HBM gauges from the dispatch loop — serving was the
        # one workload flying blind on device memory (the trainer already
        # publishes these per window). Backends without memory stats
        # publish nothing.
        from tpu_dp.obs.counters import update_device_memory_gauges

        update_device_memory_gauges(registry=self._counters)
        # Per-bucket device utilization — the fraction of the chip's peak
        # this dispatch's forward used, from the same analytic per-chip
        # FLOPs `register_serve_costs` published to the cost registry.
        from tpu_dp.obs import flightrec as _flightrec

        flops = self._bucket_flops.get(batch.bucket)
        util = (
            flops / (device_ms / 1e3) / self._peak
            if flops and self._peak and device_ms > 0 else None
        )
        if util is not None:
            self._counters.gauge(f"serve.device_util.b{batch.bucket}",
                                 round(util, 4))
            self._counters.gauge("serve.device_util", round(util, 4))
        # The heartbeat write (file I/O — the realistic raiser in this
        # tail) happens BEFORE any handle is claimed: an exception here
        # leaves every handle unclaimed, so the normal failover/shed path
        # still accounts for all of them.
        if self._hb is not None:
            self._hb.beat(
                step=self._batch_index,
                step_ms=batch.form_ms + (t3 - t0) * 1e3,
            )
        resolutions = []
        missed_by_class: dict[int, int] = {}
        completed_by_class: dict[int, int] = {}
        try:
            with self._books_lock:
                for req, sl in zip(batch.requests, batch.slices):
                    if not req.handle._claim():
                        continue  # lost a failover race; books untouched
                    latency_ms = (t3 - req.arrival) * 1e3
                    deadline_missed = t3 > req.deadline
                    cls = req.slo_class
                    completed_by_class[cls] = \
                        completed_by_class.get(cls, 0) + 1
                    if deadline_missed:
                        missed_by_class[cls] = \
                            missed_by_class.get(cls, 0) + 1
                    spans = {
                        "queue_wait": max(
                            0.0,
                            (batch.formed - req.arrival) * 1e3
                            - batch.form_ms,
                        ),
                        "batch_form": batch.form_ms,
                        "h2d": h2d_ms,
                        "device": device_ms,
                        "d2h": d2h_ms,
                        "total": latency_ms,
                    }
                    self.recorder.record(req.req_id, spans,
                                         ts=req.arrival_ts)
                    self.latency_book.note(cls, latency_ms)
                    resolutions.append(
                        (req, sl, latency_ms, deadline_missed, spans)
                    )
            # Publish counters BEFORE waking any waiter: a caller whose
            # last handle just resolved must read books that already
            # include it (the loadgen's exact-consistency audit depends
            # on this order).
            completed = sum(completed_by_class.values())
            missed = sum(missed_by_class.values())
            self._counters.inc("serve.batches")
            self._counters.inc("serve.completed", completed)
            for cls, n in sorted(completed_by_class.items()):
                self._counters.inc(f"serve.completed.c{cls}", n)
            if missed:
                self._counters.inc("serve.deadline_missed", missed)
                for cls, n in sorted(missed_by_class.items()):
                    self._counters.inc(f"serve.deadline_missed.c{cls}", n)
            self._counters.gauge("serve.batch_occupancy", batch.occupancy)
            self._counters.inc(f"serve.replica_batches.{self.sid}")
            _flightrec.record(
                "serve_dispatch", bucket=batch.bucket, replica=self.sid,
                n=len(resolutions), occupancy=batch.occupancy,
                device_ms=round(device_ms, 3), deadline_missed=missed,
                version=version,
            )
            for req, sl, latency_ms, deadline_missed, spans in resolutions:
                req.handle.model_version = version
                req.handle.served_by = self.sid
                req.handle._finish_resolve(
                    predictions[sl].copy(), confidence[sl].copy(),
                    latency_ms, deadline_missed, spans,
                )
        except BaseException:
            # A claimed handle is invisible to every other resolver (the
            # claim guard no-ops them), so whatever just raised, the
            # already-claimed handles MUST still be finished here — their
            # results exist — or their callers would block forever.
            for req, sl, latency_ms, deadline_missed, spans in resolutions:
                if not req.handle.done():
                    req.handle.model_version = version
                    req.handle.served_by = self.sid
                    req.handle._finish_resolve(
                        predictions[sl].copy(), confidence[sl].copy(),
                        latency_ms, deadline_missed, spans,
                    )
            raise

    # -- reporting -------------------------------------------------------

    def device_stats(self) -> dict:
        """The donated stats pytree, fetched: device-side ground truth.

        A replica that died mid-execution may hold a consumed (donated)
        buffer — that is reported honestly as unreadable rather than as a
        fake zero, and the cluster sum marks itself accordingly.
        """
        try:
            with self._lock:
                served = np.asarray(self._stats["served"])
                counts = np.asarray(self._stats["class_counts"])
            return {
                "served": int(served),
                "class_counts": [int(c) for c in counts],
            }
        except Exception:
            return {"served": 0, "class_counts": [], "unreadable": True}

    def snapshot(self) -> dict:
        """Host-side replica facts for the cluster report.

        `_lock` brackets exactly what it guards: the donated-stats
        bookkeeping and the model-swap pair. `status`/`quarantined` are
        GIL-atomic publishes their writers never lock — reading them
        inside the bracket would claim an exclusion that does not exist
        (DP501's mixed-discipline race, from the reader side).
        """
        with self._lock:
            batches = self._batch_index
            bucket_counts = dict(sorted(self._bucket_counts.items()))
            model_version = self.model_version
        return {
            "status": self.status,
            "batches": batches,
            "bucket_counts": bucket_counts,
            "quarantined": self.quarantined,
            "model_version": model_version,
            "retraces": self.retraces,
            "devices": int(self.mesh.devices.size),
        }
