"""Shared GSPMD/shard_map plumbing for the Pallas op modules.

One copy of the custom-partitioning support code used by both
`tpu_dp.ops.conv_block` and `tpu_dp.ops.xent`: backend detection, the
batch-axis extraction from operand shardings, batch padding, the
varying-mesh-axes (vma) union for `shard_map`'s check_vma, and the guard
for the interpret-mode fallback (Pallas interpret lowers to a grid scan
whose index scalars are vma-unvarying, which check_vma rejects — per-shard
code falls back to the op's identical XLA statement there).
"""

from __future__ import annotations

import inspect
import logging

import jax
import jax.numpy as jnp
from jax.experimental.custom_partitioning import (
    custom_partitioning as _custom_partitioning,
)
from jax.sharding import NamedSharding

logger = logging.getLogger(__name__)

# --- JAX version adaptation -------------------------------------------------
# The vma (varying-mesh-axes) machinery — `jax.typeof`, avals carrying `vma`,
# `ShapeDtypeStruct(..., vma=...)` — and `def_partition(sharding_rule=...)`
# only exist in newer JAX. Detect each capability once; older installs get
# the no-vma behavior (their shard_map has no check_vma to satisfy).

_HAS_TYPEOF = hasattr(jax, "typeof")
try:
    jax.ShapeDtypeStruct((1,), jnp.float32, vma=frozenset())
    _HAS_VMA_STRUCT = True
except TypeError:
    _HAS_VMA_STRUCT = False
_HAS_SHARDING_RULE = "sharding_rule" in inspect.signature(
    _custom_partitioning.def_partition
).parameters


def def_partition(cp, *, partition, infer_sharding_from_operands,
                  sharding_rule=None):
    """`cp.def_partition` across JAX versions.

    Newer JAX (Shardy) wants the `sharding_rule` mini-language string;
    older `def_partition` signatures reject the kwarg outright — pass it
    only where it exists (the GSPMD callbacks carry the same information).
    """
    kwargs = dict(partition=partition,
                  infer_sharding_from_operands=infer_sharding_from_operands)
    if sharding_rule is not None and _HAS_SHARDING_RULE:
        kwargs["sharding_rule"] = sharding_rule
    cp.def_partition(**kwargs)
    return cp


def shape_struct(shape, dtype, *operands):
    """`ShapeDtypeStruct` declaring the operands' vma union where supported.

    On JAX without vma-typed avals this is a plain ShapeDtypeStruct — there
    is no check_vma to satisfy there."""
    if _HAS_VMA_STRUCT:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma_of(*operands))
    return jax.ShapeDtypeStruct(shape, dtype)


def interpret() -> bool:
    """True off-TPU: run kernels in Pallas interpret mode."""
    return jax.default_backend() != "tpu"


def shard_map_interp(x) -> bool:
    """True when per-shard interpret-mode code must take the XLA fallback."""
    if not _HAS_TYPEOF:
        return False
    return interpret() and bool(getattr(jax.typeof(x), "vma", None))


def batch_axis(arg_infos):
    """The mesh-axis resource operand 0's leading (batch) dim is sharded
    over, or None.

    The partition rules built on this shard only the batch dim; when
    operand 0 arrives sharded on some *other* dim (batch unsharded), the
    rule forces full replication and GSPMD inserts an all-gather on the
    hot path — legal but almost certainly not what the caller meant, so
    it is logged rather than silent (compile-time only, once per trace).
    """
    sh = arg_infos[0].sharding
    if sh is None or not isinstance(sh, NamedSharding) or not len(sh.spec):
        return None
    if sh.spec[0] is None and any(ax is not None for ax in sh.spec[1:]):
        logger.warning(
            "Pallas op partition: operand 0 is sharded on a non-batch dim "
            "(spec %s); the batch-only partition rule will replicate it "
            "(all-gather inserted on the hot path)", sh.spec)
    return sh.spec[0]


def pad_batch(x, block):
    """Zero-pad the leading dim up to a multiple of ``block``."""
    pad = (-x.shape[0]) % block
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, *x.shape[1:]), x.dtype)])
    return x


def vma_of(*arrays):
    """Union of the mesh axes the arrays vary over (empty outside
    shard_map, and always empty on JAX without vma-typed avals)."""
    if not _HAS_TYPEOF:
        return frozenset()
    return frozenset().union(*(getattr(jax.typeof(a), "vma", frozenset())
                               for a in arrays))
