"""Shared GSPMD/shard_map plumbing for the Pallas op modules.

One copy of the custom-partitioning support code used by both
`tpu_dp.ops.conv_block` and `tpu_dp.ops.xent`: backend detection, the
batch-axis extraction from operand shardings, batch padding, the
varying-mesh-axes (vma) union for `shard_map`'s check_vma, and the guard
for the interpret-mode fallback (Pallas interpret lowers to a grid scan
whose index scalars are vma-unvarying, which check_vma rejects — per-shard
code falls back to the op's identical XLA statement there).
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

logger = logging.getLogger(__name__)


def interpret() -> bool:
    """True off-TPU: run kernels in Pallas interpret mode."""
    return jax.default_backend() != "tpu"


def shard_map_interp(x) -> bool:
    """True when per-shard interpret-mode code must take the XLA fallback."""
    return interpret() and bool(getattr(jax.typeof(x), "vma", None))


def batch_axis(arg_infos):
    """The mesh-axis resource operand 0's leading (batch) dim is sharded
    over, or None.

    The partition rules built on this shard only the batch dim; when
    operand 0 arrives sharded on some *other* dim (batch unsharded), the
    rule forces full replication and GSPMD inserts an all-gather on the
    hot path — legal but almost certainly not what the caller meant, so
    it is logged rather than silent (compile-time only, once per trace).
    """
    sh = arg_infos[0].sharding
    if sh is None or not isinstance(sh, NamedSharding) or not len(sh.spec):
        return None
    if sh.spec[0] is None and any(ax is not None for ax in sh.spec[1:]):
        logger.warning(
            "Pallas op partition: operand 0 is sharded on a non-batch dim "
            "(spec %s); the batch-only partition rule will replicate it "
            "(all-gather inserted on the hot path)", sh.spec)
    return sh.spec[0]


def pad_batch(x, block):
    """Zero-pad the leading dim up to a multiple of ``block``."""
    pad = (-x.shape[0]) % block
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, *x.shape[1:]), x.dtype)])
    return x


def vma_of(*arrays):
    """Union of the mesh axes the arrays vary over (empty outside
    shard_map)."""
    return frozenset().union(*(getattr(jax.typeof(a), "vma", frozenset())
                               for a in arrays))
