"""Fused affine+ReLU+3x3-conv Pallas TPU kernel for ResNet stage-1 shapes.

Why this kernel exists (profiled, docs/DESIGN.md "Where the other half of
peak goes"): the bench's step time is wall-to-wall convolutions, and the
early 64-channel stage is the inefficient part — XLA runs the stage-1
3x3 convs at 18-45% of bf16 peak, streaming [B,32,32,64] activations
from HBM, with the BatchNorm-normalize/ReLU chains between convs compiled
as *separate* loop fusions that cost an extra HBM round trip per tensor
(6.9% of device time on their own). The reference hits the same structure
via cuDNN (`/root/reference/cifar_example_ddp.py:104` lowers to
implicit-gemm kernels); this is the TPU answer, not a translation of it.

The kernel fuses, per batch tile, entirely in VMEM:

    z = relu(x * scale + shift [+ residual])     # the BN-apply epilogue
    y = conv3x3_SAME(z, W)                       # stride 1, C_in=C_out=C

so the normalized activation `z` never exists in HBM — and the conv is a
single MXU contraction per tile ("one-matmul conv"): rows = (b, h, w')
over the padded width, K = (dh, c_in) from three H-shifted input slices,
N = (dw, c_out) packing all three column taps as output blocks, which a
row shift then realigns. For C=64 that is a [rows,192]x[192,192] matmul —
far better MXU occupancy than the K=64, N=64 dots XLA's conv emitter can
use at this channel width.

`scale`/`shift` are per-channel f32 vectors; callers fold whatever affine
they need into them (for BatchNorm: scale = gamma/sqrt(var+eps),
shift = beta - mean*scale). `residual` is the pre-activation skip branch
(added before the ReLU), so one invocation consumes the tail of the
previous block (BN-apply + residual-add + ReLU) and produces the next
conv — a whole stage chains through VMEM. `activate=False` skips the
ReLU for use as a plain (affine-)conv.

Distribution: the op carries a `jax.experimental.custom_partitioning`
rule that shards the batch dimension over the mesh and runs the kernel
on each device's local shard — without it, GSPMD treats the pallas_call
as an opaque replicated op and serializes the hot path (verified on the
8-virtual-device CPU mesh; `tests/test_conv_block.py` pins the sharded
behavior).

Differentiation: `fused_affine_relu_conv` carries a `jax.custom_vjp`
with a hand-written backward that recomputes `z` (cheap elementwise,
verified against autodiff of the unfused statement in tests): the
weight-grad contraction is XLA's; the input-grad conv is XLA's
conv-transpose by default, or — with ``pallas_bwd`` — this same kernel
with spatially-flipped, io-swapped weights (the input-grad of a stride-1
SAME 3x3 conv is another stride-1 SAME 3x3 conv); the affine/ReLU
backward is explicit elementwise math. Off-TPU the kernel runs in Pallas
interpret mode so CPU tests exercise identical code.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dp.ops._partition import (
    batch_axis as _batch_axis,
    def_partition as _def_partition,
    interpret as _interpret,
    pad_batch as _pad_batch,
    shape_struct as _shape_struct,
    shard_map_interp as _shard_map_interp,
)

_BLOCK_B = 0  # default: auto (pick images/grid-step from the VMEM budget)
_VMEM_BUDGET_BYTES = 12 * 2**20  # leave headroom under the ~16MB VMEM


def _pad128(n: int) -> int:
    return -(-n // 128) * 128


def _auto_block_b(h: int, w: int, c: int, with_res: bool = False,
                  emit_z: bool = False) -> int:
    """Images per grid step that keep the kernel's working set under the
    VMEM budget: per image the kernel holds x, zp, the dh-concat win, the
    f32 matmul output t (lanes padded to 128), the f32 acc slice, the y
    output plus slack, and — per variant — the residual input block and
    the emitted-z output block.  Stage-1 shapes (~2.5 MB/image at
    32x32x64) fit 4; later stages progressively more.  Each `_run_local`
    call sizes itself (forward and backward invoke this separately with
    their own variant flags), so a backward pass never inherits a
    forward-tuned value unless the caller pinned block_b explicitly."""
    wp = w + 2
    img = h * w * c * 2            # one [block,h,w,c] bf16 block
    per_img = (
        img                        # x block
        + (h + 2) * wp * c * 2     # zp
        + h * wp * 3 * c * 2       # win
        + h * wp * _pad128(3 * c) * 4   # t (f32)
        + h * wp * _pad128(c) * 4       # acc (f32)
        + 3 * img                  # y output + slack (stats tile is tiny)
        + (img if with_res else 0)     # residual input block
        + (img if emit_z else 0)       # emitted z output block
    )
    return max(1, min(32, _VMEM_BUDGET_BYTES // per_img))


def _affine_act(x, scale, shift, res, activate):
    z = x.astype(jnp.float32) * scale + shift
    if res is not None:
        z = z + res.astype(jnp.float32)
    return jnp.maximum(z, 0.0) if activate else z


def _conv_kernel(x_ref, w_ref, scale_ref, shift_ref, y_ref, *, with_res,
                 activate, res_ref=None, z_ref=None, stats_ref=None,
                 valid_b=None):
    # One-matmul conv: rows = (b, h, w') with w' over the padded width,
    # K = (dh, c) built from three H-shifted slices (leading-dim slices —
    # no layout offsets, so the lane concat is legal), N = (dw, o) — all
    # nine taps in a single [rows,192] @ [192,192] MXU contraction. The
    # three dw output column-blocks are then combined by row shifts: a
    # +dw row shift within each 34-row (b,h) group realigns column block
    # dw to its output pixel, and the zero padding of zp supplies SAME
    # semantics. Rows with w' >= w are scratch and sliced off at the end;
    # pltpu.roll's wrapped rows land only there.
    bt, h, w, c = x_ref.shape
    wp = w + 2
    rows = bt * h * wp
    scale = scale_ref[0, :]
    shift = shift_ref[0, :]
    res = res_ref[:] if with_res else None
    zf = _affine_act(x_ref[:], scale, shift, res, activate)
    z = zf.astype(jnp.bfloat16)
    if z_ref is not None:
        # The transformed activation, already resident in VMEM — written out
        # so callers needing it (skip connections) skip a separate
        # read-modify-write pass over HBM.
        z_ref[:] = z.astype(z_ref.dtype)
    zp = jnp.pad(z, ((0, 0), (1, 1), (1, 1), (0, 0)))
    win = jnp.concatenate(
        [zp[:, dh:dh + h, :, :] for dh in range(3)], axis=-1
    ).reshape(rows, 3 * c)
    t = jax.lax.dot_general(
        win, w_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = t[:, 0:c]
    for dw in (1, 2):
        acc = acc + pltpu.roll(t, rows - dw, 0)[:, dw * c:(dw + 1) * c]
    yq = (acc.reshape(bt, h, wp, c)[:, :, 0:w, :]
          .astype(jnp.bfloat16))
    y_ref[:] = yq.astype(y_ref.dtype)
    if stats_ref is not None:
        # Per-channel [sum, sum-of-squares] of the rounded output — the
        # moments BatchNorm needs — accumulated across grid steps while the
        # tile is still in VMEM, so no later stats pass re-reads y from HBM.
        # Batch-pad images (rows >= valid_b) are masked out: they are conv
        # outputs of zero images, which are NOT zero (shift/ReLU/conv).
        i = pl.program_id(0)
        yf = yq.astype(jnp.float32)
        row = jax.lax.broadcasted_iota(jnp.int32, yf.shape, 0)
        keep = (row + i * bt < valid_b).astype(jnp.float32)
        yf = yf * keep
        tile = jnp.stack([jnp.sum(yf, axis=(0, 1, 2)),
                          jnp.sum(jnp.square(yf), axis=(0, 1, 2))])

        @pl.when(i == 0)
        def _():
            stats_ref[:] = tile

        @pl.when(i != 0)
        def _():
            stats_ref[:] = stats_ref[:] + tile


def _stats_of(y):
    """[sum, sum_sq] per channel of a (rounded) conv output, in f32."""
    yf = y.astype(jnp.float32)
    return jnp.stack([jnp.sum(yf, axis=(0, 1, 2)),
                      jnp.sum(jnp.square(yf), axis=(0, 1, 2))])


def _run_local(x, w, scale, shift, residual, block_b, activate,
               emit_z=False, emit_stats=False):
    """Run the kernel on (process-/shard-)local arrays."""
    if _shard_map_interp(x):
        # shard_map + interpret mode (CPU tests): Pallas interpret lowers to
        # a grid scan whose internal index scalars are vma-unvarying, which
        # check_vma rejects. Run the numerically-identical XLA statement
        # (same f32 affine, same bf16 rounding) per shard instead; the
        # kernel body itself is covered by the GSPMD/single-device tests,
        # and on TPU the real (non-interpret) kernel runs under shard_map.
        y = reference_affine_relu_conv(x, w, scale, shift, residual, activate)
        out = [y]
        if emit_z:
            z = _reference_z(x, scale, shift, residual, activate)
            out.append(z.astype(jnp.bfloat16).astype(x.dtype))
        if emit_stats:
            out.append(_stats_of(y.astype(jnp.bfloat16)))
        return tuple(out) if len(out) > 1 else y
    b, h, wd, c = x.shape
    if w.shape != (3, 3, c, c):
        raise ValueError(f"square 3x3 conv only, got weight {w.shape} "
                         f"for input channels {c}")
    if not block_b:
        block_b = min(b, _auto_block_b(h, wd, c, with_res=residual is not None,
                                       emit_z=emit_z))
    xp = _pad_batch(x, block_b)
    # Wcat[(dh, c_in), (dw, c_out)] = w[dh, dw, c_in, c_out]: K rows match
    # the kernel's dh-concat of input slices, N columns put all three dw
    # taps in one contraction.
    w3 = w.astype(jnp.bfloat16).transpose(0, 2, 1, 3).reshape(3 * c, 3 * c)
    scale2 = scale.astype(jnp.float32).reshape(1, c)
    shift2 = shift.astype(jnp.float32).reshape(1, c)
    img_spec = pl.BlockSpec((block_b, h, wd, c), lambda i: (i, 0, 0, 0),
                            memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((3 * c, 3 * c), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    vec_spec = pl.BlockSpec((1, c), lambda i: (0, 0),
                            memory_space=pltpu.VMEM)
    grid = (xp.shape[0] // block_b,)
    # Inside shard_map, avals carry the mesh axes they vary over (vma) and
    # check_vma requires the pallas out_shape to declare them: the output
    # varies over whatever the operands vary over (vma=frozenset() is
    # equivalent to not passing it).
    operands = (xp, w3, scale2, shift2) + (
        () if residual is None else (residual,))
    img_shape = _shape_struct(xp.shape, x.dtype, *operands)
    out_shape = [img_shape]
    out_specs = [img_spec]
    if emit_z:
        out_shape.append(img_shape)
        out_specs.append(img_spec)
    if emit_stats:
        out_shape.append(_shape_struct((2, c), jnp.float32, *operands))
        out_specs.append(pl.BlockSpec((2, c), lambda i: (0, 0),
                                      memory_space=pltpu.VMEM))
    single_out = len(out_shape) == 1
    with_res = residual is not None

    def body(x_ref, w_ref, sc_ref, sh_ref, *rest):
        res_ref = rest[0] if with_res else None
        outs = rest[1:] if with_res else rest
        y_ref = outs[0]
        z_ref = outs[1] if emit_z else None
        stats_ref = outs[-1] if emit_stats else None
        _conv_kernel(x_ref, w_ref, sc_ref, sh_ref, y_ref, with_res=with_res,
                     activate=activate, res_ref=res_ref, z_ref=z_ref,
                     stats_ref=stats_ref, valid_b=b)

    in_specs = [img_spec, w_spec, vec_spec, vec_spec]
    args = [xp, w3, scale2, shift2]
    if with_res:
        in_specs.append(img_spec)
        args.append(_pad_batch(residual, block_b))
    out = pl.pallas_call(
        body,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs[0] if single_out else out_specs,
        out_shape=out_shape[0] if single_out else out_shape,
        interpret=_interpret(),
    )(*args)
    if single_out:
        return out[:b]
    outs = [out[0][:b]]
    if emit_z:
        outs.append(out[1][:b])
    if emit_stats:
        outs.append(out[-1])
    return tuple(outs)


# --- GSPMD partitioning: shard the batch dim, run the kernel per shard ---

def _make_cp(with_res, emit_z=False, emit_stats=False):
    if with_res:
        def f(x, w, scale, shift, residual, block_b, activate):
            return _run_local(x, w, scale, shift, residual, block_b, activate,
                              emit_z, emit_stats)
        static = (5, 6)
    else:
        def f(x, w, scale, shift, block_b, activate):
            return _run_local(x, w, scale, shift, None, block_b, activate,
                              emit_z, emit_stats)
        static = (4, 5)
    cp = custom_partitioning(f, static_argnums=static)
    multi = emit_z or emit_stats

    def _out_shardings(mesh, batch):
        img = NamedSharding(mesh, P(batch, None, None, None))
        outs = [img]
        if emit_z:
            outs.append(img)
        if emit_stats:
            # Stats are per-channel sums over the *global* batch: the lower
            # fn all-reduces the per-shard partials, so the output is
            # replicated.
            outs.append(NamedSharding(mesh, P(None, None)))
        return tuple(outs) if multi else img

    def infer(*cb_args):
        mesh, arg_infos, _ = cb_args[-3:]
        return _out_shardings(mesh, _batch_axis(arg_infos))

    def part(*cb_args):
        block_b, activate = cb_args[:2]
        mesh, arg_infos, _ = cb_args[-3:]
        batch = _batch_axis(arg_infos)
        img = NamedSharding(mesh, P(batch, None, None, None))
        rep1 = NamedSharding(mesh, P(None))
        arg_shardings = (img, NamedSharding(mesh, P(None, None, None, None)),
                         rep1, rep1) + ((img,) if with_res else ())

        def lower(x, w, scale, shift, residual=None):
            out = _run_local(x, w, scale, shift, residual, block_b, activate,
                             emit_z, emit_stats)
            if emit_stats and batch is not None:
                # Per-shard partial sums -> global sums over whatever axis
                # the partitioner sharded the batch on (not necessarily
                # DATA_AXIS — this is mesh-generic lowering code).
                out = out[:-1] + (jax.lax.psum(out[-1], batch),)  # dplint: allow(DP103)
            return out

        if with_res:
            lower_fn = lower
        else:
            def lower_fn(x, w, scale, shift):
                return lower(x, w, scale, shift)
        return mesh, lower_fn, _out_shardings(mesh, batch), arg_shardings

    # Shardy mini-language: only the batch factor `b` is shared (x, residual,
    # outputs), so batch sharding propagates and nothing else does.
    ins = ("b h w c, p q i o, e, g, b r s t" if with_res
           else "b h w c, p q i o, e, g")
    outs = ["b h w c"]
    if emit_z:
        outs.append("b h w c")
    if emit_stats:
        outs.append("u v")  # fresh factors: stats are replicated, never
        # tied to the channel factor (the partition rule psums partials)
    _def_partition(cp, partition=part, infer_sharding_from_operands=infer,
                   sharding_rule=f"{ins} -> {', '.join(outs)}")
    return cp


_CPS = {
    (with_res, emit_z, emit_stats): _make_cp(with_res, emit_z, emit_stats)
    for with_res in (False, True)
    for emit_z in (False, True)
    for emit_stats in (False, True)
}


def _run_fused_conv(x, w, scale, shift, residual, block_b, activate,
                    emit_z=False, emit_stats=False):
    cp = _CPS[(residual is not None, emit_z, emit_stats)]
    if residual is not None:
        return cp(x, w, scale, shift, residual, block_b, activate)
    return cp(x, w, scale, shift, block_b, activate)


def _reference_z(x, scale, shift, residual, activate=True):
    return _affine_act(x, scale.astype(jnp.float32),
                       shift.astype(jnp.float32), residual, activate)


def _conv3x3(z, w):
    # bf16 operands, bf16 output — the statement Flax's nn.Conv(dtype=bf16)
    # makes (no preferred_element_type: its conv transpose can't mix a f32
    # cotangent with bf16 operands on this jax). The MXU accumulates in f32
    # internally either way; the Pallas kernel keeps its f32 VMEM
    # accumulator and rounds through bf16 on the final write to match this
    # statement bit-for-bit.
    return jax.lax.conv_general_dilated(
        z.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _fused_conv_vjp(x, w, scale, shift, residual, block_b, activate,
                    pallas_bwd, emit_z, emit_stats):
    return _run_fused_conv(x, w, scale, shift, residual, block_b, activate,
                           emit_z, emit_stats)


def _fwd_rule(x, w, scale, shift, residual, block_b, activate, pallas_bwd,
              emit_z, emit_stats):
    out = _run_fused_conv(x, w, scale, shift, residual, block_b, activate,
                          emit_z, emit_stats)
    y = out[0] if (emit_z or emit_stats) else out
    # y is saved only for the stats backward (it already exists in HBM —
    # no extra memory or recompute).
    return out, (x, w, scale, shift, residual, y if emit_stats else None)


def _bwd_core(block_b, activate, pallas_bwd, residuals, ct, ct_z=None):
    # Recompute z (cheap elementwise, fuses into the grad convs) instead of
    # saving it. The weight-grad contraction is XLA's (efficient per the
    # profile); the input-grad conv is XLA's conv-transpose by default, or
    # this kernel with flipped weights when pallas_bwd — identical math:
    # conv_transpose(ct, w) == conv3x3(ct, flip_hw(w).swap_io()) at
    # stride 1 / SAME. ct_z (emit variant) is the cotangent of the
    # emitted activation; it joins the conv's input-grad at z.
    x, w, scale, shift, residual = residuals
    z = _reference_z(x, scale, shift, residual, activate)
    # _conv3x3's primal output is bf16; the forward's final cast to x.dtype
    # transposes to this cast of the incoming cotangent.
    ctc = ct.astype(jnp.bfloat16)
    if pallas_bwd:
        # w-only vjp: no XLA dz path exists to depend on jit DCE.
        dw = jax.vjp(lambda wi: _conv3x3(z, wi), w)[1](ctc)[0]
        w_flip = jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2)
        ones = jnp.ones((x.shape[-1],), jnp.float32)
        zeros = jnp.zeros((x.shape[-1],), jnp.float32)
        dz = _run_fused_conv(ctc, w_flip, ones, zeros, None, block_b,
                             False).astype(jnp.float32)
    else:
        dz, dw = jax.vjp(_conv3x3, z, w)[1](ctc)
        dz = dz.astype(jnp.float32)
    if ct_z is not None:
        dz = dz + ct_z.astype(jnp.float32)
    # Through act and affine: gate on the post-act sign (z>0 iff pre>0).
    dpre = dz * (z > 0) if activate else dz
    dx = (dpre * scale.astype(jnp.float32)).astype(x.dtype)
    dscale = jnp.sum(dpre * x.astype(jnp.float32),
                     axis=(0, 1, 2)).astype(scale.dtype)
    dshift = jnp.sum(dpre, axis=(0, 1, 2)).astype(shift.dtype)
    dres = dpre.astype(residual.dtype) if residual is not None else None
    return dx, dw, dscale, dshift, dres


def _bwd_rule(block_b, activate, pallas_bwd, emit_z, emit_stats, residuals,
              cts):
    *core_res, y = residuals
    ct_list = list(cts) if (emit_z or emit_stats) else [cts]
    ct_y = ct_list[0]
    ct_z = ct_list[1] if emit_z else None
    if emit_stats:
        # stats = [sum(yq), sum(yq^2)]: their cotangent joins y's before the
        # conv backward (summed in f32, rounded once into the bf16 ct).
        ct_stats = ct_list[-1]
        yf = y.astype(jnp.float32)
        ct_y = (ct_y.astype(jnp.float32)
                + ct_stats[0][None, None, None, :]
                + 2.0 * yf * ct_stats[1][None, None, None, :])
    return _bwd_core(block_b, activate, pallas_bwd, tuple(core_res), ct_y,
                     ct_z)


_fused_conv_vjp.defvjp(_fwd_rule, _bwd_rule)


def fused_affine_relu_conv(x, w, scale, shift, residual, block_b=_BLOCK_B,
                           activate=True, pallas_bwd=False):
    """y = conv3x3_SAME(act(x*scale + shift [+ residual]), w), fused on TPU.

    x: [B,H,W,C] (any float dtype; affine computed in f32, conv in bf16),
    w: [3,3,C,C], scale/shift: [C], residual: [B,H,W,C] or None;
    act = ReLU when `activate` else identity. Returns y with x's dtype.
    Differentiable in x, w, scale, shift, residual. Batch-sharded under a
    mesh (custom partitioning); block_b is the per-grid-step image count.
    `pallas_bwd` routes the backward input-grad conv (the same 3x3
    stride-1 C->C shape, spatially-flipped io-swapped weights) through
    this kernel too; the weight-grad contraction stays on XLA either way.
    """
    return _fused_conv_vjp(x, w, scale, shift, residual, block_b, activate,
                           pallas_bwd, False, False)


def fused_affine_relu_conv_emit(x, w, scale, shift, residual,
                                block_b=_BLOCK_B, activate=True,
                                pallas_bwd=False):
    """Like `fused_affine_relu_conv`, but also returns the transformed
    activation z = act(x*scale + shift [+ residual]) as a second output,
    written from VMEM in the same kernel pass — callers that need it (skip
    connections) avoid a separate read-modify-write over HBM."""
    return _fused_conv_vjp(x, w, scale, shift, residual, block_b, activate,
                           pallas_bwd, True, False)


def fused_conv_bn(x, w, scale, shift, residual, block_b=_BLOCK_B,
                  activate=True, pallas_bwd=False, emit_z=False):
    """Fused conv that also emits BatchNorm moments of its output.

    Returns ``(y, [z,] stats)`` where ``stats`` is the per-channel
    ``[sum(y), sum(y^2)]`` (f32), accumulated in VMEM while each tile is
    produced — the moments `BatchNormCoeffs` needs, without the separate
    XLA reduction pass that would re-read y from HBM (batch-pad images are
    masked out). Under a sharded mesh the partition rule all-reduces the
    per-shard partials, so stats are global sums (sync-BN); under
    shard_map they are the shard's partials, to be `pmean`'d by the
    caller via ``axis_name`` — the same split the unfused BatchNorm has.
    """
    return _fused_conv_vjp(x, w, scale, shift, residual, block_b, activate,
                           pallas_bwd, emit_z, True)


def reference_affine_relu_conv(x, w, scale, shift, residual=None,
                               activate=True):
    """Unfused XLA statement of the same math (oracle for tests/benches)."""
    z = _reference_z(x, scale, shift, residual, activate)
    return _conv3x3(z, w).astype(x.dtype)
