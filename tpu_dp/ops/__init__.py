"""Ops layer: native (C++) host-side runtime pieces and Pallas TPU kernels.

The reference's native machinery all lives in libraries below its Python
surface — NCCL collectives, cuDNN kernels, the DDP C++ reducer (SURVEY.md
§2B). Here the TPU compute path is XLA-lowered (convs/matmuls hit the MXU
without hand-written kernels; Pallas kernels where XLA underperforms), and
the host-side runtime pieces — topology introspection and a Gloo-style CPU
ring allreduce fallback for host coordination off-TPU — are native C++
(`tpu_dp/ops/native/`), bound via ctypes.
"""

from tpu_dp.ops import native
from tpu_dp.ops.conv_block import (
    fused_affine_relu_conv,
    fused_affine_relu_conv_emit,
    fused_conv_bn,
)
from tpu_dp.ops.xent import mean_softmax_xent, softmax_xent

__all__ = [
    "native",
    "fused_affine_relu_conv",
    "fused_affine_relu_conv_emit",
    "fused_conv_bn",
    "mean_softmax_xent",
    "softmax_xent",
]
