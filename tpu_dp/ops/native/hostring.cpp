// tpu_dp native host runtime: topology introspection + TCP ring allreduce.
//
// The reference's collective backend is NCCL (C++/CUDA ring allreduce),
// pulled in via dist.init_process_group(backend='nccl')
// (/root/reference/cifar_example_ddp.py:57). The TPU compute path of this
// framework uses XLA collectives over ICI instead; this library is the
// host-side native fallback with the same semantics — a Gloo-style chunked
// ring allreduce over TCP between processes — used for host-only
// coordination (CI, CPU-only smoke runs) and for topology queries. It is
// deliberately dependency-free: POSIX sockets + pthreads only.
//
// Topology: rank r listens on base_port + r, accepts one connection from
// rank (r-1+n)%n, and connects to base_port + (r+1)%n. Allreduce: the
// classic ring — n-1 reduce-scatter steps then n-1 all-gather steps over
// n chunks, send/recv overlapped with a sender thread per step (full
// duplex), so bandwidth cost is 2·(n-1)/n · bytes, the same wire-optimal
// schedule NCCL uses.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <deque>
#include <thread>
#include <vector>

extern "C" {

int tpudp_cpu_count() { return (int)sysconf(_SC_NPROCESSORS_ONLN); }

int tpudp_hostname(char* buf, int len) { return gethostname(buf, (size_t)len); }

struct RingCtx {
  int rank;
  int world;
  int next_fd;  // we send to next
  int prev_fd;  // we receive from prev
  // world==1 self-loop: send_next targets this rank itself, so p2p payloads
  // queue here instead of a socket (keeps the send/recv pairing an identity
  // at world 1, like every other primitive).
  std::deque<std::vector<char>> self_queue;
};

static int set_nodelay(int fd) {
  int one = 1;
  return setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

static int read_full(int fd, char* p, size_t n) {
  while (n > 0) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return -1;
    }
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

static int write_full(int fd, const char* p, size_t n) {
  while (n > 0) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return -1;
    }
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

// Create the ring: listen on base_port+rank, connect to base_port+next.
// timeout_ms bounds both the accept and the connect-retry loop.
void* tpudp_ring_create(const char* host, int base_port, int rank, int world,
                        int timeout_ms) {
  if (world <= 0 || rank < 0 || rank >= world) return nullptr;
  RingCtx* ctx = new RingCtx{rank, world, -1, -1};
  if (world == 1) return ctx;  // trivial ring, no sockets

  int listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (listen_fd < 0) { delete ctx; return nullptr; }
  int one = 1;
  setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)(base_port + rank));
  if (bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) < 0 ||
      listen(listen_fd, 1) < 0) {
    close(listen_fd);
    delete ctx;
    return nullptr;
  }

  // Accept (from prev) on a helper thread while we connect (to next).
  int prev_fd = -1;
  std::thread acceptor([&]() {
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(listen_fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    prev_fd = accept(listen_fd, nullptr, nullptr);
  });

  int next_port = base_port + (rank + 1) % world;
  int next_fd = -1;
  for (int waited = 0; waited < timeout_ms; waited += 20) {
    next_fd = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in peer{};
    peer.sin_family = AF_INET;
    peer.sin_port = htons((uint16_t)next_port);
    if (inet_pton(AF_INET, host, &peer.sin_addr) != 1) break;
    if (connect(next_fd, (sockaddr*)&peer, sizeof(peer)) == 0) break;
    close(next_fd);
    next_fd = -1;
    usleep(20 * 1000);
  }
  acceptor.join();
  close(listen_fd);
  if (next_fd < 0 || prev_fd < 0) {
    if (next_fd >= 0) close(next_fd);
    if (prev_fd >= 0) close(prev_fd);
    delete ctx;
    return nullptr;
  }
  set_nodelay(next_fd);
  set_nodelay(prev_fd);
  ctx->next_fd = next_fd;
  ctx->prev_fd = prev_fd;
  return ctx;
}

// In-place ring allreduce on float32 data. op: 0 = sum, 1 = mean.
int tpudp_ring_allreduce(void* vctx, float* data, int64_t n, int op) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || n < 0) return -1;
  int world = ctx->world, rank = ctx->rank;
  if (world == 1 || n == 0) return 0;

  // Chunk boundaries: chunk c covers [off[c], off[c+1]).
  std::vector<int64_t> off(world + 1);
  int64_t base = n / world, rem = n % world;
  off[0] = 0;
  for (int c = 0; c < world; ++c) off[c + 1] = off[c] + base + (c < rem ? 1 : 0);

  std::vector<float> recv_buf((size_t)(base + 1));

  // Reduce-scatter: after step s, rank r owns the full sum of chunk
  // (r+1+s... ) — standard schedule: at step s, send chunk (r-s) and
  // receive+accumulate chunk (r-s-1).
  for (int s = 0; s < world - 1; ++s) {
    int send_c = ((rank - s) % world + world) % world;
    int recv_c = ((rank - s - 1) % world + world) % world;
    const char* sp = (const char*)(data + off[send_c]);
    size_t sbytes = (size_t)(off[send_c + 1] - off[send_c]) * sizeof(float);
    size_t rcount = (size_t)(off[recv_c + 1] - off[recv_c]);
    size_t rbytes = rcount * sizeof(float);
    int send_rc = 0;
    std::thread sender([&]() { send_rc = write_full(ctx->next_fd, sp, sbytes); });
    int recv_rc = read_full(ctx->prev_fd, (char*)recv_buf.data(), rbytes);
    sender.join();
    if (send_rc != 0 || recv_rc != 0) return -1;
    float* dst = data + off[recv_c];
    for (size_t i = 0; i < rcount; ++i) dst[i] += recv_buf[i];
  }

  // All-gather: at step s, send chunk (r+1-s), receive chunk (r-s).
  for (int s = 0; s < world - 1; ++s) {
    int send_c = ((rank + 1 - s) % world + world) % world;
    int recv_c = ((rank - s) % world + world) % world;
    const char* sp = (const char*)(data + off[send_c]);
    size_t sbytes = (size_t)(off[send_c + 1] - off[send_c]) * sizeof(float);
    size_t rbytes = (size_t)(off[recv_c + 1] - off[recv_c]) * sizeof(float);
    int send_rc = 0;
    std::thread sender([&]() { send_rc = write_full(ctx->next_fd, sp, sbytes); });
    int recv_rc = read_full(ctx->prev_fd, (char*)(data + off[recv_c]), rbytes);
    sender.join();
    if (send_rc != 0 || recv_rc != 0) return -1;
  }

  if (op == 1) {
    float inv = 1.0f / (float)world;
    for (int64_t i = 0; i < n; ++i) data[i] *= inv;
  }
  return 0;
}

// Ring-pipelined broadcast of raw bytes from `root` to all ranks.
// The host-side analogue of DDP's param broadcast at wrap time
// (the reference's DistributedDataParallel(...) replicates rank-0 weights,
// /root/reference/cifar_example_ddp.py:83). Store-and-forward per chunk,
// with the forward of chunk i overlapped with the receive of chunk i+1,
// so every link is busy once the pipeline fills.
int tpudp_ring_broadcast(void* vctx, char* data, int64_t nbytes, int root) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || nbytes < 0 || root < 0 || root >= ctx->world) return -1;
  int world = ctx->world;
  if (world == 1 || nbytes == 0) return 0;
  int pos = ((ctx->rank - root) % world + world) % world;

  const int64_t kChunk = 1 << 18;  // 256 KiB: fills the pipe, bounds latency
  std::thread sender;
  int send_rc = 0;
  for (int64_t off = 0; off < nbytes; off += kChunk) {
    int64_t len = nbytes - off < kChunk ? nbytes - off : kChunk;
    if (pos > 0 && read_full(ctx->prev_fd, data + off, (size_t)len) != 0) {
      if (sender.joinable()) sender.join();
      return -1;
    }
    if (pos < world - 1) {
      if (sender.joinable()) {
        sender.join();
        if (send_rc != 0) return -1;
      }
      char* p = data + off;
      sender = std::thread(
          [ctx, p, len, &send_rc]() { send_rc = write_full(ctx->next_fd, p, (size_t)len); });
    }
  }
  if (sender.joinable()) sender.join();
  return send_rc;
}

// Ring all-gather of equal-size byte segments. `data` holds world segments
// of seg_bytes each; this rank's own segment is pre-filled at index `rank`.
// n-1 steps, send/recv overlapped — the all-gather half of the wire-optimal
// allreduce schedule, exposed standalone (NCCL primitive parity).
int tpudp_ring_allgather(void* vctx, char* data, int64_t seg_bytes) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || seg_bytes < 0) return -1;
  int world = ctx->world, rank = ctx->rank;
  if (world == 1 || seg_bytes == 0) return 0;

  for (int s = 0; s < world - 1; ++s) {
    int send_c = ((rank - s) % world + world) % world;
    int recv_c = ((rank - s - 1) % world + world) % world;
    const char* sp = data + (int64_t)send_c * seg_bytes;
    int send_rc = 0;
    std::thread sender([&]() {
      send_rc = write_full(ctx->next_fd, sp, (size_t)seg_bytes);
    });
    int recv_rc = read_full(ctx->prev_fd, data + (int64_t)recv_c * seg_bytes,
                            (size_t)seg_bytes);
    sender.join();
    if (send_rc != 0 || recv_rc != 0) return -1;
  }
  return 0;
}

// Ring reduce-scatter on float32 data (NCCL ncclReduceScatter parity).
// `data` holds world equal segments of seg_n floats; on return `out`
// (seg_n floats) holds the fully-reduced segment for this rank's index.
// Same wire-optimal n-1-step schedule as the allreduce's first half, with
// chunk indexing shifted by one so rank r finishes owning segment r.
// op: 0 = sum, 1 = mean.
int tpudp_ring_reduce_scatter(void* vctx, float* data, int64_t seg_n,
                              float* out, int op) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || seg_n < 0) return -1;
  int world = ctx->world, rank = ctx->rank;
  if (seg_n == 0) return 0;
  if (world == 1) {
    memcpy(out, data, (size_t)seg_n * sizeof(float));
    return 0;
  }

  std::vector<float> recv_buf((size_t)seg_n);
  size_t seg_bytes = (size_t)seg_n * sizeof(float);
  // At step s: send chunk (r-s-1), receive+accumulate chunk (r-s-2).
  // After world-1 steps this rank has fully accumulated chunk r.
  for (int s = 0; s < world - 1; ++s) {
    int send_c = ((rank - s - 1) % world + world) % world;
    int recv_c = ((rank - s - 2) % world + world) % world;
    const char* sp = (const char*)(data + (int64_t)send_c * seg_n);
    int send_rc = 0;
    std::thread sender([&]() { send_rc = write_full(ctx->next_fd, sp, seg_bytes); });
    int recv_rc = read_full(ctx->prev_fd, (char*)recv_buf.data(), seg_bytes);
    sender.join();
    if (send_rc != 0 || recv_rc != 0) return -1;
    float* dst = data + (int64_t)recv_c * seg_n;
    for (int64_t i = 0; i < seg_n; ++i) dst[i] += recv_buf[i];
  }
  memcpy(out, data + (int64_t)rank * seg_n, seg_bytes);
  if (op == 1) {
    float inv = 1.0f / (float)world;
    for (int64_t i = 0; i < seg_n; ++i) out[i] *= inv;
  }
  return 0;
}

// Chain reduce to `root` on float32 data (NCCL ncclReduce parity), pipelined
// over chunks so every link streams concurrently once the pipe fills. The
// chain runs ring-forward from root+1 and terminates at root; only root's
// buffer holds the reduction on return (others' inputs are left intact).
// op: 0 = sum, 1 = mean (applied at root).
int tpudp_ring_reduce(void* vctx, float* data, int64_t n, int root, int op) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || n < 0 || root < 0 || root >= ctx->world) return -1;
  int world = ctx->world;
  // world==1: mean of one contribution is the identity — nothing to do.
  if (world == 1 || n == 0) return 0;
  // pos 0 = chain head (root+1): sends only. pos world-1 = root: receives
  // only. Middle ranks receive a partial, add their contribution, forward.
  int pos = ((ctx->rank - root - 1) % world + world) % world;

  const int64_t kChunk = 1 << 16;  // floats per pipeline stage (256 KiB)
  // Two alternating scratch buffers mid-chain: chunk i forwards (async)
  // from one while chunk i+1 is received into the other, so the recv and
  // the downstream send genuinely overlap at every rank.
  size_t tmp_n = (size_t)(n < kChunk ? n : kChunk);
  std::vector<float> tmp_a(tmp_n), tmp_b(tmp_n);
  std::thread sender;
  int send_rc = 0;
  bool use_a = true;
  for (int64_t off = 0; off < n; off += kChunk) {
    int64_t len = n - off < kChunk ? n - off : kChunk;
    float* chunk = data + off;
    float* tmp = use_a ? tmp_a.data() : tmp_b.data();
    use_a = !use_a;
    float* fwd = chunk;  // what we forward: own data at the head, sum mid-chain
    if (pos > 0) {
      if (read_full(ctx->prev_fd, (char*)tmp, (size_t)len * sizeof(float)) != 0) {
        if (sender.joinable()) sender.join();
        return -1;
      }
      if (pos == world - 1) {  // root: accumulate in place
        for (int64_t i = 0; i < len; ++i) chunk[i] += tmp[i];
      } else {  // middle: accumulate into tmp, forward tmp (keep own input)
        for (int64_t i = 0; i < len; ++i) tmp[i] += chunk[i];
        fwd = tmp;
      }
    }
    if (pos < world - 1) {
      if (sender.joinable()) {
        sender.join();
        if (send_rc != 0) return -1;
      }
      sender = std::thread([ctx, fwd, len, &send_rc]() {
        send_rc = write_full(ctx->next_fd, (const char*)fwd,
                             (size_t)len * sizeof(float));
      });
    }
  }
  if (sender.joinable()) sender.join();
  if (send_rc != 0) return -1;
  if (op == 1 && pos == world - 1) {
    float inv = 1.0f / (float)world;
    for (int64_t i = 0; i < n; ++i) data[i] *= inv;
  }
  return 0;
}

// Neighbor point-to-point (the restricted ncclSend/ncclRecv pair every ring
// schedule is built from): send raw bytes to the next rank / receive from
// the previous rank. These are RENDEZVOUS-BLOCKING, like ungrouped
// ncclSend/ncclRecv: a lone send_next completes only up to kernel socket
// buffering, so the symmetric all-ranks-send-then-recv pattern deadlocks for
// payloads beyond ~the socket buffer — use tpudp_ring_shift (the grouped
// sendrecv, send and recv overlapped on a sender thread) for that pattern,
// exactly as NCCL requires ncclGroupStart/End around symmetric p2p.
// Arbitrary-pair routing is the device path's job (lax.ppermute), not this
// host fallback's.
int tpudp_ring_send_next(void* vctx, const char* data, int64_t nbytes) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || nbytes < 0) return -1;
  if (nbytes == 0) return 0;
  if (ctx->world == 1) {  // self-loop: queue for our own recv_prev
    ctx->self_queue.emplace_back(data, data + nbytes);
    return 0;
  }
  return write_full(ctx->next_fd, data, (size_t)nbytes);
}

int tpudp_ring_recv_prev(void* vctx, char* data, int64_t nbytes) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || nbytes < 0) return -1;
  if (nbytes == 0) return 0;
  if (ctx->world == 1) {
    if (ctx->self_queue.empty() ||
        (int64_t)ctx->self_queue.front().size() != nbytes)
      return -1;  // no matching send_next queued: refuse, don't fabricate
    memcpy(data, ctx->self_queue.front().data(), (size_t)nbytes);
    ctx->self_queue.pop_front();
    return 0;
  }
  return read_full(ctx->prev_fd, data, (size_t)nbytes);
}

// Collective shift-by-k (the host analogue of lax.ppermute with a shift
// permutation): every rank's buffer ends holding the data that started on
// rank (r - k) mod world. k hops of overlapped neighbor exchange.
int tpudp_ring_shift(void* vctx, char* data, int64_t nbytes, int k) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx || nbytes < 0) return -1;
  int world = ctx->world;
  k = ((k % world) + world) % world;
  if (world == 1 || nbytes == 0 || k == 0) return 0;
  std::vector<char> tmp((size_t)nbytes);
  for (int hop = 0; hop < k; ++hop) {
    int send_rc = 0;
    std::thread sender(
        [&]() { send_rc = write_full(ctx->next_fd, data, (size_t)nbytes); });
    int recv_rc = read_full(ctx->prev_fd, tmp.data(), (size_t)nbytes);
    sender.join();
    if (send_rc != 0 || recv_rc != 0) return -1;
    memcpy(data, tmp.data(), (size_t)nbytes);
  }
  return 0;
}

int tpudp_ring_barrier(void* vctx) {
  float x = 1.0f;
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx) return -1;
  if (tpudp_ring_allreduce(vctx, &x, 1, 0) != 0) return -1;
  return (x == (float)ctx->world) ? 0 : -1;
}

void tpudp_ring_destroy(void* vctx) {
  RingCtx* ctx = (RingCtx*)vctx;
  if (!ctx) return;
  if (ctx->next_fd >= 0) close(ctx->next_fd);
  if (ctx->prev_fd >= 0) close(ctx->prev_fd);
  delete ctx;
}

}  // extern "C"
