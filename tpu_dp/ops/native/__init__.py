"""ctypes bindings to the native (C++) host runtime library.

Built lazily from `hostring.cpp` with g++ into `libtpudp_host.so` (cached
next to the source). Provides:

- topology introspection (`cpu_count`, `hostname`) — the host-side analogue
  of the reference's device pinning info (`torch.cuda.set_device`,
  `/root/reference/cifar_example_ddp.py:53`);
- TCP ring collectives across processes — allreduce(sum/mean), broadcast
  (DDP's rank-0 param replication, `cifar_example_ddp.py:83`), all-gather,
  and barrier — a Gloo-style fallback backing host-level collective
  semantics when no XLA mesh is available (parity with the reference's NCCL
  primitive set per SURVEY.md §2B row 1; the TPU path stays XLA-lowered and
  never uses this).

If the toolchain is unavailable the import still succeeds; `available()`
returns False and pure-Python fallbacks are used.
"""

from tpu_dp.ops.native.hostlib import (
    Ring,
    available,
    cpu_count,
    hostname,
    ring_allreduce,
    ring_barrier,
)

__all__ = [
    "Ring",
    "available",
    "cpu_count",
    "hostname",
    "ring_allreduce",
    "ring_barrier",
]
