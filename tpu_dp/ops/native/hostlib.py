"""Lazy g++ build + ctypes bindings for the native host library."""

from __future__ import annotations

import ctypes
import os
import socket as _socket
import subprocess
import threading
from pathlib import Path

import numpy as np

_SRC = Path(__file__).with_name("hostring.cpp")
_LIB = Path(__file__).with_name("libtpudp_host.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_failed = False


def _build(out: Path) -> bool:
    """Compile hostring.cpp to ``out`` (atomic: tmp + rename, so concurrent
    importers — e.g. spawned test workers — never load a half-written .so)."""
    try:
        out.parent.mkdir(parents=True, exist_ok=True)
        tmp = out.with_name(f"{out.name}.tmp.{os.getpid()}")
        cmd = [
            "g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
            str(_SRC), "-o", str(tmp),
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return True
    except (subprocess.SubprocessError, FileNotFoundError, OSError):
        return False


def _cached_lib_path() -> Path:
    """Content-addressed build location outside the source tree.

    Keyed on the source hash: editing hostring.cpp gets a fresh build
    without mtime games, and a stale/incompatible prebuilt .so in the repo
    (different glibc, different arch) never blocks a local rebuild — the
    checkout may be read-only.
    """
    import hashlib

    digest = hashlib.sha256(_SRC.read_bytes()).hexdigest()[:16]
    root = Path(
        os.environ.get("TPU_DP_CACHE_DIR")
        or os.environ.get("XDG_CACHE_HOME")
        or Path.home() / ".cache"
    )
    return root / "tpu_dp" / f"libtpudp_host-{digest}.so"


def _try_load(path: Path) -> ctypes.CDLL | None:
    try:
        return ctypes.CDLL(str(path))
    except OSError:
        return None


def _get() -> ctypes.CDLL | None:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        # Prebuilt .so next to the source: use it when fresh AND loadable.
        lib = None
        if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
            lib = _try_load(_LIB)
        if lib is None:
            # Compile-on-demand into the cache dir (rebuilds when the
            # prebuilt is stale, fails to load, or doesn't exist).
            cached = _cached_lib_path()
            lib = _try_load(cached) if cached.exists() else None
            if lib is None:
                # Cache missing OR unloadable (e.g. built on another host of
                # an NFS home, glibc upgraded since): rebuild in place.
                # Holding the module lock across the one-time compile is
                # the point: a second caller must wait for THIS build, not
                # race a duplicate compiler into the same cache path.
                # dplint: allow(DP505) one-time build serializes callers
                if not _build(cached):
                    _build_failed = True  # no compiler: available() -> False
                    return None
                lib = _try_load(cached)
        if lib is None:
            _build_failed = True
            return None
        lib.tpudp_cpu_count.restype = ctypes.c_int
        lib.tpudp_hostname.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.tpudp_hostname.restype = ctypes.c_int
        lib.tpudp_ring_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int,
        ]
        lib.tpudp_ring_create.restype = ctypes.c_void_p
        lib.tpudp_ring_allreduce.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.tpudp_ring_allreduce.restype = ctypes.c_int
        lib.tpudp_ring_broadcast.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ]
        lib.tpudp_ring_broadcast.restype = ctypes.c_int
        lib.tpudp_ring_allgather.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.tpudp_ring_allgather.restype = ctypes.c_int
        lib.tpudp_ring_reduce_scatter.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int,
        ]
        lib.tpudp_ring_reduce_scatter.restype = ctypes.c_int
        lib.tpudp_ring_reduce.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.c_int, ctypes.c_int,
        ]
        lib.tpudp_ring_reduce.restype = ctypes.c_int
        lib.tpudp_ring_send_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.tpudp_ring_send_next.restype = ctypes.c_int
        lib.tpudp_ring_recv_prev.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.tpudp_ring_recv_prev.restype = ctypes.c_int
        lib.tpudp_ring_shift.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ]
        lib.tpudp_ring_shift.restype = ctypes.c_int
        lib.tpudp_ring_barrier.argtypes = [ctypes.c_void_p]
        lib.tpudp_ring_barrier.restype = ctypes.c_int
        lib.tpudp_ring_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    """True when the C++ library built/loaded successfully."""
    return _get() is not None


def cpu_count() -> int:
    lib = _get()
    if lib is not None:
        n = lib.tpudp_cpu_count()
        if n > 0:
            return n
    return os.cpu_count() or 1


def hostname() -> str:
    lib = _get()
    if lib is not None:
        buf = ctypes.create_string_buffer(256)
        if lib.tpudp_hostname(buf, 256) == 0:
            return buf.value.decode()
    return _socket.gethostname()


class Ring:
    """A TCP ring over `world` processes for host-side collectives.

    The Gloo-style fallback for the collective layer (SURVEY.md §2B row 1);
    semantically identical to the XLA path: allreduce(sum/mean) + barrier.
    """

    def __init__(self, host: str, base_port: int, rank: int, world: int,
                 timeout_ms: int = 10_000):
        lib = _get()
        if lib is None:
            raise RuntimeError("native host library unavailable (g++ build failed)")
        self._lib = lib
        self.rank = rank
        self.world = world
        self._ctx = lib.tpudp_ring_create(
            host.encode(), base_port, rank, world, timeout_ms
        )
        if not self._ctx and world > 1:
            raise RuntimeError(
                f"ring rendezvous failed (rank {rank}/{world} @ {host}:{base_port})"
            )

    def allreduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """In-place float32 allreduce across the ring; returns the array."""
        arr = np.ascontiguousarray(array, dtype=np.float32)
        opc = {"sum": 0, "mean": 1}[op]
        rc = self._lib.tpudp_ring_allreduce(
            self._ctx,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            arr.size,
            opc,
        )
        if rc != 0:
            raise RuntimeError("ring allreduce failed")
        return arr

    def broadcast(self, array: np.ndarray, root: int = 0) -> np.ndarray:
        """In-place byte broadcast from `root` to all ranks (any dtype).

        Host-side analogue of DDP's rank-0 param replication at wrap time
        (`/root/reference/cifar_example_ddp.py:83`): non-root contents are
        overwritten with root's.
        """
        arr = np.ascontiguousarray(array)
        if self.world == 1:
            return arr
        rc = self._lib.tpudp_ring_broadcast(
            self._ctx, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, root
        )
        if rc != 0:
            raise RuntimeError("ring broadcast failed")
        if isinstance(array, np.ndarray) and arr is not array:
            array[...] = arr  # ascontiguousarray copied; honor in-place
        return arr

    def allgather(self, array: np.ndarray) -> np.ndarray:
        """Gather equal-shape per-rank arrays; returns (world, *shape)."""
        arr = np.ascontiguousarray(array)
        out = np.empty((self.world,) + arr.shape, dtype=arr.dtype)
        out[self.rank] = arr
        if self.world == 1:
            return out
        rc = self._lib.tpudp_ring_allgather(
            self._ctx, out.ctypes.data_as(ctypes.c_void_p), arr.nbytes
        )
        if rc != 0:
            raise RuntimeError("ring allgather failed")
        return out

    def reduce_scatter(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        """Reduce `array` (shape (world, *seg)) across ranks; return this
        rank's reduced segment (shape seg) — ncclReduceScatter semantics."""
        if array.shape[0] != self.world:
            raise ValueError(
                f"reduce_scatter input must have leading dim world={self.world}, "
                f"got {array.shape}"
            )
        # Always copy: the C schedule accumulates into its input buffer, and
        # NCCL's sendbuff is const — the caller's array must stay intact.
        arr = np.array(array, dtype=np.float32, order="C", copy=True)
        seg_shape = arr.shape[1:]
        out = np.empty(seg_shape, dtype=np.float32)
        seg_n = int(np.prod(seg_shape, dtype=np.int64)) if seg_shape else 1
        rc = self._lib.tpudp_ring_reduce_scatter(
            self._ctx,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            seg_n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            {"sum": 0, "mean": 1}[op],
        )
        if rc != 0:
            raise RuntimeError("ring reduce_scatter failed")
        return out

    def reduce(self, array: np.ndarray, root: int = 0,
               op: str = "sum") -> np.ndarray:
        """Reduce to `root` (ncclReduce semantics): root's returned array
        holds the reduction; other ranks get their input back unchanged.
        The caller's array is never mutated (const sendbuff, as in NCCL)."""
        arr = np.array(array, dtype=np.float32, order="C", copy=True)
        rc = self._lib.tpudp_ring_reduce(
            self._ctx,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            arr.size,
            root,
            {"sum": 0, "mean": 1}[op],
        )
        if rc != 0:
            raise RuntimeError("ring reduce failed")
        return arr

    def send_next(self, array: np.ndarray) -> None:
        """Point-to-point: send raw bytes to rank (rank+1) % world. Pair
        with the receiver's `recv_prev` — the neighbor send/recv every ring
        schedule is built from.

        Rendezvous-blocking, like an *ungrouped* ncclSend: if every rank
        calls send_next before recv_prev, payloads beyond the kernel socket
        buffer deadlock. For the symmetric everyone-sends-everyone-receives
        pattern use :meth:`exchange` (the grouped sendrecv)."""
        arr = np.ascontiguousarray(array)
        rc = self._lib.tpudp_ring_send_next(
            self._ctx, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes
        )
        if rc != 0:
            raise RuntimeError("ring send_next failed")

    def recv_prev(self, shape, dtype) -> np.ndarray:
        """Point-to-point: receive an array of `shape`/`dtype` from rank
        (rank-1) % world."""
        out = np.empty(shape, dtype=dtype)
        rc = self._lib.tpudp_ring_recv_prev(
            self._ctx, out.ctypes.data_as(ctypes.c_void_p), out.nbytes
        )
        if rc != 0:
            raise RuntimeError("ring recv_prev failed")
        return out

    def exchange(self, array: np.ndarray) -> np.ndarray:
        """Grouped neighbor sendrecv: send `array` to rank+1 while receiving
        rank-1's array (send/recv overlapped on a sender thread in C — no
        socket-buffer deadlock at any payload size). The ncclGroupStart/
        ncclSend/ncclRecv/ncclGroupEnd pattern for symmetric neighbor p2p;
        the caller's array is left intact."""
        return self.shift(np.array(array, order="C", copy=True), k=1)

    def shift(self, array: np.ndarray, k: int = 1) -> np.ndarray:
        """Collective shift-by-k (host `lax.ppermute` analogue): returns the
        array that started on rank (rank - k) % world. In place when the
        input is already contiguous (like :meth:`allreduce`); use
        :meth:`exchange` for a non-mutating k=1 shift."""
        arr = np.ascontiguousarray(array)
        rc = self._lib.tpudp_ring_shift(
            self._ctx, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, int(k)
        )
        if rc != 0:
            raise RuntimeError("ring shift failed")
        return arr

    def barrier(self) -> None:
        if self._lib.tpudp_ring_barrier(self._ctx) != 0:
            raise RuntimeError("ring barrier failed")

    def close(self) -> None:
        if getattr(self, "_ctx", None):
            self._lib.tpudp_ring_destroy(self._ctx)
            self._ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def ring_allreduce(ring: Ring, array: np.ndarray, op: str = "sum") -> np.ndarray:
    return ring.allreduce(array, op)


def ring_barrier(ring: Ring) -> None:
    ring.barrier()
