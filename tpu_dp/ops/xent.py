"""Fused softmax-cross-entropy Pallas TPU kernel (forward + custom VJP).

The reference's loss is `nn.CrossEntropyLoss()` (`/root/reference/
cifar_example.py:63`), lowered there to cuDNN/cuBLAS softmax+NLL kernels.
XLA already fuses the logsumexp chain well; this kernel goes one step
further and keeps the whole per-example computation — max, logsumexp,
label gather (forward) and softmax-minus-onehot scaling (backward) — in
VMEM with a single pass over the logits per direction, one (block_b, C)
tile per grid step. For CIFAR head sizes (C = 10/100, padded to the
128-lane tile) this trades a few HBM round trips of (B, C) intermediates
for none.

API: `softmax_xent(logits, labels) -> per-example loss (B,)`, differentiable
wrt logits via `jax.custom_vjp`. Off-TPU the same kernels run in Pallas
interpret mode, so tests exercise identical code on CPU. `tpu_dp.train.step`
uses the jnp path by default; the kernel is opt-in (`use_pallas=True` /
bench) and numerically validated against the jnp path in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.experimental.custom_partitioning import custom_partitioning
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dp.ops._partition import (
    batch_axis as _batch_axis_shared,
    def_partition as _def_partition,
    interpret as _interpret,
    pad_batch as _pad_batch,
    shape_struct as _shape_struct,
    shard_map_interp as _shard_map_interp,
)

_BLOCK_B = 256  # max batch rows per grid step; (256, 128) f32 tiles fit VMEM


def _block_for(b: int) -> int:
    # Adapt the block to the (per-shard) batch so small shards don't pad to
    # 256 and compute multiples of the needed rows.
    return min(_BLOCK_B, max(8, -(-b // 8) * 8))


def _jnp_fwd(logits, labels):
    logits = logits.astype(jnp.float32)
    m = jnp.max(logits, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(logits - m), axis=-1, keepdims=True)) + m
    true_logit = jnp.take_along_axis(
        logits, labels.astype(jnp.int32)[:, None], axis=-1)
    return (lse - true_logit)[:, 0]


def _jnp_bwd(logits, labels, ct):
    logits32 = logits.astype(jnp.float32)
    m = jnp.max(logits32, axis=-1, keepdims=True)
    e = jnp.exp(logits32 - m)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=jnp.float32)
    return ((probs - onehot) * ct.astype(jnp.float32)[:, None]).astype(
        logits.dtype)


_batch_axis = _batch_axis_shared


def _fwd_kernel(logits_ref, labels_ref, loss_ref):
    logits = logits_ref[:].astype(jnp.float32)  # (B, C)
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True)) + m
    classes = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (classes == labels_ref[:]).astype(jnp.float32)  # labels (B, 1)
    true_logit = jnp.sum(logits * onehot, axis=-1, keepdims=True)
    loss_ref[:] = lse - true_logit  # (B, 1)


def _bwd_kernel(logits_ref, labels_ref, ct_ref, dlogits_ref):
    logits = logits_ref[:].astype(jnp.float32)
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    classes = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (classes == labels_ref[:]).astype(jnp.float32)
    dlogits_ref[:] = ((probs - onehot) * ct_ref[:]).astype(dlogits_ref.dtype)


def _block_specs(num_classes, block):
    row_spec = pl.BlockSpec(
        (block, num_classes), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    col_spec = pl.BlockSpec(
        (block, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    return row_spec, col_spec


def _fwd_local(logits, labels):
    if _shard_map_interp(logits):
        return _jnp_fwd(logits, labels)
    b, c = logits.shape
    block = _block_for(b)
    logits_p = _pad_batch(logits, block)
    labels_p = _pad_batch(labels.astype(jnp.int32)[:, None], block)
    row_spec, col_spec = _block_specs(c, block)
    loss = pl.pallas_call(
        _fwd_kernel,
        grid=(logits_p.shape[0] // block,),
        in_specs=[row_spec, col_spec],
        out_specs=col_spec,
        out_shape=_shape_struct((logits_p.shape[0], 1), jnp.float32,
                                logits_p, labels_p),
        interpret=_interpret(),
    )(logits_p, labels_p)
    return loss[:b, 0]


def _bwd_local(logits, labels, ct):
    if _shard_map_interp(logits):
        return _jnp_bwd(logits, labels, ct)
    b, c = logits.shape
    block = _block_for(b)
    logits_p = _pad_batch(logits, block)
    labels_p = _pad_batch(labels.astype(jnp.int32)[:, None], block)
    ct_p = _pad_batch(ct.astype(jnp.float32)[:, None], block)
    row_spec, col_spec = _block_specs(c, block)
    dlogits = pl.pallas_call(
        _bwd_kernel,
        grid=(logits_p.shape[0] // block,),
        in_specs=[row_spec, col_spec, col_spec],
        out_specs=row_spec,
        out_shape=_shape_struct(logits_p.shape, logits.dtype,
                                logits_p, labels_p, ct_p),
        interpret=_interpret(),
    )(logits_p, labels_p, ct_p)
    return dlogits[:b]


def _make_cp(fn, n_args, out_spec_fn, rule):
    """Batch-shard a per-example kernel over the mesh (GSPMD would
    otherwise treat the pallas_call as opaque and replicate it —
    all-gathering every shard's logits; see conv_block.py)."""
    cp = custom_partitioning(fn)

    def infer(*cb_args):
        mesh, arg_infos, _ = cb_args[-3:]
        return out_spec_fn(mesh, _batch_axis(arg_infos))

    def part(*cb_args):
        mesh, arg_infos, _ = cb_args[-3:]
        batch = _batch_axis(arg_infos)
        row = NamedSharding(mesh, P(batch, None))
        vec = NamedSharding(mesh, P(batch))
        arg_shardings = (row, vec, vec)[:n_args]
        return mesh, fn, out_spec_fn(mesh, batch), arg_shardings

    _def_partition(cp, partition=part, infer_sharding_from_operands=infer,
                   sharding_rule=rule)
    return cp


_cp_fwd = _make_cp(_fwd_local, 2,
                   lambda mesh, b: NamedSharding(mesh, P(b)),
                   "b c, b -> b")
_cp_bwd = _make_cp(_bwd_local, 3,
                   lambda mesh, b: NamedSharding(mesh, P(b, None)),
                   "b c, b, b -> b c")


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_xent(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy, fused on TPU. Returns (B,).

    Batch-sharded under a mesh: the custom partitioning rule runs the
    kernel on each device's shard of the rows."""
    return _cp_fwd(logits, labels)


def _fwd_rule(logits, labels):
    return _cp_fwd(logits, labels), (logits, labels)


def _bwd_rule(residuals, ct):
    logits, labels = residuals
    return _cp_bwd(logits, labels, ct), None


softmax_xent.defvjp(_fwd_rule, _bwd_rule)


def mean_softmax_xent(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    weight: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(Weighted) mean loss via the fused kernel — drop-in for
    `tpu_dp.train.step.cross_entropy_loss`."""
    per_example = softmax_xent(logits, labels)
    if weight is None:
        return jnp.mean(per_example)
    return jnp.sum(per_example * weight) / jnp.maximum(jnp.sum(weight), 1.0)
