"""Fused softmax-cross-entropy Pallas TPU kernel (forward + custom VJP).

The reference's loss is `nn.CrossEntropyLoss()` (`/root/reference/
cifar_example.py:63`), lowered there to cuDNN/cuBLAS softmax+NLL kernels.
XLA already fuses the logsumexp chain well; this kernel goes one step
further and keeps the whole per-example computation — max, logsumexp,
label gather (forward) and softmax-minus-onehot scaling (backward) — in
VMEM with a single pass over the logits per direction, one (block_b, C)
tile per grid step. For CIFAR head sizes (C = 10/100, padded to the
128-lane tile) this trades a few HBM round trips of (B, C) intermediates
for none.

API: `softmax_xent(logits, labels) -> per-example loss (B,)`, differentiable
wrt logits via `jax.custom_vjp`. Off-TPU the same kernels run in Pallas
interpret mode, so tests exercise identical code on CPU. `tpu_dp.train.step`
uses the jnp path by default; the kernel is opt-in (`use_pallas=True` /
bench) and numerically validated against the jnp path in tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BLOCK_B = 256  # batch rows per grid step; (256, 128) f32 tiles fit VMEM easily


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(logits_ref, labels_ref, loss_ref):
    logits = logits_ref[:].astype(jnp.float32)  # (B, C)
    m = jnp.max(logits, axis=-1, keepdims=True)
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True)) + m
    classes = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (classes == labels_ref[:]).astype(jnp.float32)  # labels (B, 1)
    true_logit = jnp.sum(logits * onehot, axis=-1, keepdims=True)
    loss_ref[:] = lse - true_logit  # (B, 1)


def _bwd_kernel(logits_ref, labels_ref, ct_ref, dlogits_ref):
    logits = logits_ref[:].astype(jnp.float32)
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)
    probs = e / jnp.sum(e, axis=-1, keepdims=True)
    classes = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (classes == labels_ref[:]).astype(jnp.float32)
    dlogits_ref[:] = ((probs - onehot) * ct_ref[:]).astype(dlogits_ref.dtype)


def _block_specs(num_classes):
    row_spec = pl.BlockSpec(
        (_BLOCK_B, num_classes), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    col_spec = pl.BlockSpec(
        (_BLOCK_B, 1), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    return row_spec, col_spec


def _pad_rows(x, block):
    b = x.shape[0]
    pad = (-b) % block
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad, *x.shape[1:]), x.dtype)])
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def softmax_xent(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy, fused on TPU. Returns (B,)."""
    return _run_fwd(logits, labels)


def _run_fwd(logits, labels):
    b, c = logits.shape
    logits_p = _pad_rows(logits, _BLOCK_B)
    labels_p = _pad_rows(labels.astype(jnp.int32)[:, None], _BLOCK_B)
    row_spec, col_spec = _block_specs(c)
    loss = pl.pallas_call(
        _fwd_kernel,
        grid=(logits_p.shape[0] // _BLOCK_B,),
        in_specs=[row_spec, col_spec],
        out_specs=col_spec,
        out_shape=jax.ShapeDtypeStruct((logits_p.shape[0], 1), jnp.float32),
        interpret=_interpret(),
    )(logits_p, labels_p)
    return loss[:b, 0]


def _fwd_rule(logits, labels):
    return _run_fwd(logits, labels), (logits, labels)


def _bwd_rule(residuals, ct):
    logits, labels = residuals
    b, c = logits.shape
    logits_p = _pad_rows(logits, _BLOCK_B)
    labels_p = _pad_rows(labels.astype(jnp.int32)[:, None], _BLOCK_B)
    ct_p = _pad_rows(ct.astype(jnp.float32)[:, None], _BLOCK_B)
    row_spec, col_spec = _block_specs(c)
    dlogits = pl.pallas_call(
        _bwd_kernel,
        grid=(logits_p.shape[0] // _BLOCK_B,),
        in_specs=[row_spec, col_spec, col_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct(logits_p.shape, logits.dtype),
        interpret=_interpret(),
    )(logits_p, labels_p, ct_p)
    return dlogits[:b], None


softmax_xent.defvjp(_fwd_rule, _bwd_rule)


def mean_softmax_xent(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    weight: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """(Weighted) mean loss via the fused kernel — drop-in for
    `tpu_dp.train.step.cross_entropy_loss`."""
    per_example = softmax_xent(logits, labels)
    if weight is None:
        return jnp.mean(per_example)
    return jnp.sum(per_example * weight) / jnp.maximum(jnp.sum(weight), 1.0)
