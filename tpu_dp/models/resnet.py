"""CIFAR-variant ResNet-18/50 in Flax — the north-star models.

The reference has no ResNet (its only model is the LeNet-style `Net`,
`/root/reference/cifar_example.py:17-34`), but BASELINE.json's target configs
name "ResNet-18" and "ResNet-50 on CIFAR-100", so these are first-class
(SURVEY.md §6). CIFAR variant: 3×3 stride-1 stem, no stem max-pool (32×32
inputs would collapse under the ImageNet 7×7/s2 + pool stem), stages
[64, 128, 256, 512] with stride 2 from stage 2 on.

TPU-first notes:
- NHWC layout; convs and the final dense land on the MXU as large batched
  contractions.
- `dtype` (compute dtype) can be bfloat16 for mixed precision while parameters
  and batch-norm statistics stay float32 — BASELINE.json config 5.
- BatchNorm batch statistics are computed over the *global* (logical) batch:
  under `jit` with the batch sharded on the ``data`` mesh axis, GSPMD turns
  the mean/var reductions into cross-chip all-reduces automatically — i.e.
  sync-BN semantics fall out of the sharded program rather than needing a
  wrapper. `axis_name` is plumbed for the explicit `shard_map` path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """Two 3×3 convs + identity/projection shortcut (ResNet-18/34)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return self.act(y + residual)


class BottleneckBlock(nn.Module):
    """1×1 reduce → 3×3 → 1×1 expand (×4) bottleneck (ResNet-50+)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return self.act(y + residual)


class ResNet(nn.Module):
    """CIFAR-variant ResNet over NHWC inputs."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 10
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    axis_name: str | None = None  # set when used inside shard_map/pmap

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.axis_name,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
