"""CIFAR-variant ResNet-18/50 in Flax — the north-star models.

The reference has no ResNet (its only model is the LeNet-style `Net`,
`/root/reference/cifar_example.py:17-34`), but BASELINE.json's target configs
name "ResNet-18" and "ResNet-50 on CIFAR-100", so these are first-class
(SURVEY.md §6). CIFAR variant: 3×3 stride-1 stem, no stem max-pool (32×32
inputs would collapse under the ImageNet 7×7/s2 + pool stem), stages
[64, 128, 256, 512] with stride 2 from stage 2 on.

TPU-first notes:
- NHWC layout; convs and the final dense land on the MXU as large batched
  contractions.
- `dtype` (compute dtype) can be bfloat16 for mixed precision while parameters
  and batch-norm statistics stay float32 — BASELINE.json config 5.
- BatchNorm batch statistics are computed over the *global* (logical) batch:
  under `jit` with the batch sharded on the ``data`` mesh axis, GSPMD turns
  the mean/var reductions into cross-chip all-reduces automatically — i.e.
  sync-BN semantics fall out of the sharded program rather than needing a
  wrapper. `axis_name` is plumbed for the explicit `shard_map` path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_dp.parallel import collectives
from tpu_dp.ops.conv_block import (
    fused_affine_relu_conv,
    fused_affine_relu_conv_emit,
    fused_conv_bn,
)

ModuleDef = Any


class BatchNormCoeffs(nn.Module):
    """BatchNorm that *returns* the per-channel affine instead of applying it.

    Same parameter/variable layout as `nn.BatchNorm` (params ``scale``/
    ``bias``, batch_stats ``mean``/``var``), so a model built with this
    module loads and saves the same checkpoints as the unfused one. The
    returned ``(scale, shift)`` satisfy ``bn(x) == x * scale + shift`` and
    are consumed by the fused Pallas conv kernel, which applies them in
    f32 inside VMEM (`tpu_dp.ops.conv_block`).

    Stats math mirrors flax's BatchNorm: biased batch variance via
    E[x^2] - E[x]^2 computed in f32, running stats updated with
    ``momentum * old + (1 - momentum) * batch``; under a sharded batch the
    global mean comes out of GSPMD's all-reduce of the jnp.mean, and the
    explicit shard_map path syncs via ``axis_name`` (sync-BN semantics,
    identical to the unfused model — see models docstring).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: str | None = None
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x, stats=None):
        c = x.shape[-1]
        gamma = self.param("scale", self.scale_init, (c,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            if stats is not None:
                # Kernel-emitted [sum, sum_sq] of x (fused_conv_bn): x is
                # only consulted for its shape — no reduction re-reads it.
                count = x.shape[0] * x.shape[1] * x.shape[2]
                mean = stats[0] / count
                mean2 = stats[1] / count
            else:
                xf = x.astype(jnp.float32)
                mean = jnp.mean(xf, axis=(0, 1, 2))
                mean2 = jnp.mean(jnp.square(xf), axis=(0, 1, 2))
            if self.axis_name is not None:
                mean = collectives.pmean(mean, self.axis_name)
                mean2 = collectives.pmean(mean2, self.axis_name)
            var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1.0 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1.0 - self.momentum) * var)
        scale = gamma * jax.lax.rsqrt(var + self.epsilon)
        shift = beta - mean * scale
        return scale, shift


class _ConvKernel(nn.Module):
    """Bare 3x3 conv weight with `nn.Conv`'s param name/init, no compute.

    Exists so a fused block's weights live at the same tree paths
    (``Conv_i/kernel``) as the unfused `nn.Conv` modules — fused and
    unfused models are checkpoint-interchangeable.
    """

    features: int
    kernel_init: Callable

    @nn.compact
    def __call__(self, in_features: int):
        return self.param("kernel", self.kernel_init,
                          (3, 3, in_features, self.features), jnp.float32)


class FusedBasicBlock(nn.Module):
    """BasicBlock whose convs are the fused Pallas kernel, chained in
    "raw pre-norm" space.

    Contract: the block receives ``(x_raw, in_scale, in_shift, in_res)``
    such that its standard input activation is
    ``a_in = relu(x_raw * in_scale + in_shift [+ in_res])`` — i.e. the
    previous block's BN-apply tail is *deferred* into this block's first
    fused conv, so the normalized activation never round-trips HBM. It
    returns ``(y2_raw, out_scale, out_shift, a_in)``: the next block's
    input in the same deferred form (its residual is this block's
    materialized input activation). Entering a chain from a plain
    activation ``A`` uses ``(A, ones, zeros, None)`` — exact because
    ``relu(A) == A`` for post-ReLU activations.

    Only stride-1, channel-preserving blocks qualify (the kernel is a
    square 3x3, stride-1 conv); stride-2/projection blocks stay on the
    standard path.
    """

    filters: int
    norm: ModuleDef = BatchNormCoeffs
    kernel_init: Callable = nn.initializers.variance_scaling(
        2.0, "fan_out", "normal")
    block_b: int = 0  # 0 = auto
    dtype: Any = jnp.float32
    pallas_bwd: bool = False  # input-grad conv through the kernel too
    train: bool = False  # train mode: kernel also emits BN moments

    @nn.compact
    def __call__(self, x_raw, in_scale, in_shift, in_res):
        c = self.filters
        if x_raw.shape[-1] != c:
            raise ValueError(
                f"FusedBasicBlock needs in_channels == filters, got "
                f"{x_raw.shape[-1]} != {c}")
        w1 = _ConvKernel(c, self.kernel_init, name="Conv_0")(c)
        w2 = _ConvKernel(c, self.kernel_init, name="Conv_1")(c)
        # The emit variant writes this block's input activation (needed by
        # the skip connection) from VMEM in the same pass as the conv — no
        # separate read-modify-write over x_raw. In train mode the kernel
        # also emits each conv output's BN moments, so no stats pass
        # re-reads y from HBM; in eval the BN affine comes from running
        # stats and no moments are needed.
        if self.train:
            y1, a_in, st1 = fused_conv_bn(
                x_raw, w1, in_scale, in_shift, in_res, self.block_b, True,
                self.pallas_bwd, emit_z=True)
            s1, b1 = self.norm(name="BatchNorm_0")(y1, stats=st1)
            y2, st2 = fused_conv_bn(y1, w2, s1, b1, None, self.block_b,
                                    True, self.pallas_bwd)
            s2, b2 = self.norm(scale_init=nn.initializers.zeros,
                               name="BatchNorm_1")(y2, stats=st2)
        else:
            y1, a_in = fused_affine_relu_conv_emit(
                x_raw, w1, in_scale, in_shift, in_res, self.block_b, True,
                self.pallas_bwd)
            s1, b1 = self.norm(name="BatchNorm_0")(y1)
            y2 = fused_affine_relu_conv(y1, w2, s1, b1, None, self.block_b,
                                        True, self.pallas_bwd)
            s2, b2 = self.norm(scale_init=nn.initializers.zeros,
                               name="BatchNorm_1")(y2)
        a_in = a_in.astype(self.dtype)
        return y2, s2, b2, a_in


class FusedBottleneckBlock(nn.Module):
    """BottleneckBlock whose middle 3x3 runs on the fused Pallas kernel.

    Unlike `FusedBasicBlock` there is no cross-block chaining (the 1x1
    reduce/expand convs bound the kernel's shape), but the block-local
    fusion still wins the big pieces: the 3x3 — ~64% of the block's FLOPs
    at C_in == C_out == filters — takes the one-matmul kernel with bn1's
    apply+ReLU folded into its input transform, and in train mode the
    kernel emits bn2's moments, so neither bn1's normalize pass nor bn2's
    stats pass touches HBM. Parameter tree matches the standard block
    (Conv_i / BatchNorm_i / shortcut_*), so checkpoints are
    interchangeable. Only stride-1 blocks qualify.
    """

    filters: int
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    norm_coeffs: ModuleDef = BatchNormCoeffs
    act: Callable = nn.relu
    kernel_init: Callable = nn.initializers.variance_scaling(
        2.0, "fan_out", "normal")
    block_b: int = 0  # 0 = auto
    pallas_bwd: bool = False
    train: bool = False

    @nn.compact
    def __call__(self, x):
        if self.act is not nn.relu:
            # The fused middle conv and _materialize bake ReLU into the
            # kernel's transform; honoring a different act only at the
            # block exit would be silently inconsistent.
            raise ValueError("FusedBottleneckBlock fuses ReLU; act must be "
                             "nn.relu (use the unfused BottleneckBlock for "
                             "other activations)")
        residual = x
        y = self.conv(self.filters, (1, 1), name="Conv_0")(x)
        s1, b1 = self.norm_coeffs(name="BatchNorm_0")(y)
        w2 = _ConvKernel(self.filters, self.kernel_init, name="Conv_1")(
            self.filters)
        if self.train:
            y2, st2 = fused_conv_bn(y, w2, s1, b1, None, self.block_b,
                                    True, self.pallas_bwd)
            s2, b2 = self.norm_coeffs(name="BatchNorm_1")(y2, stats=st2)
        else:
            y2 = fused_affine_relu_conv(y, w2, s1, b1, None, self.block_b,
                                        True, self.pallas_bwd)
            s2, b2 = self.norm_coeffs(name="BatchNorm_1")(y2)
        z2 = _materialize(y2, s2, b2, None, y2.dtype)
        y3 = self.conv(self.filters * 4, (1, 1), name="Conv_2")(z2)
        y3 = self.norm(scale_init=nn.initializers.zeros,
                       name="BatchNorm_2")(y3)
        if residual.shape != y3.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 name="shortcut_conv")(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return self.act(y3 + residual)


def _materialize(x_raw, scale, shift, res, dtype):
    # Same epilogue math AND rounding as the kernel's in-VMEM transform
    # (f32 affine, rounded through bf16) — one source of truth so chain
    # interior (the kernel's emitted z) and chain exit can never drift
    # numerically, including at dtype=float32.
    from tpu_dp.ops.conv_block import _affine_act

    z = _affine_act(x_raw, scale, shift, res, True)
    return z.astype(jnp.bfloat16).astype(dtype)


class BasicBlock(nn.Module):
    """Two 3×3 convs + identity/projection shortcut (ResNet-18/34)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return self.act(y + residual)


class BottleneckBlock(nn.Module):
    """1×1 reduce → 3×3 → 1×1 expand (×4) bottleneck (ResNet-50+)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="shortcut_conv",
            )(residual)
            residual = self.norm(name="shortcut_norm")(residual)
        return self.act(y + residual)


class ResNet(nn.Module):
    """CIFAR-variant ResNet over NHWC inputs.

    ``fused_stages`` selects stages whose eligible blocks run on the
    Pallas kernel: stride-1 channel-preserving BasicBlocks become
    `FusedBasicBlock` chains, and stride-1 BottleneckBlocks run their
    middle 3x3 as a `FusedBottleneckBlock` (block-local fusion).
    Ineligible blocks (stride-2/projection) stay on the standard path and
    chains materialize around them. The parameter tree is identical either
    way (blocks are explicitly named ``BasicBlock_i``/``BottleneckBlock_i``
    in fused mode, matching the unfused auto-names), so checkpoints are
    interchangeable between fused and unfused configs.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 10
    num_filters: int = 64
    dtype: jnp.dtype = jnp.float32
    axis_name: str | None = None  # set when used inside shard_map/pmap
    fused_stages: Sequence[int] = ()
    fused_block_b: int = 0  # 0 = auto from the VMEM budget
    fused_bwd: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.variance_scaling(2.0, "fan_out", "normal"),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=self.axis_name,
        )
        norm_c = partial(
            BatchNormCoeffs,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            axis_name=self.axis_name,
        )
        fuse_basic = bool(self.fused_stages) and self.block_cls is BasicBlock
        fuse_bneck = (bool(self.fused_stages)
                      and self.block_cls is BottleneckBlock)
        fuse_mode = fuse_basic or fuse_bneck
        fused_set = set(self.fused_stages) if fuse_mode else set()

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (3, 3), name="stem_conv")(x)
        chain = None  # (x_raw, scale, shift, residual) while chaining
        if fuse_basic and 0 in fused_set:
            sc, sh = norm_c(name="stem_norm")(x)
            chain = (x, sc, sh, None)
        else:
            x = norm(name="stem_norm")(x)
            x = nn.relu(x)
        idx = 0
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                filters = self.num_filters * 2**i
                in_ch = (chain[0] if chain is not None else x).shape[-1]
                fusable = (fuse_basic and i in fused_set and strides == 1
                           and in_ch == filters)
                if fuse_bneck and i in fused_set and strides == 1:
                    x = FusedBottleneckBlock(
                        filters=filters,
                        conv=conv,
                        norm=norm,
                        norm_coeffs=norm_c,
                        block_b=self.fused_block_b,
                        pallas_bwd=self.fused_bwd,
                        train=train,
                        name=f"BottleneckBlock_{idx}",
                    )(x)
                elif fusable:
                    if chain is None:
                        # Enter a chain from a plain activation A: exact,
                        # since relu(A) == A for post-ReLU activations.
                        chain = (x, jnp.ones((in_ch,), jnp.float32),
                                 jnp.zeros((in_ch,), jnp.float32), None)
                    chain = FusedBasicBlock(
                        filters=filters,
                        norm=norm_c,
                        block_b=self.fused_block_b,
                        dtype=self.dtype,
                        pallas_bwd=self.fused_bwd,
                        train=train,
                        name=f"BasicBlock_{idx}",
                    )(*chain)
                else:
                    if chain is not None:
                        x = _materialize(*chain, self.dtype)
                        chain = None
                    block_name = None
                    if fuse_basic:
                        block_name = f"BasicBlock_{idx}"
                    elif fuse_bneck:
                        block_name = f"BottleneckBlock_{idx}"
                    x = self.block_cls(
                        filters=filters,
                        strides=strides,
                        conv=conv,
                        norm=norm,
                        name=block_name,
                    )(x)
                idx += 1
        if chain is not None:
            x = _materialize(*chain, self.dtype)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="classifier")(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
