"""Model zoo: the reference's `Net` (behavioral parity) and CIFAR ResNets.

The reference defines one model, a LeNet-style CNN
(`/root/reference/cifar_example.py:17-34`), which cannot reach the 93% top-1
north-star; BASELINE.json's configs name ResNet-18/50, so the zoo carries
both (SURVEY.md §6 note).
"""

from tpu_dp.models.net import Net
from tpu_dp.models.resnet import ResNet, ResNet18, ResNet50

_REGISTRY = {
    "net": lambda num_classes=10, **kw: Net(num_classes=num_classes, **kw),
    "resnet18": lambda num_classes=10, **kw: ResNet18(num_classes=num_classes, **kw),
    "resnet50": lambda num_classes=10, **kw: ResNet50(num_classes=num_classes, **kw),
}

# Models that understand the ResNet-only kwargs (fused Pallas stages etc.).
_RESNETS = {"resnet18", "resnet50"}

# Models carrying BatchNorm, i.e. the ones that accept ``axis_name`` for
# sync-BN inside shard_map (one source of truth — the trainer keys its
# sharded-update model construction off this, not a second name list).
BATCHNORM_MODELS = frozenset(_RESNETS)


def parse_fused_stages(spec: str | None) -> tuple[int, ...]:
    """Parse `ModelConfig.fused_stages`: '' -> none, 'all' -> all four
    stages, else comma-separated stage indices ('0' or '0,1,2,3')."""
    if not spec:
        return ()
    if spec.strip().lower() == "all":
        return (0, 1, 2, 3)
    try:
        stages = tuple(sorted({int(s) for s in spec.split(",") if s.strip()}))
    except ValueError:
        raise ValueError(
            f"fused_stages must be '', 'all', or comma-separated stage "
            f"indices, got {spec!r}") from None
    if any(s not in (0, 1, 2, 3) for s in stages):
        raise ValueError(
            f"fused_stages indices must be in 0..3, got {spec!r}")
    return stages


def build_model(name: str, num_classes: int = 10, **kwargs):
    """Construct a model by config name (`tpu_dp.config.ModelConfig.name`)."""
    key = name.lower()
    try:
        factory = _REGISTRY[key]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    if key not in _RESNETS:
        kwargs.pop("fused_stages", None)
        kwargs.pop("fused_block_b", None)
        kwargs.pop("fused_bwd", None)
    return factory(num_classes=num_classes, **kwargs)


__all__ = [
    "BATCHNORM_MODELS", "Net", "ResNet", "ResNet18", "ResNet50",
    "build_model", "parse_fused_stages",
]
