"""Model zoo: the reference's `Net` (behavioral parity) and CIFAR ResNets.

The reference defines one model, a LeNet-style CNN
(`/root/reference/cifar_example.py:17-34`), which cannot reach the 93% top-1
north-star; BASELINE.json's configs name ResNet-18/50, so the zoo carries
both (SURVEY.md §6 note).
"""

from tpu_dp.models.net import Net
from tpu_dp.models.resnet import ResNet, ResNet18, ResNet50

_REGISTRY = {
    "net": lambda num_classes=10, **kw: Net(num_classes=num_classes, **kw),
    "resnet18": lambda num_classes=10, **kw: ResNet18(num_classes=num_classes, **kw),
    "resnet50": lambda num_classes=10, **kw: ResNet50(num_classes=num_classes, **kw),
}


def build_model(name: str, num_classes: int = 10, **kwargs):
    """Construct a model by config name (`tpu_dp.config.ModelConfig.name`)."""
    try:
        factory = _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return factory(num_classes=num_classes, **kwargs)


__all__ = ["Net", "ResNet", "ResNet18", "ResNet50", "build_model"]
