"""`Net` — the reference's LeNet-style CIFAR CNN, as a Flax module.

Topology parity with `/root/reference/cifar_example.py:17-34` (duplicated at
`cifar_example_ddp.py:23-40`):

    conv1: 3→6, 5×5, valid padding        (456 params)
    maxpool 2×2 stride 2
    conv2: 6→16, 5×5, valid padding       (2 416 params)
    maxpool 2×2 stride 2
    flatten → fc1: 400→120 (48 120) → fc2: 120→84 (10 164) → fc3: 84→10 (850)

Total 62 006 parameters, matching torch's `Net` exactly. Layout is NHWC
(TPU-native; the reference's NCHW is a CUDA/cuDNN convention) and the flatten
order is therefore H·W·C rather than torch's C·H·W — weight-level parity
would need a permutation, documented here as the one intentional divergence.
ReLU after each conv and after fc1/fc2; logits (no softmax) from fc3, matching
`CrossEntropyLoss` taking raw logits (`cifar_example.py:63`).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class Net(nn.Module):
    """The reference CNN (`cifar_example.py:17-34`), NHWC, Flax."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train  # no dropout/batchnorm, matching the reference
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))  # flatten all dims except batch
        x = nn.relu(nn.Dense(120, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(84, dtype=self.dtype, name="fc2")(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc3")(x)
        return x
