"""Checkpoint save/restore — closing the reference's save-only gap.

The reference only ever saves: `torch.save(net.state_dict(), './cifar_net.pth')`
at end of training (`/root/reference/cifar_example.py:92-93`), from *every*
rank to the same path (last-writer-wins race), with DDP's `module.` key
prefix, and with no load/resume path, no optimizer state, no epoch counter
(SURVEY.md §5 "Checkpoint / resume — SAVE ONLY"). Here:

- the checkpoint is the full `TrainState` pytree (params + momentum buffers +
  batch stats + step) plus host metadata (epoch, sampler seed, config), so a
  run restores bit-exactly where it left off;
- only process 0 writes (others pass through), and the write is
  atomic (tmp file + rename) — no cross-rank or crash torn-write races;
- serialization is flax msgpack of numpy-ified arrays — no pickle of live
  objects, no `module.` prefix artifact;
- a final-weights export (`save_params`) matches the reference's
  end-of-training `state_dict` save semantics for inference handoff.
"""

from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Any

import jax
import numpy as np
from flax import serialization

from tpu_dp.obs import flightrec as _flightrec
from tpu_dp.obs.counters import counters as _counters
from tpu_dp.train.state import TrainState

_CKPT_NAME = "state.msgpack"
_META_NAME = "meta.json"

#: Checkpoint meta/manifest schema. 1 = the pre-checksum layout (no
#: ``schema`` key at all — every checkpoint written before this version);
#: 2 = + the ``integrity`` manifest (whole-payload sha256 and per-leaf
#: sha256s, verified on every load/restore path). Loaders REFUSE schemas
#: they do not know with the typed `CheckpointSchemaError` — the same
#: contract `flightrec.read_dump` and `read_comm_report` enforce — while
#: pre-checksum checkpoints still load (verification skipped, counted in
#: ``ckpt.unverified_loads``).
CKPT_SCHEMA = 2
KNOWN_SCHEMAS = (1, 2)


class CheckpointSchemaError(ValueError):
    """A checkpoint manifest declares a schema this build does not know."""


class CorruptCheckpointError(RuntimeError):
    """A checkpoint failed checksum verification — its bytes are not the
    bytes that were saved. Carries the save dir and (when the payload
    still parses) the names of the mismatching leaves, so the refusal is
    attributable. Resume paths treat it as "mark corrupt, fall back to
    the next-older complete candidate" (`tpu_dp.resilience.resume_latest`,
    the trainer's rollback/regroup restores)."""

    def __init__(self, message: str, *, path: str = "",
                 leaves: tuple[str, ...] = ()):
        super().__init__(message)
        self.path = str(path)
        self.leaves = tuple(leaves)


def _chaos_shim():
    """The storage-fault shim, IFF armed — the ONE shared accessor
    (`faultinject.storage_shim`), imported at call time because the
    `tpu_dp.resilience` package imports this module at init."""
    from tpu_dp.resilience.faultinject import storage_shim

    return storage_shim()


def _leaf_sha256(leaf) -> str:
    """sha256 over one host leaf's dtype + shape + raw bytes (metadata
    included so a re-interpreted buffer cannot collide with the original)."""
    arr = np.asarray(leaf)
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _walk_state_dict(node, prefix: str = ""):
    """Depth-first ``(path, leaf)`` pairs of a flax state dict, paths
    '/'-joined — the same key convention the quarantine/SDC tooling uses."""
    if isinstance(node, dict):
        for key in sorted(node):
            yield from _walk_state_dict(
                node[key], f"{prefix}/{key}" if prefix else str(key)
            )
    else:
        yield prefix, node


def _integrity_manifest(payload: bytes, host_state) -> dict[str, Any]:
    """The schema-2 integrity block written into meta.json at save time:
    one sha256 of the serialized payload (catches truncation/rot wholesale
    — the cheap always-checked hash) plus per-leaf sha256s (the
    attribution map: a mismatch names the rotten leaf)."""
    leaves = {
        path: _leaf_sha256(leaf)
        for path, leaf in _walk_state_dict(
            serialization.to_state_dict(host_state)
        )
    }
    return {
        "algo": "sha256",
        "payload_sha256": hashlib.sha256(payload).hexdigest(),
        "leaves": leaves,
    }


def read_meta(ckpt_dir: str | os.PathLike) -> dict[str, Any]:
    """Load + schema-check a save dir's meta.json ({} when absent).

    The one schema gate every loader shares: an unknown ``schema`` is a
    typed refusal (`CheckpointSchemaError`), never a misread."""
    meta_path = Path(ckpt_dir) / _META_NAME
    if not meta_path.exists():
        return {}
    meta = json.loads(meta_path.read_text())
    schema = meta.get("schema", 1)
    if schema not in KNOWN_SCHEMAS:
        raise CheckpointSchemaError(
            f"checkpoint {ckpt_dir} declares schema {schema!r}; this build "
            f"knows {KNOWN_SCHEMAS} — refusing to guess at its layout"
        )
    return meta


def verify_payload(payload: bytes, meta: dict[str, Any],
                   where: str | os.PathLike) -> None:
    """Verify ``payload`` against the meta's integrity manifest.

    Pre-checksum saves (schema 1 / no manifest) are counted and skipped —
    they still load. A mismatch marks ``ckpt.checksum_failures``, records
    the refusal in the flight recorder, and raises the typed
    `CorruptCheckpointError` naming the divergent leaves when the payload
    still parses (bitrot) or the tear when it does not."""
    integrity = meta.get("integrity") if meta.get("schema", 1) >= 2 else None
    if not integrity:
        _counters.inc("ckpt.unverified_loads")
        return
    if hashlib.sha256(payload).hexdigest() == integrity.get("payload_sha256"):
        _counters.inc("ckpt.verified_loads")
        return
    _counters.inc("ckpt.checksum_failures")
    bad: list[str] = []
    parses = True
    try:
        raw = serialization.msgpack_restore(payload)
        want = integrity.get("leaves") or {}
        for path, leaf in _walk_state_dict(raw):
            if path in want and _leaf_sha256(leaf) != want[path]:
                bad.append(path)
    except Exception:
        parses = False
    _flightrec.record("ckpt_corrupt", dir=str(where),
                      leaves=bad[:8], parses=parses)
    detail = (f"divergent leaves {bad[:8]}" if bad
              else "payload torn/unparseable" if not parses
              else "payload bytes differ from the saved manifest")
    raise CorruptCheckpointError(
        f"checkpoint {where} failed sha256 verification ({detail}) — "
        f"refusing to restore corrupt state",
        path=str(where), leaves=tuple(bad),
    )


def _io_retry(fn, describe: str):
    """Run one checkpoint write under the unified IO retry budget
    (``resilience.io_retry_s`` — the same budget the membership ledger
    uses): a transient EIO is a retry, not a lost save. Exhaustion
    re-raises the last OSError for the caller's degrade/raise policy."""
    from tpu_dp.resilience.retry import io_retry_params, retry_call

    retries, base_delay = io_retry_params()
    return retry_call(fn, retries=retries, base_delay=base_delay,
                      retry_on=(OSError,), jitter=0.5, describe=describe)


def leaf_to_host(x) -> np.ndarray:
    """One leaf → host numpy, whatever its device layout.

    Replicated leaves are a straight copy. Leaves sharded across *processes*
    (the sharded-update optimizer state, `train.update_sharding=sharded`)
    are not fully addressable, so the global value is assembled with an
    across-host allgather — the checkpoint always stores the canonical
    global array, never one host's shard.
    """
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def _to_host(tree):
    return jax.tree_util.tree_map(leaf_to_host, tree)


def has_cross_process_leaves(tree) -> bool:
    """True when materializing ``tree`` on host is a COLLECTIVE operation.

    A leaf sharded across processes (sharded-update optimizer state on a
    multi-host mesh) assembles via `leaf_to_host`'s across-host allgather —
    every process must walk the tree in the same order, or the writer
    deadlocks waiting for peers that already bailed behind a rank gate.
    The write gates below consult this before returning early.
    """
    return any(
        not getattr(x, "is_fully_addressable", True)
        for x in jax.tree_util.tree_leaves(tree)
    )


#: Advisory marker the guardrail layer drops into a step dir it distrusts
#: (`tpu_dp.resilience.preempt.quarantine_save_dir`); defined here so the
#: write protocol can clear a stale one without importing resilience.
QUARANTINED_MARKER = "quarantined.json"


def _atomic_write_state(
    ckpt_dir: Path, host_state, meta: dict[str, Any] | None
) -> Path:
    """The one atomic-write protocol (tmp file + rename) for state + meta.

    Every save is stamped with the manifest schema and the integrity
    block (`_integrity_manifest`) so every later load can prove the bytes
    it reads are the bytes that were written. Transient write errors are
    retried on the unified IO budget (`_io_retry`); the storage-fault
    shim's seams (`_chaos_shim`) sit inside the retried block (a
    transient injected EIO must be retried like a real one) and after the
    final rename (``torn``/``bitrot`` defeat per-file atomicity by
    corrupting a COMMITTED save).
    """
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    payload = serialization.to_bytes(host_state)
    meta_out = dict(meta or {})
    meta_out["schema"] = CKPT_SCHEMA
    meta_out["integrity"] = _integrity_manifest(payload, host_state)
    meta_text = json.dumps(meta_out, indent=2, default=str)

    def _write():
        shim = _chaos_shim()
        if shim is not None:
            shim.on_write(ckpt_dir / _CKPT_NAME)
        tmp = ckpt_dir / (_CKPT_NAME + ".tmp")
        tmp.write_bytes(payload)
        os.replace(tmp, ckpt_dir / _CKPT_NAME)
        meta_tmp = ckpt_dir / (_META_NAME + ".tmp")
        meta_tmp.write_text(meta_text)
        os.replace(meta_tmp, ckpt_dir / _META_NAME)

    _io_retry(_write, describe=f"checkpoint write {ckpt_dir.name}")
    shim = _chaos_shim()
    if shim is not None:
        shim.post_commit(ckpt_dir)
    # A fresh complete write into this dir supersedes any quarantine
    # suspicion on its previous contents: a post-rollback replay re-saves
    # CLEAN state into the same step_<n> dirs (same atomic protocol), and
    # a surviving marker would keep `find_candidates` distrusting a save
    # that no longer carries the condemned bytes.
    try:
        (ckpt_dir / QUARANTINED_MARKER).unlink()
    except FileNotFoundError:
        pass
    return ckpt_dir / _CKPT_NAME


def save_checkpoint(
    ckpt_dir: str | os.PathLike,
    state: TrainState,
    meta: dict[str, Any] | None = None,
) -> Path | None:
    """Write state + metadata; process 0 only. Returns the path (rank 0).

    With cross-process-sharded leaves the host materialization is itself a
    collective, so every process runs it; only the write is rank-gated.
    """
    host_state = None
    if has_cross_process_leaves(state):
        host_state = _to_host(state)  # all processes participate
    if jax.process_index() != 0:  # dplint: allow(DP101) host-only IO
        return None
    if host_state is None:
        host_state = _to_host(state)
    return _atomic_write_state(Path(ckpt_dir), host_state, meta)


def _relayout_opt_leaf(saved: np.ndarray, like: np.ndarray,
                       where: str) -> np.ndarray:
    """Reshard one saved optimizer-state leaf onto ``like``'s layout.

    The sharded weight update (`train.update_sharding=sharded`) stores each
    opt-state leaf as a flat 1-D array zero-padded to a multiple of the
    world size — a layout that depends on the topology it was written
    under. This relayout is value-preserving across every transition
    because only zeros are ever added or dropped:

    - flat(world A) → flat(world B): truncate or zero-extend (the tail
      beyond the true element count is padding by construction);
    - replicated → flat: flatten + zero-pad;
    - flat → replicated: take the leading true-count elements, reshape.
    """
    saved = np.asarray(saved)
    if saved.shape == tuple(like.shape):
        return saved
    flat = saved.reshape(-1)
    if like.ndim == 1:
        out = np.zeros(like.shape[0], dtype=like.dtype)
        k = min(out.size, flat.size)
        out[:k] = flat[:k]
        return out
    if flat.size < like.size:
        raise ValueError(
            f"checkpoint opt_state leaf {where}: saved {saved.shape} has "
            f"{flat.size} elements, target {tuple(like.shape)} needs "
            f"{like.size} — not a shard-layout transition"
        )
    return flat[: like.size].reshape(like.shape).astype(like.dtype)


def _maybe_reshard_opt_state(raw: Any, host_target: TrainState) -> Any:
    """Relayout ``raw['opt_state']`` onto the target's shard layout.

    A checkpoint written under one topology/update-sharding mode restores
    under another: leaf shapes that already match pass through untouched
    (the common case — and the fast path `from_state_dict` would take
    anyway); a structural mismatch is left for `from_state_dict` to
    diagnose (it is a different-optimizer error, not a layout one).
    """
    if not isinstance(raw, dict) or "opt_state" not in raw:
        return raw
    target_sd = serialization.to_state_dict(host_target)
    saved_opt, target_opt = raw["opt_state"], target_sd.get("opt_state")
    s_leaves, s_def = jax.tree_util.tree_flatten(saved_opt)
    t_leaves, t_def = jax.tree_util.tree_flatten(target_opt)
    if s_def != t_def:
        return raw
    paths = jax.tree_util.tree_leaves_with_path(saved_opt)
    new_leaves = [
        _relayout_opt_leaf(s, t, jax.tree_util.keystr(p))
        for (p, _), s, t in zip(paths, s_leaves, t_leaves)
    ]
    raw = dict(raw)
    raw["opt_state"] = jax.tree_util.tree_unflatten(s_def, new_leaves)
    return raw


def _reconcile_residuals(raw: Any, host_target: TrainState) -> Any:
    """Reconcile the int8 codec's error-feedback residuals on restore.

    The residual state (`tpu_dp.parallel.quant`; ``TrainState.residuals``)
    is a dict of ``f32[world, qpad]`` leaves keyed by params-leaf path.
    Restores must survive every transition the opt state survives:

    - **older checkpoint, no residuals at all** (pre-codec, or written with
      the codec off) → zero-initialized residuals shaped like the target
      (error feedback restarts; the pending correction it forgets is
      bounded by ONE step's quantization error);
    - **codec turned off** (target carries none) → saved residuals are
      dropped;
    - **same layout** → exact round trip (the kill+resume contract);
    - **world size or block size changed** → *pending-correction-
      preserving* reshard: the sum of every
      replica's pending error is remapped from the old per-chunk layout
      into replica 0's row of the new layout, zeros elsewhere — the total
      un-transmitted correction Σ_r residual_r is exactly what error
      feedback owes the trajectory, and replica 0 pays the whole debt on
      its first post-restore step;
    - **the quantizable-leaf set changed** (block size crossing a leaf's
      threshold): keys the target lacks are dropped, keys it gained start
      at zero;
    - **the bucket layout changed** (`train.bucket_mb` turned on/off or
      retuned, docs/PERF.md "Overlapped collectives") → *bucket-exact*
      reshard: residual keys are self-describing leaf compositions
      (`bucketing.composition` — a per-leaf key is the single-leaf case),
      so every saved key not passed through bitwise is DECOMPOSED into
      per-params-leaf pending corrections (`quant.decompose_residual`)
      and the target's keys are COMPOSED back from that pool
      (`quant.compose_residual`, debt on replica 0's row) — a leaf moving
      between buckets, splitting out of one, or merging into another
      carries its pending correction along exactly.
    """
    if not isinstance(raw, dict):
        return raw
    target_sd = serialization.to_state_dict(host_target)
    if "residuals" not in target_sd:
        return raw
    target_res = target_sd.get("residuals") or {}
    saved_res = raw.get("residuals") or {}
    if not isinstance(saved_res, dict):
        saved_res = {}
    target_params = target_sd.get("params", {})

    def _leaf_elements(key: str) -> int | None:
        node: Any = target_params
        for part in key.split("/"):
            if not isinstance(node, dict) or part not in node:
                return None
            node = node[part]
        return int(np.asarray(node).size)

    from tpu_dp.parallel import bucketing, quant

    out = {}
    remap_targets = []
    consumed_keys = set()
    for key, like in target_res.items():
        like = np.asarray(like)
        saved = saved_res.get(key)
        if saved is not None and np.asarray(saved).shape == like.shape:
            # Same key, same layout: exact round trip (the kill+resume
            # bitwise contract) — bucketed keys included.
            out[key] = np.asarray(saved).astype(like.dtype)
            consumed_keys.add(key)
            continue
        remap_targets.append((key, like))
    if remap_targets:
        # Pending-correction pool: every saved residual NOT passed through
        # bitwise decomposes into per-leaf debt vectors. A params leaf
        # lives in exactly one composition per layout, so nothing double-
        # counts: a leaf whose saved bucket survived bitwise is not in the
        # pool, and its target key was already emitted above.
        leaf_sizes: dict[str, int] = {}
        for key in list(saved_res) + [k for k, _ in remap_targets]:
            for lk in bucketing.composition(key):
                if lk not in leaf_sizes:
                    n = _leaf_elements(lk)
                    if n is not None:
                        leaf_sizes[lk] = n
        pending: dict[str, np.ndarray] = {}
        for key, saved in saved_res.items():
            if key in consumed_keys:
                continue
            pending.update(quant.decompose_residual(saved, leaf_sizes, key))
        for key, like in remap_targets:
            out[key] = quant.compose_residual(pending, like, leaf_sizes,
                                              key)
    raw = dict(raw)
    raw["residuals"] = out
    return raw


def load_checkpoint(
    ckpt_dir: str | os.PathLike, target: TrainState, verify: bool = True
) -> tuple[TrainState, dict[str, Any]]:
    """Restore a `TrainState` (shaped like `target`) + metadata.

    Optimizer state is resharded onto ``target``'s layout when the
    checkpoint was written under a different topology or
    ``train.update_sharding`` mode (`_relayout_opt_leaf`) — a run killed on
    8 chips resumes on 4, and a replicated checkpoint upgrades to the
    sharded update in place. The int8 wire codec's error-feedback
    residuals ride the same path (`_reconcile_residuals`): same-layout
    restores are exact, world/block-size changes preserve the total
    pending correction, checkpoints predating the codec load with
    zero-initialized residuals.

    Every load schema-checks the manifest (`read_meta` — unknown schemas
    are a typed `CheckpointSchemaError`) and, unless ``verify=False``,
    proves the payload against its integrity checksums (`verify_payload`
    — a mismatch is a typed `CorruptCheckpointError`, the signal the
    resume paths turn into "mark corrupt, fall back to the next-older
    candidate"). Pre-checksum saves load with verification skipped and
    counted.
    """
    ckpt_dir = Path(ckpt_dir)
    meta = read_meta(ckpt_dir)
    payload = (ckpt_dir / _CKPT_NAME).read_bytes()
    if verify:
        verify_payload(payload, meta, ckpt_dir)
    host_target = _to_host(target)
    raw = serialization.msgpack_restore(payload)
    raw = _maybe_reshard_opt_state(raw, host_target)
    raw = _reconcile_residuals(raw, host_target)
    state = serialization.from_state_dict(host_target, raw)
    return state, meta


def checkpoint_exists(ckpt_dir: str | os.PathLike) -> bool:
    return (Path(ckpt_dir) / _CKPT_NAME).exists()


def missing_save_files(step_dir: str | os.PathLike) -> list[str]:
    """Required save files absent from ``step_dir``; empty = complete.

    THE definition of save completeness (both renames landed — a torn
    write, a crash between the two renames, leaves one behind). The
    manager's own scans (`CheckpointManager.complete_dirs`,
    `CheckpointManager.latest_dir`) and the resume scan
    (`tpu_dp.resilience.preempt.find_candidates`) must never disagree on
    it, so all of them call here.
    """
    d = Path(step_dir)
    return [name for name in (_CKPT_NAME, _META_NAME)
            if not (d / name).exists()]


class CheckpointManager:
    """Step-numbered checkpoints with retention and async saves.

    The manager features the reference entirely lacks (its one `torch.save`
    is end-of-training, every-rank, same-path — `cifar_example.py:92-93`):

    - each save lands in ``<dir>/step_<n>``, with an atomically-updated
      ``latest`` pointer file, so a partially-written checkpoint is never
      the one a resume sees;
    - ``keep`` bounds disk: oldest step dirs are pruned after each save;
    - ``async_save=True`` snapshots the state to host arrays synchronously
      (cheap: device→host copy) and does serialization + IO on a worker
      thread, so training never stalls on disk. ``wait()`` joins the
      in-flight write (called automatically before the next save and by
      ``close()``).

    Saves are process-0-only like the base functions (other processes
    no-op). ``latest_dir``/``restore`` read whatever disk *this* process
    sees — on a pod where each host has its own disk, call them from
    process 0 and broadcast the result (as ``Trainer._maybe_resume`` does).
    """

    def __init__(self, ckpt_dir: str | os.PathLike, keep: int = 3,
                 async_save: bool = True):
        self.ckpt_dir = Path(ckpt_dir)
        self.keep = int(keep)
        self.async_save = bool(async_save)
        self._thread = None
        self._error: BaseException | None = None

    def step_dirs(self) -> list[Path]:
        """Every ``step_<n>`` dir under the root, oldest→newest, complete
        or not (`complete_dirs` filters; the resume scan attributes each
        exclusion)."""
        if not self.ckpt_dir.exists():
            return []
        import re

        dirs = [
            p for p in self.ckpt_dir.iterdir()
            if p.is_dir() and re.fullmatch(r"step_\d+", p.name)
        ]
        return sorted(dirs, key=lambda p: int(p.name.split("_")[1]))

    # retained for callers of the pre-public name
    _step_dirs = step_dirs

    def wait(self) -> None:
        """Join the in-flight async write; re-raise its failure, if any.

        A checkpoint that silently failed to write is worse than a crash —
        the run would keep training with nothing to resume from — so worker
        exceptions surface here (and therefore on the next ``save``/
        ``restore``/``close``), wrapped with the checkpoint context."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                f"async checkpoint write to {self.ckpt_dir} failed"
            ) from err

    def save(self, state: TrainState, meta: dict[str, Any] | None = None,
             step: int | None = None, host_state=None) -> Path | None:
        """Checkpoint ``state`` under ``step_<n>`` (n defaults to state.step).

        ``host_state`` lets a caller hand over an already-materialized host
        copy of ``state`` (the resilience snapshot layer's double buffer)
        instead of paying a fresh device→host copy + allocation here; the
        buffer must stay untouched until the next ``save``/``wait``.

        Cross-process-sharded leaves make the host materialization a
        collective (`has_cross_process_leaves`): every process assembles,
        only process 0 keeps the result and writes.
        """
        if host_state is None and has_cross_process_leaves(state):
            host_state = _to_host(state)  # all processes participate
        if jax.process_index() != 0:  # dplint: allow(DP101) host-only IO
            return None
        self.wait()
        n = int(state.step) if step is None else int(step)
        step_dir = self.ckpt_dir / f"step_{n:010d}"
        if host_state is None:
            host_state = _to_host(state)  # snapshot NOW: donation-safe

        def _write():
            _atomic_write_state(step_dir, host_state, meta)

            # Publish: latest points at a fully-written checkpoint only.
            # The pointer flip is the commit point of the whole save, so
            # it rides the same route as the state write (DP401): under
            # the IO retry budget, with the storage-fault seam consulted
            # inside the retried block — before this, a transient EIO
            # here orphaned a fully-written checkpoint, and chaos trials
            # could not even inject that failure.
            def _publish():
                shim = _chaos_shim()
                if shim is not None:
                    shim.on_write(self.ckpt_dir / "latest")
                ptr_tmp = self.ckpt_dir / "latest.tmp"
                ptr_tmp.write_text(step_dir.name)
                os.replace(ptr_tmp, self.ckpt_dir / "latest")

            _io_retry(_publish, describe=f"publish latest={step_dir.name}")
            # Retention: prune oldest beyond keep (never the one just written).
            if self.keep > 0:
                import shutil

                for old in self.step_dirs()[: -self.keep]:
                    if old != step_dir:
                        shutil.rmtree(old, ignore_errors=True)

        if self.async_save:
            import threading

            def _guarded():
                try:
                    _write()
                except BaseException as e:  # surfaced by the next wait()
                    self._error = e

            self._thread = threading.Thread(target=_guarded, daemon=True)
            self._thread.start()
        else:
            _write()
        return step_dir / _CKPT_NAME

    def complete_dirs(self) -> list[Path]:
        """Every step dir holding a complete save, oldest→newest.

        A complete save always has both files; a torn write (a crash
        between the two renames — e.g. a host dying mid-snapshot during
        preemption) must never be resumed from, so partial dirs are
        excluded here and the elastic-regroup/resume paths fall back to
        the previous complete one (`tpu_dp.resilience.find_latest`).
        """
        return [d for d in self.step_dirs() if not missing_save_files(d)]

    def latest_dir(self) -> Path | None:
        """Directory of the newest complete checkpoint, or None."""
        ptr = self.ckpt_dir / "latest"
        if ptr.exists():
            name = ptr.read_text().strip()
            cand = self.ckpt_dir / name
            # The pointer is only trusted when it names a COMPLETE save —
            # both files. (`latest` is written after the step dir, so this
            # should be impossible; a crash-interrupted filesystem can
            # still produce it — torn dir or a zero-byte pointer — and
            # resuming a torn dir would fail the regroup it exists to
            # serve.)
            if name and not missing_save_files(cand):
                return cand
            if name:
                import logging

                logging.getLogger(__name__).warning(
                    "checkpoint pointer %s names incomplete dir %s; "
                    "falling back to the newest complete save", ptr, cand,
                )
        dirs = self.complete_dirs()
        return dirs[-1] if dirs else None

    def restore(self, target: TrainState) -> tuple[TrainState, dict[str, Any]]:
        """Restore the newest checkpoint (shaped like ``target``)."""
        self.wait()
        latest = self.latest_dir()
        if latest is None:
            raise FileNotFoundError(f"no checkpoint under {self.ckpt_dir}")
        return load_checkpoint(latest, target)

    def close(self) -> None:
        self.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def load_params_only(
    ckpt_dir: str | os.PathLike,
    target_params,
    target_batch_stats=None,
) -> tuple[Any, Any, dict[str, Any]]:
    """Inference-side restore: params (+ BN stats) from a full checkpoint,
    with the optimizer state never materialized.

    The serving path (`tpu_dp.serve.InferenceEngine.from_checkpoint`) needs
    the model weights out of a *training* checkpoint without paying for —
    or even knowing about — the optimizer: momentum buffers double the
    payload it would otherwise place on device, and under
    ``train.update_sharding=sharded`` their layout additionally depends on
    the world size the checkpoint was written under. This loader restores
    only the ``params`` (and, when a target is given, ``batch_stats``)
    subtrees against their targets; every training-only subtree —
    ``opt_state`` AND the int8 wire codec's error-feedback ``residuals``
    (post-PR-10 checkpoints carry them; serving never needs pending
    gradient corrections) — is dropped without shape validation, device
    transfer, or the resharding dance `load_checkpoint` performs. That
    subtree selection (never a whole-tree `from_state_dict`, which would
    demand a shape-compatible target for every training-only leaf) is
    exactly why a checkpoint written under ANY world size, update-sharding
    mode, or collective dtype loads here unchanged: params and batch stats
    are always stored in the canonical global (replicated) layout
    (`leaf_to_host`), so there is nothing to reshard — pinned by
    `tests/test_serve.py::test_load_params_only_drops_int8_residuals`.

    Returns ``(params, batch_stats, meta)``; ``batch_stats`` is ``{}``
    when no target is given or the checkpoint carries none.
    """
    ckpt_dir = Path(ckpt_dir)
    meta = read_meta(ckpt_dir)  # typed refusal of unknown schemas
    payload = (ckpt_dir / _CKPT_NAME).read_bytes()
    # Serving restores verify too: a hot swap onto bit-rotted weights
    # would serve garbage with no error anywhere.
    verify_payload(payload, meta, ckpt_dir)
    raw = serialization.msgpack_restore(payload)
    if not isinstance(raw, dict) or "params" not in raw:
        raise ValueError(
            f"{ckpt_dir / _CKPT_NAME} is not a TrainState checkpoint "
            f"(no 'params' subtree) — for a bare `save_params` export use "
            f"`load_params`"
        )
    # Training-only subtrees are dropped HERE, by never touching them:
    # only the keys below are read out of `raw`. A new TrainState field
    # (like PR 10's `residuals`) therefore can never break serving.
    params = serialization.from_state_dict(
        _to_host(target_params), raw["params"], name="params"
    )
    batch_stats = {}
    if target_batch_stats:
        batch_stats = serialization.from_state_dict(
            _to_host(target_batch_stats), raw.get("batch_stats", {}),
            name="batch_stats",
        )
    return params, batch_stats, meta


def save_params(path: str | os.PathLike, params) -> Path | None:
    """Final-weights export — `torch.save(state_dict)` analogue
    (`cifar_example.py:92-93`), written once by process 0, clean key names."""
    if jax.process_index() != 0:  # dplint: allow(DP101) host-only IO
        return None
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = serialization.to_bytes(_to_host(params))

    # The export is the artifact serving promotes from: routed like the
    # checkpoint seams (DP401) so a transient EIO retries instead of
    # losing the final weights, and chaos trials can fault it.
    def _write():
        shim = _chaos_shim()
        if shim is not None:
            shim.on_write(path)
        path.write_bytes(payload)

    _io_retry(_write, describe=f"export params {path.name}")
    return path


def load_params(path: str | os.PathLike, target):
    return serialization.from_bytes(_to_host(target), Path(path).read_bytes())
