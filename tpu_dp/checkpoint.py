"""Checkpoint save/restore — closing the reference's save-only gap.

The reference only ever saves: `torch.save(net.state_dict(), './cifar_net.pth')`
at end of training (`/root/reference/cifar_example.py:92-93`), from *every*
rank to the same path (last-writer-wins race), with DDP's `module.` key
prefix, and with no load/resume path, no optimizer state, no epoch counter
(SURVEY.md §5 "Checkpoint / resume — SAVE ONLY"). Here:

- the checkpoint is the full `TrainState` pytree (params + momentum buffers +
  batch stats + step) plus host metadata (epoch, sampler seed, config), so a
  run restores bit-exactly where it left off;
- only process 0 writes (others pass through), and the write is
  atomic (tmp file + rename) — no cross-rank or crash torn-write races;
- serialization is flax msgpack of numpy-ified arrays — no pickle of live
  objects, no `module.` prefix artifact;
- a final-weights export (`save_params`) matches the reference's
  end-of-training `state_dict` save semantics for inference handoff.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import jax
import numpy as np
from flax import serialization

from tpu_dp.train.state import TrainState

_CKPT_NAME = "state.msgpack"
_META_NAME = "meta.json"


def _to_host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def save_checkpoint(
    ckpt_dir: str | os.PathLike,
    state: TrainState,
    meta: dict[str, Any] | None = None,
) -> Path | None:
    """Write state + metadata; process 0 only. Returns the path (rank 0)."""
    ckpt_dir = Path(ckpt_dir)
    if jax.process_index() != 0:
        return None
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    payload = serialization.to_bytes(_to_host(state))
    tmp = ckpt_dir / (_CKPT_NAME + ".tmp")
    tmp.write_bytes(payload)
    os.replace(tmp, ckpt_dir / _CKPT_NAME)
    meta_tmp = ckpt_dir / (_META_NAME + ".tmp")
    meta_tmp.write_text(json.dumps(meta or {}, indent=2, default=str))
    os.replace(meta_tmp, ckpt_dir / _META_NAME)
    return ckpt_dir / _CKPT_NAME


def load_checkpoint(
    ckpt_dir: str | os.PathLike, target: TrainState
) -> tuple[TrainState, dict[str, Any]]:
    """Restore a `TrainState` (shaped like `target`) + metadata."""
    ckpt_dir = Path(ckpt_dir)
    payload = (ckpt_dir / _CKPT_NAME).read_bytes()
    state = serialization.from_bytes(_to_host(target), payload)
    meta_path = ckpt_dir / _META_NAME
    meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
    return state, meta


def checkpoint_exists(ckpt_dir: str | os.PathLike) -> bool:
    return (Path(ckpt_dir) / _CKPT_NAME).exists()


def save_params(path: str | os.PathLike, params) -> Path | None:
    """Final-weights export — `torch.save(state_dict)` analogue
    (`cifar_example.py:92-93`), written once by process 0, clean key names."""
    if jax.process_index() != 0:
        return None
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(serialization.to_bytes(_to_host(params)))
    return path


def load_params(path: str | os.PathLike, target):
    return serialization.from_bytes(_to_host(target), Path(path).read_bytes())
