"""Chaos engineering for the resilience stack (docs/CHAOS.md).

Every recovery path shipped so far — snapshots, elastic shrink/grow,
guardrails, the SDC audit — is proven against one hand-placed fault per
test, while real preemption at pod scale delivers *composed* failures.
This package attacks the interactions:

- `storage` — the storage-fault shim behind the ``ioerr``/``torn``/
  ``bitrot``/``slowfs``/``enospc`` fault kinds: deterministic corruption
  injected at the checkpoint/snapshot/ledger IO seams
  (`tpu_dp.resilience.faultinject` arms it; `tpu_dp.checkpoint` and the
  membership ledger consult it);
- `runner` — the seeded trial harness (`python -m tpu_dp.chaos`): samples
  multi-fault schedules from a declared palette, runs the real
  ``train.py`` as subprocesses under an auto-restarting supervisor loop,
  verdicts each trial with the invariant auditor (oracle params,
  coverage, legal exits, artifact well-formedness, bounded recovery) and
  shrinks failing schedules to a minimal reproducing spec string.

Kept import-light on purpose: `tpu_dp.checkpoint` and the ledger consult
the shim through ``sys.modules`` so a production run that never armed a
storage fault never even imports this package.
"""
