"""Storage-fault shim: deterministic disk failures at the IO seams.

The fault domain `TPU_DP_FAULT` could not reach before this module: the
whole recovery story (tmp+rename snapshots, the ``latest`` pointer, the
membership ledger) trusts the filesystem, and a dying host does not. The
shim is armed by `tpu_dp.resilience.faultinject.FaultInjector` when a
storage plan's step boundary is reached, and consulted by exactly three
seams:

- ``on_write(path)`` — immediately before a checkpoint/snapshot payload
  or ledger file is written (`tpu_dp.checkpoint._atomic_write_state`,
  the membership ledger's atomic/exclusive writes). ``ioerr`` fails the
  next ``n`` calls with a transient ``EIO`` (the retry budgets must
  absorb it); ``enospc`` fails every later call with ``ENOSPC`` (the
  degrade paths must absorb *that*).
- ``on_read(path)`` — before a ledger read (`elastic._read_json`).
  ``slowfs`` sleeps ``ms`` per read, stressing the jittered retry
  schedule and the protocol poll loops above it.
- ``post_commit(step_dir)`` — after a save's BOTH renames landed.
  ``torn`` truncates the committed payload (both files exist, so only a
  parse/checksum can reveal the tear — defeating per-file atomicity
  exactly like a dying host does); ``bitrot`` flips bytes inside the
  committed payload (silent corruption only the checksum manifest can
  catch). One-shot: the first commit after arming is the victim.

Everything is no-op-cheap when nothing is armed; the seams reach the
shim through ``sys.modules`` so production processes never import it.
The shim never touches jax and is safe from the async checkpoint writer
thread (state transitions are single-word flag flips).
"""

from __future__ import annotations

import errno
import logging
import os
import time
from pathlib import Path

from tpu_dp.obs import flightrec as _flightrec
from tpu_dp.obs.counters import counters as _counters

logger = logging.getLogger(__name__)

#: the payload file post_commit corrupts (tpu_dp.checkpoint._CKPT_NAME;
#: named here literally so the shim stays import-free of checkpoint).
_PAYLOAD_NAME = "state.msgpack"


class StorageFaultShim:
    """Armed storage faults, applied at the IO seams (one shim/process)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self._ioerr_left = 0
        self._enospc = False
        self._slowfs_ms = 0.0
        self._slowfs_left: int | None = None  # None = unbounded
        self._torn_pending = False
        self._bitrot_pending = False
        self.active = False

    def _update_active(self) -> None:
        self.active = bool(
            self._ioerr_left or self._enospc or self._slowfs_ms
            or self._torn_pending or self._bitrot_pending
        )

    # -- arming (FaultInjector.on_step at the plan's boundary) -----------

    def arm(self, plan) -> None:
        """Arm one storage `FaultPlan` (kind in ``STORAGE_KINDS``)."""
        kind = plan.kind
        if kind == "ioerr":
            self._ioerr_left += max(1, int(plan.count))
        elif kind == "enospc":
            self._enospc = True
        elif kind == "slowfs":
            self._slowfs_ms = float(plan.delay_ms) or 50.0
            self._slowfs_left = int(plan.count) or None
        elif kind == "torn":
            self._torn_pending = True
        elif kind == "bitrot":
            self._bitrot_pending = True
        else:
            raise ValueError(f"not a storage fault kind: {kind!r}")
        self._update_active()
        _counters.inc("chaos.storage_armed")
        _flightrec.record("storage_fault_armed", step=plan.step,
                         fault=kind)

    # -- the seams -------------------------------------------------------

    def on_write(self, path: str | os.PathLike) -> None:
        """Checkpoint/snapshot/ledger write seam; may raise OSError."""
        if not self.active:
            return
        if self._enospc:
            _counters.inc("chaos.storage_faults")
            _flightrec.record("storage_fault", fault="enospc",
                             path=str(path))
            raise OSError(errno.ENOSPC,
                          f"injected ENOSPC (chaos) writing {path}")
        if self._ioerr_left > 0:
            self._ioerr_left -= 1
            self._update_active()
            _counters.inc("chaos.storage_faults")
            _flightrec.record("storage_fault", fault="ioerr",
                             path=str(path))
            raise OSError(errno.EIO,
                          f"injected transient EIO (chaos) writing {path}")

    def on_read(self, path: str | os.PathLike) -> None:
        """Ledger read seam: ``slowfs`` latency."""
        if not self.active or not self._slowfs_ms:
            return
        if self._slowfs_left is not None:
            if self._slowfs_left <= 0:
                self._slowfs_ms = 0.0
                self._update_active()
                return
            self._slowfs_left -= 1
        _counters.inc("chaos.storage_slow_reads")
        time.sleep(self._slowfs_ms / 1000.0)

    def post_commit(self, step_dir: str | os.PathLike) -> None:
        """Corrupt a JUST-COMMITTED save (``torn``/``bitrot``), one-shot."""
        if not self.active or not (self._torn_pending
                                   or self._bitrot_pending):
            return
        payload = Path(step_dir) / _PAYLOAD_NAME
        if not payload.exists():
            return
        if self._torn_pending:
            self._torn_pending = False
            size = payload.stat().st_size
            with open(payload, "r+b") as f:
                f.truncate(max(1, size // 2))
            kind = "torn"
        else:
            self._bitrot_pending = False
            with open(payload, "r+b") as f:
                f.seek(max(0, payload.stat().st_size // 2))
                byte = f.read(1) or b"\x00"
                f.seek(-len(byte), os.SEEK_CUR)
                f.write(bytes([byte[0] ^ 0xFF]))
            kind = "bitrot"
        self._update_active()
        _counters.inc("chaos.storage_faults")
        _flightrec.record("storage_fault", fault=kind,
                         path=str(payload))
        logger.warning("chaos: %s injected into committed save %s",
                       kind, payload)


#: The process-wide shim `FaultInjector` arms and the IO seams consult.
shim = StorageFaultShim()
