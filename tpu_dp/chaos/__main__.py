"""``python -m tpu_dp.chaos`` — the seeded chaos harness CLI.

    python -m tpu_dp.chaos --seed 20260809 --trials 5 \
        --out artifacts/chaos_report.json

Exit 0 when every trial's invariants are green; exit 1 on the first
failing trial, after shrinking its schedule to a minimal reproducing
spec string (replay it with ``--resilience.fault='<spec>'`` on the trial
config — docs/CHAOS.md "Replaying a minimized spec").

``--tamper-oracle`` is the auditor self-test: it corrupts the oracle
export before comparison, so a correct harness MUST exit nonzero with a
minimized spec — the CI lane proves the gate trips.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
from pathlib import Path

from tpu_dp.chaos.runner import DEFAULT_PALETTE, run_chaos


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpu_dp.chaos",
        description="composed-fault chaos trials over the real train.py",
    )
    ap.add_argument("--seed", type=int, default=0,
                    help="trial-generator seed (trial i draws from "
                         "Random(f'{seed}:{i}') — replayable individually)")
    ap.add_argument("--trials", type=int, default=5)
    ap.add_argument("--timeout-s", type=float, default=180.0,
                    help="wedge bound per trial, relaunches included")
    ap.add_argument("--kinds", default="",
                    help="comma-separated palette restriction "
                         "(default: the full palette)")
    ap.add_argument("--out", default=None,
                    help="write the JSON report here (CI artifact)")
    ap.add_argument("--workdir", default=None,
                    help="trial scratch root (default: a tempdir, "
                         "removed on success, kept on failure)")
    ap.add_argument("--tamper-oracle", action="store_true",
                    help="auditor self-test: corrupt the oracle so the "
                         "gate MUST trip (expected exit: nonzero)")
    args = ap.parse_args(argv)

    palette = DEFAULT_PALETTE
    if args.kinds:
        want = {k.strip() for k in args.kinds.split(",") if k.strip()}
        unknown = want - {e.kind for e in DEFAULT_PALETTE}
        if unknown:
            ap.error(f"unknown palette kinds {sorted(unknown)}; "
                     f"known: {sorted(e.kind for e in DEFAULT_PALETTE)}")
        palette = tuple(e for e in DEFAULT_PALETTE if e.kind in want)

    ephemeral = args.workdir is None
    workdir = Path(args.workdir) if args.workdir else Path(
        tempfile.mkdtemp(prefix="tpu_dp_chaos."))
    try:
        report = run_chaos(
            seed=args.seed, trials=args.trials, workdir=workdir,
            timeout_s=args.timeout_s, palette=palette,
            tamper_oracle=args.tamper_oracle,
        )
    except RuntimeError as e:
        print(f"chaos: {e}", file=sys.stderr)
        return 2
    if args.out:
        out = Path(args.out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(json.dumps(report, indent=2) + "\n")
    ok = report["ok"]
    n = len(report["trials"])
    print(f"chaos: {n} trial(s), "
          f"{sum(1 for t in report['trials'] if t['ok'])} green — "
          f"{'OK' if ok else 'FAIL'}")
    if not ok and report.get("minimized_spec"):
        print(f"chaos: minimal reproducing spec: "
              f"{report['minimized_spec']!r}")
    if ephemeral and ok:
        shutil.rmtree(workdir, ignore_errors=True)
    elif not ok:
        print(f"chaos: trial artifacts kept under {workdir}")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
