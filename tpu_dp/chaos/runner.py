"""Seeded composed-fault chaos trials over the real ``train.py``.

Each trial (docs/CHAOS.md):

1. **samples** a multi-fault schedule from the declared palette — 1–2
   ``;``-composed clauses of `tpu_dp.resilience.faultinject` grammar,
   steps and parameters drawn from a seeded RNG (``Random(f"{seed}:{i}")``
   — string seeding is version-stable) so every trial replays from
   ``(seed, index)`` alone;
2. **runs** the real ``train.py`` as a subprocess under a supervisor
   loop: an injected kill (137) or preemption (143) relaunches with
   ``--resume=auto`` and the not-yet-fired remainder of the schedule
   (storage clauses are re-injected even past their boundary — they arm
   at boundaries but apply at IO calls, and a kill takes their evidence
   down with it; `_relaunch_remainder`), parking the dead incarnation's
   flight-recorder dumps where the relaunch cannot overwrite them — the
   auto-restarting fleet supervisor, simulated honestly;
3. **verdicts** the trial with the invariant auditor (`audit_trial`):

   - *no wedge* — every incarnation exits within the timeout;
   - *legal exits* — intermediate codes only from the schedule's own
     kill/preempt clauses ({137, 143}), final code 0;
   - *artifacts parse* — the flight-recorder dump passes
     `flightrec.read_dump` and ``obsctl timeline`` rebuilds the run;
   - *coverage* — the final dump's exit step equals the expected applied
     optimizer steps (total minus guard-quarantined), across every
     relaunch/rollback generation;
   - *oracle* — for schedules whose recovery contract is exact
     (kill/preempt resume, storage faults, spike rollback), the final
     params export is **bitwise identical** to a never-faulted oracle
     run of the same config — the strongest exactly-once statement
     there is: any replayed, dropped or corrupted batch moves the
     params;

4. on failure, **shrinks** the schedule (`shrink_schedule`: greedy
   1-minimal clause removal, re-running the trial per candidate) and
   reports the minimal reproducing spec string.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import random
import shutil
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Sequence

from tpu_dp.obs import flightrec as flightrec_mod
from tpu_dp.resilience.faultinject import (
    KILL_EXIT_CODE,
    STORAGE_KINDS,
    FaultPlan,
)

PREEMPTED_EXIT_CODE = 143
#: optimizer steps per trial run: synthetic 48 / batch 4 × 2 epochs.
TRIAL_STEPS_PER_EPOCH = 12
TRIAL_EPOCHS = 2
TRIAL_TOTAL_STEPS = TRIAL_STEPS_PER_EPOCH * TRIAL_EPOCHS
#: faults land in the interior so every schedule leaves room to recover.
_FAULT_STEPS = (2, 18)


@dataclasses.dataclass(frozen=True)
class PaletteEntry:
    """One samplable fault kind and its invariant contract."""

    kind: str
    #: recovery replays to bitwise-equal final params (oracle invariant)
    oracle_exact: bool = True
    #: guard.action this kind needs compiled in ("" = guard stays off)
    guard_action: str = ""
    #: worlds the kind is meaningful at (1 = single-process trials)
    min_world: int = 1

    def sample(self, rng: random.Random) -> FaultPlan:
        step = rng.randint(*_FAULT_STEPS)
        extra: dict = {}
        if self.kind == "delay":
            extra["delay_ms"] = float(rng.choice((50, 100, 200)))
        if self.kind == "spike":
            extra["scale"] = float(rng.choice((1e5, 1e6)))
        if self.kind == "slowfs":
            extra["delay_ms"] = float(rng.choice((20, 50)))
        if self.kind == "ioerr":
            extra["count"] = rng.choice((1, 2))
        return FaultPlan(kind=self.kind, step=step, **extra)


#: The default palette `python -m tpu_dp.chaos` samples from. ``slowfs``
#: is ledger-read latency, so it only joins multi-rank (elastic) trials;
#: ``nan`` breaks the oracle contract by design (the quarantined batch
#: is withheld from the trajectory) and is audited by its quarantine
#: count instead.
DEFAULT_PALETTE = (
    PaletteEntry("kill"),
    PaletteEntry("preempt"),
    PaletteEntry("delay"),
    PaletteEntry("ioerr"),
    PaletteEntry("enospc"),
    PaletteEntry("torn"),
    PaletteEntry("bitrot"),
    PaletteEntry("spike", guard_action="rollback"),
    PaletteEntry("nan", oracle_exact=False, guard_action="skip"),
    PaletteEntry("slowfs", min_world=2),
)


@dataclasses.dataclass
class TrialSchedule:
    """A sampled trial: clauses + the config they need compiled in."""

    clauses: list[FaultPlan]
    guard_action: str = ""  # "" | "skip" | "rollback"

    @property
    def spec(self) -> str:
        return ";".join(c.to_spec() for c in self.clauses)

    @property
    def oracle_exact(self) -> bool:
        by_kind = {e.kind: e for e in DEFAULT_PALETTE}
        return all(by_kind[c.kind].oracle_exact for c in self.clauses
                   if c.kind in by_kind)


def sample_schedule(rng: random.Random,
                    palette: Sequence[PaletteEntry] = DEFAULT_PALETTE,
                    world: int = 1) -> TrialSchedule:
    """Sample one composed schedule: 1-2 clauses, at most one guard kind
    (one ``guard.action`` per process), at most one process-death kind
    per incarnation chain position (the supervisor consumes them in step
    order either way)."""
    pool = [e for e in palette if world >= e.min_world]
    n = rng.choice((1, 1, 2))  # bias toward single faults; pairs compose
    clauses: list[FaultPlan] = []
    guard_action = ""
    deaths = 0
    for _ in range(n):
        entry = rng.choice(pool)
        if entry.guard_action:
            if guard_action and entry.guard_action != guard_action:
                continue  # one sentinel policy per process
            guard_action = entry.guard_action
        if entry.kind in ("kill", "preempt"):
            if deaths >= 2:
                continue
            deaths += 1
        plan = entry.sample(rng)
        if world > 1 and entry.kind in ("kill", "preempt", "delay"):
            # Rank-targeted, never rank 0 (the save/export writer).
            plan = dataclasses.replace(plan,
                                       rank=rng.randint(1, world - 1))
        clauses.append(plan)
    clauses.sort(key=lambda c: (c.step, c.kind))
    if not clauses:
        clauses = [PaletteEntry("delay").sample(rng)]
    return TrialSchedule(clauses=clauses, guard_action=guard_action)


# ---------------------------------------------------------------------------
# running one trial
# ---------------------------------------------------------------------------


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _trial_argv(ckpt_dir: Path, spec: str, guard_action: str,
                resume: bool, extra_argv: Sequence[str] = ()) -> list[str]:
    args = [
        sys.executable, str(_repo_root() / "train.py"),
        "--data.dataset=synthetic",
        f"--data.synthetic_train_size={TRIAL_STEPS_PER_EPOCH * 4}",
        "--data.synthetic_test_size=16", "--data.batch_size=4",
        f"--train.epochs={TRIAL_EPOCHS}", "--train.log_every=100",
        "--train.eval_at_end=false", "--train.steps_per_call=1",
        "--parallel.num_devices=1",
        f"--train.ckpt_dir={ckpt_dir}", "--train.ckpt_async=false",
        "--resilience.snapshot_every_steps=3",
    ]
    if guard_action:
        args += ["--guard.enabled=true",
                 f"--guard.action={guard_action}",
                 "--guard.spike_min_steps=4", "--guard.spike_z=12"]
    # Caller-supplied config overrides (the tune chaos gate compiles the
    # candidate's knobs into the trial) — before the fault/resume args so
    # they can never shadow the schedule under test.
    args += list(extra_argv)
    if spec:
        args.append(f"--resilience.fault={spec}")
    if resume:
        args.append("--resume=auto")
    return args


def _trial_env() -> dict:
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               PYTHONPATH=str(_repo_root()))
    env.pop("TPU_DP_FAULT", None)
    return env


@dataclasses.dataclass
class TrialResult:
    schedule: TrialSchedule
    incarnations: list[dict]      # [{"exit": rc, "wall_s": s, "spec": str}]
    ckpt_dir: Path
    wall_s: float
    timed_out: bool = False

    @property
    def final_exit(self) -> int | None:
        return self.incarnations[-1]["exit"] if self.incarnations else None


def _relaunch_remainder(clauses: Sequence[FaultPlan]) -> list[FaultPlan]:
    """The schedule remainder a supervisor relaunch re-injects.

    The fired death is the earliest remaining kill/preempt (its fire
    ended the process, so nothing later-step fired after it); clauses at
    or before that boundary are spent. EXCEPT the storage domain:
    storage faults are host-boundary ARMED but applied at the next IO
    call, so a death at the same boundary can land before the fault ever
    touched a write — pruning by step would silently drop the fault from
    the trial — and a kill (`os._exit`, no dump, no summary) takes any
    applied-fault evidence down with it either way. Re-injected storage
    clauses re-arm at the first boundary after resume (they are
    boundary-≥-K kinds, unlike the exact-step device seams), keeping the
    fault in the story and landing its evidence in an incarnation whose
    artifacts survive for the auditor's DEGRADE teeth.
    """
    deaths = [c.step for c in clauses if c.kind in ("kill", "preempt")]
    died_at = min(deaths, default=0)
    return [c for c in clauses
            if c.step > died_at or c.kind in STORAGE_KINDS]


def run_trial(schedule: TrialSchedule, workdir: Path,
              timeout_s: float = 180.0,
              max_relaunches: int = 3,
              extra_argv: Sequence[str] = ()) -> TrialResult:
    """One trial under the supervisor loop (see module docstring).
    ``extra_argv`` rides every incarnation (launch and relaunch alike)."""
    workdir.mkdir(parents=True, exist_ok=True)
    ckpt = workdir / "ck"
    clauses = list(schedule.clauses)
    incarnations: list[dict] = []
    # Monotonic on purpose (DP403): the trial budget must survive NTP
    # steps under the supervisor — wall-clock here once stretched or
    # collapsed `timeout_s` with the host's clock discipline.
    t0 = time.monotonic()
    resume = False
    deadline = t0 + timeout_s
    while True:
        spec = ";".join(c.to_spec() for c in clauses)
        argv = _trial_argv(ckpt, spec, schedule.guard_action, resume,
                           extra_argv)
        budget = deadline - time.monotonic()
        if budget <= 0:
            return TrialResult(schedule, incarnations, ckpt,
                               time.monotonic() - t0, timed_out=True)
        t1 = time.monotonic()
        try:
            proc = subprocess.run(
                argv, cwd=_repo_root(), env=_trial_env(),
                capture_output=True, text=True, timeout=budget,
            )
        except subprocess.TimeoutExpired as e:
            incarnations.append({
                "exit": None, "spec": spec,
                "wall_s": round(time.monotonic() - t1, 1),
                "stdout": (e.stdout or b"")[-4000:].decode(
                    "utf-8", "replace")
                if isinstance(e.stdout, bytes) else (e.stdout or "")[-4000:],
            })
            return TrialResult(schedule, incarnations, ckpt,
                               time.monotonic() - t0, timed_out=True)
        incarnations.append({
            "exit": proc.returncode, "spec": spec,
            "wall_s": round(time.monotonic() - t1, 1),
            "stdout": proc.stdout[-8000:],
            "stderr": proc.stderr[-4000:],
        })
        if proc.returncode in (KILL_EXIT_CODE, PREEMPTED_EXIT_CODE) \
                and len(incarnations) <= max_relaunches:
            # A relaunch outside an elastic join reuses rank tag 0, so
            # its flight-recorder dump would OVERWRITE the predecessor's
            # (a preempted incarnation's counters are fault evidence the
            # auditor needs). Park the dead incarnation's dumps where the
            # next incarnation cannot clobber them and the final
            # timeline glob does not see them twice.
            obs_dir = ckpt / "obs"
            prev = sorted(obs_dir.glob(flightrec_mod.DUMP_GLOB)) \
                if obs_dir.exists() else []
            if prev:
                arch = obs_dir / f"chaos_inc{len(incarnations) - 1:02d}"
                arch.mkdir(exist_ok=True)
                for f in prev:
                    f.rename(arch / f.name)
            # The supervisor's restart: resume from the newest save, with
            # the schedule's not-yet-fired remainder.
            clauses = _relaunch_remainder(clauses)
            resume = True
            continue
        return TrialResult(schedule, incarnations, ckpt,
                           time.monotonic() - t0)


# ---------------------------------------------------------------------------
# the invariant auditor
# ---------------------------------------------------------------------------


def _file_sha256(path: Path) -> str:
    return hashlib.sha256(path.read_bytes()).hexdigest()


def audit_trial(result: TrialResult,
                oracle_params: Path | None) -> list[str]:
    """Every violated invariant, empty = the trial is green."""
    failures: list[str] = []
    sched = result.schedule
    if result.timed_out:
        failures.append(
            f"WEDGE: trial did not finish within its timeout "
            f"(spec {sched.spec!r})")
        return failures

    # -- legal exit codes ----------------------------------------------
    legal_mid = set()
    if any(c.kind == "kill" for c in sched.clauses):
        legal_mid.add(KILL_EXIT_CODE)
    if any(c.kind == "preempt" for c in sched.clauses):
        legal_mid.add(PREEMPTED_EXIT_CODE)
    for inc in result.incarnations[:-1]:
        if inc["exit"] not in legal_mid:
            failures.append(
                f"ILLEGAL EXIT: intermediate incarnation exited "
                f"{inc['exit']} (legal here: {sorted(legal_mid)})")
    if result.final_exit != 0:
        failures.append(
            f"ILLEGAL EXIT: final incarnation exited {result.final_exit} "
            f"(expected 0)")
        return failures  # everything below needs a completed run

    # -- artifacts parse ------------------------------------------------
    obs_dir = result.ckpt_dir / "obs"
    dumps = sorted(obs_dir.glob(flightrec_mod.DUMP_GLOB))
    # Dumps from incarnations a relaunch superseded, parked by the
    # supervisor so the relaunch could not overwrite them. Their
    # counters are fault evidence; their exit events are not the run's
    # final clock.
    archived = sorted(obs_dir.glob("chaos_inc*/" + flightrec_mod.DUMP_GLOB))
    if not dumps:
        failures.append("ARTIFACTS: no flight-recorder dump found")
        return failures
    counters: dict = {}

    def _read(d: Path) -> dict | None:
        try:
            return flightrec_mod.read_dump(d)
        except (OSError, ValueError) as e:
            failures.append(f"ARTIFACTS: flightrec dump {d.name} "
                            f"unreadable: {e}")
            return None

    def _merge_counters(payload: dict) -> None:
        # Counter registries are per-process; summing across incarnation
        # dumps gives the trial-wide totals the teeth below audit.
        for key, val in (payload.get("counters") or {}).items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                counters[key] = counters.get(key, 0) + val

    for d in archived:
        payload = _read(d)
        if payload is not None:
            _merge_counters(payload)
    exit_step = None
    for d in dumps:
        payload = _read(d)
        if payload is None:
            continue
        _merge_counters(payload)
        for ev in payload.get("events", ()):
            if ev.get("kind") == "exit":
                exit_step = ev.get("step", exit_step)
    try:
        from tpu_dp.obs.obsctl import RunArtifacts, build_timeline

        timeline = build_timeline(RunArtifacts(result.ckpt_dir),
                                  include_steps=True)
        if not timeline.get("events"):
            failures.append("ARTIFACTS: obsctl timeline is empty")
    except Exception as e:
        failures.append(f"ARTIFACTS: obsctl timeline failed: {e}")

    # -- coverage -------------------------------------------------------
    # The exit event carries the HOST window clock: every window of every
    # epoch dispatched exactly once across all relaunch/rollback
    # generations (a quarantined batch skips its UPDATE, not its window,
    # so the host clock still reaches the full count). The applied-update
    # side of coverage is the oracle check below — any dropped, replayed
    # or corrupted batch moves the params.
    if exit_step != TRIAL_TOTAL_STEPS:
        failures.append(
            f"COVERAGE: final exit step {exit_step} != the "
            f"{TRIAL_TOTAL_STEPS} windows the run owes across all "
            f"generations")

    # -- schedule-specific teeth ---------------------------------------
    if any(c.kind in ("ioerr", "enospc") for c in sched.clauses):
        wrote_errs = (counters.get("snapshot.write_errors", 0)
                      + counters.get("ckpt.write_errors", 0)
                      + counters.get("retry.retries", 0))
        if wrote_errs <= 0:
            failures.append(
                "DEGRADE: injected write faults left no trace (no "
                "snapshot/ckpt write_errors, no retries)")
    if sched.guard_action == "skip":
        quarantined = int(counters.get("guard.quarantined", 0))
        # Quarantine evidence exists only where artifacts survive: a
        # kill (`os._exit` 137) writes no dump and prints no summary, so
        # a quarantine inside a killed incarnation is unauditable, not
        # wrong — the teeth only bite when the nan clause rode an
        # incarnation that terminated observably.
        observable = any(
            "nan:" in (inc.get("spec") or "")
            and inc.get("exit") != KILL_EXIT_CODE
            for inc in result.incarnations)
        if observable and quarantined != 1:
            failures.append(
                f"GUARD: nan:skip trial expected exactly 1 quarantined "
                f"batch in the surviving artifacts, saw {quarantined}")

    # -- oracle ---------------------------------------------------------
    if sched.oracle_exact and oracle_params is not None:
        mine = result.ckpt_dir / "final_params.msgpack"
        if not mine.exists():
            failures.append("ORACLE: run left no final_params.msgpack")
        elif _file_sha256(mine) != _file_sha256(oracle_params):
            failures.append(
                f"ORACLE: final params diverge bitwise from the "
                f"never-faulted oracle (spec {sched.spec!r}) — a batch "
                f"was replayed, dropped, or corrupted")
    return failures


# ---------------------------------------------------------------------------
# shrinking
# ---------------------------------------------------------------------------


def shrink_schedule(clauses: Sequence[FaultPlan],
                    still_fails: Callable[[list[FaultPlan]], bool]
                    ) -> list[FaultPlan]:
    """Greedy 1-minimal reduction: drop clauses one at a time while the
    reduced schedule still reproduces the failure. The result is
    1-minimal (removing ANY single remaining clause makes the trial
    pass), which is what a bug report needs — not globally minimal,
    which would cost exponential re-runs."""
    cur = list(clauses)
    changed = True
    while changed and len(cur) > 1:
        changed = False
        for i in range(len(cur)):
            cand = cur[:i] + cur[i + 1:]
            if still_fails(cand):
                cur = cand
                changed = True
                break
    return cur


# ---------------------------------------------------------------------------
# the harness
# ---------------------------------------------------------------------------


def _oracle_for(guard_action: str, cache: dict, workdir: Path,
                timeout_s: float) -> Path | None:
    """The never-faulted oracle export for a guard config (one run per
    distinct config per harness invocation, cached)."""
    if guard_action in cache:
        return cache[guard_action]
    odir = workdir / f"oracle_{guard_action or 'plain'}"
    res = run_trial(TrialSchedule(clauses=[], guard_action=guard_action),
                    odir, timeout_s=timeout_s)
    path = odir / "ck" / "final_params.msgpack"
    if res.final_exit != 0 or not path.exists():
        raise RuntimeError(
            f"oracle run failed (exit {res.final_exit}) — the chaos "
            f"harness cannot verdict without its ground truth")
    cache[guard_action] = path
    return path


def run_chaos(seed: int, trials: int, workdir: Path,
              timeout_s: float = 180.0,
              palette: Sequence[PaletteEntry] = DEFAULT_PALETTE,
              tamper_oracle: bool = False,
              log=print) -> dict:
    """Run ``trials`` seeded trials; returns the report dict (``ok``,
    per-trial verdicts, and the minimized spec of the first failure).

    ``tamper_oracle`` corrupts the oracle export after it is produced —
    the auditor-must-trip self-test: a harness whose invariants cannot
    fail is a rubber stamp. The self-test samples from the oracle-exact
    subset of the palette only: a ``nan`` schedule never compares the
    oracle (`oracle_exact=False`), so an unlucky seed would exit 0 with
    the gate never evaluated — the exact false confidence the self-test
    exists to rule out.
    """
    if tamper_oracle:
        palette = [e for e in palette if e.oracle_exact]
    workdir = Path(workdir)
    workdir.mkdir(parents=True, exist_ok=True)
    oracle_cache: dict = {}
    report: dict = {"schema": 1, "seed": seed, "trials": [],
                    "ok": True, "minimized_spec": None,
                    "tampered_oracle": bool(tamper_oracle)}
    for index in range(trials):
        rng = random.Random(f"{seed}:{index}")  # str: stable, not hash()
        schedule = sample_schedule(rng, palette)
        log(f"chaos trial {index}: spec {schedule.spec!r}"
            + (f" (guard.action={schedule.guard_action})"
               if schedule.guard_action else ""))
        oracle = _oracle_for(schedule.guard_action, oracle_cache,
                             workdir, timeout_s)
        if tamper_oracle:
            tampered = workdir / f"tampered_oracle_{index}.msgpack"
            blob = bytearray(oracle.read_bytes())
            blob[len(blob) // 2] ^= 0xFF
            tampered.write_bytes(bytes(blob))
            oracle = tampered
        result = run_trial(schedule, workdir / f"trial_{index:03d}",
                           timeout_s=timeout_s)
        failures = audit_trial(result, oracle)
        verdict = {
            "index": index,
            "spec": schedule.spec,
            "guard_action": schedule.guard_action,
            "oracle_exact": schedule.oracle_exact,
            "incarnations": [
                {k: v for k, v in inc.items()
                 if k in ("exit", "spec", "wall_s")}
                for inc in result.incarnations
            ],
            "wall_s": round(result.wall_s, 1),
            "failures": failures,
            "ok": not failures,
        }
        report["trials"].append(verdict)
        if failures:
            report["ok"] = False
            log(f"chaos trial {index}: FAIL")
            for f in failures:
                log(f"  - {f}")
            log("chaos: shrinking the failing schedule ...")

            def still_fails(cand: list[FaultPlan]) -> bool:
                sub = TrialSchedule(clauses=list(cand),
                                    guard_action=schedule.guard_action)
                sub_dir = workdir / (
                    f"shrink_{index:03d}_"
                    + hashlib.sha256(sub.spec.encode()).hexdigest()[:8]
                )
                if sub_dir.exists():
                    # Duplicate clauses make two candidates share a spec
                    # (and so a dir); a stale ckpt tree's archived dumps
                    # would double-count into the auditor's counters.
                    shutil.rmtree(sub_dir)
                sub_res = run_trial(sub, sub_dir, timeout_s=timeout_s)
                return bool(audit_trial(sub_res, oracle))

            minimal = shrink_schedule(schedule.clauses, still_fails)
            spec = ";".join(c.to_spec() for c in minimal)
            report["minimized_spec"] = spec
            verdict["minimized_spec"] = spec
            log(f"chaos: minimal reproducing spec: {spec!r}")
            log(f"chaos: replay with --resilience.fault='{spec}' "
                f"(see docs/CHAOS.md)")
            break  # first failure is the bug report; stop burning trials
        log(f"chaos trial {index}: ok "
            f"({len(result.incarnations)} incarnation(s), "
            f"{verdict['wall_s']}s)")
    return report
