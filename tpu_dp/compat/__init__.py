"""Interop with the reference's torch checkpoint format."""

from tpu_dp.compat.torch_compat import (
    export_net_state_dict,
    import_net_state_dict,
    load_torch_checkpoint,
)

__all__ = [
    "export_net_state_dict",
    "import_net_state_dict",
    "load_torch_checkpoint",
]
