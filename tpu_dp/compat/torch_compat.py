"""Torch `state_dict` ↔ Flax params conversion for the reference's `Net`.

The reference saves `net.state_dict()` to `./cifar_net.pth`
(`/root/reference/cifar_example.py:92-93`); in the DDP variant the keys carry
DDP's `module.` prefix (`cifar_example_ddp.py:118-119`, SURVEY.md §5
checkpoint notes). This module closes the migration story (SURVEY.md §7 hard
part (e)): weights trained with the reference import losslessly into the
Flax `Net`, and vice versa. Three representation differences are mapped:

1. `module.` prefix — stripped on import, never emitted on export;
2. layout — torch convs are OIHW, Flax convs are HWIO; torch Linear weights
   are (out, in), Flax Dense kernels are (in, out);
3. flatten order — `Net` flattens the 16×5×5 conv2 output into fc1's input;
   torch flattens NCHW as (C,H,W) while this framework's NHWC flattens as
   (H,W,C), so fc1's input dimension is permuted accordingly.

Functions take/return plain dicts of numpy arrays; `load_torch_checkpoint`
soft-imports torch only to unpickle a `.pth` file.
"""

from __future__ import annotations

import numpy as np

# conv2 output feeding fc1: 16 channels × 5 × 5 spatial (`cifar_example.py:23`)
_C, _H, _W = 16, 5, 5


def _fc1_permutation() -> np.ndarray:
    """perm[flax_row] = torch_column for fc1's 400-dim input."""
    perm = np.empty(_C * _H * _W, dtype=np.int64)
    for h in range(_H):
        for w in range(_W):
            for c in range(_C):
                flax_idx = (h * _W + w) * _C + c  # NHWC flatten
                torch_idx = (c * _H + h) * _W + w  # NCHW flatten
                perm[flax_idx] = torch_idx
    return perm


def _strip_prefix(state_dict: dict) -> dict:
    """Remove DDP's `module.` wrapper prefix if present."""
    if any(k.startswith("module.") for k in state_dict):
        return {k.removeprefix("module."): v for k, v in state_dict.items()}
    return state_dict


def import_net_state_dict(state_dict: dict) -> dict:
    """Torch `Net` state_dict (numpy-valued) → Flax `Net` params tree."""
    sd = {k: np.asarray(v) for k, v in _strip_prefix(state_dict).items()}
    perm = _fc1_permutation()

    def conv(name):
        return {
            "kernel": sd[f"{name}.weight"].transpose(2, 3, 1, 0),  # OIHW→HWIO
            "bias": sd[f"{name}.bias"],
        }

    def dense(name, row_perm=None):
        kernel = sd[f"{name}.weight"].T  # (out,in) → (in,out)
        if row_perm is not None:
            kernel = kernel[row_perm]
        return {"kernel": kernel, "bias": sd[f"{name}.bias"]}

    return {
        "conv1": conv("conv1"),
        "conv2": conv("conv2"),
        "fc1": dense("fc1", perm),
        "fc2": dense("fc2"),
        "fc3": dense("fc3"),
    }


def export_net_state_dict(params: dict) -> dict:
    """Flax `Net` params tree → torch-layout state_dict (clean key names)."""
    perm = _fc1_permutation()
    inv = np.argsort(perm)
    out = {}
    for name in ("conv1", "conv2"):
        out[f"{name}.weight"] = np.asarray(
            params[name]["kernel"]
        ).transpose(3, 2, 0, 1)  # HWIO→OIHW
        out[f"{name}.bias"] = np.asarray(params[name]["bias"])
    for name in ("fc1", "fc2", "fc3"):
        kernel = np.asarray(params[name]["kernel"])
        if name == "fc1":
            kernel = kernel[inv]
        out[f"{name}.weight"] = kernel.T
        out[f"{name}.bias"] = np.asarray(params[name]["bias"])
    return out


def load_torch_checkpoint(path) -> dict:
    """Unpickle a reference `.pth` into a Flax `Net` params tree."""
    import torch  # soft dependency: only needed to read torch's pickle format

    sd = torch.load(path, map_location="cpu")
    return import_net_state_dict(
        {k: v.detach().numpy() for k, v in sd.items()}
    )
