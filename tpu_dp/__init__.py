"""tpu_dp — a TPU-native data-parallel training framework.

Brand-new framework with the capabilities of the rensortino/DDP-Tutorial
reference (a PyTorch DistributedDataParallel CIFAR-10 tutorial), re-designed
TPU-first: one jitted train step over a named JAX device mesh in which the
gradient all-reduce over ICI is part of the compiled program, a host-sharded
epoch-seeded input pipeline, Flax models, psum-synced eval metrics, pytree
checkpointing with resume, and `jax.distributed.initialize` bootstrap in place
of a launcher. Single-chip and N-chip runs are the same code path with
different mesh shapes — erasing the single/DDP script fork that structures the
reference (`/root/reference/cifar_example.py` vs `cifar_example_ddp.py`).
"""

from tpu_dp import (
    config,
    data,
    metrics,
    models,
    obs,
    ops,
    parallel,
    resilience,
    serve,
    train,
    utils,
)
from tpu_dp.checkpoint import (
    CheckpointManager,
    load_checkpoint,
    load_params_only,
    save_checkpoint,
)
from tpu_dp.config import Config
from tpu_dp.parallel import dist
from tpu_dp.train.state import TrainState

__version__ = "0.1.0"

__all__ = [
    "CheckpointManager",
    "Config",
    "TrainState",
    "checkpoint",
    "config",
    "data",
    "dist",
    "load_checkpoint",
    "load_params_only",
    "metrics",
    "models",
    "obs",
    "ops",
    "parallel",
    "resilience",
    "save_checkpoint",
    "serve",
    "train",
    "utils",
]
