"""tpu_dp — a TPU-native data-parallel training framework.

Brand-new framework with the capabilities of the rensortino/DDP-Tutorial
reference (a PyTorch DistributedDataParallel CIFAR-10 tutorial), re-designed
TPU-first: one jitted train step over a named JAX device mesh in which the
gradient all-reduce over ICI is part of the compiled program, a host-sharded
epoch-seeded input pipeline, Flax models, psum-synced eval metrics, pytree
checkpointing with resume, and `jax.distributed.initialize` bootstrap in place
of a launcher. Single-chip and N-chip runs are the same code path with
different mesh shapes — erasing the single/DDP script fork that structures the
reference (`/root/reference/cifar_example.py` vs `cifar_example_ddp.py`).

Submodules and the top-level conveniences resolve lazily (PEP 562): the
forensic CLIs (`python -m tpu_dp.obs`, `python -m tpu_dp.analysis`) and
every test that shells out to them must not pay the multi-second JAX
import for artifact reads that never touch a device. `import tpu_dp`
stays cheap; `tpu_dp.train`, `from tpu_dp import Config`, etc. import
exactly what they name on first access.
"""

import importlib

__version__ = "0.1.0"

_SUBMODULES = (
    "analysis",
    "chaos",
    "checkpoint",
    "config",
    "data",
    "metrics",
    "models",
    "obs",
    "ops",
    "parallel",
    "resilience",
    "serve",
    "train",
    "tune",
    "utils",
)

# convenience name -> (module, attribute)
_ATTRS = {
    "CheckpointManager": ("tpu_dp.checkpoint", "CheckpointManager"),
    "load_checkpoint": ("tpu_dp.checkpoint", "load_checkpoint"),
    "load_params_only": ("tpu_dp.checkpoint", "load_params_only"),
    "save_checkpoint": ("tpu_dp.checkpoint", "save_checkpoint"),
    "Config": ("tpu_dp.config", "Config"),
    "dist": ("tpu_dp.parallel", "dist"),
    "TrainState": ("tpu_dp.train.state", "TrainState"),
}

__all__ = sorted({*_SUBMODULES, *_ATTRS})


def __getattr__(name):
    if name in _ATTRS:
        module, attr = _ATTRS[name]
        return getattr(importlib.import_module(module), attr)
    if name in _SUBMODULES:
        return importlib.import_module(f"tpu_dp.{name}")
    raise AttributeError(f"module 'tpu_dp' has no attribute {name!r}")


def __dir__():
    return sorted({*globals(), *__all__})
