"""Self-tuning harness suite (`tpu_dp/tune/`, docs/TUNE.md).

Units for every leg of ISSUE 16's tentpole: the search-space grammar
(aliases, quoting, pinned-only refusals, `auto`), the analytic bucket
prior's sizing math, deterministic ranking with the exposed-comm
tie-break, the ledger's cache/resume behavior, and the two acceptance
properties run end-to-end with a stub trial runner — same seed emits a
byte-identical ``tuned.json``, and a populated ledger resumes without
re-running a single trial. The chaos gate is exercised through a stub
gate here (the planted fast-but-fragile candidate must be rejected with
receipts); the real subprocess gate runs in `tools/run_tier1.sh --tune`.
Satellites ride along: the shared coupling guard + dplint DP105, the
archive's ``schema``/``config_hash`` stamp, and `--profile` precedence
through the real `parse_cli`.

Everything here is jax-free and subprocess-free — the tune package's
parsing/driver half is stdlib-only by design.
"""

import json

import pytest

from tpu_dp.analysis import coupling
from tpu_dp.config import Config, coupling_warning, parse_cli
from tpu_dp.obs.objective import (
    is_tied,
    objective_value,
    tiebreak_value,
    trial_signals,
)
from tpu_dp.tune import prior
from tpu_dp.tune.profile import (
    PROFILE_SCHEMA,
    ProfileError,
    ProfileMismatchError,
    apply_profile,
    build_profile,
    check_key,
    config_hash,
    dump_profile,
    load_profile,
    make_key,
)
from tpu_dp.tune.search import (
    PLANTED_BLOCK_SIZE,
    Ledger,
    rank,
    run_search,
)
from tpu_dp.tune.space import (
    BUDGETS,
    DEFAULT_SPACE,
    EXECUTABLE_KNOBS,
    SearchSpace,
    SpaceError,
    point_label,
    rung_key,
)
from tpu_dp.tune.trial import trial_cfg

pytestmark = pytest.mark.tune

_QUIET = {"log": lambda *a, **k: None}

#: A 4-point executable grid (2 buckets x 2 block sizes, int8 pinned).
SMALL_SPEC = ("train.update_sharding=sharded;train.bucket_mb=0.0,1.0;"
              "train.quant_block_size=64,128;train.collective_dtype=int8")


def stub_record(knobs):
    """A deterministic fenced-looking BENCH record: the score and the
    exposed-comm tie-breaker are pure functions of the knob hash, so two
    searches over the same grid measure 'the same machine'."""
    h = int(config_hash(knobs), 16)
    value = 100.0 + (h % 97)
    return {
        "value": value,
        "goodput": round(value * 0.9, 4),
        "mfu": 0.41,
        "n_chips": 8,
        "backend": "cpu",
        "device_kind": "cpu",
        "config": dict(sorted(knobs.items())),
        "latency": {"p95_ms": 12.5},
        "comm": {"comm_ms": 30.0,
                 "exposed_comm_ms": round(1.0 + (h % 13) / 10, 4),
                 "overlap_frac": 0.8},
    }


class StubRunner:
    """Counts invocations so the resume test can assert 'zero re-runs'."""

    def __init__(self, record=stub_record):
        self.calls = []
        self.record = record

    def __call__(self, knobs, rung):
        self.calls.append((config_hash(knobs), rung_key(rung)))
        return self.record(knobs)


def search_kwargs(workdir, **over):
    kw = dict(seed=7, budget="tiny", space=SearchSpace.parse(SMALL_SPEC),
              workdir=workdir, **_QUIET)
    kw.update(over)
    return kw


# ---------------------------------------------------------------------------
# space grammar
# ---------------------------------------------------------------------------

def test_space_aliases_resolve_to_dotted_paths():
    space = SearchSpace.parse("bucket_mb=0,1;collective_dtype=int8")
    assert set(space.knobs) == {"train.bucket_mb",
                                "train.collective_dtype"}
    assert space.knobs["train.bucket_mb"] == (0, 1)


def test_space_quoted_serve_ladder_is_one_candidate():
    space = SearchSpace.parse("serve.buckets='1,2,4,8'")
    assert space.knobs["serve.buckets"] == ("1,2,4,8",)


def test_space_unbalanced_quote_refused():
    with pytest.raises(SpaceError, match="unbalanced quote"):
        SearchSpace.parse("serve.buckets='1,2")


def test_space_pinned_knob_refuses_multiple_candidates():
    with pytest.raises(SpaceError, match="pinned-only"):
        SearchSpace.parse("serve.max_wait_ms=1.0,2.0")


def test_space_auto_only_on_bucket_mb():
    with pytest.raises(SpaceError, match="auto"):
        SearchSpace.parse("quant_block_size=auto")


def test_space_unknown_and_duplicate_and_empty_refused():
    with pytest.raises(SpaceError, match="unknown knob"):
        SearchSpace.parse("train.nope=1")
    with pytest.raises(SpaceError, match="twice"):
        SearchSpace.parse("bucket_mb=1;train.bucket_mb=2")
    with pytest.raises(SpaceError, match="empty"):
        SearchSpace.parse("  ;  ")
    with pytest.raises(SpaceError, match="not knob"):
        SearchSpace.parse("bucket_mb")


def test_space_spec_round_trips():
    space = SearchSpace.parse(DEFAULT_SPACE)
    assert space.needs_prior
    again = SearchSpace.parse(space.spec)
    assert again.knobs == space.knobs


def test_space_enumerate_refuses_unresolved_auto():
    space = SearchSpace.parse("train.bucket_mb=auto;collective_dtype=int8")
    with pytest.raises(SpaceError, match="unresolved"):
        space.enumerate()
    resolved = space.with_bucket_candidates([0.0, 2.5])
    grid = resolved.enumerate()
    assert [g["train.bucket_mb"] for g in grid] == [0.0, 2.5]


def test_space_grid_is_full_cartesian_product():
    grid = SearchSpace.parse(SMALL_SPEC).enumerate()
    assert len(grid) == 4
    assert len({config_hash(g) for g in grid}) == 4
    for g in grid:
        assert g["train.update_sharding"] == "sharded"


def test_point_label_mentions_knobs_and_hash():
    knobs = {"train.bucket_mb": 1.0, "train.quant_block_size": 64,
             "train.collective_dtype": "int8"}
    label = point_label(knobs)
    assert "bucket1.0" in label and "block64" in label and "int8" in label
    assert config_hash(knobs) in label


def test_budgets_are_escalating_rungs():
    for name, rungs in BUDGETS.items():
        steps = [r["measure_steps"] for r in rungs]
        assert steps == sorted(steps), name
        assert all(rung_key(r).startswith("m") for r in rungs)


# ---------------------------------------------------------------------------
# the bucket prior
# ---------------------------------------------------------------------------

def probe_record(comm_ms=30.0, exposed=8.0, payload_mb=44.0):
    return {"comm": {"comm_ms": comm_ms, "exposed_comm_ms": exposed,
                     "overlap_frac": 0.7},
            "grad_payload_mb": payload_mb}


def test_prior_sizes_candidates_from_exposed_window():
    # K* = ceil(30 / (0.25 * 8)) = 15 -> bracket {8, 15, 30} buckets.
    got = prior.bucket_candidates(probe_record())
    assert got[0] == 0.0 and len(got) == 4
    assert got == sorted(got)
    for mb in got[1:]:
        k = 44.0 / mb
        assert prior.MIN_BUCKETS <= round(k) <= prior.MAX_BUCKETS


def test_prior_degenerates_to_control_when_nothing_to_reclaim():
    assert prior.bucket_candidates(
        probe_record(exposed=0.01)) == [0.0]
    assert prior.bucket_candidates({"comm": {}}) == [0.0]
    assert prior.bucket_candidates(
        probe_record(payload_mb=None)) == [0.0]


def test_prior_reads_quant_f32_wire_accounting_first():
    rec = {"comm": {"comm_ms": 20.0, "exposed_comm_ms": 4.0},
           "quant": {"wire_bytes_per_step": {"f32": 10 * 2**20}},
           "grad_payload_mb": 999.0}
    assert prior.grad_payload_mb(rec) == 10.0
    info = prior.describe(rec, [0.0, 1.25])
    assert info["grad_payload_mb"] == 10.0
    assert info["candidates"] == [0.0, 1.25]
    assert info["target_exposed_frac"] == prior.TARGET_EXPOSED_FRAC


# ---------------------------------------------------------------------------
# objective + ranking
# ---------------------------------------------------------------------------

def test_objective_none_for_failed_trial_never_zero():
    assert objective_value({"error": "boom"}) is None
    assert objective_value({"value": 12.0}) == 12.0
    assert objective_value({"goodput": 3.0}, "goodput") == 3.0
    with pytest.raises(ValueError, match="unknown objective"):
        objective_value({}, "vibes")


def test_tiebreak_missing_comm_ranks_last():
    assert tiebreak_value({}) == float("inf")
    assert tiebreak_value({"comm": {"exposed_comm_ms": 1.5}}) == 1.5


def _entry(score, tiebreak, tag):
    return {"knobs": {"train.bucket_mb": tag}, "score": score,
            "tiebreak": tiebreak,
            "config_hash": config_hash({"train.bucket_mb": tag}),
            "record": {}}


def test_rank_score_then_tiebreak_then_hash():
    clear = [_entry(110.0, 9.0, 1), _entry(100.0, 0.1, 2)]
    assert [e["score"] for e in rank(clear)] == [110.0, 100.0]
    # Within the 3% tie window the lower exposed-comm number wins even
    # against the nominally higher score.
    tied = [_entry(101.0, 2.0, 3), _entry(100.0, 1.0, 4)]
    assert [e["score"] for e in rank(tied)] == [100.0, 101.0]
    assert is_tied(100.0, 101.0) and not is_tied(100.0, 110.0)


def test_rank_unmeasured_trials_sink():
    entries = [_entry(None, float("inf"), 5), _entry(50.0, 1.0, 6)]
    ranked = rank(entries)
    assert ranked[0]["score"] == 50.0 and ranked[-1]["score"] is None


def test_trial_signals_carries_obsctl_units():
    sig = trial_signals(stub_record({"train.bucket_mb": 0.0}))
    assert sig["img_per_sec_per_chip"] is not None
    assert sig["exposed_comm_ms"] is not None
    assert sig["p95_ms"] == 12.5


# ---------------------------------------------------------------------------
# ledger
# ---------------------------------------------------------------------------

def test_ledger_caches_and_survives_corrupt_lines(tmp_path):
    path = tmp_path / "ledger.jsonl"
    led = Ledger(path)
    knobs = {"train.bucket_mb": 1.0}
    rec = led.trial(knobs, "m1l2", lambda: {"value": 1.0})
    assert led.misses == 1 and rec["value"] == 1.0
    # A crashed writer's torn line must not poison the resume.
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"kind": "trial", "config_hash": TORN\n')
    led2 = Ledger(path)
    assert led2.trial(knobs, "m1l2",
                      lambda: pytest.fail("cache miss")) == rec
    assert led2.hits == 1 and led2.misses == 0


def test_ledger_digest_tracks_file_bytes(tmp_path):
    led = Ledger(tmp_path / "ledger.jsonl")
    empty = led.digest()
    led.trial({"train.bucket_mb": 0.0}, "m1l2", lambda: {"value": 2.0})
    assert led.digest() != empty
    assert len(led.digest()) == 12


# ---------------------------------------------------------------------------
# the search driver: determinism + resume (acceptance properties)
# ---------------------------------------------------------------------------

def test_search_same_seed_same_bytes(tmp_path):
    profiles = []
    for run in ("a", "b"):
        runner = StubRunner()
        profile = run_search(runner=runner,
                             **search_kwargs(tmp_path / run))
        out = tmp_path / f"tuned_{run}.json"
        dump_profile(profile, out)
        profiles.append((out.read_bytes(), runner.calls, profile))
    assert profiles[0][0] == profiles[1][0]
    assert profiles[0][1] == profiles[1][1]  # identical trial sequence
    prof = profiles[0][2]
    assert prof["schema"] == PROFILE_SCHEMA
    assert prof["provenance"]["trial_sequence"] == [
        h for h, _ in profiles[0][1]]
    assert prof["config_hash"] == config_hash(prof["config"])
    # The key comes from the winner's own fenced record.
    assert prof["key"] == {"workload": "resnet18", "devices": 8,
                           "backend": "cpu", "device_kind": "cpu"}


def test_search_resume_reruns_nothing(tmp_path):
    first = StubRunner()
    profile = run_search(runner=first, **search_kwargs(tmp_path))
    assert len(first.calls) == 4
    resumed = StubRunner()
    again = run_search(runner=resumed, **search_kwargs(tmp_path))
    assert resumed.calls == []  # every trial served from the ledger
    assert json.dumps(again, sort_keys=True) == \
        json.dumps(profile, sort_keys=True)


def test_search_different_seed_different_order(tmp_path):
    orders = {}
    for seed in (7, 8):
        runner = StubRunner()
        run_search(runner=runner,
                   **search_kwargs(tmp_path / str(seed), seed=seed))
        orders[seed] = runner.calls
    assert sorted(orders[7]) == sorted(orders[8])  # same grid...
    assert orders[7] != orders[8]  # ...different seeded order


def test_search_halving_promotes_top_half(tmp_path):
    runner = StubRunner()
    run_search(runner=runner,
               **search_kwargs(tmp_path, budget="small"))
    rungs = [r for _, r in runner.calls]
    assert rungs.count("m2l3") == 4  # every point runs the cheap rung
    assert rungs.count("m6l6") == 2  # top half graduates


def test_search_auto_bucket_runs_probe_and_stamps_prior(tmp_path):
    spec = ("train.update_sharding=sharded;train.bucket_mb=auto;"
            "train.quant_block_size=64;train.collective_dtype=int8")

    def record(knobs):
        rec = stub_record(knobs)
        rec["comm"] = {"comm_ms": 30.0, "exposed_comm_ms": 8.0,
                       "overlap_frac": 0.7}
        rec["grad_payload_mb"] = 44.0
        return rec

    runner = StubRunner(record)
    profile = run_search(runner=runner,
                         **search_kwargs(
                             tmp_path, space=SearchSpace.parse(spec)))
    info = profile["provenance"]["bucket_prior"]
    assert info["candidates"][0] == 0.0 and len(info["candidates"]) == 4
    assert profile["provenance"]["grid_points"] == len(info["candidates"])
    # Probe first, then one trial per prior-sized candidate.
    assert len(runner.calls) == 1 + len(info["candidates"])


def test_search_all_failed_trials_raise(tmp_path):
    runner = StubRunner(lambda knobs: {"error": "wedged"})
    with pytest.raises(RuntimeError, match="every trial failed"):
        run_search(runner=runner, **search_kwargs(tmp_path))


def test_search_flags_coupled_grid_points(tmp_path):
    big_bucket = 4.0 * 2  # computed: this test must not trip DP105 itself
    spec = (f"train.update_sharding=sharded;train.bucket_mb={big_bucket};"
            f"train.quant_block_size=256;train.collective_dtype=int8")
    profile = run_search(runner=StubRunner(),
                         **search_kwargs(
                             tmp_path, space=SearchSpace.parse(spec)))
    assert any("int8 codec" in w for w in profile["warnings"])


# ---------------------------------------------------------------------------
# the chaos gate (stubbed): the planted fragile candidate must lose
# ---------------------------------------------------------------------------

class StubGate:
    def __init__(self, ok=lambda tamper: not tamper):
        self.calls = []
        self.ok = ok

    def __call__(self, knobs, workdir, *, seed, tamper=False):
        self.calls.append((knobs.get("train.quant_block_size"), tamper))
        ok = self.ok(tamper)
        return {"ok": ok, "config_hash": config_hash(knobs),
                "seed": seed,
                "failures": [] if ok else ["ORACLE: divergence"]}


def test_gate_rejects_planted_fragile_candidate(tmp_path):
    gate = StubGate()
    profile = run_search(runner=StubRunner(), gate=gate,
                         plant_fragile=True, **search_kwargs(tmp_path))
    # The planted candidate topped the leaderboard (10x synthesized
    # score) and was gated FIRST, against the tampered oracle.
    assert gate.calls[0] == (PLANTED_BLOCK_SIZE, True)
    rejected = profile["chaos_gate"]["rejected"]
    assert len(rejected) == 1 and rejected[0]["synthesized"]
    assert str(PLANTED_BLOCK_SIZE) in rejected[0]["label"]
    # The crown moved down to a real, gate-passing config.
    assert profile["config"]["train.quant_block_size"] != PLANTED_BLOCK_SIZE
    assert profile["chaos_gate"]["verdict"]["ok"]
    assert profile["objective"]["value"] is not None


def test_gate_all_rejections_raise_with_receipts(tmp_path):
    gate = StubGate(ok=lambda tamper: False)
    with pytest.raises(RuntimeError, match="failed the chaos gate"):
        run_search(runner=StubRunner(), gate=gate,
                   **search_kwargs(tmp_path))
    assert len(gate.calls) == 3  # MAX_GATE_ATTEMPTS, then surface


def test_gate_verdicts_are_ledger_cached(tmp_path):
    kw = search_kwargs(tmp_path)
    gate = StubGate()
    run_search(runner=StubRunner(), gate=gate, **kw)
    assert len(gate.calls) == 1
    gate2 = StubGate()
    run_search(runner=StubRunner(), gate=gate2, **kw)
    assert gate2.calls == []  # verdict replayed from the ledger


# ---------------------------------------------------------------------------
# profile contract: load/validate/precedence/mismatch
# ---------------------------------------------------------------------------

GOOD_KNOBS = {"train.update_sharding": "sharded",
              "train.collective_dtype": "int8",
              "train.quant_block_size": 128,
              "train.bucket_mb": 2.0}


def write_profile(tmp_path, knobs=None, key=None, name="tuned.json"):
    profile = build_profile(
        key=key or make_key("resnet18", 8, "cpu"),
        knobs=dict(knobs or GOOD_KNOBS),
        claims={"img_per_sec_per_chip": 123.0, "goodput": 110.0},
        objective={"name": "throughput", "value": 123.0},
        provenance={"seed": 0})
    path = tmp_path / name
    dump_profile(profile, path)
    return path


def test_profile_round_trip(tmp_path):
    path = write_profile(tmp_path)
    loaded = load_profile(path)
    assert loaded["config"]["train.bucket_mb"] == 2.0
    assert loaded["key"]["devices"] == 8


def test_profile_builder_refuses_unknown_knobs():
    with pytest.raises(ProfileError, match="not tunable"):
        build_profile(key=make_key("resnet18", 8, "cpu"),
                      knobs={"train.nope": 1}, claims={},
                      objective={}, provenance={})


def test_profile_edited_config_refused(tmp_path):
    path = write_profile(tmp_path)
    payload = json.loads(path.read_text())
    payload["config"]["train.bucket_mb"] = 64.0  # hand-edit, no re-tune
    path.write_text(json.dumps(payload))
    with pytest.raises(ProfileError, match="config_hash"):
        load_profile(path)


def test_profile_schema_gate(tmp_path):
    path = write_profile(tmp_path)
    payload = json.loads(path.read_text())
    payload["schema"] = "tpu_dp.tune/profile/v99"
    path.write_text(json.dumps(payload))
    with pytest.raises(ProfileError, match="unsupported schema"):
        load_profile(path)
    path.write_text('{"schema": "something/else", "key": {}}')
    with pytest.raises(ProfileError, match="really a tuned.json"):
        load_profile(path)
    path.write_text("not json")
    with pytest.raises(ProfileError, match="not valid JSON"):
        load_profile(path)


def test_profile_key_mismatch_is_typed_refusal(tmp_path):
    profile = load_profile(write_profile(tmp_path))
    check_key(profile, workload="resnet18", devices=8, backend="cpu")
    with pytest.raises(ProfileMismatchError, match="devices 8 != 1"):
        check_key(profile, workload="resnet18", devices=1, backend="cpu")
    with pytest.raises(ProfileMismatchError, match="re-run"):
        check_key(profile, workload="resnet18", devices=8, backend="tpu")
    with pytest.raises(ProfileMismatchError, match="workload"):
        check_key(profile, workload="resnet50", devices=8, backend="cpu")


def test_apply_profile_explicit_flags_win(tmp_path):
    profile = load_profile(write_profile(tmp_path))
    cfg = Config()
    applied = apply_profile(cfg, profile,
                            explicit={"train.bucket_mb"})
    assert cfg.train.bucket_mb != 2.0  # explicit path untouched
    assert cfg.train.quant_block_size == 128
    assert cfg.train.collective_dtype == "int8"
    assert "train.bucket_mb" not in applied
    assert "train.quant_block_size" in applied


def test_parse_cli_profile_precedence(tmp_path):
    path = write_profile(tmp_path)
    cfg = parse_cli([f"--profile={path}", "--train.bucket_mb=9"])
    assert cfg.train.bucket_mb == 9.0  # the typed flag wins
    assert cfg.train.quant_block_size == 128  # the profile fills gaps
    assert cfg.train.collective_dtype == "int8"
    assert cfg.train.profile == str(path)
    with pytest.raises(ValueError, match="at most one --profile"):
        parse_cli([f"--profile={path}", f"--profile={path}"])
    with pytest.raises(ValueError, match="needs a tuned.json"):
        parse_cli(["--profile="])


# ---------------------------------------------------------------------------
# the coupling guard: one rule, three surfaces
# ---------------------------------------------------------------------------

def test_coupling_warning_trips_only_on_the_pair():
    assert coupling_warning(4.0, 256, "int8")
    assert coupling_warning(8, "512", "i8")  # CLI-string coercion
    assert coupling_warning(3.9, 256, "int8") is None
    assert coupling_warning(4.0, 255, "int8") is None
    assert coupling_warning(4.0, 256, "bf16") is None
    assert coupling_warning(4.0, 256, "") is None
    assert coupling_warning("garbage", 256, "int8") is None


DP105_TRIP = (
    "def fast_config():\n"
    "    return dict(bucket_mb=8.0, quant_block_size=512,\n"
    "                collective_dtype='int8')\n"
)


def test_dp105_flags_hardcoded_cliff_with_scope_symbol():
    findings = coupling.lint_source("x.py", DP105_TRIP)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "DP105" and f.symbol == "fast_config"
    assert "int8" in f.message


def test_dp105_dict_and_argv_forms():
    src = (
        'CFG = {"train.bucket_mb": 4.0, "train.quant_block_size": 256,\n'
        '       "train.collective_dtype": "int8"}\n'
        'ARGV = ["--train.bucket_mb=8", "--train.quant_block_size=256",\n'
        '        "--train.collective_dtype=int8"]\n'
    )
    findings = coupling.lint_source("x.py", src)
    assert sorted(f.line for f in findings) == [1, 3]


def test_dp105_silent_below_threshold_and_on_variables():
    ok = (
        "a = dict(bucket_mb=1.0, quant_block_size=512,\n"
        "         collective_dtype='int8')\n"
        "b = dict(bucket_mb=8.0, quant_block_size=512,\n"
        "         collective_dtype='bf16')\n"
        "blk = 512\n"
        "c = dict(bucket_mb=8.0, quant_block_size=blk,\n"
        "         collective_dtype='int8')\n"  # non-constant: not a pin
    )
    assert coupling.lint_source("x.py", ok) == []


def test_dp105_pragma_suppresses():
    src = DP105_TRIP.replace(
        "collective_dtype='int8')",
        "collective_dtype='int8')  # dplint: allow(DP105)")
    assert coupling.lint_source("x.py", src) == []


def test_dp105_registered_in_rules_table():
    from tpu_dp.analysis.report import RULES
    title, failure = RULES["DP105"]
    assert "coupled" in title and "coupling_warning" in failure


# ---------------------------------------------------------------------------
# trial config mapping + archive stamp (satellite 2)
# ---------------------------------------------------------------------------

def test_trial_cfg_forces_comm_profile_and_maps_knobs():
    knobs = {"train.bucket_mb": 1.5, "train.quant_block_size": 64,
             "train.collective_dtype": "int8",
             "train.update_sharding": "sharded"}
    cfg = trial_cfg(knobs, {"measure_steps": 2, "latency_steps": 3},
                    model="resnet18", per_chip_batch=2, platform="cpu")
    assert cfg["comm_profile"] is True
    assert cfg["bucket_mb"] == 1.5 and cfg["quant_block_size"] == 64
    assert cfg["collective_dtype"] == "int8"
    assert cfg["measure_steps"] == 2 and cfg["steps_per_call"] == 1


def test_archive_stamps_schema_and_config_hash(tmp_path, monkeypatch):
    from tpu_dp.tune.trial import load_bench

    bench = load_bench()
    monkeypatch.setattr(bench, "RESULTS_PATH",
                        tmp_path / "results.jsonl")
    bench.archive({"value": 1.0, "backend": "cpu",
                   "config": {"bucket_mb": 1.0}})
    row = json.loads(
        (tmp_path / "results.jsonl").read_text().splitlines()[0])
    assert row["schema"] == bench.ARCHIVE_SCHEMA
    assert row["config_hash"] == config_hash({"bucket_mb": 1.0})
    assert row["smoke"] is True  # cpu rows stay tagged


def test_executable_knobs_are_a_subset_of_profile_knobs():
    from tpu_dp.tune.profile import PROFILE_KNOBS
    assert EXECUTABLE_KNOBS <= set(PROFILE_KNOBS)
