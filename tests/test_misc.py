"""Metrics, checkpoint round-trip, config system, dist helpers."""

import jax
import numpy as np
import pytest

from tpu_dp import checkpoint as ckpt
from tpu_dp.config import Config, PRESETS, parse_cli
from tpu_dp.metrics import Accuracy, Mean
from tpu_dp.models import Net
from tpu_dp.parallel import dist
from tpu_dp.train import SGD, create_train_state


def test_accuracy_and_mean():
    acc = Accuracy()
    acc.update(3, 4)
    acc.update(1, 4)
    assert acc.compute() == pytest.approx(0.5)
    m = Mean()
    m.update(2.0, 3)
    m.update(5.0, 1)
    assert m.compute() == pytest.approx((6.0 + 5.0) / 4)
    # Weighted mean fixes the reference's ÷2000-regardless-of-remainder
    # quirk (`cifar_example.py:86`).
    acc.reset(); m.reset()
    assert acc.compute() == 0.0 and m.compute() == 0.0


def test_checkpoint_roundtrip(tmp_path):
    """Save → restore closes the reference's save-only gap (SURVEY.md §5)."""
    model, opt = Net(), SGD(0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    state = state.replace(step=state.step + 7)
    path = ckpt.save_checkpoint(tmp_path / "ck", state, {"epoch": 3})
    assert path is not None and ckpt.checkpoint_exists(tmp_path / "ck")

    fresh = create_train_state(
        model, jax.random.PRNGKey(1), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    restored, meta = ckpt.load_checkpoint(tmp_path / "ck", fresh)
    assert meta["epoch"] == 3
    assert int(restored.step) == 7
    for a, b in zip(
        jax.tree_util.tree_leaves(restored.params),
        jax.tree_util.tree_leaves(state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_params_export_roundtrip(tmp_path):
    model = Net()
    v = model.init(jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32))
    p = ckpt.save_params(tmp_path / "w.msgpack", v["params"])
    assert p is not None
    loaded = ckpt.load_params(p, v["params"])
    for a, b in zip(
        jax.tree_util.tree_leaves(loaded), jax.tree_util.tree_leaves(v["params"])
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_config_defaults_are_reference_values():
    c = Config()
    assert c.data.batch_size == 4  # `cifar_example.py:42`
    assert c.optim.lr == 0.001 and c.optim.momentum == 0.9  # `:64`
    assert c.train.epochs == 2  # `:66`
    assert c.train.log_every == 2000  # `:84`


def test_config_overrides_and_presets():
    c = parse_cli(["--preset=resnet18_8chip_gb1024", "--train.epochs=3",
                   "--model.bf16=true", "--optim.lr=0.5"])
    assert c.model.name == "resnet18"
    assert c.data.batch_size == 1024
    assert c.train.epochs == 3 and c.model.bf16 and c.optim.lr == 0.5
    assert set(PRESETS) == {
        "reference", "resnet18_cifar10", "resnet50_cifar100",
        "resnet18_8chip_gb1024", "bf16_cosine_gb4096",
    }
    with pytest.raises(ValueError):
        Config().override("optim.nonexistent", "1")


def test_dist_context_and_barrier(mesh8):
    ctx = dist.initialize()
    assert ctx.process_count == 1 and ctx.is_main_process
    assert dist.device_count() == 8
    assert mesh8.shape[dist.DATA_AXIS] == 8
    dist.barrier(mesh8)  # completes without deadlock/error


def test_barrier_reuses_executable(mesh8):
    """Repeated barriers on one mesh must not retrace (VERDICT r4 weak #6).

    `_BARRIER_TRACES` increments at trace time; after a warmup call,
    further barriers on the same mesh reuse the cached executable.
    """
    dist.barrier(mesh8)  # warmup: may trace
    before = dist._BARRIER_TRACES[0]
    for _ in range(3):
        dist.barrier(mesh8)
    assert dist._BARRIER_TRACES[0] == before, "barrier retraced on same mesh"


def test_schedule_shapes():
    from tpu_dp.train import cosine_lr, make_schedule

    s = cosine_lr(1.0, total_steps=100, warmup_steps=10)
    assert float(s(0)) == pytest.approx(0.0)
    assert float(s(10)) == pytest.approx(1.0, abs=1e-6)
    assert float(s(100)) == pytest.approx(0.0, abs=1e-6)
    assert float(s(55)) == pytest.approx(0.5, abs=0.01)
    with pytest.raises(ValueError):
        make_schedule("nope", 0.1)


def test_examples_cifar_minimal_smoke(tmp_path, monkeypatch, capsys):
    """The migration example runs end-to-end (tiny synthetic data)."""
    from pathlib import Path

    monkeypatch.syspath_prepend(str(Path(__file__).resolve().parent.parent))
    import examples.cifar_minimal as ex
    from tpu_dp.data.cifar import make_synthetic

    monkeypatch.chdir(tmp_path)
    monkeypatch.setattr(ex, "EPOCHS", 1)
    monkeypatch.setattr(ex, "BATCH", 16)
    monkeypatch.setattr(ex, "LOG_EVERY", 4)
    monkeypatch.setattr(
        ex, "load_dataset",
        lambda name, root, train=True, **kw: make_synthetic(
            128 if train else 64, 10, seed=0, name="synthetic"
        ),
    )
    ex.main()
    out = capsys.readouterr().out
    assert "Finished Training" in out
    assert "Accuracy of the network on the 64 test images" in out
    assert (tmp_path / "cifar_net.msgpack").exists()


def test_checkpoint_manager_retention_async_and_restore(tmp_path):
    """CheckpointManager: async writes, keep-N pruning, latest-pointer restore."""
    import jax.numpy as jnp

    from tpu_dp.checkpoint import CheckpointManager
    from tpu_dp.models import Net
    from tpu_dp.train import SGD, create_train_state

    model = Net()
    opt = SGD(momentum=0.9)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )

    with CheckpointManager(tmp_path / "ck", keep=2, async_save=True) as mgr:
        for n in (1, 2, 3, 4):
            s = state.replace(step=jnp.asarray(n, jnp.int32))
            mgr.save(s, meta={"epoch": n}, step=n)
        mgr.wait()
        kept = sorted(p.name for p in (tmp_path / "ck").iterdir()
                      if p.name.startswith("step_"))
        assert kept == ["step_0000000003", "step_0000000004"]

        restored, meta = mgr.restore(state)
        assert int(restored.step) == 4
        assert meta["epoch"] == 4
        for a, b in zip(
            jax.tree_util.tree_leaves(restored.params),
            jax.tree_util.tree_leaves(state.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Stale/corrupt latest pointer falls back to newest complete step dir.
    (tmp_path / "ck" / "latest").write_text("step_9999999999")
    mgr2 = CheckpointManager(tmp_path / "ck", keep=2)
    assert mgr2.latest_dir().name == "step_0000000004"


def test_checkpoint_manager_async_failure_surfaces(tmp_path):
    """A failed async write raises on the next wait/save, never silently."""
    from tpu_dp.checkpoint import CheckpointManager
    from tpu_dp.models import Net
    from tpu_dp.train import SGD, create_train_state

    state = create_train_state(
        Net(), jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        SGD(0.9),
    )
    target = tmp_path / "notadir"
    target.write_text("file where the ckpt dir must go")  # mkdir will fail
    mgr = CheckpointManager(target, async_save=True)
    mgr.save(state, step=1)
    with pytest.raises(RuntimeError, match="async checkpoint write"):
        mgr.wait()


def test_config_file_roundtrip(tmp_path):
    """--config reloads a to_dict dump or checkpoint meta.json exactly."""
    import json

    src = parse_cli(["--preset=resnet18_cifar10", "--train.epochs=7"])
    plain = tmp_path / "cfg.json"
    plain.write_text(json.dumps(src.to_dict()))
    loaded = parse_cli([f"--config={plain}"])
    assert loaded.to_dict() == src.to_dict()

    # Checkpoint meta layout: the config sits under a "config" key, and a
    # checkpoint-destination decision is mandatory (writing into the source
    # run's ckpt_dir would prune the checkpoints being reproduced).
    meta = tmp_path / "meta.json"
    meta.write_text(json.dumps({"epoch": 3, "config": src.to_dict()}))
    from_meta = parse_cli([f"--config={meta}", "--optim.lr=0.2",
                           "--train.ckpt_dir=/tmp/newrun"])
    assert from_meta.optim.lr == 0.2
    assert from_meta.train.epochs == 7
    with pytest.raises(ValueError, match="ckpt_dir"):
        parse_cli([f"--config={meta}"])
    with pytest.raises(ValueError, match="ckpt_dir"):
        # resume=false is not a destination decision; the gate must hold.
        parse_cli([f"--config={meta}", "--train.resume=false"])

    # The parallel section is environment, not experiment: never restored.
    src.parallel.coordinator_address = "10.0.0.1:8476"
    src.parallel.process_id = 1
    stale = tmp_path / "stale.json"
    stale.write_text(json.dumps(src.to_dict()))
    fresh = parse_cli([f"--config={stale}"])
    assert fresh.parallel.coordinator_address is None
    assert fresh.parallel.process_id is None

    # Values are type-checked/coerced: hand-edited strings cannot silently
    # flip booleans, and JSON float-ified ints come back as ints.
    c = Config.from_dict({"model": {"bf16": "false"}, "train": {"epochs": 3.0}})
    assert c.model.bf16 is False and c.train.epochs == 3

    with pytest.raises(ValueError):
        parse_cli([f"--config={plain}", "--preset=reference"])
    with pytest.raises(ValueError):
        Config.from_dict({"nonexistent_section": {}})
    with pytest.raises(ValueError):
        Config.from_dict({"optim": {"nonexistent": 1}})
    with pytest.raises(ValueError):
        Config.from_dict({"optim": 5})
    with pytest.raises(ValueError, match="expected int"):
        Config.from_dict({"train": {"epochs": True}})
    with pytest.raises(ValueError, match="expected bool"):
        Config.from_dict({"model": {"bf16": 1}})
    with pytest.raises(ValueError, match="scalar"):
        Config.from_dict({"model": {"num_classes": [10]}})


def test_dist_describe_topology(mesh8):
    d = dist.describe(mesh8)
    assert d["devices"] == 8 and d["processes"] == 1
    assert d["local_devices"] >= 1 and d["host_cpus"] >= 1
    assert isinstance(d["host"], str) and d["host"]
    assert d["platform"] == "cpu"
