"""The egress watcher (`tools/egress_watch.sh`) probes for network egress
independently of the TPU relay, logs every probe (the round needs positive
evidence that egress never opened), and on success queues the real-data
training stage onto the capture queue and exits.

Driven via the EGRESS_* env hooks (fake probe, tmp log/stage paths, fast
sleeps) — no network, no jax. Mirrors tests/test_watcher.py.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WATCH = REPO / "tools" / "egress_watch.sh"


def _spawn(tmp: Path, probe_cmd: str):
    env = dict(
        os.environ,
        EGRESS_LOG=str(tmp / "egress.log"),
        EGRESS_STAGES=str(tmp / "stages.txt"),
        EGRESS_PROBE_CMD=probe_cmd,
        EGRESS_SLEEP_S="1",
    )
    return subprocess.Popen(["bash", str(WATCH)], env=env, cwd=str(REPO),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            preexec_fn=os.setsid)


def _killpg(p):
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    p.wait()


def _wait(until, timeout_s: float = 20.0, what: str = ""):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if until():
            return
        time.sleep(0.25)
    pytest.fail(f"egress watcher did not reach expected state: {what}")


def test_closed_egress_keeps_probing_and_logging(tmp_path):
    (tmp_path / "stages.txt").write_text("# queue\n")
    p = _spawn(tmp_path, "exit 1")
    try:
        log = tmp_path / "egress.log"
        _wait(lambda: log.exists()
              and log.read_text().count("\n") >= 2, what="two probe cycles")
        assert p.poll() is None, "watcher must keep running while closed"
        # The queue must be untouched: no realdata stage without a fetch.
        assert (tmp_path / "stages.txt").read_text() == "# queue\n"
    finally:
        _killpg(p)


def test_open_egress_queues_realdata_and_exits(tmp_path):
    stages = tmp_path / "stages.txt"
    stages.write_text("# queue\n")
    p = _spawn(tmp_path, "exit 0")
    try:
        _wait(lambda: p.poll() is not None, what="watcher exit on success")
        assert p.returncode == 0
        text = stages.read_text()
        assert "realdata_train|" in text, text
        # Appended, not inserted: existing queue content keeps priority.
        assert text.startswith("# queue\n")
        log = (tmp_path / "egress.log").read_text()
        assert "egress OPEN" in log and "realdata_train queued" in log
    finally:
        _killpg(p)


def test_single_instance_flock(tmp_path):
    (tmp_path / "stages.txt").write_text("")
    p1 = _spawn(tmp_path, "exit 1")
    try:
        log = tmp_path / "egress.log"
        _wait(lambda: log.exists() and "started" in log.read_text(),
              what="first instance start")
        p2 = _spawn(tmp_path, "exit 1")
        try:
            _wait(lambda: p2.poll() is not None, what="second instance exit")
            assert p2.returncode == 0
            assert "another egress watcher holds" in log.read_text()
        finally:
            _killpg(p2)
        assert p1.poll() is None, "first instance must survive"
    finally:
        _killpg(p1)
