"""Tests for the fused affine+ReLU+3x3-conv Pallas kernel (ops/conv_block).

Run in Pallas interpret mode on CPU (tests/conftest.py forces the cpu
backend), so the exact kernel code the TPU runs is exercised here. The
oracle is the unfused XLA statement of the same math
(`reference_affine_relu_conv`), itself pinned against
`lax.conv_general_dilated` — the op the reference's cuDNN convs
(`/root/reference/cifar_example.py:20-25`) map to on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.ops.conv_block import (
    fused_affine_relu_conv,
    reference_affine_relu_conv,
)


def _inputs(b=4, h=8, w=8, c=64, seed=0, dtype=jnp.bfloat16):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (b, h, w, c), dtype)
    wt = (jax.random.normal(ks[1], (3, 3, c, c)) * 0.1).astype(jnp.float32)
    scale = jax.random.normal(ks[2], (c,)) * 0.5 + 1.0
    shift = jax.random.normal(ks[3], (c,)) * 0.1
    res = jax.random.normal(ks[4], (b, h, w, c), dtype)
    return x, wt, scale, shift, res


@pytest.mark.parametrize("with_res", [False, True])
def test_forward_matches_xla(with_res):
    x, wt, scale, shift, res = _inputs()
    r = res if with_res else None
    y = fused_affine_relu_conv(x, wt, scale, shift, r, 2)
    yr = reference_affine_relu_conv(x, wt, scale, shift, r)
    # atol = one bf16 ulp at the output magnitudes (same bound as
    # test_rectangular_spatial): interpret-mode accumulation order differs
    # from lax.conv's reduction by JAX version.
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0, atol=1e-2,
    )


def test_batch_not_divisible_by_block():
    # 5 images with block_b=2: the pad row must not leak into outputs.
    x, wt, scale, shift, _ = _inputs(b=5)
    y = fused_affine_relu_conv(x, wt, scale, shift, None, 2)
    yr = reference_affine_relu_conv(x, wt, scale, shift, None)
    assert y.shape == yr.shape == (5, 8, 8, 64)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0, atol=2e-5,
    )


def test_same_padding_edges():
    # A constant-1 input makes border outputs differ from interior ones
    # exactly by the zero-padding contribution — a direct probe that the
    # kernel's row-shift trick reproduces SAME-conv edge semantics.
    c = 64
    x = jnp.ones((2, 8, 8, c), jnp.float32)
    wt = jnp.ones((3, 3, c, c), jnp.float32) * 0.01
    scale = jnp.ones((c,))
    shift = jnp.zeros((c,))
    y = fused_affine_relu_conv(x, wt, scale, shift, None, 2)
    yr = reference_affine_relu_conv(x, wt, scale, shift, None)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=1e-6, atol=1e-4,
    )
    # Interior = 9 taps, edge = 6, corner = 4 (rel 1e-2: bf16 rounding).
    got = np.asarray(y, np.float32)[0, :, :, 0]
    assert got[4, 4] == pytest.approx(9 * 0.64, rel=1e-2)
    assert got[0, 4] == pytest.approx(6 * 0.64, rel=1e-2)
    assert got[0, 0] == pytest.approx(4 * 0.64, rel=1e-2)


@pytest.mark.parametrize("with_res", [False, True])
@pytest.mark.parametrize("pallas_bwd", [False, True])
def test_grads_match_xla(with_res, pallas_bwd):
    x, wt, scale, shift, res = _inputs(b=2)
    r = res if with_res else None
    argnums = (0, 1, 2, 3, 4) if with_res else (0, 1, 2, 3)

    def loss_fused(x, wt, s, b, r=None):
        return jnp.sum(
            fused_affine_relu_conv(x, wt, s, b, r, 2, True, pallas_bwd)
            .astype(jnp.float32) ** 2)

    def loss_ref(x, wt, s, b, r=None):
        return jnp.sum(
            reference_affine_relu_conv(x, wt, s, b, r).astype(jnp.float32) ** 2)

    args = (x, wt, scale, shift) + ((res,) if with_res else ())
    gf = jax.grad(loss_fused, argnums=argnums)(*args)
    gr = jax.grad(loss_ref, argnums=argnums)(*args)
    for name, a, b_ in zip("x w scale shift res".split(), gf, gr):
        a = np.asarray(a, np.float32)
        b_ = np.asarray(b_, np.float32)
        scale_ref = np.max(np.abs(b_)) + 1e-6
        # atol = one bf16 ulp of the normalized cotangents (accumulation
        # order differs between the fused backward and the oracle).
        np.testing.assert_allclose(
            a / scale_ref, b_ / scale_ref, rtol=0, atol=1e-3,
            err_msg=f"grad mismatch for {name}")


def test_jit_and_dtype_preserved():
    x, wt, scale, shift, _ = _inputs()
    y = jax.jit(lambda *a: fused_affine_relu_conv(*a, None, 2))(
        x, wt, scale, shift)
    assert y.dtype == x.dtype
    assert y.shape == x.shape


def test_batch_sharding_propagates_under_mesh(mesh8):
    # Without the op's custom partitioning rule, GSPMD treats the
    # pallas_call as an opaque op and replicates it — the output sharding
    # here is the regression probe (it was PartitionSpec() before the rule).
    from jax.sharding import NamedSharding, PartitionSpec as P

    x, wt, scale, shift, res = _inputs(b=16)
    xs = jax.device_put(x, NamedSharding(mesh8, P("data")))
    rs = jax.device_put(res, NamedSharding(mesh8, P("data")))
    ws = jax.device_put(wt, NamedSharding(mesh8, P()))

    f = jax.jit(lambda x, w, r: fused_affine_relu_conv(x, w, scale, shift,
                                                       r, 2))
    y = f(xs, ws, rs)
    assert y.sharding.spec == P("data")
    yr = reference_affine_relu_conv(x, wt, scale, shift, res)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32), atol=2e-2)

    g = jax.jit(jax.grad(lambda x, w, r: jnp.sum(
        fused_affine_relu_conv(x, w, scale, shift, r, 2)
        .astype(jnp.float32) ** 2), argnums=(0, 1)))
    gx, gw = g(xs, ws, rs)
    assert gx.sharding.spec == P("data")
    grx, grw = jax.grad(lambda x, w: jnp.sum(
        reference_affine_relu_conv(x, w, scale, shift, res)
        .astype(jnp.float32) ** 2), argnums=(0, 1))(x, wt)
    for a, b in ((gx, grx), (gw, grw)):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        m = np.abs(b).max() + 1e-6
        np.testing.assert_allclose(a / m, b / m, atol=1e-2)


def test_activate_false_is_plain_affine_conv():
    x, wt, scale, shift, _ = _inputs()
    y = fused_affine_relu_conv(x, wt, scale, shift, None, 2, False)
    yr = reference_affine_relu_conv(x, wt, scale, shift, None, activate=False)
    # atol = one bf16 ulp at this magnitude: accumulation order differs
    # between the kernel's single f32 accumulator and lax.conv's reduction.
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0, atol=4e-2,
    )
    # With ReLU on, a negative-heavy input must differ.
    y_act = fused_affine_relu_conv(x, wt, scale, shift, None, 2, True)
    assert np.abs(np.asarray(y_act, np.float32)
                  - np.asarray(y, np.float32)).max() > 0.1


def test_rejects_non_3x3():
    x, _, scale, shift, _ = _inputs()
    bad = jnp.zeros((1, 1, 64, 64), jnp.float32)
    with pytest.raises(ValueError, match="3x3"):
        fused_affine_relu_conv(x, bad, scale, shift, None, 2)


def test_emit_variant_outputs_and_grads():
    from tpu_dp.ops.conv_block import (
        _reference_z, fused_affine_relu_conv_emit,
    )

    x, wt, scale, shift, res = _inputs(b=4)
    y, z = fused_affine_relu_conv_emit(x, wt, scale, shift, res, 2)
    y0 = fused_affine_relu_conv(x, wt, scale, shift, res, 2)
    np.testing.assert_array_equal(np.asarray(y, np.float32),
                                  np.asarray(y0, np.float32))
    zm = _reference_z(x, scale, shift, res).astype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(z, np.float32),
                                  np.asarray(zm, np.float32))

    # Gradients of a loss using BOTH outputs, vs the unfused statement.
    def loss_fused(x, wt, s, b, r):
        y, z = fused_affine_relu_conv_emit(x, wt, s, b, r, 2)
        return (jnp.sum(y.astype(jnp.float32) ** 2)
                + jnp.sum(z.astype(jnp.float32) ** 2))

    def loss_ref(x, wt, s, b, r):
        y = reference_affine_relu_conv(x, wt, s, b, r)
        z = _reference_z(x, s, b, r).astype(jnp.bfloat16).astype(x.dtype)
        return (jnp.sum(y.astype(jnp.float32) ** 2)
                + jnp.sum(z.astype(jnp.float32) ** 2))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, wt, scale, shift,
                                                       res)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, wt, scale, shift,
                                                     res)
    # atol 1e-2 (bf16 ulp), not 1e-5: the oracle's two branches each round
    # their x/res cotangent to bf16 before summing, while the fused backward
    # sums the y- and z-path cotangents in f32 and rounds once — the fused
    # result is the *more* accurate of the two. (The z-only path matches the
    # oracle exactly; pinned above via the bit-equal forward outputs.)
    for name, a, b_ in zip("x w scale shift res".split(), gf, gr):
        a = np.asarray(a, np.float32)
        b_ = np.asarray(b_, np.float32)
        m = np.abs(b_).max() + 1e-6
        np.testing.assert_allclose(a / m, b_ / m, rtol=0, atol=1e-2,
                                   err_msg=f"grad mismatch for {name}")


def test_fused_conv_bn_stats_under_mesh(mesh8):
    """Sharded batch: the partition rule must psum the per-shard stat
    partials, so the (replicated) stats equal the global-batch sums."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_dp.ops.conv_block import _stats_of, fused_conv_bn

    x, wt, scale, shift, res = _inputs(b=16)
    xs = jax.device_put(x, NamedSharding(mesh8, P("data")))
    rs = jax.device_put(res, NamedSharding(mesh8, P("data")))

    f = jax.jit(lambda x, r: fused_conv_bn(x, wt, scale, shift, r, 2))
    y, stats = f(xs, rs)
    assert y.sharding.spec == P("data")
    y_ref = fused_affine_relu_conv(x, wt, scale, shift, res, 2)
    expected = _stats_of(np.asarray(y_ref))
    got = np.asarray(stats)
    scale_ref = np.abs(np.asarray(expected)).max() + 1e-6
    np.testing.assert_allclose(got / scale_ref, np.asarray(expected) / scale_ref,
                               atol=1e-5)


def test_fused_conv_bn_pad_masking():
    """Batch-pad images must not pollute the emitted stats: conv outputs of
    zero images are NOT zero (shift/ReLU/conv), so masking is load-bearing."""
    from tpu_dp.ops.conv_block import _stats_of, fused_conv_bn

    x, wt, scale, shift, _ = _inputs(b=5)  # pads to 6 with block_b=2
    y, stats = fused_conv_bn(x, wt, scale, shift, None, 2)
    expected = _stats_of(np.asarray(y))
    scale_ref = np.abs(np.asarray(expected)).max() + 1e-6
    np.testing.assert_allclose(np.asarray(stats) / scale_ref,
                               np.asarray(expected) / scale_ref, atol=1e-5)


def test_fused_conv_bn_grads_through_stats():
    """Differentiating THROUGH the stats output against an independent
    oracle (autodiff of the unfused statement + _stats_of): a regression
    in the hand-written stats cotangent (the 2*y factor, the f32
    promotion) must not cancel out as it would in fused-vs-fused tests."""
    from tpu_dp.ops.conv_block import _stats_of, fused_conv_bn

    x, wt, scale, shift, res = _inputs(b=4)
    weights = jnp.arange(2 * 64, dtype=jnp.float32).reshape(2, 64) / 64.0

    def loss_fused(x, wt, s, b, r):
        y, st = fused_conv_bn(x, wt, s, b, r, 2)
        return jnp.sum(y.astype(jnp.float32) ** 2) + jnp.sum(st * weights)

    def loss_ref(x, wt, s, b, r):
        y = reference_affine_relu_conv(x, wt, s, b, r)
        st = _stats_of(y.astype(jnp.bfloat16))
        return jnp.sum(y.astype(jnp.float32) ** 2) + jnp.sum(st * weights)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3, 4))(x, wt, scale, shift,
                                                       res)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3, 4))(x, wt, scale, shift,
                                                     res)
    # bf16-ulp tolerance: cotangent accumulation rounding differs (the
    # fused backward sums branch cotangents in f32, the oracle per branch).
    for name, a, b_ in zip("x w scale shift res".split(), gf, gr):
        a = np.asarray(a, np.float32)
        b_ = np.asarray(b_, np.float32)
        m = np.abs(b_).max() + 1e-6
        np.testing.assert_allclose(a / m, b_ / m, rtol=0, atol=1e-2,
                                   err_msg=f"grad mismatch for {name}")


def test_rectangular_spatial():
    # H != W: the row-shift realignment is width-stride-specific, so a
    # rectangular case guards the indexing math.
    x, wt, scale, shift, _ = _inputs(b=3, h=6, w=10, seed=9)
    y = fused_affine_relu_conv(x, wt, scale, shift, None, 2)
    yr = reference_affine_relu_conv(x, wt, scale, shift, None)
    # atol = one bf16 ulp at this magnitude (accumulation-order rounding).
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=0, atol=1e-2,
    )


def test_auto_block_b_accounts_for_variant_blocks():
    # ADVICE r3: the VMEM working-set model must include the residual
    # input block and the emitted-z output block, so variant grids can
    # only shrink (never exceed the budget the plain kernel was sized to).
    from tpu_dp.ops.conv_block import _auto_block_b

    plain = _auto_block_b(32, 32, 64)
    res = _auto_block_b(32, 32, 64, with_res=True)
    emit = _auto_block_b(32, 32, 64, emit_z=True)
    both = _auto_block_b(32, 32, 64, with_res=True, emit_z=True)
    assert plain >= res >= both >= 1
    assert plain >= emit >= both


def test_fused_bottleneck_rejects_non_relu_act():
    # ADVICE r3: the fused middle conv bakes ReLU into the kernel; a
    # different `act` must fail loudly, not apply only at the block exit.
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import pytest

    from tpu_dp.models.resnet import FusedBottleneckBlock

    blk = FusedBottleneckBlock(filters=8, act=nn.gelu)
    with pytest.raises(ValueError, match="ReLU"):
        blk.init(jax.random.PRNGKey(0), jnp.zeros((2, 8, 8, 32)))
