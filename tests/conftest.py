"""Test harness: 8 virtual CPU devices in one process.

The standard JAX fake-backend trick (SURVEY.md §4 "Multi-device without a
cluster"): `--xla_force_host_platform_device_count=8` exposes 8 CPU "devices"
so mesh collectives — the DDP-equivalence property and psum'd metrics — are
testable in plain pytest with no TPU attached. Must run before jax imports.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The build environment's sitecustomize pre-imports jax (TPU plugin
# registration), so the env vars above are too late for it — force the
# platform through the live config as well, before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from tpu_dp.parallel import dist

    return dist.data_mesh()


@pytest.fixture(scope="session")
def mesh1():
    import jax

    from tpu_dp.parallel import dist

    return dist.data_mesh(devices=jax.devices()[:1])


@pytest.fixture()
def rng():
    return np.random.default_rng(0)
