"""Bucketed, overlap-scheduled gradient collectives (`train.bucket_mb`;
docs/PERF.md "Overlapped collectives") + the async double-buffered input
feed — the two halves of ROADMAP item 4.

The correctness story, proven on the 8-device CPU mesh:

1. **Bucket plan units** — reverse production order, size targeting, the
   single-giant-leaf degenerate case, the self-describing composition key
   (a per-leaf key is the single-leaf case), `parse_bucket_mb` validation,
   and the bucketed `wire_report` accounting.
2. **Collective level** — bucketed f32 reduce-scatter matches the
   monolithic path bitwise on this backend (the documented contract is
   reduction-order tolerance, docs/PERF.md); bf16/int8 wires within their
   codec bounds; per-bucket error-feedback residuals; sub-threshold
   buckets ride the f32 fallback; the compiled schedule issues buckets in
   reverse production order (the overlap property's precondition).
3. **Step level** — bucketed training parity vs the replicated f32
   reference across all three wire dtypes lives in the ONE wire-dtype
   parity harness (tests/test_quant.py, bucketed × {f32, bf16, int8});
   here: the error-feedback telescoping property survives bucketing
   (no-EF ablation ≥ 2x worse) and the windowed multi-step composition.
4. **Analyzer** — DP301 accepts the K-bucket schedule and rejects a
   dropped or duplicated bucket; DP304's fingerprint artifact round-trips
   the bucket layout; Level 2 still proves exactly-one-reduction-per-leaf
   through the bucketed exchange.
5. **commprof** — a profiled CPU capture of the bucketed program
   reconciles exactly K reduce-scatters per step against the fingerprint
   schedule, with per-bucket wire bytes byte-exact vs `quant.wire_report`.
6. **Checkpoint** — bucketed residuals round-trip bitwise same-layout;
   resharding across bucket-size changes, per-leaf <-> bucketed layout
   flips, and codec-off targets all preserve (or deliberately drop) the
   pending error-feedback correction leaf-exactly.
7. **Input feed** — device placement is genuinely async: no per-batch
   host sync (the `data_wait` span shrinks vs the `sync_placement`
   comparator) and the double buffer keeps the next batch's placement in
   flight while the consumer computes.

Fast lane: ``pytest -m overlap``.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import Net
from tpu_dp.parallel import bucketing, collectives, dist, quant
from tpu_dp.train import (
    SGD,
    constant_lr,
    create_train_state,
    make_train_step_shard_map,
    shard_optimizer,
)

pytestmark = pytest.mark.overlap

WORLD = 8
BLOCK = 64
BB = 4 * 1024  # 4 KB buckets: several buckets even on toy trees


def _sample():
    return np.zeros((1, 32, 32, 3), np.float32)


def _make_batch(seed, n=16):
    ds = make_synthetic(n, 10, seed=seed, name="synthetic")
    return {"image": normalize(ds.images), "label": ds.labels}


def _copy(state):
    return jax.tree_util.tree_map(jnp.array, state)


def _l2(a, b):
    return float(np.sqrt(sum(
        float(np.sum((np.asarray(x) - np.asarray(y)) ** 2))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b))
    )))


def _tree(rng):
    return {
        "a": jnp.asarray(rng.normal(size=(400, 120)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(5, 5, 3, 6)).astype(np.float32)),
        "c": jnp.asarray(rng.normal(size=(1000,)).astype(np.float32)),
    }


def _per_replica(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.stack([x * (i + 1) for i in range(WORLD)]), tree
    )


# --------------------------------------------------------------------------
# 1. bucket plan units
# --------------------------------------------------------------------------

def test_parse_bucket_mb_validation():
    assert bucketing.parse_bucket_mb(0) == 0
    assert bucketing.parse_bucket_mb(None) == 0
    assert bucketing.parse_bucket_mb(1) == 2**20
    assert bucketing.parse_bucket_mb(0.5) == 2**19
    with pytest.raises(ValueError, match="bucket_mb"):
        bucketing.parse_bucket_mb(-1)
    with pytest.raises(ValueError, match="bucket_bytes"):
        bucketing.plan_buckets([("a", 10)], WORLD, 0)


def test_plan_reverse_production_order_and_size_target():
    """Buckets fill from the LAST leaf backwards (backward produces
    gradients in reverse forward order) and close at the byte target."""
    leaves = [("l0", 1000), ("l1", 50), ("l2", 3000), ("l3", 8)]
    plan = bucketing.plan_buckets(leaves, world=8,
                                  bucket_bytes=4 * 1024)  # 1024 f32 elems
    # Reverse order: l3 (8 -> padded 8), l2 (3000) closes bucket 0;
    # l1, l0 close bucket 1 at the tail.
    assert [b.keys for b in plan] == [("l3", "l2"), ("l1", "l0")]
    assert [b.index for b in plan] == [0, 1]
    assert plan[0].elements == 3008 and plan[1].elements == 1050
    # Every leaf exactly once across the union — the exactly-once seed.
    seen = [k for b in plan for k in b.keys]
    assert sorted(seen) == sorted(k for k, _ in leaves)


def test_plan_single_giant_leaf_owns_bucket():
    plan = bucketing.plan_buckets(
        [("small", 4), ("giant", 10_000_000)], world=8, bucket_bytes=2**20)
    assert [b.keys for b in plan] == [("giant",), ("small",)]


def test_composition_key_roundtrip():
    b = bucketing.GradBucket(index=0, keys=("fc1/kernel", "conv2/bias"),
                             sizes=(48000, 16))
    assert bucketing.composition(b.key) == ["fc1/kernel", "conv2/bias"]
    # Single-leaf buckets degenerate to the plain leaf key — unbucketed
    # residual checkpoints are the single-leaf case of the same grammar.
    solo = bucketing.GradBucket(index=0, keys=("conv1/kernel",),
                                sizes=(450,))
    assert solo.key == "conv1/kernel"
    assert bucketing.composition(solo.key) == ["conv1/kernel"]


def test_quantize_threshold_is_per_bucket():
    """Concatenation is what lets small leaves compress: alone below the
    world*block threshold, together above it."""
    leaves = [("x", 300), ("y", 300)]
    plan = bucketing.plan_buckets(leaves, world=8, bucket_bytes=2**20,
                                  block_size=64, int8=True)
    assert len(plan) == 1 and plan[0].quantizes  # 600 >= 8*64
    tiny = bucketing.plan_buckets([("x", 300)], world=8, bucket_bytes=2**20,
                                  block_size=64, int8=True)
    assert not tiny[0].quantizes  # 300 < 512: f32 fallback bucket


def test_wire_report_bucketed_accounting(rng):
    tree = _tree(rng)
    mono = quant.wire_report(tree, WORLD, BLOCK)
    buck = quant.wire_report(tree, WORLD, BLOCK, bucket_bytes=BB)
    # f32/bf16 bytes are padding-preserving under concatenation.
    assert buck["wire_bytes_per_step"]["f32"] == \
        mono["wire_bytes_per_step"]["f32"]
    assert buck["wire_bytes_per_step"]["bf16"] == \
        mono["wire_bytes_per_step"]["bf16"]
    # int8 block padding is per bucket; the layout summary rides along.
    assert buck["bucket_bytes"] == BB
    assert len(buck["buckets"]) >= 2
    assert sum(e["leaves"] for e in buck["buckets"]) == buck["leaves"] == 3
    plan = bucketing.plan_for_tree(tree, WORLD, BB, block_size=BLOCK,
                                   int8=True)
    assert len(buck["buckets"]) == len(plan)
    # Small leaves compress inside buckets: more quantized leaves than
    # the per-leaf layout could manage.
    assert buck["quantized_leaves"] >= mono["quantized_leaves"]


# --------------------------------------------------------------------------
# 2. collective level
# --------------------------------------------------------------------------

def _roundtrip_bucketed(mesh8, tree, dtype=None, bucket_bytes=BB):
    from jax.sharding import PartitionSpec as P

    from tpu_dp.train.step import _shard_map

    def via_bucketed(t):
        sh = collectives.psum_scatter_bucketed(
            t, dist.DATA_AXIS, world=WORLD, mean=True, dtype=dtype,
            bucket_bytes=bucket_bytes)
        return collectives.all_gather(sh, t, dist.DATA_AXIS)

    def via_mono(t):
        return collectives.all_gather(
            collectives.psum_scatter(t, dist.DATA_AXIS, world=WORLD,
                                     mean=True), t, dist.DATA_AXIS)

    fb = jax.jit(_shard_map(via_bucketed, mesh8, (P(dist.DATA_AXIS),), P()))
    fm = jax.jit(_shard_map(via_mono, mesh8, (P(dist.DATA_AXIS),), P()))
    return fb, fm


def test_bucketed_scatter_matches_monolithic_f32(mesh8, rng):
    """Bucketed f32 vs the monolithic reduce-scatter: concatenation does
    not change the per-element cross-replica addition order, so on the
    CPU backend the result is bitwise (the documented cross-backend
    contract is reduction-order tolerance, docs/PERF.md)."""
    tree = _tree(rng)
    args = _per_replica(tree)
    fb, fm = _roundtrip_bucketed(mesh8, tree)
    out_b, out_m = fb(args), fm(args)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out_b[k]),
                                      np.asarray(out_m[k]))
        assert out_b[k].dtype == out_m[k].dtype


def test_bucketed_scatter_bf16_wire_tolerance(mesh8, rng):
    tree = _tree(rng)
    args = _per_replica(tree)
    fb, fm = _roundtrip_bucketed(mesh8, tree, dtype=jnp.bfloat16)
    out_b, out_m = fb(args), fm(args)
    identical = True
    for k in tree:
        a, m = np.asarray(out_b[k]), np.asarray(out_m[k])
        np.testing.assert_allclose(a, m, atol=np.abs(m).max() * 8e-3)
        identical &= bool(np.array_equal(a, m))
    assert not identical, "bf16 wire produced bitwise f32 — never cast?"


def test_bucketed_quant_scatter_and_per_bucket_residuals(mesh8, rng):
    from jax.sharding import PartitionSpec as P

    from tpu_dp.train.step import _shard_map

    tree = _tree(rng)
    args = _per_replica(tree)
    res = quant.init_residuals(tree, WORLD, BLOCK, bucket_bytes=BB)
    plan = bucketing.plan_for_tree(tree, WORLD, BB, block_size=BLOCK,
                                   int8=True)
    # Residuals keyed by the composition of each QUANTIZING bucket.
    assert set(res) == {b.key for b in plan if b.quantizes}

    def via_q(t, r):
        sh, nr, st = collectives.psum_scatter_quant_bucketed(
            t, r, dist.DATA_AXIS, world=WORLD, mean=True,
            block_size=BLOCK, bucket_bytes=BB)
        full = collectives.all_gather(sh, t, dist.DATA_AXIS)
        st = {k: collectives.psum(v, dist.DATA_AXIS) for k, v in st.items()}
        return full, nr, st

    fq = jax.jit(_shard_map(
        via_q, mesh8, (P(dist.DATA_AXIS), P(dist.DATA_AXIS)),
        (P(), P(dist.DATA_AXIS), P())))
    _, fm = _roundtrip_bucketed(mesh8, tree)
    (out_q, new_res, stats), out_m = fq(args, res), fm(args)
    for k in tree:
        a, m = np.asarray(out_q[k]), np.asarray(out_m[k])
        assert np.abs(a - m).max() <= np.abs(m).max() * 0.01 + 1e-6, k
    # The SMALL leaf compressed inside its bucket (not the f32 fallback
    # the per-leaf layout forced): provably non-bitwise.
    assert not np.array_equal(np.asarray(out_q["b"]),
                              np.asarray(out_m["b"]))
    assert int(stats["overflow"]) == 0
    for key, leaf in new_res.items():
        assert np.abs(np.asarray(leaf)).max() > 0, key


def test_sub_threshold_bucket_rides_f32_fallback(mesh8, rng):
    """A bucket below world*block elements keeps the plain f32 wire and
    carries no residual — bitwise vs the monolithic f32 scatter."""
    from jax.sharding import PartitionSpec as P

    from tpu_dp.train.step import _shard_map

    # Pytree (sorted-key) order is a_tiny, z_big; reverse production
    # order walks it backwards: "z_big" closes bucket 0 alone, "a_tiny"
    # (40 < world*block = 512) is the trailing sub-threshold bucket.
    tree = {"a_tiny": jnp.asarray(rng.normal(size=(40,)).astype(np.float32)),
            "z_big": jnp.asarray(rng.normal(size=(4096,)).astype(np.float32))}
    args = _per_replica(tree)
    bb = 2 * 1024
    plan = bucketing.plan_for_tree(tree, WORLD, bb, block_size=BLOCK,
                                   int8=True)
    assert [b.keys for b in plan] == [("z_big",), ("a_tiny",)]
    assert [b.quantizes for b in plan] == [True, False]
    res = quant.init_residuals(tree, WORLD, BLOCK, bucket_bytes=bb)
    assert set(res) == {"z_big"}

    def via_q(t, r):
        sh, nr, st = collectives.psum_scatter_quant_bucketed(
            t, r, dist.DATA_AXIS, world=WORLD, mean=True,
            block_size=BLOCK, bucket_bytes=bb)
        return collectives.all_gather(sh, t, dist.DATA_AXIS)

    fq = jax.jit(_shard_map(
        via_q, mesh8, (P(dist.DATA_AXIS), P(dist.DATA_AXIS)), P()))
    _, fm = _roundtrip_bucketed(mesh8, tree, bucket_bytes=bb)
    out_q, out_m = fq(args, res), fm(args)
    np.testing.assert_array_equal(np.asarray(out_q["a_tiny"]),
                                  np.asarray(out_m["a_tiny"]))


def test_compiled_schedule_has_k_buckets_in_reverse_production_order(
        mesh8, rng):
    """The compiled module carries exactly K separate reduce-scatters, in
    the plan's issue order (bucket 0 = the LAST leaves, produced first in
    backward) — the `optimization_barrier` token chain is what keeps the
    optimizer passes from globbing them back into one exchange."""
    from tpu_dp.analysis.hlo import collect_ops

    tree = _tree(rng)
    args = _per_replica(tree)
    plan = bucketing.plan_for_tree(tree, WORLD, BB)
    fb, _ = _roundtrip_bucketed(mesh8, tree)
    text = fb.lower(args).compile().as_text()
    scatters = [op for op in collect_ops(text)
                if op.kind == "reduce-scatter"]
    assert len(scatters) == len(plan) >= 2
    from tpu_dp.analysis.hlo import _shape_elements
    got = [_shape_elements(op.shape) for op in scatters]
    want = [sum(collectives.shard_size(n, WORLD) for n in b.sizes)
            for b in plan]
    # Compiled HLO is scheduled: textual order == execution order, and it
    # must be the plan's reverse-production issue order.
    assert got == want


# --------------------------------------------------------------------------
# 3. step level
# --------------------------------------------------------------------------

def _states(bucket_mb=0.05):
    model = Net()
    opt = SGD(momentum=0.9)
    sopt = shard_optimizer(SGD(momentum=0.9), WORLD)
    rng = jax.random.PRNGKey(0)
    state_r = create_train_state(model, rng, _sample(), opt)
    state_s = create_train_state(model, rng, _sample(), sopt)
    state_q = state_s.replace(residuals=quant.init_residuals(
        state_s.params, WORLD, 256,
        bucket_bytes=bucketing.parse_bucket_mb(bucket_mb)))
    return model, opt, sopt, state_r, state_s, state_q


def test_bucketed_error_feedback_ablation_is_measurably_worse(mesh8):
    """The telescoping property survives bucketing: over a 24-step
    fixed-seed run the no-EF ablation drifts ≥2x farther from the f32
    trajectory than the per-bucket-EF run (same contract as the per-leaf
    harness, tests/test_quant.py). Measured margin ~4.7x at 0.01 MB
    buckets; at 0.05 MB × block 256 the margin compresses to ~1.3x —
    cross-leaf blocks share one absmax scale, the documented
    bucket-size/block-size coupling of docs/PERF.md."""
    model, opt, sopt, state_r, _, state_q = _states(bucket_mb=0.01)
    lr = constant_lr(0.01)
    step_r = make_train_step_shard_map(model, opt, mesh8, lr)
    step_ef = make_train_step_shard_map(
        model, sopt, mesh8, lr, update_sharding="sharded",
        collective_dtype="int8", bucket_mb=0.01)
    step_no = make_train_step_shard_map(
        model, sopt, mesh8, lr, update_sharding="sharded",
        collective_dtype="int8", quant_error_feedback=False,
        bucket_mb=0.01)
    sr, se, sn = _copy(state_r), _copy(state_q), _copy(state_q)
    for i in range(24):
        batch = _make_batch(i)
        sr, _ = step_r(sr, batch)
        se, _ = step_ef(se, batch)
        sn, _ = step_no(sn, batch)
    d_ef = _l2(se.params, sr.params)
    d_no = _l2(sn.params, sr.params)
    assert d_ef * 2 < d_no, (d_ef, d_no)
    for leaf in jax.tree_util.tree_leaves(sn.residuals):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)
    for leaf in jax.tree_util.tree_leaves(se.residuals):
        assert np.abs(np.asarray(leaf)).max() > 0


def test_bucketed_multi_step_window_tracks_f32(mesh8):
    """Bucketing composes with the windowed device-side loop."""
    from tpu_dp.train import make_multi_step

    model, opt, sopt, state_r, state_s, _ = _states()
    K = 4
    loop_r = make_multi_step(model, opt, mesh8, constant_lr(0.05),
                             num_steps=K)
    loop_b = make_multi_step(model, sopt, mesh8, constant_lr(0.05),
                             num_steps=K, update_sharding="sharded",
                             bucket_mb=0.05)
    batches = [_make_batch(100 + i) for i in range(K)]
    pool = {"image": np.stack([b["image"] for b in batches]),
            "label": np.stack([b["label"] for b in batches])}
    sr, _ = loop_r(_copy(state_r), pool)
    sb, _ = loop_b(_copy(state_s), pool)
    assert int(sb.step) == K
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_factory_and_config_validation():
    from tpu_dp.train import make_multi_step
    from tpu_dp.train.step import make_multi_step_resident

    model, opt, sopt, *_ = _states()
    mesh = dist.data_mesh()
    with pytest.raises(ValueError, match="bucket_mb"):
        make_train_step_shard_map(model, opt, mesh, constant_lr(0.1),
                                  bucket_mb=1.0)  # replicated mode
    with pytest.raises(ValueError, match="bucket_mb"):
        make_train_step_shard_map(model, sopt, mesh, constant_lr(0.1),
                                  update_sharding="sharded", bucket_mb=-1)
    # The windowed factories refuse too — a silently-dropped bucket_mb
    # would leave the caller believing the overlap schedule is armed.
    with pytest.raises(ValueError, match="bucket_mb"):
        make_multi_step(model, opt, mesh, constant_lr(0.1), num_steps=2,
                        bucket_mb=1.0)
    with pytest.raises(ValueError, match="bucket_mb"):
        make_multi_step_resident(model, opt, mesh, constant_lr(0.1),
                                 num_steps=2, bucket_mb=1.0)
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    cfg = Config()
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_train_size = 16
    cfg.data.synthetic_test_size = 8
    cfg.train.bucket_mb = 1.0  # replicated update: must refuse
    with pytest.raises(ValueError, match="bucket_mb"):
        Trainer(cfg)


# --------------------------------------------------------------------------
# 4. analyzer
# --------------------------------------------------------------------------

@pytest.mark.analysis
def test_gradsync_bucketed_exactly_once():
    from tpu_dp.analysis import gradsync

    for wire in (None, "int8"):
        findings, report = gradsync.verify_repo_step(
            update_sharding="sharded", collective_dtype=wire,
            bucket_mb=0.05,
        )
        assert findings == [], [f.message for f in findings]
        assert report and all(c == 1 for c in report.values()), report


@pytest.fixture(scope="module")
def _bucketed_program():
    """One compiled bucketed sharded train step + its plan (module-scoped:
    the compile is the expensive part, every analyzer/commprof test below
    shares it)."""
    model, opt, sopt, state_r, state_s, _ = _states()
    mesh = dist.data_mesh()
    step = make_train_step_shard_map(
        model, sopt, mesh, constant_lr(0.05), update_sharding="sharded",
        bucket_mb=0.05)
    plan = bucketing.plan_for_tree(
        state_s.params, WORLD, bucketing.parse_bucket_mb(0.05))
    batch = _make_batch(0)
    return step, _copy(state_s), batch, plan


@pytest.mark.analysis
def test_dp301_accepts_k_bucket_schedule(_bucketed_program, tmp_path):
    from tpu_dp.analysis.hlo import (
        analyze_module,
        bucket_expectations,
        lower_and_compile,
        write_fingerprint_artifact,
    )

    step, state, batch, plan = _bucketed_program
    text, _, warns = lower_and_compile(step, (state, batch))
    layout = bucket_expectations(plan, WORLD, 256)
    findings, record = analyze_module(
        text, label="bucketed", where=("x.py", 1), world=WORLD,
        donated_leaves=len(jax.tree_util.tree_leaves(state)),
        metric_reductions=2, expect_grad_reduce=True,
        donation_warnings=warns, update_sharding="sharded",
        bucket_layout=layout,
    )
    assert findings == [], [f.message for f in findings]
    # DP304: the fingerprint artifact round-trips the bucket layout.
    art = {"version": 1, "world": WORLD, "backend": "cpu", "digest": "x",
           "programs": {"bucketed": record}}
    path = tmp_path / "fp.json"
    write_fingerprint_artifact(str(path), art)
    back = json.loads(path.read_text())
    assert back["programs"]["bucketed"]["buckets"] == layout
    assert len(back["programs"]["bucketed"]["buckets"]) == len(plan) >= 2


@pytest.mark.analysis
def test_dp301_rejects_dropped_and_duplicated_bucket(_bucketed_program):
    from tpu_dp.analysis.hlo import (
        analyze_module,
        bucket_expectations,
        lower_and_compile,
    )

    step, state, batch, plan = _bucketed_program
    text, _, _ = lower_and_compile(step, (state, batch))
    layout = bucket_expectations(plan, WORLD, 256)

    def run(declared):
        findings, _ = analyze_module(
            text, label="bucketed", where=("x.py", 1), world=WORLD,
            metric_reductions=2, expect_grad_reduce=True,
            update_sharding="sharded", bucket_layout=declared,
        )
        return [f for f in findings if f.rule == "DP301"]

    # Declaring a bucket the program does not compile == the program
    # DROPPED a declared bucket (those leaves never reduce).
    extra_bucket = layout + [{"wire": "f32", "shard_elements": 4242}]
    got = run(extra_bucket)
    assert got and any("MISSING" in f.message for f in got)
    # Declaring FEWER buckets than compiled == a duplicated/stray
    # exchange beyond the plan.
    got = run(layout[:1])
    assert got and any("EXTRA" in f.message for f in got)


# --------------------------------------------------------------------------
# 5. commprof: K buckets reconcile on a real profiled capture
# --------------------------------------------------------------------------

def test_commprof_reconciles_k_buckets_on_profiled_capture(
        _bucketed_program, tmp_path):
    """A real jax.profiler capture of the bucketed program reconciles
    exactly K reduce-scatters per step per device against the fingerprint
    schedule, with the grad-exchange bytes byte-exact vs the bucketed
    `quant.wire_report` — and a tampered expectation must NOT reconcile."""
    from tpu_dp.obs import commprof, xplane

    step, state0, batch, plan = _bucketed_program
    expected = commprof.expected_schedule(step, (_copy(state0), batch))
    state = _copy(state0)
    state, _ = step(state, batch)  # warmup outside the trace
    trace_dir = tmp_path / "trace"
    with jax.profiler.trace(str(trace_dir)):
        state, m = step(state, batch)
        state, m = step(state, batch)
        jax.block_until_ready(m)
    summary = xplane.summarize_robust(str(trace_dir))
    wire_rep = quant.wire_report(
        state.params, WORLD, 256,
        bucket_bytes=bucketing.parse_bucket_mb(0.05))
    steps = 2
    rep = commprof.breakdown(
        summary, steps=steps,
        devices=WORLD if summary.get("source") == "host" else 1,
        expected_total={k: v * steps for k, v in expected["counts"].items()},
        collectives=expected["collectives"], world=WORLD,
        wire_report=wire_rep, wire_dtype="",
    )
    recon = rep["reconciliation"]
    assert recon["ok"], recon
    assert recon["by_kind"]["reduce-scatter"]["per_step_observed"] == \
        len(plan)
    assert rep["wire"]["reconciliation"]["ok"], rep["wire"]
    assert rep["wire"]["reconciliation"]["schedule_bytes_per_step"] == \
        wire_rep["wire_bytes_per_step"]["f32"]
    # Tamper: expect one extra scatter per step -> must NOT reconcile.
    bad = dict(expected["counts"])
    bad["reduce-scatter"] = bad.get("reduce-scatter", 0) + 1
    rep_bad = commprof.breakdown(
        summary, steps=steps,
        devices=WORLD if summary.get("source") == "host" else 1,
        expected_total={k: v * steps for k, v in bad.items()},
    )
    assert not rep_bad["reconciliation"]["ok"]


# --------------------------------------------------------------------------
# 6. checkpoint: bucket-exact residual resharding
# --------------------------------------------------------------------------

def _fill_residuals(state, gen):
    """Recognizable nonzero residuals, zero outside valid element slots
    (the invariant a real trajectory maintains) — built by composing
    known per-leaf pending vectors into each key's layout."""
    sizes = {
        "/".join(str(getattr(x, "key", x)) for x in path): leaf.size
        for path, leaf in jax.tree_util.tree_leaves_with_path(state.params)
    }
    pend = {k: gen.normal(size=n).astype(np.float32) * 1e-3
            for k, n in sizes.items()}
    filled = {
        key: jnp.asarray(quant.compose_residual(pend, np.asarray(leaf),
                                                sizes, key))
        for key, leaf in state.residuals.items()
    }
    return state.replace(residuals=filled), pend, sizes


def _pendings(state, sizes):
    out = {}
    for key, leaf in state.residuals.items():
        out.update(quant.decompose_residual(np.asarray(leaf), sizes, key))
    return out


def test_bucketed_residuals_roundtrip_same_layout_bitwise(tmp_path):
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    *_, state_q = _states()
    state_q, _, _ = _fill_residuals(state_q, np.random.default_rng(1))
    save_checkpoint(tmp_path, state_q, {"epoch": 0})
    restored, _ = load_checkpoint(tmp_path, _states()[5])
    for key, leaf in state_q.residuals.items():
        np.testing.assert_array_equal(np.asarray(restored.residuals[key]),
                                      np.asarray(leaf))


@pytest.mark.parametrize("src_mb,dst_mb", [
    (0.0, 0.05),    # per-leaf layout -> bucketed
    (0.05, 0.0),    # bucketed -> per-leaf
    (0.05, 0.01),   # bucket-size retune
], ids=["leaf->bucket", "bucket->leaf", "bucket-resize"])
def test_residual_reshard_across_bucket_layouts_preserves_pending(
        tmp_path, src_mb, dst_mb):
    """The acceptance contract: resume across a bucket-layout change
    preserves the pending error-feedback correction LEAF-exactly (total
    debt per params leaf; replica 0 owes it all in the new layout)."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    src = _states(bucket_mb=src_mb)[5]
    src, pend, sizes = _fill_residuals(src, np.random.default_rng(7))
    save_checkpoint(tmp_path, src, {"epoch": 0})
    dst = _states(bucket_mb=dst_mb)[5]
    restored, _ = load_checkpoint(tmp_path, dst)
    assert set(restored.residuals) == set(dst.residuals)
    got = _pendings(restored, sizes)
    src_pend = _pendings(src, sizes)
    # Leaves covered by BOTH layouts carry their pending debt exactly;
    # leaves the new layout covers but the old one did not (a small leaf
    # entering a quantizing bucket) start clean; leaves the new layout
    # stopped covering are deliberately forfeited.
    carried = set(src_pend) & set(got)
    assert carried, "no leaf covered by both layouts — vacuous test"
    for k in carried:
        np.testing.assert_allclose(got[k], src_pend[k], atol=1e-7,
                                   err_msg=k)
    for k in set(got) - set(src_pend):
        np.testing.assert_array_equal(got[k], 0.0)
    # The debt sits on replica 0; everyone else starts clean.
    for key, leaf in restored.residuals.items():
        np.testing.assert_array_equal(np.asarray(leaf)[1:], 0.0)


def test_bucketed_residuals_drop_when_codec_off(tmp_path):
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model, opt, sopt, state_r, state_s, state_q = _states()
    state_q, _, _ = _fill_residuals(state_q, np.random.default_rng(2))
    save_checkpoint(tmp_path, state_q, {"epoch": 0})
    dropped, _ = load_checkpoint(tmp_path, state_s.replace(residuals={}))
    assert dropped.residuals == {}


def test_real_run_residuals_survive_bucket_resize(tmp_path, mesh8):
    """End-to-end: REAL residuals from a few bucketed int8 steps, saved,
    restored into a different bucket size — per-leaf pending corrections
    carried over exactly; training continues without shape errors."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model, opt, sopt, state_r, state_s, state_q = _states(bucket_mb=0.05)
    step = make_train_step_shard_map(
        model, sopt, mesh8, constant_lr(0.05), update_sharding="sharded",
        collective_dtype="int8", bucket_mb=0.05)
    s = _copy(state_q)
    for i in range(3):
        s, _ = step(s, _make_batch(i))
    save_checkpoint(tmp_path, s, {"epoch": 0})

    sizes = {
        "/".join(str(getattr(x, "key", x)) for x in path): leaf.size
        for path, leaf in jax.tree_util.tree_leaves_with_path(s.params)
    }
    before = _pendings(s, sizes)
    dst = _states(bucket_mb=0.01)[5]
    restored, _ = load_checkpoint(tmp_path, dst)
    after = _pendings(restored, sizes)
    carried = set(before) & set(after)
    assert carried
    for k in carried:
        np.testing.assert_allclose(after[k], before[k], atol=1e-6,
                                   err_msg=k)
    for k in set(after) - set(before):
        np.testing.assert_array_equal(after[k], 0.0)
    step2 = make_train_step_shard_map(
        model, sopt, mesh8, constant_lr(0.05), update_sharding="sharded",
        collective_dtype="int8", bucket_mb=0.01)
    s2, m = step2(_copy(restored), _make_batch(9))
    assert int(s2.step) == 4 and np.isfinite(float(m["loss"]))


# --------------------------------------------------------------------------
# 7. input feed: async double-buffered placement
# --------------------------------------------------------------------------

def _timed_pipeline(monkeypatch, transfer_s, sync, prefetch):
    """A DataPipeline whose device placement 'transfer' completes
    ``transfer_s`` after dispatch: `shard_batch` is an async dispatch
    (returns immediately, stamps a ready time), `jax.block_until_ready`
    waits it out — the model of a real h2d copy."""
    from tpu_dp.data import pipeline as pl
    from tpu_dp.data.cifar import make_synthetic

    def fake_shard_batch(batch, mesh, spec=None):
        return dict(batch, _ready_at=time.perf_counter() + transfer_s)

    def fake_block(x):
        if isinstance(x, dict) and "_ready_at" in x:
            time.sleep(max(0.0, x["_ready_at"] - time.perf_counter()))
        return x

    monkeypatch.setattr(pl, "shard_batch", fake_shard_batch)
    monkeypatch.setattr(jax, "block_until_ready", fake_block)
    ds = make_synthetic(64, 10, seed=0, name="synthetic")
    mesh = dist.data_mesh()
    return pl.DataPipeline(ds, 8, mesh, shuffle=False, prefetch=prefetch,
                           sync_placement=sync)


def _consume(pipe, work_s=0.0):
    """Iterate the pipeline; return total time blocked in next() — the
    data_wait span the trainer records."""
    waits = []
    it = iter(pipe)
    while True:
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            break
        waits.append(time.perf_counter() - t0)
        assert "image" in item
        if work_s:
            time.sleep(work_s)  # the consumer's "step"
    return sum(waits), len(waits)


def test_async_placement_shrinks_data_wait(monkeypatch):
    """The satellite's proof: with a per-batch 'transfer' of 30 ms and a
    30 ms consumer step, the sync-placement pipeline (the old world: a
    host sync per batch) pays the transfer on the data_wait span every
    batch; the async double-buffered default hides it under the step.
    Coarse margins — sleeps, not wall-clock guesses."""
    sync_wait, n1 = _consume(
        _timed_pipeline(monkeypatch, 0.03, sync=True, prefetch=0),
        work_s=0.03)
    async_wait, n2 = _consume(
        _timed_pipeline(monkeypatch, 0.03, sync=False, prefetch=0),
        work_s=0.03)
    assert n1 == n2 == 8
    assert sync_wait > 0.03 * (n1 - 1), (sync_wait, n1)
    assert async_wait < sync_wait * 0.5, (async_wait, sync_wait)


def test_double_buffer_keeps_next_placement_in_flight(monkeypatch):
    """Batch k+1's placement is DISPATCHED before the consumer finishes
    batch k — the two-slot double buffer, observable from dispatch
    timestamps even with the prefetch thread off."""
    from tpu_dp.data import pipeline as pl
    from tpu_dp.data.cifar import make_synthetic

    dispatches = []

    def fake_shard_batch(batch, mesh, spec=None):
        dispatches.append(time.perf_counter())
        return batch

    monkeypatch.setattr(pl, "shard_batch", fake_shard_batch)
    ds = make_synthetic(32, 10, seed=0, name="synthetic")
    pipe = pl.DataPipeline(ds, 8, dist.data_mesh(), shuffle=False,
                           prefetch=0)
    it = iter(pipe)
    next(it)
    # Before the consumer asks for batch 1, its placement is in flight.
    assert len(dispatches) >= 2
    consumed_at = time.perf_counter()
    next(it)
    assert dispatches[1] <= consumed_at


def test_sync_placement_knob_blocks_per_batch(monkeypatch):
    """The escape hatch really is the old world: sync_placement=True
    calls block_until_ready once per placed batch."""
    from tpu_dp.data import pipeline as pl
    from tpu_dp.data.cifar import make_synthetic

    blocks = []
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda x: blocks.append(1) or x)
    ds = make_synthetic(32, 10, seed=0, name="synthetic")
    mesh = dist.data_mesh()
    _consume(pl.DataPipeline(ds, 8, mesh, shuffle=False, prefetch=0,
                             sync_placement=True))
    assert len(blocks) == 4
    blocks.clear()
    _consume(pl.DataPipeline(ds, 8, mesh, shuffle=False, prefetch=0))
    assert blocks == []  # the async default never host-syncs per batch


def test_windows_path_double_buffers_and_matches(monkeypatch):
    """The windowed feed rides the same double buffer and yields the same
    windows (order + content) as before."""
    from tpu_dp.data import pipeline as pl
    from tpu_dp.data.cifar import make_synthetic

    ds = make_synthetic(64, 10, seed=0, name="synthetic")
    mesh = dist.data_mesh()
    pipe = pl.DataPipeline(ds, 8, mesh, shuffle=False, prefetch=2)
    got = [(n, np.asarray(item["label"]).copy())
           for n, item in pipe.windows(3)]
    assert [n for n, _ in got] == [3, 3, 1, 1]
    flat = np.concatenate([lab.reshape(-1) for _, lab in got])
    np.testing.assert_array_equal(flat, ds.labels)
