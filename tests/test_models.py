"""Model unit tests — shapes and parameter counts vs the reference spec.

SURVEY.md §4 Unit: "model forward shapes/param counts vs `Net` spec
(`cifar_example.py:20-25`: conv 3→6→16, fc 400→120→84→10)".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.models import Net, ResNet18, ResNet50, build_model


def _param_count(params):
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def test_net_output_shape_and_param_count():
    model = Net()
    x = np.zeros((4, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (4, 10)
    # Exact torch parity: conv1 456 + conv2 2416 + fc1 48120 + fc2 10164
    # + fc3 850 = 62006 (`cifar_example.py:20-25`).
    assert _param_count(variables["params"]) == 62_006


def test_net_layer_shapes():
    model = Net()
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    p = variables["params"]
    assert p["conv1"]["kernel"].shape == (5, 5, 3, 6)
    assert p["conv2"]["kernel"].shape == (5, 5, 6, 16)
    assert p["fc1"]["kernel"].shape == (400, 120)  # 16·5·5 = 400
    assert p["fc2"]["kernel"].shape == (120, 84)
    assert p["fc3"]["kernel"].shape == (84, 10)


@pytest.mark.parametrize("factory,expected_min", [(ResNet18, 11e6)])
def test_resnet18_forward(factory, expected_min):
    model = factory(num_classes=10)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    # CIFAR ResNet-18 ≈ 11.17M params.
    n = _param_count(variables["params"])
    assert expected_min < n < 12e6
    assert "batch_stats" in variables


def test_resnet50_builds():
    model = build_model("resnet50", num_classes=100)
    x = np.zeros((1, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 100)


def test_resnet50_train_step_tiny(mesh1):
    """One DP train step through the bottleneck blocks (BASELINE config 3's
    model): pins the 1x1-reduce/3x3/1x1-expand backward path, the
    shape-triggered projection shortcuts, and the zero-init residual BN
    scale under jit — at tiny widths so CPU compile stays fast. Forward
    alone (test_resnet50_builds) would miss a broken custom-VJP or
    BN-stat plumbing in the blocks."""
    from tpu_dp.data.cifar import make_synthetic, normalize
    from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

    mesh = mesh1
    model = build_model("resnet50", num_classes=100, num_filters=8)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(model, opt, mesh, constant_lr(0.1))
    ds = make_synthetic(8, 100, seed=0, name="r50")
    state, m = step(state, {"image": normalize(ds.images), "label": ds.labels})
    assert int(state.step) == 1
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) > 0
    assert int(m["count"]) == 8


def test_net_bf16_compute():
    model = Net(dtype=jnp.bfloat16)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    # Params stay f32; logits come back f32 (final dense computes in f32).
    kinds = {x.dtype for x in jax.tree_util.tree_leaves(variables["params"])}
    assert kinds == {np.dtype(np.float32)}
    assert model.apply(variables, x).dtype == jnp.float32


class TestFusedResNet:
    """Fused Pallas-block ResNet ≡ the standard one (tpu_dp/ops/conv_block).

    The fused model must be a pure execution-strategy change: identical
    parameter tree (checkpoint-interchangeable), bit-identical eval
    forward, train forward within bf16 rounding, and a working train step.
    """

    def _models(self, fused_stages, **kw):
        m0 = build_model("resnet18", num_classes=10, dtype=jnp.bfloat16, **kw)
        m1 = build_model("resnet18", num_classes=10, dtype=jnp.bfloat16,
                         fused_stages=fused_stages, fused_block_b=4, **kw)
        return m0, m1

    def test_param_trees_and_init_identical(self):
        m0, m1 = self._models((0,))
        x = np.zeros((2, 32, 32, 3), np.float32)
        v0 = m0.init(jax.random.PRNGKey(0), x, train=False)
        v1 = m1.init(jax.random.PRNGKey(0), x, train=False)
        assert (jax.tree_util.tree_structure(v0)
                == jax.tree_util.tree_structure(v1))
        assert jax.tree_util.tree_all(jax.tree_util.tree_map(
            lambda a, b: bool(jnp.array_equal(a, b)), v0, v1))

    @pytest.mark.parametrize("fused_stages", [(0,), (0, 1, 2, 3)])
    def test_forward_equivalence(self, fused_stages):
        m0, m1 = self._models(fused_stages)
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3),
                              jnp.float32)
        v = m0.init(jax.random.PRNGKey(0), x, train=False)
        # Eval mode: affine from running stats — must agree to bf16 exactness.
        ye0 = m0.apply(v, x, train=False)
        ye1 = m1.apply(v, x, train=False)
        np.testing.assert_allclose(np.asarray(ye0, np.float32),
                                   np.asarray(ye1, np.float32), atol=1e-6)
        # Train mode: batch-stats path, bf16-rounding-level agreement.
        y0, s0 = m0.apply(v, x, train=True, mutable=["batch_stats"])
        y1, s1 = m1.apply(v, x, train=True, mutable=["batch_stats"])
        scale = float(jnp.abs(y0).max()) + 1e-6
        np.testing.assert_allclose(np.asarray(y0, np.float32) / scale,
                                   np.asarray(y1, np.float32) / scale,
                                   atol=5e-3)
        for d in jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda a, b: float(jnp.abs(a - b).max()), s0, s1)):
            assert d < 5e-3

    def test_fused_bwd_grads_match_default(self):
        # fused_bwd changes only the backward execution path: gradients of
        # the same loss must agree with the XLA-backward fused model.
        from tpu_dp.train.step import cross_entropy_loss

        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3),
                              jnp.float32)
        labels = jnp.array([0, 1, 2, 3])
        kw = dict(num_classes=10, num_filters=16, dtype=jnp.bfloat16,
                  fused_stages=(0,), fused_block_b=2)
        m0 = build_model("resnet18", **kw)
        m1 = build_model("resnet18", fused_bwd=True, **kw)
        v = m0.init(jax.random.PRNGKey(0), x, train=False)

        def loss(model, params):
            out, _ = model.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return cross_entropy_loss(out, labels)

        g0 = jax.grad(lambda p: loss(m0, p))(v["params"])
        g1 = jax.grad(lambda p: loss(m1, p))(v["params"])
        for a, b in zip(jax.tree_util.tree_leaves(g0),
                        jax.tree_util.tree_leaves(g1)):
            m = float(jnp.abs(a).max()) + 1e-6
            np.testing.assert_allclose(np.asarray(a, np.float32) / m,
                                       np.asarray(b, np.float32) / m,
                                       atol=2e-2)

    def test_fused_train_step(self, mesh1):
        from tpu_dp.data.cifar import make_synthetic, normalize
        from tpu_dp.train import (
            SGD, constant_lr, create_train_state, make_train_step,
        )

        model = build_model("resnet18", num_classes=10, num_filters=64,
                            dtype=jnp.bfloat16, fused_stages=(0,),
                            fused_block_b=4)
        opt = SGD(momentum=0.9, weight_decay=5e-4)
        state = create_train_state(
            model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
            opt)
        step = make_train_step(model, opt, mesh1, constant_lr(0.1))
        ds = make_synthetic(8, 10, seed=0, name="fused")
        state, m = step(state, {"image": normalize(ds.images),
                                "label": ds.labels})
        assert int(state.step) == 1
        assert np.isfinite(float(m["loss"])) and float(m["loss"]) > 0

    def test_resnet50_fused_bottleneck_equivalence(self):
        """ResNet-50's stride-1 bottlenecks run their middle 3x3 on the
        kernel: same param tree (checkpoint-interchangeable), bit-identical
        eval forward, train forward within bf16 rounding."""
        kw = dict(num_classes=100, num_filters=16, dtype=jnp.bfloat16)
        m0 = build_model("resnet50", **kw)
        m1 = build_model("resnet50", fused_stages=(0, 1, 2, 3),
                         fused_block_b=2, **kw)
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32, 32, 3),
                              jnp.float32)
        v0 = m0.init(jax.random.PRNGKey(0), x, train=False)
        v1 = m1.init(jax.random.PRNGKey(0), x, train=False)
        assert (jax.tree_util.tree_structure(v0)
                == jax.tree_util.tree_structure(v1))
        assert jax.tree_util.tree_all(jax.tree_util.tree_map(
            lambda a, b: bool(jnp.array_equal(a, b)), v0, v1))
        ye0 = m0.apply(v0, x, train=False)
        ye1 = m1.apply(v0, x, train=False)
        np.testing.assert_allclose(np.asarray(ye0, np.float32),
                                   np.asarray(ye1, np.float32), atol=1e-6)
        y0, st0 = m0.apply(v0, x, train=True, mutable=["batch_stats"])
        y1, st1 = m1.apply(v0, x, train=True, mutable=["batch_stats"])
        s = float(jnp.abs(y0).max()) + 1e-6
        np.testing.assert_allclose(np.asarray(y0, np.float32) / s,
                                   np.asarray(y1, np.float32) / s,
                                   atol=5e-3)
        # Running-stat updates (incl. BatchNorm_1 fed by kernel-emitted
        # moments) must track the unfused model too.
        for d in jax.tree_util.tree_leaves(jax.tree_util.tree_map(
                lambda a, b: float(jnp.abs(a - b).max()), st0, st1)):
            assert d < 5e-3

    def test_parse_fused_stages(self):
        from tpu_dp.models import parse_fused_stages

        assert parse_fused_stages("") == ()
        assert parse_fused_stages(None) == ()
        assert parse_fused_stages("all") == (0, 1, 2, 3)
        assert parse_fused_stages("0") == (0,)
        assert parse_fused_stages("2,0") == (0, 2)
        with pytest.raises(ValueError):
            parse_fused_stages("one")

    def test_fused_shard_map_step_matches_gspmd(self, mesh8):
        """Both distributed statements of the fused model agree: the
        explicit shard_map step (per-shard kernel + lax.pmean) and the
        GSPMD step (custom_partitioning shards the batch dim)."""
        from tpu_dp.data.cifar import make_synthetic, normalize
        from tpu_dp.parallel import dist
        from tpu_dp.train import (
            SGD, constant_lr, create_train_state, make_train_step,
            make_train_step_shard_map,
        )

        opt = SGD(momentum=0.9)
        ds = make_synthetic(16, 10, seed=0, name="fused_sm")
        batch = {"image": normalize(ds.images), "label": ds.labels}
        x0 = np.zeros((1, 32, 32, 3), np.float32)

        mf = build_model("resnet18", num_classes=10, dtype=jnp.bfloat16,
                         fused_stages=(0,), fused_block_b=2,
                         axis_name=dist.DATA_AXIS)
        sf = create_train_state(mf, jax.random.PRNGKey(0), x0, opt)
        _, m_sm = make_train_step_shard_map(mf, opt, mesh8, constant_lr(0.1))(
            sf, dict(batch))

        mg = build_model("resnet18", num_classes=10, dtype=jnp.bfloat16,
                         fused_stages=(0,), fused_block_b=2)
        sg = create_train_state(mg, jax.random.PRNGKey(0), x0, opt)
        _, m_g = make_train_step(mg, opt, mesh8, constant_lr(0.1))(
            sg, dict(batch))

        # rel 2e-4 (~3x the observed 7e-5), not exactness: the two programs
        # differ structurally (shard_map's interpret fallback runs the
        # unfused XLA statement, GSPMD runs the emit kernel), so XLA may
        # reassociate the f32 BN-stat reductions differently — compile-order
        # rounding, verified bit-identical in eager forward.
        assert float(m_sm["loss"]) == pytest.approx(float(m_g["loss"]),
                                                    rel=2e-4)

    def test_checkpoint_interchangeable_unfused_to_fused(self, tmp_path,
                                                         mesh1):
        """The interchangeability claim end to end: a checkpoint saved from
        an UNFUSED run restores into a FUSED model (and trains a step) —
        the fused path is an execution strategy, not a different model."""
        from tpu_dp.checkpoint import load_checkpoint, save_checkpoint
        from tpu_dp.data.cifar import make_synthetic, normalize
        from tpu_dp.train import (
            SGD, constant_lr, create_train_state, make_train_step,
        )

        mesh = mesh1
        opt = SGD(momentum=0.9)
        x0 = np.zeros((1, 32, 32, 3), np.float32)
        ds = make_synthetic(8, 10, seed=0, name="ckpt_x")
        batch = {"image": normalize(ds.images), "label": ds.labels}

        m0 = build_model("resnet18", num_classes=10, num_filters=16,
                         dtype=jnp.bfloat16)
        s0 = create_train_state(m0, jax.random.PRNGKey(0), x0, opt)
        s0, _ = make_train_step(m0, opt, mesh, constant_lr(0.1))(
            s0, dict(batch))
        save_checkpoint(tmp_path, s0, {"step": 1})

        m1 = build_model("resnet18", num_classes=10, num_filters=16,
                         dtype=jnp.bfloat16, fused_stages=(0,),
                         fused_block_b=2)
        s1 = create_train_state(m1, jax.random.PRNGKey(7), x0, opt)
        restored, meta = load_checkpoint(tmp_path, s1)
        assert meta["step"] == 1
        # Bit-identical restore of the unfused run's FULL state (params,
        # momentum buffers, batch_stats, step) into the fused model's tree.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                       np.asarray(b)),
            restored, jax.device_get(s0))
        # ...and the fused model trains from it.
        restored = jax.tree_util.tree_map(jnp.asarray, restored)
        s2, metrics = make_train_step(m1, opt, mesh, constant_lr(0.1))(
            restored, dict(batch))
        assert int(s2.step) == 2
        assert np.isfinite(float(metrics["loss"]))

    def test_resnet50_fused_train_step_mesh8(self, mesh8):
        """Fused bottlenecks under the 8-device GSPMD mesh: the kernel's
        partitioning (incl. the stats psum) must compose with the sharded
        train step."""
        from tpu_dp.data.cifar import make_synthetic, normalize
        from tpu_dp.train import (
            SGD, constant_lr, create_train_state, make_train_step,
        )

        model = build_model("resnet50", num_classes=100, num_filters=8,
                            dtype=jnp.bfloat16, fused_stages=(0,),
                            fused_block_b=2)
        opt = SGD(momentum=0.9)
        state = create_train_state(
            model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3),
                                                   np.float32), opt)
        step = make_train_step(model, opt, mesh8, constant_lr(0.1))
        ds = make_synthetic(16, 100, seed=0, name="r50_mesh")
        state, m = step(state, {"image": normalize(ds.images),
                                "label": ds.labels})
        assert int(state.step) == 1
        assert np.isfinite(float(m["loss"])) and float(m["loss"]) > 0
        assert int(m["count"]) == 16

    @pytest.mark.slow
    def test_fused_training_trajectory_tracks_unfused(self, mesh1):
        """24 optimizer steps, same data order: the fused-all model's loss
        trajectory must track the unfused one closely at every step — a
        slow-bias bug (e.g. subtly wrong kernel-emitted stat normalization)
        would compound here while staying invisible to single-step tests."""
        from tpu_dp.data.cifar import make_synthetic, normalize
        from tpu_dp.train import (
            SGD, constant_lr, create_train_state, make_train_step,
        )

        opt = SGD(momentum=0.9)
        ds = make_synthetic(256, 10, seed=0, name="traj")
        imgs = normalize(ds.images)
        labels = ds.labels
        x0 = np.zeros((1, 32, 32, 3), np.float32)

        def run(fused):
            kw = dict(num_classes=10, num_filters=16, dtype=jnp.bfloat16)
            if fused:
                kw.update(fused_stages=(0, 1, 2, 3))
            m = build_model("resnet18", **kw)
            s = create_train_state(m, jax.random.PRNGKey(0), x0, opt)
            step = make_train_step(m, opt, mesh1, constant_lr(0.05))
            losses = []
            for i in range(24):
                lo = (i * 32) % 256
                s, met = step(s, {"image": imgs[lo:lo + 32],
                                  "label": labels[lo:lo + 32]})
                losses.append(float(met["loss"]))
            return losses

        l0 = run(False)
        l1 = run(True)
        assert l0[-1] < 0.5 and l1[-1] < 0.5  # both actually converge
        for i, (a, b) in enumerate(zip(l0, l1)):
            assert abs(a - b) < 0.05, f"step {i}: {a} vs {b}"
