"""Model unit tests — shapes and parameter counts vs the reference spec.

SURVEY.md §4 Unit: "model forward shapes/param counts vs `Net` spec
(`cifar_example.py:20-25`: conv 3→6→16, fc 400→120→84→10)".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.models import Net, ResNet18, ResNet50, build_model


def _param_count(params):
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def test_net_output_shape_and_param_count():
    model = Net()
    x = np.zeros((4, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x)
    assert logits.shape == (4, 10)
    # Exact torch parity: conv1 456 + conv2 2416 + fc1 48120 + fc2 10164
    # + fc3 850 = 62006 (`cifar_example.py:20-25`).
    assert _param_count(variables["params"]) == 62_006


def test_net_layer_shapes():
    model = Net()
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    p = variables["params"]
    assert p["conv1"]["kernel"].shape == (5, 5, 3, 6)
    assert p["conv2"]["kernel"].shape == (5, 5, 6, 16)
    assert p["fc1"]["kernel"].shape == (400, 120)  # 16·5·5 = 400
    assert p["fc2"]["kernel"].shape == (120, 84)
    assert p["fc3"]["kernel"].shape == (84, 10)


@pytest.mark.parametrize("factory,expected_min", [(ResNet18, 11e6)])
def test_resnet18_forward(factory, expected_min):
    model = factory(num_classes=10)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    # CIFAR ResNet-18 ≈ 11.17M params.
    n = _param_count(variables["params"])
    assert expected_min < n < 12e6
    assert "batch_stats" in variables


def test_resnet50_builds():
    model = build_model("resnet50", num_classes=100)
    x = np.zeros((1, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 100)


def test_resnet50_train_step_tiny(mesh1):
    """One DP train step through the bottleneck blocks (BASELINE config 3's
    model): pins the 1x1-reduce/3x3/1x1-expand backward path, the
    shape-triggered projection shortcuts, and the zero-init residual BN
    scale under jit — at tiny widths so CPU compile stays fast. Forward
    alone (test_resnet50_builds) would miss a broken custom-VJP or
    BN-stat plumbing in the blocks."""
    from tpu_dp.data.cifar import make_synthetic, normalize
    from tpu_dp.train import SGD, constant_lr, create_train_state, make_train_step

    mesh = mesh1
    model = build_model("resnet50", num_classes=100, num_filters=8)
    opt = SGD(momentum=0.9, weight_decay=5e-4)
    state = create_train_state(
        model, jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32), opt
    )
    step = make_train_step(model, opt, mesh, constant_lr(0.1))
    ds = make_synthetic(8, 100, seed=0, name="r50")
    state, m = step(state, {"image": normalize(ds.images), "label": ds.labels})
    assert int(state.step) == 1
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) > 0
    assert int(m["count"]) == 8


def test_net_bf16_compute():
    model = Net(dtype=jnp.bfloat16)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    # Params stay f32; logits come back f32 (final dense computes in f32).
    kinds = {x.dtype for x in jax.tree_util.tree_leaves(variables["params"])}
    assert kinds == {np.dtype(np.float32)}
    assert model.apply(variables, x).dtype == jnp.float32
