"""Live efficiency accounting (`tpu_dp.obs.costs`, ISSUE 9).

The acceptance property: the trainer's live ``obs.mfu`` / ``obs.goodput``
gauges are computed from the SAME cost registry — and, with
``obs.measure_flops``, from the same XLA cost analysis of the same
compiled program — as bench.py's offline MFU, tolerance-checked here so
the two can never drift. Plus the registry/meter units and the serve
engine's per-bucket utilization from the shared registry.
"""

import json

import numpy as np
import pytest

from tpu_dp.obs import costs

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _fresh_registry():
    costs.registry.reset()
    yield
    costs.registry.reset()


# -- registry / resolver units ---------------------------------------------

def test_registry_measured_outranks_analytic():
    r = costs.CostRegistry()
    r.register("train_step", 1e9, source="w1_step_cost_analysis",
               check="ok")
    kept = r.register("train_step", 5e9, source="analytic")
    assert kept.flops_per_step_per_chip == 1e9  # analytic cannot demote
    upgraded = r.register("train_step", 2e9,
                          source="w1_step_cost_analysis", check="ok")
    assert upgraded.flops_per_step_per_chip == 2e9  # measured replaces


def test_registry_alias_shares_cost_and_mfu():
    r = costs.CostRegistry()
    r.register("train_step", 4e9, source="analytic")
    assert r.alias("multi_step", "train_step").tag == "multi_step"
    assert r.alias("missing_alias", "no_such_tag") is None
    # 4e9 FLOPs x 10 steps / 2 s / 1e12 peak = 0.02
    assert r.mfu("multi_step", 10, 2.0, 1e12) == pytest.approx(0.02)
    assert r.mfu("multi_step", 10, 2.0, None) is None
    assert r.mfu("unknown", 10, 2.0, 1e12) is None


def test_register_analytic_known_and_unknown_models():
    r = costs.CostRegistry()
    cost = r.register_analytic("train_step", "resnet18", 128)
    assert cost.flops_per_step_per_chip == pytest.approx(3.0e9 * 128)
    assert r.register_analytic("other", "made_up_model", 128) is None


def test_resolve_without_analytic_yardstick():
    # The ambiguity-free w1 reading resolves, marked unchecked.
    f, src, check = costs.resolve_flops_per_step(None, 7e9, 1, 64, None)
    assert (f, src, check) == (7e9, "w1_step_cost_analysis", "unchecked")
    # A scan program without a yardstick falls back to the body reading.
    f, src, check = costs.resolve_flops_per_step(9e9, None, 30, 64, None)
    assert (f, src, check) == (9e9, "scan_cost_analysis_body", "unchecked")
    # Nothing at all: explicitly unavailable, never a fabricated number.
    f, src, check = costs.resolve_flops_per_step(None, None, 1, 64, None)
    assert (f, src, check) == (None, "unavailable", "unavailable")


def test_resolve_with_yardstick_matches_bench_semantics():
    # Same contract test_bench pins on the bench re-exports; here against
    # the source module directly.
    f, src, check = costs.resolve_flops_per_step(None, 3.1e9 * 64, 1, 64,
                                                 3.0e9)
    assert src == "w1_step_cost_analysis" and check == "ok"
    f, src, check = costs.resolve_flops_per_step(3.0e9 * 64 * 30, None, 30,
                                                 64, 3.0e9)
    assert src == "scan_cost_analysis_divided"
    assert f == pytest.approx(3.0e9 * 64)


def test_goodput_bounds_and_serve_flops():
    assert costs.goodput(0.0, 100.0) == 1.0
    assert costs.goodput(25.0, 100.0) == pytest.approx(0.75)
    assert costs.goodput(200.0, 100.0) == 0.0  # clamped, never negative
    assert costs.goodput(1.0, 0.0) == 0.0
    assert costs.serve_flops_per_image("resnet18") == pytest.approx(1e9)
    assert costs.serve_flops_per_image("nope") is None


def test_efficiency_meter_weighted_rollup():
    r = costs.CostRegistry()
    r.register("train_step", 1e9, source="analytic")
    m = costs.EfficiencyMeter(r, peak=1e12)
    first = m.observe("train_step", 1, 10.0, 1.0)   # 10 ms step, gp 0.9
    assert first["goodput"] == pytest.approx(0.9)
    assert first["mfu"] == pytest.approx(1e9 / 10e-3 / 1e12, rel=1e-3)
    m.observe("train_step", 3, 30.0, 0.0)           # 3 steps @10ms, gp 1.0
    roll = m.rollup()
    assert roll["steps"] == 4 and roll["windows"] == 2
    # goodput is step-weighted: (0.9*1 + 1.0*3) / 4
    assert roll["goodput"] == pytest.approx(0.975)
    assert roll["step_time_ms"]["max"] == pytest.approx(10.0)
    assert "mfu" in roll
    empty = costs.EfficiencyMeter(r, peak=None)
    assert empty.rollup() is None
    no_peak = empty.observe("train_step", 1, 10.0, 0.0)
    assert "mfu" not in no_peak  # absence, never a wrong number


def test_bench_reexports_are_the_costs_module():
    """bench.py must stay a re-export, not a fork (single source of
    truth — the satellite contract)."""
    import bench

    assert bench.peak_flops is costs.peak_flops
    assert bench.resolve_flops_per_step is costs.resolve_flops_per_step
    assert bench.FLOPS_CHECK_RTOL == costs.FLOPS_CHECK_RTOL
    assert bench.PEAK_FLOPS_BY_KIND is costs.PEAK_FLOPS_BY_KIND
    assert bench.MODEL_SPECS["resnet18"][0] == (
        costs.MODEL_TRAIN_FLOPS_PER_IMAGE["resnet18"]
    )


# -- trainer live gauges vs bench's computation (the acceptance) -----------

def _cfg(tmp_path, **overrides):
    from tpu_dp.config import Config

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 64
    c.data.synthetic_test_size = 16
    c.data.batch_size = 16
    c.data.prefetch = 1
    c.train.epochs = 1
    c.train.log_every = 2
    c.train.eval_at_end = False
    c.train.ckpt_dir = str(tmp_path / "ck")
    for k, v in overrides.items():
        section, field = k.split(".")
        setattr(getattr(c, section), field, v)
    return c


def test_trainer_mfu_agrees_with_bench_computation(tmp_path):
    """Live ``obs.mfu`` on the 8-device CPU smoke vs bench.py's offline
    computation FROM THE SAME PROGRAM: ``obs.measure_flops`` registers
    the XLA cost analysis of the trainer's own compiled step; bench's
    `compile_with_flops` + `resolve_flops_per_step` over that identical
    program must land on the identical flops-per-step, and the published
    mfu/step-time gauges must satisfy mfu = flops / step_time / peak."""
    import bench
    from tpu_dp.obs.counters import counters
    from tpu_dp.train.trainer import Trainer

    # Small peak => O(0.1) mfu values, so 4-decimal gauge rounding is
    # far below the 2% comparison slack.
    peak = 1e9
    cfg = _cfg(tmp_path, **{"train.obs": "full",
                            "obs.measure_flops": True,
                            "obs.peak_flops_override": peak})
    tr = Trainer(cfg)
    cost = costs.registry.get("train_step")
    assert cost is not None and cost.source == "w1_step_cost_analysis"

    # bench's computation, same program, same helpers.
    _, step_flops, _ = bench.compile_with_flops(
        tr.train_step, *tr._step_arg_structs()
    )
    per_chip = tr.global_batch_size / tr.num_devices
    resolved, source, _ = bench.resolve_flops_per_step(
        None, step_flops, 1, per_chip, None
    )
    assert source == "w1_step_cost_analysis"
    assert resolved == pytest.approx(cost.flops_per_step_per_chip)

    tr.fit()
    snap = counters.snapshot()
    mfu = snap.get("obs.mfu")
    step_ms = snap.get("obs.step_time_ms")
    assert mfu is not None and mfu > 0
    assert snap.get("obs.goodput") is not None
    assert snap.get("obs.flops_per_step_per_chip") == pytest.approx(
        cost.flops_per_step_per_chip
    )
    # Internal consistency of the published window: the three gauges are
    # one equation (rounding is the only slack).
    assert mfu == pytest.approx(
        cost.flops_per_step_per_chip / (step_ms / 1e3) / peak, rel=0.02
    )
    # The schema-3 records carry the same signals, and the epoch record's
    # efficiency rollup brackets the per-step values.
    records = [json.loads(l) for l in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    per_step = [r for r in records if "spans" in r and "epoch" not in r]
    assert per_step and all(r["schema"] == 3 for r in records)
    assert all("goodput" in r and "mfu" in r for r in per_step)
    epoch_rec = next(r for r in records if "epoch" in r)
    eff = epoch_rec["efficiency"]
    step_mfus = [r["mfu"] for r in per_step]
    assert min(step_mfus) <= eff["mfu"] <= max(step_mfus)
    assert eff["steps"] == len(per_step)


def test_trainer_without_cost_publishes_no_mfu(tmp_path):
    """Unknown model, no measurement: goodput/step-time still publish,
    MFU is ABSENT (never fabricated) — same absence-over-zero principle
    as the memory gauges."""
    from tpu_dp.obs.counters import counters
    from tpu_dp.train.trainer import Trainer

    counters.reset()
    cfg = _cfg(tmp_path, **{"train.obs": "full",
                            "obs.peak_flops_override": 1e12})
    tr = Trainer(cfg)
    tr.fit()
    snap = counters.snapshot()
    assert "obs.mfu" not in snap
    assert snap.get("obs.goodput") is not None
    records = [json.loads(l) for l in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    per_step = [r for r in records if "spans" in r and "epoch" not in r]
    assert per_step and all("mfu" not in r for r in per_step)
    assert all("goodput" in r for r in per_step)


# -- serve: per-bucket utilization from the same registry ------------------

def test_serve_engine_publishes_bucket_utilization():
    import jax

    from tpu_dp.models import build_model
    from tpu_dp.obs.counters import Counters
    from tpu_dp.serve import InferenceEngine

    model = build_model("net")
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    reg = Counters()
    engine = InferenceEngine(
        model, variables["params"], buckets=(1, 2),
        slo_ms=5000.0, max_wait_ms=1.0,
        flops_per_image=1e6, peak_flops=1e12, registry=reg,
    )
    # Registered per bucket in the SHARED cost registry (the trainer's).
    assert costs.registry.get("serve_step@b1") is not None
    assert costs.registry.get("serve_step@b2") is not None
    with engine:
        h = engine.submit(np.zeros((1, 32, 32, 3), np.uint8))
        h.wait(timeout=30)
    snap = reg.snapshot()
    assert snap.get("serve.device_util.b1", 0) > 0
    assert snap.get("serve.device_util", 0) > 0
    assert engine.report()["device_util"] == snap["serve.device_util"]


def test_serve_engine_unknown_model_publishes_no_utilization():
    import jax

    from tpu_dp.models import build_model
    from tpu_dp.obs.counters import Counters
    from tpu_dp.serve import InferenceEngine

    model = build_model("net")
    variables = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        train=False,
    )
    reg = Counters()
    engine = InferenceEngine(
        model, variables["params"], buckets=(1,),
        slo_ms=5000.0, max_wait_ms=1.0, registry=reg,
    )
    with engine:
        engine.submit(np.zeros((1, 32, 32, 3), np.uint8)).wait(timeout=30)
    assert "serve.device_util" not in reg.snapshot()
