"""Cross-replica sharded weight update (`train.update_sharding=sharded`).

The correctness property of the sharded update (Xu et al., "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" —
PAPERS.md; docs/PERF.md): reduce-scatter(grads) → 1/world optimizer update →
all-gather(params) is *the same computation* as all-reduce(grads) → full
replicated update, element for element — so for f32 SGD the two paths must
produce **bitwise-identical** parameter trajectories, including momentum
state, across gradient accumulation and leaves whose element counts do not
divide the mesh (`Net`'s f32[5,5,3,6] on 8 devices pads 450 → 456).

Around that headline property: the collective wrappers' pad/unpad round
trip, the ~1/world optimizer-state memory claim, the windowed and
device-resident sharded loops, checkpoint resharding across topology/mode
changes, the EQuARX-style bf16 wire knob, factory validation, and
end-to-end Trainer parity.

Fast lane: ``pytest -m shard_update``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import Net
from tpu_dp.parallel import collectives
from tpu_dp.train import (
    SGD,
    ShardedUpdate,
    constant_lr,
    create_train_state,
    make_train_step,
    make_train_step_shard_map,
    shard_optimizer,
)

pytestmark = pytest.mark.shard_update

WORLD = 8


def _make_batch(seed, n):
    ds = make_synthetic(n, 10, seed=seed, name="synthetic")
    return {"image": normalize(ds.images), "label": ds.labels}


def _copy(state):
    return jax.tree_util.tree_map(jnp.array, state)


def _sample():
    return np.zeros((1, 32, 32, 3), np.float32)


def _states(momentum=0.9):
    model = Net()
    opt = SGD(momentum=momentum)
    sopt = shard_optimizer(SGD(momentum=momentum), WORLD)
    rng = jax.random.PRNGKey(0)
    state_r = create_train_state(model, rng, _sample(), opt)
    state_s = create_train_state(model, rng, _sample(), sopt)
    return model, opt, sopt, state_r, state_s


def _gathered_opt(sharded_opt_state, replicated_opt_state):
    """Sharded opt leaves (flat, padded) trimmed onto the replicated shapes."""
    return jax.tree_util.tree_map(
        lambda s, r: np.asarray(s)[: r.size].reshape(r.shape),
        sharded_opt_state, replicated_opt_state,
    )


# --------------------------------------------------------------------------
# collective wrappers: pad/unpad round trip
# --------------------------------------------------------------------------

def test_psum_scatter_all_gather_is_bitwise_pmean(mesh8):
    """all_gather(psum_scatter(t, mean=True), t) == pmean(t), bitwise,
    including leaves that do not divide the world size."""
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS
    from tpu_dp.train.step import _shard_map

    tree = {
        "odd": jnp.asarray(
            np.random.default_rng(0).normal(size=(5, 5, 3, 6)).astype(np.float32)
        ),  # 450 elements: pads to 456 on 8 devices
        "even": jnp.asarray(
            np.random.default_rng(1).normal(size=(16,)).astype(np.float32)
        ),
        "tiny": jnp.asarray(np.float32([3.0])),  # 1 element: pads to 8
    }

    def via_scatter(t):
        shards = collectives.psum_scatter(t, DATA_AXIS, world=WORLD, mean=True)
        return collectives.all_gather(shards, t, DATA_AXIS)

    def via_pmean(t):
        return collectives.pmean(t, DATA_AXIS)

    args = jax.tree_util.tree_map(
        lambda x: jnp.stack([x * (i + 1) for i in range(WORLD)]), tree
    )
    spec_in, spec_out = (P(DATA_AXIS),), P()
    f_s = jax.jit(_shard_map(via_scatter, mesh8, spec_in, spec_out))
    f_p = jax.jit(_shard_map(via_pmean, mesh8, spec_in, spec_out))
    out_s, out_p = f_s(args), f_p(args)
    for a, b in zip(jax.tree_util.tree_leaves(out_s),
                    jax.tree_util.tree_leaves(out_p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_shard_slice_matches_scatter_layout(mesh8):
    """shard_slice hands replica i exactly the slice psum_scatter would:
    gathering the slices reconstructs the original leaf."""
    from jax.sharding import PartitionSpec as P

    from tpu_dp.parallel.dist import DATA_AXIS
    from tpu_dp.train.step import _shard_map

    x = jnp.arange(450, dtype=jnp.float32).reshape(5, 90)

    def roundtrip(v):
        shards = collectives.shard_slice(v, DATA_AXIS, world=WORLD)
        return collectives.all_gather(shards, v, DATA_AXIS)

    f = jax.jit(_shard_map(roundtrip, mesh8, (P(),), P()))
    np.testing.assert_array_equal(np.asarray(f(x)), np.asarray(x))


def test_padded_and_shard_size():
    assert collectives.padded_size(450, 8) == 456
    assert collectives.shard_size(450, 8) == 57
    assert collectives.padded_size(16, 8) == 16
    assert collectives.shard_size(1, 8) == 1


# --------------------------------------------------------------------------
# the headline parity property
# --------------------------------------------------------------------------

@pytest.mark.parametrize("accum_steps", [1, 4])
def test_sharded_update_bitwise_matches_replicated(mesh8, accum_steps):
    """f32 SGD: sharded and replicated updates are the same computation —
    params AND momentum bitwise-identical over a multi-step trajectory,
    accum ∈ {1,4}, with non-divisible leaf sizes (Net on 8 devices)."""
    model, opt, sopt, state_r, state_s = _states()
    step_r = make_train_step_shard_map(model, opt, mesh8, constant_lr(0.05),
                                       accum_steps=accum_steps)
    step_s = make_train_step_shard_map(model, sopt, mesh8, constant_lr(0.05),
                                       accum_steps=accum_steps,
                                       update_sharding="sharded")
    sr, ss = _copy(state_r), _copy(state_s)
    n = 16 * accum_steps
    for i in range(3):
        flat = _make_batch(i, n)
        if accum_steps > 1:
            batch = {
                "image": flat["image"].reshape(accum_steps, 16, 32, 32, 3),
                "label": flat["label"].reshape(accum_steps, 16),
            }
        else:
            batch = flat
        sr, mr = step_r(sr, batch)
        ss, ms = step_s(ss, batch)
        assert float(mr["loss"]) == float(ms["loss"])
        assert int(mr["correct"]) == int(ms["correct"])
        assert int(mr["count"]) == int(ms["count"])
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(ss.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(sr.opt_state),
        jax.tree_util.tree_leaves(_gathered_opt(ss.opt_state, sr.opt_state)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_weight_decay_and_exclusion_bitwise(mesh8):
    """Weight decay — including the path-keyed bias/scale exclusion mask —
    works unchanged on shard trees (the shard layout preserves key paths),
    bitwise vs the replicated update."""
    model = Net()
    kw = dict(momentum=0.9, weight_decay=5e-4,
              decay_exclude_bias_and_norm=True)
    opt = SGD(**kw)
    sopt = shard_optimizer(SGD(**kw), WORLD)
    rng = jax.random.PRNGKey(0)
    state_r = create_train_state(model, rng, _sample(), opt)
    state_s = create_train_state(model, rng, _sample(), sopt)
    step_r = make_train_step_shard_map(model, opt, mesh8, constant_lr(0.05))
    step_s = make_train_step_shard_map(model, sopt, mesh8, constant_lr(0.05),
                                       update_sharding="sharded")
    sr, ss = _copy(state_r), _copy(state_s)
    for i in range(2):
        batch = _make_batch(i, 16)
        sr, _ = step_r(sr, batch)
        ss, _ = step_s(ss, batch)
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(ss.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gspmd_factory_rejects_sharded_optimizer(mesh8):
    sopt = shard_optimizer(SGD(momentum=0.9), WORLD)
    with pytest.raises(ValueError, match="incompatible"):
        make_train_step(Net(), sopt, mesh8, constant_lr(0.05))


def test_sharded_matches_gspmd_path(mesh8):
    """Sharded explicit-collectives path vs the GSPMD-inferred path: the
    two ends of the implementation spectrum agree bitwise for f32 SGD."""
    model, opt, sopt, state_r, state_s = _states()
    step_g = make_train_step(model, opt, mesh8, constant_lr(0.05))
    step_s = make_train_step_shard_map(model, sopt, mesh8, constant_lr(0.05),
                                       update_sharding="sharded")
    sg, ss = _copy(state_r), _copy(state_s)
    for i in range(3):
        batch = _make_batch(i, 16)
        sg, _ = step_g(sg, batch)
        ss, _ = step_s(ss, batch)
    for a, b in zip(jax.tree_util.tree_leaves(sg.params),
                    jax.tree_util.tree_leaves(ss.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_opt_state_memory_is_one_over_world(mesh8):
    """The memory claim: every optimizer-state leaf is laid out flat over
    the data axis — per-replica shard = padded_size/world elements, ~1/world
    of the replicated layout (exactly 1/world + padding)."""
    _, opt, sopt, state_r, state_s = _states()
    repl_leaves = jax.tree_util.tree_leaves(state_r.opt_state)
    shard_leaves = jax.tree_util.tree_leaves(state_s.opt_state)
    assert len(repl_leaves) == len(shard_leaves)
    repl_elems = sum(x.size for x in repl_leaves)
    per_replica = 0
    for r, s in zip(repl_leaves, shard_leaves):
        assert s.ndim == 1
        assert s.size == collectives.padded_size(r.size, WORLD)
        per_replica += s.size // WORLD

    # Laid onto the mesh by the step's in_shardings, each device addresses
    # exactly its shard.
    step_s = make_train_step_shard_map(Net(), sopt, mesh8, constant_lr(0.05),
                                       update_sharding="sharded")
    new_state, _ = step_s(_copy(state_s), _make_batch(0, 16))
    for r, leaf in zip(repl_leaves,
                       jax.tree_util.tree_leaves(new_state.opt_state)):
        shards = leaf.addressable_shards
        assert len(shards) == WORLD
        assert shards[0].data.size == collectives.shard_size(r.size, WORLD)
    assert per_replica <= repl_elems // WORLD + len(repl_leaves)  # pad slack


def test_bf16_collective_dtype_close_to_f32(mesh8):
    """EQuARX-style wire compression: bf16 reduce-scatter tracks the f32
    trajectory within bf16 tolerance (and is NOT bitwise — it really ran
    through the compressed path)."""
    model, opt, sopt, state_r, state_s = _states()
    step_r = make_train_step_shard_map(model, opt, mesh8, constant_lr(0.05))
    step_b = make_train_step_shard_map(model, sopt, mesh8, constant_lr(0.05),
                                       update_sharding="sharded",
                                       collective_dtype="bf16")
    sr, sb = _copy(state_r), _copy(state_s)
    for i in range(2):
        batch = _make_batch(i, 16)
        sr, _ = step_r(sr, batch)
        sb, _ = step_b(sb, batch)
    identical = True
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(sb.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0.02, atol=2e-3)
        identical &= bool(np.array_equal(np.asarray(a), np.asarray(b)))
    assert not identical, "bf16 wire dtype produced bitwise-f32 results?"


# --------------------------------------------------------------------------
# windowed + device-resident sharded loops
# --------------------------------------------------------------------------

def test_sharded_multi_step_matches_replicated_multi_step(mesh8):
    """The windowed sharded loop vs the windowed replicated loop: the
    headline bitwise property holds inside the scanned dispatch too (the
    scan-vs-host-loop comparison itself is only ulp-close — XLA fuses scan
    bodies differently — and is already covered for the shared body by
    test_step.test_scanned_multi_step_matches_host_loop)."""
    from tpu_dp.train import make_multi_step

    model, opt, sopt, state_r, state_s = _states()
    K, n = 4, 16
    sched = constant_lr(0.05)
    loop_r = make_multi_step(model, opt, mesh8, sched, num_steps=K)
    loop_s = make_multi_step(model, sopt, mesh8, sched, num_steps=K,
                             update_sharding="sharded")
    batches = [_make_batch(100 + i, n) for i in range(K)]
    pool = {
        "image": np.stack([b["image"] for b in batches]),
        "label": np.stack([b["label"] for b in batches]),
    }
    sr, mr = loop_r(_copy(state_r), pool)
    ss, ms = loop_s(_copy(state_s), pool)
    assert int(sr.step) == int(ss.step) == K
    np.testing.assert_array_equal(np.asarray(mr["loss"]),
                                  np.asarray(ms["loss"]))
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(ss.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(
        jax.tree_util.tree_leaves(sr.opt_state),
        jax.tree_util.tree_leaves(_gathered_opt(ss.opt_state, sr.opt_state)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_resident_loop_matches_replicated(mesh8):
    """Device-resident feed + sharded update ≡ resident feed + replicated
    update: the feed redesign and the update redesign compose."""
    from tpu_dp.parallel.sharding import replicated_sharding, shard_batch
    from tpu_dp.train.step import make_multi_step_resident

    model, opt, sopt, state_r, state_s = _states()
    K, n = 3, 16
    sched = constant_lr(0.05)
    ds = make_synthetic(K * n, 10, seed=7, name="res")
    data = shard_batch({"image": ds.images, "label": ds.labels}, mesh8,
                       spec=replicated_sharding(mesh8))
    idx = np.arange(K * n, dtype=np.int32).reshape(K, n)

    loop_r = make_multi_step_resident(model, opt, mesh8, sched, num_steps=K)
    loop_s = make_multi_step_resident(model, sopt, mesh8, sched, num_steps=K,
                                      update_sharding="sharded")
    sr, _ = loop_r(_copy(state_r), data, idx)
    ss, _ = loop_s(_copy(state_s), data, idx)
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(ss.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------------
# checkpoint resharding: topology & mode changes
# --------------------------------------------------------------------------

def test_checkpoint_reshards_across_world_sizes(tmp_path):
    """A sharded checkpoint written under world=8 restores into a world=4
    layout (and back), values preserved — preemption on one topology,
    resume on another."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model = Net()
    rng = jax.random.PRNGKey(0)
    opt8 = shard_optimizer(SGD(momentum=0.9), 8)
    opt4 = shard_optimizer(SGD(momentum=0.9), 4)
    state8 = create_train_state(model, rng, _sample(), opt8)
    # Fill momentum with recognizable values (init is zeros everywhere) —
    # keeping the padding region zero, as any real trajectory does (padded
    # grads are zero, so padded momentum stays zero).
    true_sizes = [p.size for p in jax.tree_util.tree_leaves(state8.params)]
    state8 = state8.replace(opt_state=jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(state8.opt_state),
        [
            jnp.where(jnp.arange(s.size) < n,
                      jnp.arange(s.size, dtype=s.dtype) + 1.0, 0.0)
            for s, n in zip(jax.tree_util.tree_leaves(state8.opt_state),
                            true_sizes)
        ],
    ))
    save_checkpoint(tmp_path / "w8", state8, {"epoch": 0})

    target4 = create_train_state(model, rng, _sample(), opt4)
    restored4, _ = load_checkpoint(tmp_path / "w8", target4)
    for s8, s4, p in zip(
        jax.tree_util.tree_leaves(state8.opt_state),
        jax.tree_util.tree_leaves(restored4.opt_state),
        jax.tree_util.tree_leaves(state8.params),
    ):
        n = p.size
        assert s4.size == collectives.padded_size(n, 4)
        # True elements preserved; any new tail is zero padding.
        np.testing.assert_array_equal(np.asarray(s4)[:n], np.asarray(s8)[:n])
        np.testing.assert_array_equal(np.asarray(s4)[n:], 0)


def test_checkpoint_reshards_across_update_modes(tmp_path):
    """replicated ↔ sharded transitions restore value-preserving: a run can
    turn the sharded update on (or off) at a checkpoint boundary."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model = Net()
    rng = jax.random.PRNGKey(0)
    opt = SGD(momentum=0.9)
    sopt = shard_optimizer(SGD(momentum=0.9), 8)
    state_r = create_train_state(model, rng, _sample(), opt)
    state_r = state_r.replace(opt_state=jax.tree_util.tree_map(
        lambda s: jnp.arange(s.size, dtype=s.dtype).reshape(s.shape),
        state_r.opt_state,
    ))
    save_checkpoint(tmp_path / "repl", state_r, {"epoch": 0})

    # replicated → sharded
    target_s = create_train_state(model, rng, _sample(), sopt)
    restored_s, _ = load_checkpoint(tmp_path / "repl", target_s)
    for r, s in zip(jax.tree_util.tree_leaves(state_r.opt_state),
                    jax.tree_util.tree_leaves(restored_s.opt_state)):
        np.testing.assert_array_equal(np.asarray(s)[: r.size],
                                      np.asarray(r).reshape(-1))

    # sharded → replicated
    save_checkpoint(tmp_path / "shard", restored_s, {"epoch": 0})
    restored_r, _ = load_checkpoint(tmp_path / "shard",
                                    create_train_state(model, rng, _sample(),
                                                       opt))
    for a, b in zip(jax.tree_util.tree_leaves(state_r.opt_state),
                    jax.tree_util.tree_leaves(restored_r.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_same_layout_unchanged(tmp_path):
    """The fast path: matching layouts round-trip untouched (regression
    guard on the reshard hook)."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model = Net()
    rng = jax.random.PRNGKey(0)
    sopt = shard_optimizer(SGD(momentum=0.9), 8)
    state = create_train_state(model, rng, _sample(), sopt)
    save_checkpoint(tmp_path, state, {"epoch": 0})
    restored, _ = load_checkpoint(
        tmp_path, create_train_state(model, rng, _sample(), sopt))
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------------------------
# factory validation
# --------------------------------------------------------------------------

def test_factory_rejects_mismatched_optimizer(mesh8):
    opt = SGD(momentum=0.9)
    sopt = shard_optimizer(SGD(momentum=0.9), 8)
    with pytest.raises(ValueError, match="ShardedUpdate"):
        make_train_step_shard_map(Net(), opt, mesh8, constant_lr(0.05),
                                  update_sharding="sharded")
    with pytest.raises(ValueError, match="incompatible"):
        make_train_step_shard_map(Net(), sopt, mesh8, constant_lr(0.05))
    with pytest.raises(ValueError, match="update_sharding"):
        make_train_step_shard_map(Net(), opt, mesh8, constant_lr(0.05),
                                  update_sharding="diagonal")
    with pytest.raises(ValueError, match="collective_dtype"):
        make_train_step_shard_map(Net(), sopt, mesh8, constant_lr(0.05),
                                  update_sharding="sharded",
                                  collective_dtype="int4")
    # A wire dtype on the replicated path would be silently ignored —
    # rejected at the factory boundary instead.
    with pytest.raises(ValueError, match="collective_dtype"):
        make_train_step_shard_map(Net(), opt, mesh8, constant_lr(0.05),
                                  collective_dtype="bf16")
    with pytest.raises(ValueError, match="world"):
        ShardedUpdate(opt, 0)


def test_trainer_validates_update_sharding(tmp_path):
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    def cfg(**kw):
        c = Config()
        c.data.dataset = "synthetic"
        c.data.synthetic_train_size = 64
        c.data.synthetic_test_size = 16
        c.data.batch_size = 16
        c.train.ckpt_dir = str(tmp_path / "ck")
        for k, v in kw.items():
            sec, name = k.split(".")
            setattr(getattr(c, sec), name, v)
        return c

    with pytest.raises(ValueError, match="update_sharding"):
        Trainer(cfg(**{"train.update_sharding": "maybe"}))
    with pytest.raises(ValueError, match="collective_dtype"):
        Trainer(cfg(**{"train.collective_dtype": "bf16"}))


# --------------------------------------------------------------------------
# end to end: Trainer parity
# --------------------------------------------------------------------------

def test_trainer_sharded_parity(tmp_path):
    """Two Trainers, identical config except update_sharding: bitwise-equal
    final params after a full fit() (steps, checkpointing, eval included).
    Covers the trainer wiring: sharded step factory selection, sharded
    opt-state init, windowed dispatch, and checkpoint save of the sharded
    state."""
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    def cfg(mode, sub):
        c = Config()
        c.data.dataset = "synthetic"
        c.data.synthetic_train_size = 64
        c.data.synthetic_test_size = 16
        c.data.batch_size = 16
        c.data.prefetch = 1
        c.train.epochs = 1
        c.train.log_every = 100
        c.train.eval_at_end = True
        c.train.steps_per_call = 2
        c.train.ckpt_dir = str(tmp_path / sub)
        c.train.update_sharding = mode
        c.optim.lr = 0.05
        return c

    t_r = Trainer(cfg("replicated", "repl"))
    r_res = t_r.fit()
    t_s = Trainer(cfg("sharded", "shard"))
    s_res = t_s.fit()

    assert isinstance(t_s.optimizer, ShardedUpdate)
    assert int(t_r.state.step) == int(t_s.state.step) == 4
    for a, b in zip(jax.tree_util.tree_leaves(t_r.state.params),
                    jax.tree_util.tree_leaves(t_s.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert r_res["eval"]["accuracy"] == s_res["eval"]["accuracy"]


def test_trainer_sharded_batchnorm_model(tmp_path):
    """BatchNorm model (ResNet-18) through the sharded trainer path: the
    model is rebuilt with axis_name=DATA_AXIS (sync-BN inside shard_map),
    init uses the axis-free twin, and the trajectory tracks the replicated
    GSPMD run (global-batch stats) to sync-BN tolerance."""
    from tpu_dp.config import Config
    from tpu_dp.parallel.dist import DATA_AXIS
    from tpu_dp.train.trainer import Trainer

    def cfg(mode, sub):
        c = Config()
        c.model.name = "resnet18"
        c.model.num_classes = 10
        c.data.dataset = "synthetic"
        c.data.synthetic_train_size = 32
        c.data.synthetic_test_size = 16
        c.data.batch_size = 16
        c.data.prefetch = 1
        c.train.epochs = 1
        c.train.log_every = 100
        # Eval on: the sync-BN model must also evaluate (train=False uses
        # running stats — no axis collective, so plain jit works).
        c.train.eval_at_end = mode == "sharded"
        c.train.ckpt_dir = str(tmp_path / sub)
        c.train.update_sharding = mode
        c.optim.lr = 0.01
        return c

    t_s = Trainer(cfg("sharded", "shard"))
    assert getattr(t_s.model, "axis_name", None) == DATA_AXIS
    assert getattr(t_s._init_model, "axis_name", None) is None
    res = t_s.fit()
    assert "eval" in res
    t_r = Trainer(cfg("replicated", "repl"))
    assert getattr(t_r.model, "axis_name", None) is None
    t_r.fit()
    assert int(t_r.state.step) == int(t_s.state.step) == 2
    for a, b in zip(jax.tree_util.tree_leaves(t_r.state.params),
                    jax.tree_util.tree_leaves(t_s.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(t_r.state.batch_stats),
                    jax.tree_util.tree_leaves(t_s.state.batch_stats)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
