"""dplint Level 5 (`tpu_dp.analysis.concurrency`) — concurrency rules.

Three layers of coverage, mirroring `tests/test_hostproto.py`:

1. Adversarial fixtures (`tests/fixtures/dplint/conc/`): one known-bad
   module per rule, DP501–DP505. Each marks its finding lines with
   ``# EXPECT: <RULE>`` and carries a pragma'd twin that must NOT fire;
   the test drives the real CLI (`python -m tpu_dp.analysis conc` via
   `cli.main(["conc", ...])`) and asserts the exit code, rule, file, and
   the EXACT finding set (a pragma'd twin firing is as much a regression
   as a violation not firing).
2. The shipped tree is clean: `python -m tpu_dp.analysis conc` exits 0
   (every real violation this PR found was fixed or pragma-audited), and
   the one real race fix (`ServeReplica.snapshot`'s mixed lock
   discipline) is pinned both on the shipped file and as a minimal
   reproducer of the bug shape.
3. Engine unit tests for the subtle clean/flag boundaries: the __init__
   / unreachable-method exemptions, per-cycle pragma scoping, same-lock
   re-entry, the family-aware DP503 rendezvous contract, `wait_for`'s
   built-in predicate loop, closures inheriting their method's class
   lockset, and timed-vs-untimed queue gets.

Fast lane: ``pytest -m conc`` (part of the `tools/run_tier1.sh --lint`
CI lane).
"""

from __future__ import annotations

import json
import os
import re
import shutil
import textwrap

import pytest

from tpu_dp.analysis import concurrency
from tpu_dp.analysis.cli import main as dplint_main
from tpu_dp.analysis.report import RULES

pytestmark = pytest.mark.conc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "dplint", "conc")
CONC_RULES = {r for r in RULES if r.startswith("DP5")}

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*(DP\d{3})")
_ALLOW_RE = re.compile(r"#\s*dplint:\s*allow\(\s*(DP\d{3})")

FIXTURE_FILES = sorted(
    f for f in os.listdir(FIXTURES) if f.endswith(".py")
)


def _expected_findings(path: str) -> list[tuple[str, int]]:
    out = []
    with open(path, encoding="utf-8") as f:
        for lineno, text in enumerate(f, start=1):
            for m in _EXPECT_RE.finditer(text):
                out.append((m.group(1), lineno))
    return out


def _run_conc(capsys, argv: list[str]) -> tuple[int, dict]:
    rc = dplint_main(["conc"] + argv + ["--json"])
    payload = json.loads(capsys.readouterr().out)
    return rc, payload


# -- 1. every adversarial fixture fires exactly its declared set ----------

@pytest.mark.parametrize("fixture", FIXTURE_FILES)
def test_fixture_fires_exact_expected_set(fixture, capsys):
    path = os.path.join(FIXTURES, fixture)
    expected = set(_expected_findings(path))
    assert expected, f"{fixture} declares no # EXPECT: comments"

    rc, payload = _run_conc(capsys, [path])
    assert rc == 1, f"{fixture}: expected exit 1, got {rc}"
    got = {(f["rule"], f["line"]) for f in payload["findings"]}
    # Exact equality: a missing violation AND a firing pragma'd twin are
    # both regressions.
    assert got == expected, (
        f"{fixture}: expected exactly {sorted(expected)}, got {sorted(got)}"
    )
    for f in payload["findings"]:
        assert f["path"] == path
        assert f["rule"] in CONC_RULES
        assert f["message"]


def test_every_conc_rule_has_firing_case_and_pragma_twin():
    """Both directions per rule, inside the Level-5 fixture set: at
    least one `# EXPECT: DP50x` firing line AND one `# dplint:
    allow(DP50x)` twin that the exact-set test above proves silent."""
    firing: set[str] = set()
    twinned: set[str] = set()
    for fixture in FIXTURE_FILES:
        text = open(os.path.join(FIXTURES, fixture),
                    encoding="utf-8").read()
        firing.update(m.group(1) for m in _EXPECT_RE.finditer(text))
        twinned.update(m.group(1) for m in _ALLOW_RE.finditer(text))
    assert firing == CONC_RULES, (
        f"conc rules without a firing fixture: {CONC_RULES - firing}"
    )
    assert twinned >= CONC_RULES, (
        f"conc rules without a pragma'd twin: {CONC_RULES - twinned}"
    )


def test_conc_list_rules(capsys):
    rc = dplint_main(["conc", "--list-rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for rule in sorted(CONC_RULES):
        assert rule in out


def test_conc_baseline_roundtrip(tmp_path, capsys):
    """--write-baseline / --baseline wire through the shared machinery:
    a recorded fixture stops failing, and an unrecorded one still does."""
    path = os.path.join(FIXTURES, "dp505_blocking_under_lock.py")
    baseline = tmp_path / "conc_baseline.json"
    rc = dplint_main(["conc", path, "--write-baseline", str(baseline)])
    capsys.readouterr()
    assert rc == 0 and baseline.exists()
    rc, payload = _run_conc(capsys, [path, "--baseline", str(baseline)])
    assert rc == 0 and payload["findings"] == []
    other = os.path.join(FIXTURES, "dp501_unguarded_write.py")
    rc, payload = _run_conc(capsys, [other, "--baseline", str(baseline)])
    assert rc == 1 and payload["findings"]


# -- 2. the shipped tree is clean -----------------------------------------

def test_shipped_tree_lints_clean(capsys):
    rc, payload = _run_conc(capsys, [os.path.join(REPO, "tpu_dp")])
    assert payload["findings"] == []
    assert rc == 0


def test_tampered_copy_planted_in_scratch_package_fails(tmp_path, capsys):
    """The CI lane's negative direction: a fixture copied into a scratch
    package (outside tpu_dp/, as `tools/run_tier1.sh --lint` plants it)
    must still fail with rule+file+line attribution."""
    pkg = tmp_path / "scratchpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    planted = pkg / "monitor.py"
    shutil.copy(os.path.join(FIXTURES, "dp501_unguarded_write.py"),
                planted)

    rc, payload = _run_conc(capsys, [str(tmp_path)])
    assert rc == 1
    findings = payload["findings"]
    assert any(
        f["rule"] == "DP501" and f["path"] == str(planted) and f["line"] > 0
        for f in findings
    )


def test_replica_snapshot_lock_discipline_regression():
    """The real DP501 finding this PR fixed: `snapshot()` must not mix
    guarded and bare access to the serve thread's status fields. Linting
    the shipped file pins the fix against reverts."""
    path = os.path.join(REPO, "tpu_dp", "serve", "replica.py")
    findings = [f for f in concurrency.lint_file(path)
                if f.rule == "DP501"]
    assert findings == []


def test_dp501_catches_the_snapshot_status_race_shape():
    """Minimal reproducer of the replica bug: the serve loop thread
    writes `self.status` bare while `snapshot()` reads it under
    `self._lock` — the guarded reader believes the lock excludes the
    writer, and it does not."""
    src = """
    import threading


    class Replica:
        def __init__(self):
            self._lock = threading.Lock()
            self.status = "idle"
            self._t = threading.Thread(target=self._loop, daemon=True)

        def _loop(self, stop):
            while not stop.is_set():
                self.status = "working"

        def snapshot(self):
            with self._lock:
                return {"status": self.status}
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP501"]
    assert "status" in findings[0].message


# -- 3. engine boundaries --------------------------------------------------

def _lint(src: str, path: str = "fix.py") -> list:
    return concurrency.lint_source(path, textwrap.dedent(src))


def test_dp501_init_and_unreachable_writes_are_exempt():
    """__init__ runs before the thread exists, and `bump` is not
    reachable from the Thread target — neither bare write races the
    guarded reader."""
    src = """
    import threading


    class Server:
        def __init__(self):
            self._lock = threading.Lock()
            self.epoch = 0
            self._t = threading.Thread(target=self._serve, daemon=True)

        def _serve(self, stop):
            while not stop.is_set():
                pass

        def bump(self):
            self.epoch = self.epoch + 1

        def read(self):
            with self._lock:
                return self.epoch
    """
    assert _lint(src) == []


def test_dp502_same_lock_reenter_is_not_a_cycle():
    src = """
    import threading

    r_lock = threading.RLock()


    def nested():
        with r_lock:
            with r_lock:
                pass
    """
    assert _lint(src) == []


def test_dp502_pragma_is_scoped_to_its_own_cycle():
    """The pragma on the audited c/d cycle must not silence the
    unrelated a/b deadlock in the same module."""
    src = """
    import threading

    a_lock = threading.Lock()
    b_lock = threading.Lock()
    c_lock = threading.Lock()
    d_lock = threading.Lock()


    def fwd():
        with a_lock:
            with b_lock:
                pass


    def rev():
        with b_lock:
            with a_lock:
                pass


    def boot():
        with c_lock:
            with d_lock:
                pass


    def teardown():
        with d_lock:
            with c_lock:  # dplint: allow(DP502)
                pass
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP502"]
    assert "a_lock" in findings[0].message
    assert "b_lock" in findings[0].message


def test_dp503_trailing_producer_matches_a_gated_await():
    """A handshake await with no peer branch is answered by its
    family's producer later in the same suite — a rendezvous, not a
    wedge."""
    src = """
    def establish(ledger, sid, leader, rec):
        if sid != leader:
            ledger.await_join_ready(rec)
        ledger.confirm_join_ready(rec)
    """
    assert _lint(src) == []


def test_dp503_trailing_copy_does_not_match_a_symmetric_collective():
    """A symmetric collective is matched only by the peer BRANCH: a
    second copy after the `if` means the gated ranks run it twice —
    still divergent."""
    src = """
    def regroup(dist, rank, shard):
        if rank == 0:
            dist.barrier(shard)
        dist.barrier(shard)
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP503"]


def test_dp503_raising_guard_is_a_loud_exit_not_a_silent_skip():
    src = """
    def settle(dist, plan, sid, shard):
        if sid not in plan.survivors:
            raise RuntimeError("evicted")
        return dist.allgather(shard)
    """
    assert _lint(src) == []


def test_dp504_wait_for_and_joined_self_handle_are_clean():
    src = """
    import threading


    class Writer:
        def __init__(self):
            self._cond = threading.Condition()
            self._t = threading.Thread(target=self._run)
            self._t.start()

        def _run(self):
            with self._cond:
                self._cond.wait_for(lambda: True, timeout=1.0)

        def close(self):
            self._t.join(1.0)
    """
    assert _lint(src) == []


def test_dp505_untimed_get_flagged_timed_get_clean():
    src = """
    import threading

    feed_lock = threading.Lock()


    def broken(q):
        with feed_lock:
            return q.get()


    def bounded(q):
        with feed_lock:
            return q.get(timeout=0.5)
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP505"]
    assert "untimed" in findings[0].message


def test_dp505_closure_inherits_its_methods_class_lock():
    """`cls_of` fixpoint: a closure defined inside a method holds the
    CLASS's `self._lock`, so its blocking call under that lock fires."""
    src = """
    import threading
    import time


    class Pump:
        def __init__(self):
            self._lock = threading.Lock()

        def run(self):
            def tick():
                with self._lock:
                    time.sleep(0.1)
            return tick
    """
    findings = _lint(src)
    assert [f.rule for f in findings] == ["DP505"]
    assert "time.sleep" in findings[0].message


def test_dp100_syntax_error_is_reported_not_raised():
    findings = _lint("def broken(:\n")
    assert [f.rule for f in findings] == ["DP100"]
