"""The outage-capture watcher (`tools/r4_watch.sh`) drains its stage queue
correctly: priority order, per-stage .done checkpoints, failed stages
retried a bounded number of times without blocking the queue behind them.

The watcher exists because the TPU relay comes back in windows sometimes
minutes long (benchmarks/longrun_r3/README.md); these tests drive it with
the R4_* env hooks (fake probe, tmp capture dir, fast sleeps) — no TPU,
no jax.
"""

from __future__ import annotations

import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WATCH = REPO / "tools" / "r4_watch.sh"


def _run_watcher(cap: Path, probe_cmd: str, until, timeout_s: float = 25.0):
    env = dict(os.environ, R4_CAPTURE_DIR=str(cap),
               R4_PROBE_CMD=probe_cmd, R4_SLEEP_S="1")
    p = subprocess.Popen(["bash", str(WATCH)], env=env, cwd=str(REPO),
                        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if until():
                return
            time.sleep(0.25)
        pytest.fail(
            f"watcher did not reach expected state in {timeout_s}s; log:\n"
            + (cap / "watch.log").read_text())
    finally:
        p.kill()
        p.wait()


def test_stages_run_in_order_and_checkpoint(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text(
        "# comment line\n"
        f"first|30|echo one >> {cap}/order\n"
        f"second|30|echo two >> {cap}/order\n"
    )
    def done_and_idled_again():
        # Wait past completion until the watcher has gone around the loop
        # at least twice more (logged probes), so the no-re-run assertion
        # below is made against a watcher that had the chance to re-run.
        if not (cap / "second.done").exists():
            return False
        log = (cap / "watch.log").read_text()
        return log.count("probe ok") + log.count("no runnable stages") >= 3

    _run_watcher(cap, "true", done_and_idled_again)
    assert (cap / "first.done").exists()
    # .done checkpoints held: the later loops did not re-run the stages
    # (the order file would have grown).
    assert (cap / "order").read_text().splitlines() == ["one", "two"]


def test_failing_stage_does_not_block_queue_and_is_bounded(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text(
        "bad|30|false\n"
        f"after|30|echo ran >> {cap}/proof\n"
    )
    # Probe stays up, so 'bad' is a genuine stage failure: the watcher
    # must move past it to 'after' in the same window.
    _run_watcher(cap, "true", lambda: (cap / "after.done").exists())
    assert (cap / "proof").read_text().splitlines() == ["ran"]
    assert not (cap / "bad.done").exists()
    assert int((cap / "bad.fail").read_text()) >= 1

    # Retries are bounded at 3: run until the fail counter saturates.
    _run_watcher(cap, "true",
                 lambda: (cap / "bad.fail").exists()
                 and int((cap / "bad.fail").read_text()) >= 3)
    assert int((cap / "bad.fail").read_text()) == 3


def test_wedge_kill_does_not_count_toward_attempt_bound(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    # The stage simulates the relay wedging mid-run: it drops the
    # relay_down marker (failing the post-failure probe) and dies. Such
    # kills must NOT consume one of the 3 attempts — the stage is retried
    # at the next window instead (VERDICT: the long stages the watcher
    # exists for are exactly the ones a short window kills).
    (cap / "stages.txt").write_text(
        f"wedged|30|touch {cap}/relay_down && false\n"
        f"after|30|echo ran >> {cap}/proof\n"
    )
    _run_watcher(cap, f"test ! -f {cap}/relay_down",
                 lambda: "relay down — back to probing" in
                 ((cap / "watch.log").read_text()
                  if (cap / "watch.log").exists() else ""))
    assert not (cap / "wedged.fail").exists()
    assert not (cap / "after.done").exists()  # queue falls back to probing


def test_no_probe_no_stages(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text(f"only|30|echo x >> {cap}/proof\n")
    # Probe always fails (relay down): no stage may run.
    _run_watcher(cap, "false",
                 lambda: "probe failed" in
                 ((cap / "watch.log").read_text()
                  if (cap / "watch.log").exists() else ""))
    assert not (cap / "proof").exists()
    assert not (cap / "only.done").exists()
