"""The outage-capture watcher (`tools/r4_watch.sh`) drains its stage queue
correctly: priority order, per-stage .done checkpoints, failed stages
retried a bounded number of times without blocking the queue behind them.

The watcher exists because the TPU relay comes back in windows sometimes
minutes long (benchmarks/longrun_r3/README.md); these tests drive it with
the R4_* env hooks (fake probe, tmp capture dir, fast sleeps) — no TPU,
no jax.
"""

from __future__ import annotations

import os
import signal
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
WATCH = REPO / "tools" / "r4_watch.sh"


def _spawn(cap: Path, probe_cmd: str):
    env = dict(os.environ, R4_CAPTURE_DIR=str(cap),
               R4_PROBE_CMD=probe_cmd, R4_SLEEP_S="1")
    # Own process group: teardown must kill the watcher's children too
    # (a surviving `sleep` would briefly hold the flock fd it inherited
    # and block the next watcher instance the test starts).
    return subprocess.Popen(["bash", str(WATCH)], env=env, cwd=str(REPO),
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            preexec_fn=os.setsid)


def _killpg(p):
    try:
        os.killpg(p.pid, signal.SIGKILL)
    except ProcessLookupError:
        pass
    p.wait()


def _run_watcher(cap: Path, probe_cmd: str, until, timeout_s: float = 25.0):
    p = _spawn(cap, probe_cmd)
    try:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if until():
                return
            time.sleep(0.25)
        pytest.fail(
            f"watcher did not reach expected state in {timeout_s}s; log:\n"
            + (cap / "watch.log").read_text())
    finally:
        _killpg(p)


def test_stages_run_in_order_and_checkpoint(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text(
        "# comment line\n"
        f"first|30|echo one >> {cap}/order\n"
        f"second|30|echo two >> {cap}/order\n"
    )
    def done_and_idled_again():
        # Wait past completion until the watcher has gone around the loop
        # at least twice more (logged probes), so the no-re-run assertion
        # below is made against a watcher that had the chance to re-run.
        if not (cap / "second.done").exists():
            return False
        log = (cap / "watch.log").read_text()
        return log.count("probe ok") + log.count("no runnable stages") >= 3

    _run_watcher(cap, "true", done_and_idled_again)
    assert (cap / "first.done").exists()
    # .done checkpoints held: the later loops did not re-run the stages
    # (the order file would have grown).
    assert (cap / "order").read_text().splitlines() == ["one", "two"]


def test_failing_stage_does_not_block_queue_and_is_bounded(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text(
        "bad|30|false\n"
        f"after|30|echo ran >> {cap}/proof\n"
    )
    # Probe stays up, so 'bad' is a genuine stage failure: the watcher
    # must move past it to 'after' in the same window.
    _run_watcher(cap, "true", lambda: (cap / "after.done").exists())
    assert (cap / "proof").read_text().splitlines() == ["ran"]
    assert not (cap / "bad.done").exists()
    assert int((cap / "bad.fail").read_text()) >= 1

    # Retries are bounded at 3: run until the fail counter saturates.
    _run_watcher(cap, "true",
                 lambda: (cap / "bad.fail").exists()
                 and int((cap / "bad.fail").read_text()) >= 3)
    assert int((cap / "bad.fail").read_text()) == 3


def test_wedge_kill_does_not_count_toward_attempt_bound(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    # The stage simulates the relay wedging mid-run: it drops the
    # relay_down marker (failing the post-failure probe) and dies. Such
    # kills must NOT consume one of the 3 attempts — the stage is retried
    # at the next window instead (VERDICT: the long stages the watcher
    # exists for are exactly the ones a short window kills).
    (cap / "stages.txt").write_text(
        f"wedged|30|touch {cap}/relay_down && false\n"
        f"after|30|echo ran >> {cap}/proof\n"
    )
    _run_watcher(cap, f"test ! -f {cap}/relay_down",
                 lambda: "relay down — back to probing" in
                 ((cap / "watch.log").read_text()
                  if (cap / "watch.log").exists() else ""))
    assert not (cap / "wedged.fail").exists()
    assert not (cap / "after.done").exists()  # queue falls back to probing


def test_no_probe_no_stages(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text(f"only|30|echo x >> {cap}/proof\n")
    # Probe always fails (relay down): no stage may run.
    _run_watcher(cap, "false",
                 lambda: "probe failed" in
                 ((cap / "watch.log").read_text()
                  if (cap / "watch.log").exists() else ""))
    assert not (cap / "proof").exists()
    assert not (cap / "only.done").exists()


def test_second_watcher_instance_exits(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text("")
    p1 = _spawn(cap, "false")
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            log = ((cap / "watch.log").read_text()
                   if (cap / "watch.log").exists() else "")
            if "watcher started" in log:
                break
            time.sleep(0.2)
        else:
            pytest.fail("first watcher never logged startup in 10s")
        # Second instance must yield the capture dir and exit promptly.
        env = dict(os.environ, R4_CAPTURE_DIR=str(cap),
                   R4_PROBE_CMD="false", R4_SLEEP_S="1")
        p2 = subprocess.run(["bash", str(WATCH)], env=env, cwd=str(REPO),
                            timeout=10)
        assert p2.returncode == 0
        assert "another watcher holds" in (cap / "watch.log").read_text()
        assert p1.poll() is None  # first instance unaffected
    finally:
        _killpg(p1)


def test_pause_file_idles_watcher(tmp_path):
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "pause").touch()
    (cap / "stages.txt").write_text(f"only|30|echo x >> {cap}/proof\n")
    p = _spawn(cap, "true")
    try:
        time.sleep(3)
        assert not (cap / "proof").exists()  # paused: nothing ran
        (cap / "pause").unlink()
        deadline = time.time() + 15
        while time.time() < deadline and not (cap / "only.done").exists():
            time.sleep(0.25)
        assert (cap / "only.done").exists()  # resumed after unpause
    finally:
        _killpg(p)


def test_lock_released_even_if_stage_child_survives(tmp_path):
    # Killing the watcher by PID (the documented method) while a stage
    # child is still running must release the lock: children run with
    # fd 9 closed, so a restarted watcher takes over instead of bowing
    # out to a corpse's child.
    cap = tmp_path / "cap"
    cap.mkdir()
    (cap / "stages.txt").write_text("slow|30|sleep 5\n")
    p1 = _spawn(cap, "true")
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            log = ((cap / "watch.log").read_text()
                   if (cap / "watch.log").exists() else "")
            if "stage slow: starting" in log:
                break
            time.sleep(0.2)
        else:
            pytest.fail("stage never started")
        os.kill(p1.pid, signal.SIGKILL)  # watcher only; sleep child survives
        p1.wait()
        _run_watcher(
            cap, "true",
            lambda: (cap / "watch.log").read_text().count("watcher started")
            >= 2)
    finally:
        _killpg(p1)
