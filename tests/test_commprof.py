"""Comm/compute attribution suite (tpu_dp/obs/{chips,xplane,commprof}.py,
obsctl watch).

- the unified chip-spec registry is the single source the MFU math, the
  breakdown tool, and the wire-bandwidth gauges all read (cross-import
  pins so the old drift-prone copies cannot come back);
- the xplane parser against the checked-in tiny fixture (host-thunk
  layout, infra skipped, interval/overlap math) + typed refusals
  (unrecognized layouts, unknown comm-report schemas);
- the CommProfiler window scheduling (range + every-N cadence) with
  injected profiler fns;
- `obsctl watch` rule parsing and trip/no-trip against a synthetic
  metrics stream;
- the CPU-backend END-TO-END: an 8-device sharded-update training run
  with an in-run capture window whose parsed breakdown reconciles
  exactly — per-step collective kinds/counts vs the program's own static
  schedule, wire bytes vs quant.wire_report — and whose gauges land in
  metrics records, the flight recorder, obsctl diff, and obsctl watch.
"""

import json
from pathlib import Path

import pytest

pytestmark = [pytest.mark.obs, pytest.mark.commprof]

FIXTURE_DIR = Path(__file__).parent / "fixtures" / "xplane"


def _has_xplane_proto() -> bool:
    try:
        from tpu_dp.obs.xplane import import_xplane_pb2

        import_xplane_pb2()
        return True
    except Exception:
        return False


# --------------------------------------------------------------------------
# chips: one registry, no more drift-prone copies
# --------------------------------------------------------------------------

def test_chip_registry_is_the_single_source():
    from tpu_dp.obs import chips, costs

    # costs' table is DERIVED from the registry, and peak_flops delegates.
    assert costs.PEAK_FLOPS_BY_KIND == tuple(
        (sub, spec.peak_flops) for sub, spec in chips.CHIP_SPECS
    )
    for sub, spec in chips.CHIP_SPECS:
        assert costs.peak_flops(sub) == chips.peak_flops(sub)
    # The historical v5e numbers profile_breakdown hardcoded.
    v5e = chips.chip_spec("TPU v5 lite")
    assert v5e is not None
    assert v5e.peak_flops == 197e12
    assert v5e.hbm_gbs == 819.0
    assert v5e.ici_gbs is not None
    # Match-order discipline survives: "v5 lite" is v5e, bare "v5" is v5p.
    assert chips.chip_spec("tpu v5").name == "v5p"
    assert chips.chip_spec("unknown accelerator") is None
    assert chips.ici_gbs("v2") is None  # unknown field: absent, never 0


def test_profile_breakdown_consumes_the_registry():
    import tools.profile_breakdown as pb

    # The drift-prone local constants are gone; the tool reads chips.
    assert not hasattr(pb, "V5E_PEAK_TFLOPS")
    assert not hasattr(pb, "V5E_PEAK_HBM_GBS")
    from tpu_dp.obs import chips

    assert pb._V5E is chips.chip_spec("v5e")


def test_collective_kinds_pinned_to_analyzer():
    from tpu_dp.analysis import hlo
    from tpu_dp.obs import xplane

    # The reconciliation compares trace events against the DP304 schedule;
    # both sides must classify collectives identically.
    assert tuple(xplane.COLLECTIVE_KINDS) == tuple(hlo._COLLECTIVE_KINDS)


# --------------------------------------------------------------------------
# xplane parser: fixture, refusals, interval math
# --------------------------------------------------------------------------

@pytest.mark.skipif(not _has_xplane_proto(),
                    reason="TF xplane proto unavailable")
def test_fixture_parses_host_layout():
    from tpu_dp.obs import xplane

    s = xplane.summarize(FIXTURE_DIR)
    assert s["source"] == "host"
    # Two thread lines x one all-reduce each; infra events skipped.
    assert s["collectives"]["counts"] == {"all-reduce": 2}
    names = {op["name"] for op in s["ops"]}
    assert names == {"all-reduce.1", "loop_fusion.2"}
    # Interval math: the two lines' identical spans merge — comm is the
    # 1 ms all-reduce, compute the 2 ms fusion starting at 0.5 ms, so
    # 0.5 ms of comm is exposed and overlap is 50%.
    assert s["comm_s"] == pytest.approx(1e-3, rel=1e-6)
    assert s["compute_s"] == pytest.approx(2e-3, rel=1e-6)
    assert s["exposed_comm_s"] == pytest.approx(0.5e-3, rel=1e-6)


@pytest.mark.skipif(not _has_xplane_proto(),
                    reason="TF xplane proto unavailable")
def test_unrecognized_layout_refused(tmp_path):
    from tpu_dp.obs import xplane

    # An empty XSpace (no device plane, no host thunk lines) must be a
    # typed refusal, not an empty breakdown.
    (tmp_path / "empty.xplane.pb").write_bytes(b"")
    with pytest.raises(xplane.XplaneError, match="unrecognized"):
        xplane.summarize(tmp_path)


def test_no_trace_dir_refused(tmp_path):
    from tpu_dp.obs import xplane

    with pytest.raises(xplane.XplaneError, match="no xplane.pb"):
        xplane.summarize(tmp_path)


def test_comm_report_schema_refusal(tmp_path):
    from tpu_dp.obs import commprof

    p = tmp_path / "comm_report.json"
    p.write_text(json.dumps({"schema": 99, "comm_ms": 1.0}))
    with pytest.raises(commprof.CommProfileError, match="schema"):
        commprof.read_comm_report(p)
    commprof.write_comm_report(p, {"schema": commprof.SCHEMA, "comm_ms": 1})
    assert commprof.read_comm_report(p)["comm_ms"] == 1


def test_exposed_interval_math():
    from tpu_dp.obs.xplane import exposed_seconds

    comm = [(0.0, 1.0), (2.0, 3.0), (2.5, 3.5)]   # union [0,1] + [2,3.5]
    compute = [(0.5, 2.2), (3.4, 4.0)]
    # exposed: [0,0.5) + [2.2,3.4) = 0.5 + 1.2
    assert exposed_seconds(comm, compute) == pytest.approx(1.7)
    assert exposed_seconds(comm, []) == pytest.approx(2.5)
    assert exposed_seconds([], compute) == 0.0


def test_base_op_name():
    from tpu_dp.obs.xplane import base_op_name

    assert base_op_name("all-reduce.12") == "all-reduce"
    assert base_op_name("%reduce-scatter.3 = f32[8]{0} ...") \
        == "reduce-scatter"
    assert base_op_name("all-gather-start.1") == "all-gather"
    assert base_op_name("all-gather-done.1") == "all-gather-done"
    assert base_op_name("loop_fusion.2") == "loop_fusion"


# --------------------------------------------------------------------------
# wire bytes + reconciliation units
# --------------------------------------------------------------------------

def test_shape_bytes():
    from tpu_dp.obs.commprof import shape_bytes

    assert shape_bytes("f32[8,100]") == 3200
    assert shape_bytes("s8[16]") == 16
    assert shape_bytes("bf16[4]") == 8
    assert shape_bytes("(f32[2], s32[3])") == 20
    assert shape_bytes("f32[]") == 4
    assert shape_bytes("weird[10]") == 0  # unknown dtype: never a guess


def test_wire_bytes_rules():
    from tpu_dp.obs.commprof import wire_bytes_from_schedule

    colls = [
        {"kind": "reduce-scatter", "shape": "f32[25]"},   # 1/8 shard
        {"kind": "all-gather", "shape": "f32[200]"},
        {"kind": "all-reduce", "shape": "f32[]"},          # metric scalar
        {"kind": "all-to-all", "shape": "s8[800]"},
        {"kind": "all-to-all", "shape": "f32[8]"},         # scales
    ]
    w = wire_bytes_from_schedule(colls, world=8)
    assert w["grad_exchange"] == 25 * 4 * 8 + 800 + 8 * 4
    assert w["params_gather"] == 200 * 4
    assert w["grad_allreduce"] == 0  # scalar metric never counts


def test_reconcile_exact_and_mismatch():
    from tpu_dp.obs.commprof import reconcile

    exp = {"reduce-scatter": 20, "all-gather": 20, "all-reduce": 4}
    obs = {"reduce-scatter": 160, "all-gather": 160, "all-reduce": 32}
    r = reconcile(exp, obs, steps=2, devices=8)
    assert r["ok"]
    assert r["by_kind"]["reduce-scatter"]["per_step_observed"] == 10.0
    # One missing event -> mismatch; an unexpected kind -> mismatch.
    r = reconcile(exp, dict(obs, **{"all-gather": 159}), 2, 8)
    assert not r["ok"] and not r["by_kind"]["all-gather"]["ok"]
    r = reconcile(exp, dict(obs, **{"collective-permute": 8}), 2, 8)
    assert not r["ok"]


def test_parse_comm_profile_steps():
    from tpu_dp.obs.commprof import (
        CommProfileError,
        parse_comm_profile_steps,
    )

    assert parse_comm_profile_steps("") is None
    assert parse_comm_profile_steps(None) is None
    assert parse_comm_profile_steps("4:6") == ("range", 4, 6)
    assert parse_comm_profile_steps("every:100") == ("every", 100, 1)
    assert parse_comm_profile_steps("every:100:8") == ("every", 100, 8)
    for bad in ("nope", "6:4", "every:0", "every:4:8", "every:1:2:3"):
        with pytest.raises((CommProfileError, ValueError)):
            parse_comm_profile_steps(bad)


def test_comm_profiler_every_mode_scheduling(tmp_path, monkeypatch):
    from tpu_dp.obs import commprof

    monkeypatch.setattr(
        commprof.xplane, "summarize_robust",
        lambda d: {"source": "host", "comm_s": 8e-3, "compute_s": 1e-2,
                   "exposed_comm_s": 2e-3,
                   "collectives": {"counts": {"all-reduce": 8},
                                   "dur_s": {"all-reduce": 8e-3}}},
    )
    published = []
    cp = commprof.CommProfiler(
        tmp_path, ("every", 5, 1), devices=4, world=4,
        expected_fn=lambda: {"counts": {"all-reduce": 2}, "collectives": []},
        publish=lambda rep, s, e, d: published.append((s, e, rep)),
        start_fn=lambda d: None, stop_fn=lambda: None,
    )
    for step in range(1, 13):
        cp.on_window_start(step, 1)
        cp.on_step(step)
    # Windows at steps 5 and 10, one step each.
    assert [(s, e) for s, e, _ in published] == [(5, 6), (10, 11)]
    rep = published[0][2]
    assert rep["steps"] == 1 and rep["devices"] == 4
    # 8 raw events / 4 devices / 1 step == the expected 2 per step.
    assert rep["reconciliation"]["ok"]
    # comm 8ms over 4 devices = 2ms/step; exposed 0.5ms; overlap 0.75.
    assert rep["comm_ms"] == pytest.approx(2.0)
    assert rep["exposed_comm_ms"] == pytest.approx(0.5)
    assert rep["overlap_frac"] == pytest.approx(0.75)
    assert cp.reports == 2


def test_comm_profiler_every_mode_rearms_after_step_jump(tmp_path,
                                                         monkeypatch):
    """A step jump past a pending cadence window (resume, regroup) must
    arm the window THIS dispatch covers, not silently drop one capture."""
    from tpu_dp.obs import commprof

    monkeypatch.setattr(
        commprof.xplane, "summarize_robust",
        lambda d: {"source": "host", "comm_s": 0.0, "compute_s": 1e-2,
                   "exposed_comm_s": 0.0, "collectives": {}},
    )
    published = []
    cp = commprof.CommProfiler(
        tmp_path, ("every", 4, 1), devices=1, world=4,
        publish=lambda rep, s, e, d: published.append((s, e)),
        start_fn=lambda d: None, stop_fn=lambda: None,
    )
    cp.on_window_start(1, 1)   # pending window [4, 5)
    cp.on_step(1)
    # The step clock jumps: the next dispatch covers [11, 19). The stale
    # [4, 5) window retires AND [12, 13) arms within the same dispatch
    # (snapping outward to the window, like any StepProfiler range).
    cp.on_window_start(11, 8)
    cp.on_step(18)
    assert published == [(11, 19)]
    assert cp.reports == 1


def test_comm_profiler_every_mode_wide_window_covers_jump(tmp_path,
                                                          monkeypatch):
    """A step jump landing INSIDE a W>1 cadence window still captures
    that window (snapping outward), not the next cadence."""
    from tpu_dp.obs import commprof

    monkeypatch.setattr(
        commprof.xplane, "summarize_robust",
        lambda d: {"source": "host", "comm_s": 0.0, "compute_s": 1e-2,
                   "exposed_comm_s": 0.0, "collectives": {}},
    )
    published = []
    cp = commprof.CommProfiler(
        tmp_path, ("every", 10, 3), devices=1, world=4,
        publish=lambda rep, s, e, d: published.append((s, e, rep)),
        start_fn=lambda d: None, stop_fn=lambda: None,
    )
    cp.on_window_start(11, 1)  # resumed into [10, 13)
    cp.on_step(11)
    cp.on_window_start(12, 1)
    cp.on_step(12)             # window's last step (end - 1) ran
    assert [(s, e) for s, e, _ in published] == [(11, 13)]
    assert published[0][2]["steps"] == 2  # the partial capture, honest


def test_step_profiler_records_flightrec_events(tmp_path):
    from tpu_dp.obs import flightrec
    from tpu_dp.utils.profiling import StepProfiler

    flightrec.recorder.reset()
    prof = StepProfiler(str(tmp_path), 3, 5, start_fn=lambda d: None,
                        stop_fn=lambda: None, label="unit")
    prof.on_window_start(1, 1)
    prof.on_step(1)
    prof.on_window_start(3, 1)   # arms
    prof.on_step(3)
    prof.on_window_start(4, 1)
    prof.on_step(4)              # stops (end-1 == 4)
    evs = [e for e in flightrec.recorder.events()
           if e["kind"].startswith("profile_")]
    assert [e["kind"] for e in evs] == ["profile_start", "profile_stop"]
    assert evs[0]["trace_dir"] == str(tmp_path)
    assert evs[0]["label"] == "unit"
    assert (evs[0]["start_step"], evs[0]["end_step"]) == (3, 5)
    flightrec.recorder.reset()


# --------------------------------------------------------------------------
# obsctl watch: rules + trip/no-trip over a synthetic stream
# --------------------------------------------------------------------------

def _write_stream(run: Path, dip_step: int | None = None,
                  exposed_ms: float = 0.6) -> Path:
    recs = []
    for i in range(1, 11):
        mfu = 0.2 if i == dip_step else 0.5
        recs.append({"ts": f"2026-08-01T10:00:{i:02d}+00:00", "step": i,
                     "schema": 3, "mfu": mfu, "goodput": 0.95,
                     "spans": {"data_wait": 1.0, "dispatch": 2.0},
                     "counters": {"obs.step_time_ms": 10.0,
                                  "quant.overflow": 0.0}})
    recs.append({"ts": "2026-08-01T10:00:12+00:00", "step": 10,
                 "schema": 3, "event": "comm_profile", "comm_ms": 2.0,
                 "exposed_comm_ms": exposed_ms, "overlap_frac": 0.7})
    run.mkdir(parents=True, exist_ok=True)
    (run / "metrics.jsonl").write_text(
        "\n".join(json.dumps(r) for r in recs) + "\n")
    base = run / "base.json"
    base.write_text(json.dumps({"mfu": 0.5, "goodput": 0.95,
                                "p95_ms": 10.0, "exposed_comm_ms": 0.5}))
    return base


def test_watch_rule_parsing():
    from tpu_dp.obs.obsctl import WatchRule

    r = WatchRule("mfu<0.9*baseline")
    assert (r.signal, r.op, r.factor, r.const) == ("mfu", "<", 0.9, None)
    assert r.bound({"mfu": 0.5}) == pytest.approx(0.45)
    assert r.bound({}) is None  # baseline lacks the signal: no-data
    r = WatchRule("exposed_comm_ms>=5")
    assert (r.signal, r.const) == ("exposed_comm_ms", 5.0)
    assert WatchRule("goodput <= baseline*0.8").factor == 0.8
    assert WatchRule("heartbeat_age_s>baseline").factor == 1.0
    for bad in ("mfu!!3", "<0.5", "mfu<", "mfu<nope"):
        with pytest.raises(ValueError):
            WatchRule(bad)


def test_watch_rule_unknown_signal_rejected():
    """A typo'd signal must be a parse-time usage error — it would
    otherwise never evaluate, and a second healthy rule seeing data
    would mask the dead gate under exit 0."""
    from tpu_dp.obs.obsctl import WatchRule

    with pytest.raises(ValueError, match="unknown signal"):
        WatchRule("exposed_com_ms>1.5*baseline")


def test_health_scan_accepts_shared_beats(tmp_path):
    """`scan(beats=)` must match a fresh-read scan — `end_signals` shares
    one file pass between the straggler scan and the last-beat ages."""
    from tpu_dp.obs.health import HealthMonitor

    def beat(rank, step, step_ms):
        with open(tmp_path / f"heartbeat_r{rank:05d}.jsonl", "a") as f:
            f.write(json.dumps({"rank": rank, "step": step,
                                "ts": 100.0 + step,
                                "step_ms": step_ms}) + "\n")

    for step in range(1, 4):
        beat(0, step, 10.0)
        beat(1, step, 200.0 if step == 2 else 10.0)  # step-2 straggler
    mon = HealthMonitor(tmp_path, world=2)
    fresh = [(i.kind, i.rank, i.step) for i in mon.scan()]
    shared = [(i.kind, i.rank, i.step)
              for i in mon.scan(beats=mon.read_beats())]
    assert fresh == shared and ("straggler", 1, 2) in shared


def test_end_signals_ignore_departed_epochs(tmp_path):
    """heartbeat_age_s is a state-of-the-run signal: a rank that
    legitimately departed in an elastic shrink (its old epoch's stream
    stops forever) must not read as permanently stale."""
    from tpu_dp.obs.obsctl import RunArtifacts, end_signals

    def beat(d, rank, ts):
        with open(d / f"heartbeat_r{rank:05d}.jsonl", "a") as f:
            f.write(json.dumps({"rank": rank, "step": 1, "ts": ts,
                                "step_ms": 10.0}) + "\n")

    obs = tmp_path / "obs"
    me1 = obs / "me0001"
    me1.mkdir(parents=True)
    beat(obs, 0, 500.0)
    beat(obs, 2, 500.0)   # departs; its stream ends here
    beat(me1, 0, 999.0)   # survivors re-homed and healthy
    beat(me1, 1, 999.0)
    sig = end_signals(RunArtifacts(tmp_path), now=1000.0)
    assert sig["heartbeat_age_s"] == pytest.approx(1.0)


def test_metrics_tail_incremental(tmp_path):
    """The live-watch tail parses only appended bytes and defers a
    partial trailing line to the next tick (shared `tpu_dp.obs.tail`
    reader; obsctl's old private name must stay importable)."""
    from tpu_dp.obs.obsctl import _MetricsTail
    from tpu_dp.obs.tail import JsonlTail

    assert _MetricsTail is JsonlTail
    path = tmp_path / "metrics.jsonl"
    tail = _MetricsTail(path)
    assert tail.poll() == []  # absent file: no data, no error
    with open(path, "w") as f:
        f.write(json.dumps({"step": 1}) + "\n")
    assert [r["step"] for r in tail.poll()] == [1]
    assert tail.poll() == []
    with open(path, "a") as f:
        f.write(json.dumps({"step": 2}) + "\n")
        f.write('{"step": 3')  # sink mid-append
    assert [r["step"] for r in tail.poll()] == [2]
    with open(path, "a") as f:
        f.write(', "mfu": 0.5}\n')
    assert [r["step"] for r in tail.poll()] == [3]


def test_watch_trips_and_exit_codes(tmp_path):
    from tpu_dp.obs import obsctl

    base = _write_stream(tmp_path / "run", dip_step=7)
    run = str(tmp_path / "run")
    alerts = tmp_path / "run" / "alerts.jsonl"
    # Trip on the mid-run MFU dip, archiving the alert events.
    rc = obsctl.main(["watch", run, "--replay", "--baseline", str(base),
                      "--rule", "mfu<0.9*baseline",
                      "--alerts-out", str(alerts)])
    assert rc == 1
    ev = json.loads(alerts.read_text().splitlines()[0])
    assert ev["kind"] == "alert" and ev["step"] == 7
    assert ev["value"] == pytest.approx(0.2)
    # The archived alert merges into the forensic timeline as a marker.
    timeline = obsctl.build_timeline(obsctl.RunArtifacts(run))
    kinds = [e["kind"] for e in timeline["events"]]
    assert "alert" in kinds and "comm_profile" in kinds
    assert "alert" in obsctl.MARKER_KINDS

    # Clean rules on a clean stream exit 0.
    clean = _write_stream(tmp_path / "clean")
    rc = obsctl.main(["watch", str(tmp_path / "clean"), "--replay",
                      "--baseline", str(clean),
                      "--rule", "mfu<0.9*baseline",
                      "--rule", "goodput<0.8",
                      "--rule", "quant_overflow_per_step>0",
                      "--rule", "overlap_frac<0.5"])
    assert rc == 0
    # Exposed-comm regression vs the baseline trips.
    rc = obsctl.main(["watch", str(tmp_path / "clean"), "--replay",
                      "--baseline", str(clean),
                      "--rule", "exposed_comm_ms>1.1*baseline"])
    assert rc == 1
    # No rule ever saw data -> refuse to certify (exit 2, like diff).
    rc = obsctl.main(["watch", str(tmp_path / "clean"), "--replay",
                      "--rule", "straggler_ratio>3"])
    assert rc == 2
    # Usage errors: bad rule / baseline rule without --baseline / none.
    assert obsctl.main(["watch", run, "--replay", "--rule", "mfu!!3"]) == 2
    assert obsctl.main(["watch", run, "--replay",
                        "--rule", "mfu<0.9*baseline"]) == 2
    assert obsctl.main(["watch", run, "--replay"]) == 2


def test_diff_gates_comm_signals(tmp_path):
    from tpu_dp.obs import obsctl

    _write_stream(tmp_path / "run", exposed_ms=0.6)
    eff = obsctl.run_efficiency(obsctl.RunArtifacts(tmp_path / "run"))
    assert eff["comm_ms"] == 2.0
    assert eff["exposed_comm_ms"] == 0.6
    assert eff["overlap_frac"] == 0.7
    # BENCH-style baseline with a comm block: exposed regression trips.
    bench = {"mfu": 0.5, "goodput": 0.95, "p95_ms": 10.0,
             "comm": {"comm_ms": 2.0, "exposed_comm_ms": 0.4,
                      "overlap_frac": 0.8}}
    p = tmp_path / "bench.json"
    p.write_text(json.dumps(bench))
    verdict = obsctl.diff_verdict(eff, obsctl.load_baseline(p), 0.1)
    bad = {c["signal"] for c in verdict["checks"]
           if c["verdict"] == "regressed"}
    assert "exposed_comm_ms" in bad and "overlap_frac" in bad
    # A run with no comm data skips the comm signals, never "0".
    plain = tmp_path / "plain"
    plain.mkdir()
    (plain / "metrics.jsonl").write_text(json.dumps(
        {"ts": "2026-08-01T10:00:01+00:00", "step": 1, "schema": 3,
         "mfu": 0.5, "goodput": 0.9, "spans": {"dispatch": 1.0}}) + "\n")
    eff2 = obsctl.run_efficiency(obsctl.RunArtifacts(plain))
    assert "comm_ms" not in eff2
    v2 = obsctl.diff_verdict(eff2, obsctl.load_baseline(p), 0.1)
    comm_checks = {c["signal"]: c["verdict"] for c in v2["checks"]}
    assert comm_checks["exposed_comm_ms"] == "skipped"


# --------------------------------------------------------------------------
# the CPU-backend end-to-end: capture -> parse -> reconcile -> gate
# --------------------------------------------------------------------------

@pytest.mark.skipif(not _has_xplane_proto(),
                    reason="TF xplane proto unavailable")
def test_inrun_comm_profile_sharded_reconciles(tmp_path):
    """The acceptance run: 8-device sharded update, in-run window [4, 6).

    The parsed breakdown must reconcile exactly with the program's own
    static collective schedule (reduce-scatter + all-gather + metric
    all-reduces, once per step per device), the wire bytes with
    quant.wire_report, and the gauges must land in every downstream
    surface: metrics records, the flight recorder, comm_report.json,
    obsctl diff, and obsctl watch (trip on an injected regression, exit
    0 clean).
    """
    import jax

    from tpu_dp.config import Config
    from tpu_dp.obs import flightrec, obsctl
    from tpu_dp.obs.commprof import read_comm_report
    from tpu_dp.train.trainer import Trainer

    cfg = Config()
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_train_size = 80
    cfg.data.synthetic_test_size = 16
    cfg.data.batch_size = 8
    cfg.data.device_resident = "off"
    cfg.train.epochs = 1
    cfg.train.eval_at_end = False
    cfg.train.steps_per_call = 1
    cfg.train.obs = "full"
    cfg.train.update_sharding = "sharded"
    cfg.train.ckpt_dir = str(tmp_path / "ck")
    cfg.obs.comm_profile_steps = "4:6"
    tr = Trainer(cfg)
    tr.fit()

    world = len(jax.devices())
    rep = read_comm_report(tr.obs_dir / "comm_report.json")
    assert rep["start_step"] == 4 and rep["end_step"] == 6
    assert rep["steps"] == 2 and rep["devices"] == world
    recon = rep["reconciliation"]
    assert recon["ok"], recon
    # The sharded update's schedule: reduce-scatter + all-gather groups
    # plus the two metric scalar all-reduces, exactly once per step.
    kinds = set(recon["by_kind"])
    assert {"reduce-scatter", "all-gather", "all-reduce"} <= kinds
    assert recon["by_kind"]["all-reduce"]["per_step_observed"] == 2.0
    assert recon["by_kind"]["reduce-scatter"]["per_step_observed"] == \
        recon["by_kind"]["reduce-scatter"]["per_step_expected"]
    # Wire bytes: schedule-derived == quant.wire_report's layout math.
    assert rep["wire"]["reconciliation"]["ok"], rep["wire"]
    assert rep["comm_ms"] > 0 and rep["compute_ms"] > 0
    assert rep["overlap_frac"] is not None

    # Schema-3 surfaces: the comm_profile event + the gauges in counter
    # snapshots of records written after the window.
    metrics = [json.loads(line) for line in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    events = [r for r in metrics if r.get("event") == "comm_profile"]
    assert len(events) == 1
    assert events[0]["reconciled"] is True
    assert events[0]["comm_ms"] == rep["comm_ms"]
    assert any("obs.comm_ms" in (r.get("counters") or {}) for r in metrics)

    # Flight recorder: the capture window is discoverable from artifacts.
    dump = flightrec.read_dump(
        sorted(tr.obs_dir.glob("flightrec_r*.json"))[0])
    kinds = [e["kind"] for e in dump["events"]]
    assert "profile_start" in kinds and "profile_stop" in kinds
    assert "comm_profile" in kinds

    # obsctl diff reads the comm signals from the run.
    eff = obsctl.run_efficiency(obsctl.RunArtifacts(tmp_path / "ck"))
    assert eff["exposed_comm_ms"] == rep["exposed_comm_ms"]

    # obsctl watch: exit 0 on the clean run, 1 on an injected
    # exposed-comm regression (the acceptance gate).
    base = tmp_path / "base.json"
    rc = obsctl.main(["diff", str(tmp_path / "ck"),
                      "--write-baseline", str(base)])
    assert rc == 0
    rc = obsctl.main(["watch", str(tmp_path / "ck"), "--replay",
                      "--baseline", str(base),
                      "--rule", "exposed_comm_ms>1.5*baseline",
                      "--rule", "goodput<0.5*baseline"])
    assert rc == 0
    tampered = tmp_path / "tampered.json"
    payload = json.loads(base.read_text())
    payload["exposed_comm_ms"] = rep["exposed_comm_ms"] / 100.0
    tampered.write_text(json.dumps(payload))
    rc = obsctl.main(["watch", str(tmp_path / "ck"), "--replay",
                      "--baseline", str(tampered),
                      "--rule", "exposed_comm_ms>1.5*baseline"])
    assert rc == 1

    # The timeline shows the whole story from artifacts alone.
    timeline = obsctl.build_timeline(obsctl.RunArtifacts(tmp_path / "ck"))
    tkinds = [e["kind"] for e in timeline["events"]]
    assert "profile_start" in tkinds and "comm_profile" in tkinds


# --------------------------------------------------------------------------
# serving capture parity
# --------------------------------------------------------------------------

@pytest.mark.skipif(not _has_xplane_proto(),
                    reason="TF xplane proto unavailable")
def test_serve_batch_ranged_capture(tmp_path):
    """`serve.profile_batches` arms the same StepProfiler window over
    batch indices: the replica's capture lands an xplane trace under its
    per-sid subdir, parseable by the same library, with the flightrec
    profile_start/profile_stop discoverability. The range is 0-based
    half-open over the documented batch indices — 0:1 captures exactly
    the first batch (an off-by-one here captured nothing at all)."""
    import numpy as np

    import jax
    from tpu_dp.models import build_model
    from tpu_dp.obs import flightrec, xplane
    from tpu_dp.serve import InferenceEngine
    from tpu_dp.train.state import create_train_state
    from tpu_dp.train.optim import SGD

    flightrec.recorder.reset()
    model = build_model("net")
    state = create_train_state(model, jax.random.PRNGKey(0),
                               np.zeros((1, 32, 32, 3), np.float32),
                               SGD(momentum=0.0))
    engine = InferenceEngine(
        model, state.params, buckets=(1,), slo_ms=10_000.0,
        profile_dir=str(tmp_path / "prof"), profile_batches=(0, 1),
    )
    engine.start()
    try:
        handles = [engine.submit(np.zeros((32, 32, 3), np.uint8))
                   for _ in range(3)]
        for h in handles:
            assert h.wait(timeout=60.0)
    finally:
        engine.stop()
    trace_root = tmp_path / "prof" / "r0"
    assert xplane.find_xplane(trace_root) is not None
    s = xplane.summarize(trace_root)
    assert s["ops"], "capture window recorded no op events"
    kinds = [e["kind"] for e in flightrec.recorder.events()
             if e["kind"].startswith("profile_")]
    assert "profile_start" in kinds and "profile_stop" in kinds
    flightrec.recorder.reset()
