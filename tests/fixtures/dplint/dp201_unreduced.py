"""Adversarial dplint fixture — DP201: gradient never reduced.

A per-shard step that applies raw local gradients straight to the
(replicated) params: no data-axis collective anywhere, so each replica
trains on its own shard and the "replicated" params silently diverge.

`DPLINT_LOCAL_STEP` is the dplint jaxpr-pass hook: a zero-arg factory
returning ``(step_fn, example_args)`` that the CLI traces with the
``data`` axis bound.
"""

import jax
import jax.numpy as jnp


def DPLINT_LOCAL_STEP():
    def loss_fn(params, x):
        return jnp.sum((x @ params) ** 2)

    def step(state, batch):  # EXPECT: DP201
        grads = jax.grad(loss_fn)(state["params"], batch["x"])
        # BUG: no collectives.pmean(grads) before the update.
        new_params = state["params"] - 0.1 * grads
        return {"params": new_params}, {"grad_norm": jnp.sum(grads**2)}

    example = (
        {"params": jnp.ones((4, 2), jnp.float32)},
        {"x": jnp.ones((8, 4), jnp.float32)},
    )
    return step, example
