"""Adversarial dplint fixture — DP203: collective over an unknown mesh axis.

The reduction is spelled over ``"model"`` but the data-parallel mesh
defines only the ``data`` axis; the program only fails when the full step
finally traces — or deadlocks on a mesh where the name happens to exist
with a different size.
"""

import jax
import jax.numpy as jnp


def DPLINT_LOCAL_STEP():
    def loss_fn(params, x):
        return jnp.sum((x @ params) ** 2)

    def step(state, batch):  # EXPECT: DP203
        grads = jax.grad(loss_fn)(state["params"], batch["x"])
        # BUG: the mesh has no "model" axis.
        grads = jax.lax.pmean(grads, "model")  # dplint: allow(DP103)
        return {"params": state["params"] - 0.1 * grads}, {}

    example = (
        {"params": jnp.ones((4, 2), jnp.float32)},
        {"x": jnp.ones((8, 4), jnp.float32)},
    )
    return step, example
