"""Adversarial dplint fixture — DP105: coupled bucket/quant knobs
pinned at a known quality cliff.

`bucket_mb >= 4` with `quant_block_size >= 256` under the int8 codec
shares coarse absmax scales across many MB of fused gradient payload
(docs/PERF.md "Bucket-size/block-size coupling") — a convergence cliff
no throughput number shows. Each knob alone is fine; hardcoding the
*pair* is what fires. The suppressed twin at the bottom is the
deliberate-site idiom.
"""


def fast_but_lossy_config() -> dict:
    return dict(  # EXPECT: DP105
        bucket_mb=8.0,
        quant_block_size=512,
        collective_dtype="int8",
    )


LAUNCH_ARGV = [  # EXPECT: DP105
    "--train.update_sharding=sharded",
    "--train.bucket_mb=4",
    "--train.quant_block_size=256",
    "--train.collective_dtype=int8",
]

# Below the cliff on either axis: silent.
FINE_SMALL_BUCKETS = {"train.bucket_mb": 1.0,
                      "train.quant_block_size": 512,
                      "train.collective_dtype": "int8"}
FINE_BF16 = dict(bucket_mb=8.0, quant_block_size=512,
                 collective_dtype="bf16")

# A deliberate trip (e.g. a test of the runtime warning) is pragma'd.
DELIBERATE = dict(bucket_mb=8.0, quant_block_size=512,  # dplint: allow(DP105)
                  collective_dtype="int8")
