"""Adversarial dplint fixture — DP303: buffer donation silently dropped.

The caller donates its parameter buffers (``donate_argnums=(0,)``) expecting
XLA to reuse them in place — but the output dtype differs from the input, so
XLA cannot alias and *drops the donation with only a warning*: the program
quietly double-allocates every "donated" buffer. At scale this is the
difference between a model fitting in HBM and an OOM three hours in. The
compiled module's missing ``input_output_alias`` entries are the only
artifact of the drop.
"""

import jax
import jax.numpy as jnp


def DPLINT_HLO_PROGRAM():
    def step(params):  # EXPECT: DP303
        # BUG: dtype changes f32 -> bf16, so the donated f32 buffers can
        # never be reused for the bf16 outputs; XLA drops the aliasing.
        return jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params
        )

    params = {
        "w": jnp.zeros((64, 64), jnp.float32),
        "b": jnp.zeros((64,), jnp.float32),
    }
    return {
        "fn": step,
        "args": (params,),
        "jit_kwargs": {"donate_argnums": (0,)},
    }
