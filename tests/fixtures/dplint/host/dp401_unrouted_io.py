"""Adversarial dplint fixture — DP401: protocol-seam IO outside the route.

The broken publish writes the ledger pointer bare: a transient EIO loses
the publish, and the chaos harness's storage-fault shim never sees the
seam (the PR 14 fault-that-never-fires shape). The routed twin hands the
write to the retry router with the shim consulted inside the retried
block; the audited twin carries the allow-pragma.
"""

from pathlib import Path

from tpu_dp.resilience.retry import retry_call


def _storage_shim():
    return None  # stand-in for faultinject.storage_shim


def _ledger_io(fn, describe: str):
    # A local one-level wrapper: DP401 must discover this as a router
    # because its body calls retry_call.
    return retry_call(fn, retries=3, retry_on=(OSError,), describe=describe)


def broken_publish(ledger_dir: Path, epoch: int) -> None:
    ptr = ledger_dir / "latest.tmp"
    ptr.write_text(str(epoch))  # EXPECT: DP401


def routed_publish(ledger_dir: Path, epoch: int) -> None:
    def _write():
        shim = _storage_shim()
        if shim is not None:
            shim.on_write(ledger_dir / "latest")
        (ledger_dir / "latest").write_text(str(epoch))

    _ledger_io(_write, f"publish latest={epoch}")


def audited_marker(ledger_dir: Path) -> None:
    # dplint: allow(DP401) advisory marker outside the IO protocol
    (ledger_dir / "seen.marker").touch()
