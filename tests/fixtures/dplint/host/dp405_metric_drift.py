"""Adversarial dplint fixture — DP405: counter/gauge name drift.

The broken increment names a metric the registry has never heard of — an
obsctl diff/watch signal naming it would silently never fire. The
registered, family-prefixed, and pragma'd twins stay clean.
"""

from tpu_dp.obs.counters import counters


def broken_inc() -> None:
    counters.inc("zorble.count")  # EXPECT: DP405


def registered_inc() -> None:
    counters.inc("retry.attempts")


def family_gauge(sid: int) -> None:
    counters.gauge(f"serve.replica_health.{sid}", 1.0)


def audited_inc() -> None:
    counters.inc("zorble.audited")  # dplint: allow(DP405)
