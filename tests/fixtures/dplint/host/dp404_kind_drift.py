"""Adversarial dplint fixture — DP404: flightrec event-kind drift.

Three drift shapes: an emit of a kind the registry has never heard of, a
rendered marker kind that is not registered, and a registered marker kind
no analyzed emit site publishes (dead forensics — the pre-registry
``dump_request`` bug). The registered emit and the pragma'd twin stay
clean.
"""

from tpu_dp.obs import flightrec

MARKER_KINDS = (
    "guard_rollback",
    "zorble_rendered",  # EXPECT: DP404
    "profile_start",  # EXPECT: DP404
)


def broken_emit(step: int) -> None:
    flightrec.record("zorble_event", step=step)  # EXPECT: DP404


def registered_emit(step: int) -> None:
    flightrec.record("guard_rollback", step=step)


def audited_emit(step: int) -> None:
    flightrec.record("zorble_local", step=step)  # dplint: allow(DP404)
