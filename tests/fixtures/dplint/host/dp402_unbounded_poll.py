"""Adversarial dplint fixture — DP402: unbounded blocking poll.

The broken wait polls a barrier directory forever: when a peer died
before acking, this process wedges with it. The bounded twin derives a
monotonic deadline from the config timeout; the audited twin is a
run-forever service loop bounded by its stop flag.
"""

import time
from pathlib import Path


def broken_wait_for_acks(acks_dir: Path, expected: int) -> None:
    while True:
        if len(list(acks_dir.glob("*.done"))) >= expected:
            return
        time.sleep(0.05)  # EXPECT: DP402


def bounded_wait_for_acks(acks_dir: Path, expected: int,
                          timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    while True:
        if len(list(acks_dir.glob("*.done"))) >= expected:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(f"{expected} acks not seen in {timeout_s}s")
        time.sleep(0.05)


def audited_service_loop(stop, work) -> None:
    # dplint: allow(DP402) flag-bounded service loop, no natural deadline
    while True:
        if stop.is_set():
            return
        time.sleep(0.05)
        work()
