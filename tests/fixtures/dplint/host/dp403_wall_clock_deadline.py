"""Adversarial dplint fixture — DP403: wall-clock deadline arithmetic.

The broken budget derives a deadline from `time.time()`: an NTP step
stretches or collapses it silently. The monotonic twin is the fix; the
data-stamp function shows the deliberate non-finding (wall-clock as
recorded data, not arithmetic); the audited twin compares against an
external wall-clock stamp on purpose.
"""

import time


def broken_budget(timeout_s: float) -> float:
    return time.time() + timeout_s  # EXPECT: DP403


def monotonic_budget(timeout_s: float) -> float:
    return time.monotonic() + timeout_s


def stamped_record(reason: str) -> dict:
    # Wall-clock as DATA is fine: no Compare/BinOp, no finding.
    return {"reason": reason, "ts": time.time()}


def audited_cross_process_expiry(stamp_from_ledger: float) -> bool:
    # dplint: allow(DP403) comparing an external wall-clock stamp
    return time.time() >= stamp_from_ledger
