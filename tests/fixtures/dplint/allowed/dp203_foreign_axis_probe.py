"""Pragma'd twin of dp203_bad_axis — DP203 audited, must NOT fire.

Identical bug shape (a collective spelled over an axis the data-parallel
mesh does not define), audited as a staging shim for a model-parallel
mesh this binary does not build yet. The pragma on the step's `def` line
(where the jaxpr pass attributes its finding) is the audit record.
"""

import jax
import jax.numpy as jnp


def DPLINT_LOCAL_STEP():
    def loss_fn(params, x):
        return jnp.sum((x @ params) ** 2)

    def step(state, batch):  # dplint: allow(DP203) staged MP axis
        grads = jax.grad(loss_fn)(state["params"], batch["x"])
        grads = jax.lax.pmean(grads, "model")  # dplint: allow(DP103)
        return {"params": state["params"] - 0.1 * grads}, {}

    example = (
        {"params": jnp.ones((4, 2), jnp.float32)},
        {"x": jnp.ones((8, 4), jnp.float32)},
    )
    return step, example
