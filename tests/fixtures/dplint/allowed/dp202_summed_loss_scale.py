"""Pragma'd twin of dp202_double_reduced — DP202 audited, must NOT fire.

Identical bug shape (one pmean per microbatch plus one per update), but
here the double averaging is deliberate: the outer pmean folds in a
cross-replica loss-scale consensus and the inner one is compensated by
the ACCUM_STEPS rescale. The pragma on the step's `def` line (where the
jaxpr pass attributes its finding) is the audit record.
"""

import jax
import jax.numpy as jnp

ACCUM_STEPS = 2


def DPLINT_LOCAL_STEP():
    def loss_fn(params, x):
        return jnp.sum((x @ params) ** 2)

    def step(state, batch):  # dplint: allow(DP202) compensated rescale
        def micro(grads_acc, x_mb):
            g = jax.grad(loss_fn)(state["params"], x_mb)
            g = jax.lax.pmean(g, "data")  # dplint: allow(DP103)
            return grads_acc + g, None

        zeros = jnp.zeros_like(state["params"])
        grads, _ = jax.lax.scan(micro, zeros, batch["x"])
        grads = grads / ACCUM_STEPS
        grads = jax.lax.pmean(grads, "data")  # dplint: allow(DP103)
        new_params = state["params"] - 0.1 * grads
        return {"params": new_params}, {}

    example = (
        {"params": jnp.ones((4, 2), jnp.float32)},
        {"x": jnp.ones((ACCUM_STEPS, 8, 4), jnp.float32)},
    )
    return step, example
