"""Pragma'd twin of dp201_unreduced — DP201 audited, must NOT fire.

Identical bug shape (no data-axis reduction before the update), but this
one is a deliberate replica-local probe: each replica fits a throwaway
head on its own shard to estimate local gradient noise, and the results
are never folded back into the replicated params. The pragma on the
step's `def` line (where the jaxpr pass attributes its finding) is the
audit record; the clean-twin test drives the full CLI and requires
exit 0.
"""

import jax
import jax.numpy as jnp


def DPLINT_LOCAL_STEP():
    def loss_fn(params, x):
        return jnp.sum((x @ params) ** 2)

    def step(state, batch):  # dplint: allow(DP201) replica-local probe
        grads = jax.grad(loss_fn)(state["params"], batch["x"])
        new_params = state["params"] - 0.1 * grads
        return {"params": new_params}, {"grad_norm": jnp.sum(grads**2)}

    example = (
        {"params": jnp.ones((4, 2), jnp.float32)},
        {"x": jnp.ones((8, 4), jnp.float32)},
    )
    return step, example
