"""Pragma'd twin of dp302_host_callback — DP302 audited, must NOT fire.

Identical bug shape (`jax.debug.print` compiled into the step as a
host-callback custom-call), audited as a debug build behind a flag that
never ships. The pragma on the program's `def` line (where the HLO pass
attributes its finding) is the audit record.
"""

import jax
import jax.numpy as jnp


def DPLINT_HLO_PROGRAM():
    def step(x):  # dplint: allow(DP302) debug build, never ships
        jax.debug.print("loss={v}", v=x.sum())
        return x + 1.0

    return {"fn": step, "args": (jnp.zeros((8,), jnp.float32),)}
