"""Pragma'd twin of dp301_extra_allgather — DP301 audited, must NOT fire.

Identical bug shape (sharded input, replicated output, so GSPMD
materializes a cross-replica all-gather), audited as a deploy-time
export program that runs exactly once — the gather is the point, not a
per-step leak. The pragma on the program's `def` line (where the HLO
pass attributes its finding) is the audit record.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dp.parallel import dist


def DPLINT_HLO_PROGRAM():
    mesh = dist.data_mesh()

    def step(x):  # dplint: allow(DP301) one-shot export gather
        return x * 2.0

    fn = jax.jit(
        step,
        in_shardings=(NamedSharding(mesh, P(dist.DATA_AXIS)),),
        out_shardings=NamedSharding(mesh, P()),
    )
    return {"fn": fn, "args": (jnp.zeros((16, 4), jnp.float32),)}
