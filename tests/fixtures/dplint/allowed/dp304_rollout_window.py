"""Pragma'd twin of dp304_fingerprint_mismatch — DP304 audited, must NOT
fire.

Identical bug shape (the compiled collective schedule no longer digests
to the pinned fingerprint), audited for a rollout window in which the
old pin is kept until every rank has the new binary. The pragma on the
program's `def` line (where the HLO pass attributes its finding) is the
audit record.
"""

import jax.numpy as jnp


def DPLINT_HLO_PROGRAM():
    def step(x):  # dplint: allow(DP304) rollout window, repin after
        return x * 2.0

    return {
        "fn": step,
        "args": (jnp.zeros((8,), jnp.float32),),
        "expect_fingerprint": "0" * 64,
    }
