"""Pragma'd twin of dp303_dropped_donation — DP303 audited, must NOT fire.

Identical bug shape (dtype-changing output defeats the donation, XLA
drops the aliasing with only a warning), audited as a one-shot bf16
export where the double allocation is accepted. The pragma on the
program's `def` line (where the HLO pass attributes its finding) is the
audit record.
"""

import jax
import jax.numpy as jnp


def DPLINT_HLO_PROGRAM():
    def step(params):  # dplint: allow(DP303) one-shot bf16 export
        return jax.tree_util.tree_map(
            lambda p: p.astype(jnp.bfloat16), params
        )

    params = {
        "w": jnp.zeros((64, 64), jnp.float32),
        "b": jnp.zeros((64,), jnp.float32),
    }
    return {
        "fn": step,
        "args": (params,),
        "jit_kwargs": {"donate_argnums": (0,)},
    }
