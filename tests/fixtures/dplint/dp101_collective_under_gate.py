"""Adversarial dplint fixture — DP101: collective under a rank gate.

Only rank 0 reaches the psum; every other rank blocks in it forever the
next time the collective fires. This is the exact shape of the classic
multi-host deadlock (a "quick metrics allreduce" tucked into a
`process_index == 0` logging branch).
"""

import jax

from tpu_dp.parallel import collectives


def broken_epoch_summary(metrics):
    if jax.process_index() == 0:
        total = collectives.psum(metrics["loss"])  # EXPECT: DP101
        print("epoch loss:", total)


def audited_probe_summary(metrics):
    # Single-host probe tool: world size is pinned to 1 here, so the
    # gated psum cannot exclude a peer.
    if jax.process_index() == 0:  # dplint: allow(DP101)
        return collectives.psum(metrics["loss"])
