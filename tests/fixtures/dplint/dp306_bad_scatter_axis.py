"""Adversarial dplint fixture — DP301: sharded-update schedule over the
wrong axis — the gradient reduce-scatter and the params all-gather disagree.

The sharded weight update (`train.update_sharding=sharded`, docs/PERF.md)
is only correct when its two ring halves run over the *same* axis: each
replica updates the shard the reduce-scatter handed it, and the all-gather
reassembles exactly those shards. This program scatters over one axis but
gathers over another (the classic wrong-`axis_name` slip once a second mesh
axis exists): every replica updates one shard and gathers a *different*
one — numerically wrong parameters on every replica, while source and
jaxpr both look like a perfectly reasonable scatter/update/gather sequence.
Only the compiled artifact shows the two collectives' replica groups
disagreeing, which is exactly what DP301's sharded-mode classification
checks (`update_sharding: "sharded"` in the hook declaration).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpu_dp.train.step import _shard_map


def DPLINT_HLO_PROGRAM():
    # A 2-D mesh: the data axis plus a second (model) axis — the setting
    # where a wrong axis_name literal can even exist.
    devices = np.array(jax.devices()[:8]).reshape(4, 2)
    mesh = Mesh(devices, ("data", "model"))

    def step(g):  # EXPECT: DP301
        flat = jnp.pad(g.reshape(-1), (0, (-g.size) % 2))
        # BUG: the gradient reduce-scatter runs over the *model* axis...
        shard = jax.lax.psum_scatter(  # dplint: allow(DP103) adversarial fixture
            flat, "model", scatter_dimension=0, tiled=True
        ) / 2.0
        new_shard = shard - 0.1 * shard  # the "optimizer update" on 1/N
        # ...but the updated params are all-gathered over *data*: each
        # replica gathers shards it never updated.
        full = jax.lax.all_gather(  # dplint: allow(DP103) adversarial fixture
            new_shard, "data", axis=0, tiled=True
        )
        return full[: g.size].reshape(g.shape)

    fn = jax.jit(_shard_map(step, mesh, (P(),), P()))
    return {
        "fn": fn,
        "args": (jnp.zeros((30,), jnp.float32),),
        "update_sharding": "sharded",
        "expect_grad_reduce": True,
    }
