"""Adversarial dplint fixture — DP202: gradient reduced more than once.

The classic gradient-accumulation bug: one pmean per microbatch *inside*
the scan, plus the "standard" pmean after it. The update is silently
rescaled — no crash, no hang, just a wrong effective learning rate.
"""

import jax
import jax.numpy as jnp

ACCUM_STEPS = 2


def DPLINT_LOCAL_STEP():
    def loss_fn(params, x):
        return jnp.sum((x @ params) ** 2)

    def step(state, batch):  # EXPECT: DP202
        def micro(grads_acc, x_mb):
            g = jax.grad(loss_fn)(state["params"], x_mb)
            # BUG: reduced once per microbatch...
            g = jax.lax.pmean(g, "data")  # dplint: allow(DP103)
            return grads_acc + g, None

        zeros = jnp.zeros_like(state["params"])
        grads, _ = jax.lax.scan(micro, zeros, batch["x"])
        grads = grads / ACCUM_STEPS
        # ...AND once per update.
        grads = jax.lax.pmean(grads, "data")  # dplint: allow(DP103)
        new_params = state["params"] - 0.1 * grads
        return {"params": new_params}, {}

    example = (
        {"params": jnp.ones((4, 2), jnp.float32)},
        {"x": jnp.ones((ACCUM_STEPS, 8, 4), jnp.float32)},
    )
    return step, example
