"""Adversarial dplint fixture — DP305: retrace hazard at the jit boundary.

`jax.jit` called inside the loop builds a *fresh wrapper object* — with a
fresh, empty trace cache — on every iteration: every call retraces and
recompiles the function, turning a microsecond dispatch into a multi-second
compile, silently. (The runtime half of this rule is
`tpu_dp.analysis.recompile.RecompileGuard`, which counts post-warmup
trace-cache growth on the real step functions.)
"""

import jax


def hot_loop(xs):
    total = 0.0
    for x in xs:
        # BUG: a fresh jit wrapper (and empty compile cache) per iteration.
        total = total + jax.jit(lambda v: v * v)(x)  # EXPECT: DP305
    return total


def audited_cold_loop(xs):
    total = 0.0
    for x in xs:
        # One-shot startup calibration: the fresh wrapper per dtype probe
        # is deliberate and the compile cost is paid exactly once.
        total = total + jax.jit(lambda v: v * v)(x)  # dplint: allow(DP305)
    return total
