"""Adversarial dplint fixture — DP104: host sync inside the hot step.

`jax.device_get` / `.block_until_ready()` inside a jitted step serialize
dispatch against execution on every iteration — the async-dispatch
pipeline the whole TPU step-time story rests on collapses.
"""

import jax
import jax.numpy as jnp


@jax.jit
def chatty_step(state, batch):
    loss = jnp.mean((batch - state) ** 2)
    host_loss = jax.device_get(loss)  # EXPECT: DP104
    loss.block_until_ready()  # EXPECT: DP104
    return state - 0.1 * host_loss


@jax.jit
def audited_probe_step(state, batch):
    loss = jnp.mean((batch - state) ** 2)
    # Debug-only probe step: the stall is the point (step-time floor
    # measurement), never enabled in the hot loop.
    loss.block_until_ready()  # dplint: allow(DP104)
    return state - 0.1 * loss
