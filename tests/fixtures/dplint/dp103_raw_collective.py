"""Adversarial dplint fixture — DP103: raw collective / wrong axis literal.

The raw `jax.lax.psum` dodges the audited wrappers in
`tpu_dp.parallel.collectives`; the wrapper call over a literal `"model"`
axis names an axis the one-axis data-parallel mesh does not define.
"""

import jax

from tpu_dp.parallel import collectives


def sneaky_allreduce(grads):
    return jax.lax.psum(grads, "data")  # EXPECT: DP103


def wrong_axis(grads):
    return collectives.pmean(grads, "model")  # EXPECT: DP103
