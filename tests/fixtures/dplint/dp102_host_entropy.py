"""Adversarial dplint fixture — DP102: host nondeterminism in device code.

`time.time()` evaluates once per process at trace time, so each replica
compiles a different constant into what must be one identical SPMD
program; the nondeterministically-seeded PRNGKey gives every process its
own "replicated" init.
"""

import time

import jax
import jax.numpy as jnp


@jax.jit
def noisy_step(x):
    jitter = time.time()  # EXPECT: DP102
    return x * jitter


def divergent_init():
    key = jax.random.PRNGKey(int(time.time()))  # EXPECT: DP102
    return jax.random.normal(key, (4,)) + jnp.zeros((4,))


@jax.jit
def audited_salted_step(x):
    # Deliberate per-process salt, folded back out before any collective
    # sees the value.
    salt = time.time()  # dplint: allow(DP102)
    return x + (salt - salt)
