"""Adversarial dplint fixture — DP301: extra all-gather in the compiled HLO.

A "DP" program whose output sharding disagrees with what it computes: the
input is sharded over ``data`` but the output is declared replicated, so the
GSPMD partitioner silently materializes a cross-replica all-gather — per
step, over the whole activation. Nothing at the source or jaxpr level is
wrong; only the compiled artifact shows the collective. This is exactly what
a bad `PartitionSpec` in `parallel/sharding.py` looks like after compilation.

`DPLINT_HLO_PROGRAM` is the dplint Level-3 hook: a zero-arg factory
returning the program (pre-jitted or a callable plus ``jit_kwargs``) and
example args; the CLI lowers, compiles, and verifies the HLO text.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_dp.parallel import dist


def DPLINT_HLO_PROGRAM():
    mesh = dist.data_mesh()

    def step(x):  # EXPECT: DP301
        return x * 2.0

    fn = jax.jit(
        step,
        in_shardings=(NamedSharding(mesh, P(dist.DATA_AXIS)),),
        # BUG: replicating an un-reduced sharded tensor forces an
        # all-gather of the whole activation every step.
        out_shardings=NamedSharding(mesh, P()),
    )
    return {"fn": fn, "args": (jnp.zeros((16, 4), jnp.float32),)}
