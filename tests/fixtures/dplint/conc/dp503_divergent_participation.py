"""Adversarial dplint fixture — DP503: rank-gated participation divergence.

Three wedges: a rank-local quiesce read gating an allgather its peers
never enter (the PR 14 chaos bug, statically), a mismatched handshake
(the leader publishes an epoch record while the peers block on a quiesce
ack nobody produces), and a rank-gated early return that skips the
barrier every other rank stands in. The clean twins are the legal
shapes: a publish/await rendezvous, an unconditional collective behind a
loudly *raising* guard, and an audited one-sided joiner await.
"""


def broken_gate(dist, quiesced, rank):
    if quiesced.get(rank):
        return dist.allgather(quiesced)  # EXPECT: DP503


def broken_handshake(ledger, sid, leader, payload):
    if sid == leader:
        ledger.publish_epoch(payload)
    else:
        ledger.await_quiesced(payload)  # EXPECT: DP503


def broken_early_exit(dist, rank, shard):
    if rank != 0:
        return None
    return dist.barrier(shard)  # EXPECT: DP503


def clean_rendezvous(ledger, sid, leader, payload):
    if sid == leader:
        ledger.publish_epoch(payload)
    else:
        ledger.await_epoch(payload)


def clean_loud_guard(dist, plan, sid, shard):
    if sid not in plan:
        raise RuntimeError(f"rank {sid} evicted from the plan")
    return dist.barrier(shard)


def audited_joiner_wait(ledger, sid, deadline_s):
    if sid is None:
        # Joiner side of the admission handshake: an incumbent peer
        # branch does not exist in this process by construction.
        return ledger.await_epoch(deadline_s)  # dplint: allow(DP503)
