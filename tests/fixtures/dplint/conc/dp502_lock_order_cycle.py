"""Adversarial dplint fixture — DP502: lock-acquisition-order cycle.

`broken_enqueue` nests books -> stats while `broken_report` nests
stats -> books: two threads entering from opposite ends deadlock. The
second cycle hides one call down — `broken_flush` holds the journal
lock and calls a helper that takes the index lock, while
`broken_compact` nests them the other way. The audited twin documents
a deliberately reversed nesting on a pair of locks whose holders can
never overlap (boot vs teardown).
"""

import threading

books_lock = threading.Lock()
stats_lock = threading.Lock()
journal_lock = threading.Lock()
index_lock = threading.Lock()
boot_lock = threading.Lock()
side_lock = threading.Lock()

BOOKS = {}
STATS = {}


def broken_enqueue(key, n):
    with books_lock:
        with stats_lock:  # EXPECT: DP502
            STATS[key] = STATS.get(key, 0) + n
            BOOKS[key] = n


def broken_report(key):
    with stats_lock:
        with books_lock:
            return STATS.get(key), BOOKS.get(key)


def _touch_index(key):
    with index_lock:
        BOOKS[key] = True


def broken_flush(key):
    with journal_lock:
        _touch_index(key)  # EXPECT: DP502


def broken_compact(key):
    with index_lock:
        with journal_lock:
            BOOKS.pop(key, None)


def audited_boot(key):
    with boot_lock:
        with side_lock:
            STATS[key] = 0


def audited_teardown(key):
    # Boot and teardown are serialized by the process lifecycle: the
    # reversed nesting can never run concurrently with `audited_boot`.
    with side_lock:
        with boot_lock:  # dplint: allow(DP502)
            STATS.pop(key, None)
