"""Adversarial dplint fixture — DP505: lock held across a blocking call.

Durable IO and `time.sleep` under a lock stall every peer contending
for it; the third case hides the blocking call one level down in a
helper. Twins: snapshot-then-write outside the critical section, and
the audited donated-buffer bracket whose whole point is pinning the
swap pair across the device sync.
"""

import json
import threading
import time

state_lock = threading.Lock()
ring_lock = threading.Lock()
swap_lock = threading.Lock()

STATE = {}


def broken_publish(path, payload):
    with state_lock:
        STATE.update(payload)
        path.write_text(json.dumps(STATE))  # EXPECT: DP505


def broken_backoff(delay_s):
    with ring_lock:
        time.sleep(delay_s)  # EXPECT: DP505


def _settle(result):
    result.block_until_ready()


def broken_swap(result):
    with swap_lock:
        _settle(result)  # EXPECT: DP505


def clean_publish(path, payload):
    with state_lock:
        STATE.update(payload)
        snapshot = json.dumps(STATE)
    path.write_text(snapshot)


def audited_swap(result):
    with swap_lock:
        # Donated-buffer bracket: the swap pair stays pinned until the
        # device writes land; releasing early is the use-after-donate.
        result.block_until_ready()  # dplint: allow(DP505)
