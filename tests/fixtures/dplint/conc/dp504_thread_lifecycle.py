"""Adversarial dplint fixture — DP504: thread lifecycle / wait discipline.

A non-daemon worker that is never joined (nor even stored) keeps the
process alive past every drain path; a daemon poller with no stop flag
can never be drained; a bare `Condition.wait` misses wakeups and wakes
spuriously, both by spec. Twins: a joined worker, a flag-checked
poller, a predicate-`while` wait, and an audited process-lifetime
fire-and-forget.
"""

import threading
import time


def _drain_once(q):
    q.put_nowait(None)


def broken_spawn(q):
    threading.Thread(target=_drain_once, args=(q,)).start()  # EXPECT: DP504


def _poll_forever(q):
    while True:
        q.put_nowait(time.monotonic())
        time.sleep(0.05)


def broken_daemon(q):
    threading.Thread(  # EXPECT: DP504
        target=_poll_forever, args=(q,), daemon=True,
    ).start()


def broken_wait(cond, ready):
    with cond:
        if not ready():
            cond.wait(1.0)  # EXPECT: DP504


def clean_join(q):
    t = threading.Thread(target=_drain_once, args=(q,))
    t.start()
    t.join()


_STOP = threading.Event()


def _poll_until_stopped(q):
    while not _STOP.is_set():
        q.put_nowait(time.monotonic())
        time.sleep(0.05)


def clean_daemon(q):
    threading.Thread(
        target=_poll_until_stopped, args=(q,), daemon=True,
    ).start()


def clean_predicate_wait(cond, ready):
    with cond:
        while not ready():
            cond.wait(1.0)


def audited_fire_and_forget(sock):
    # Process-lifetime responder: it must outlive every caller and dies
    # with the interpreter; there is deliberately nothing to join.
    # dplint: allow(DP504) process-lifetime responder, nothing to join
    threading.Thread(target=_drain_once, args=(sock,)).start()
