"""Adversarial dplint fixture — DP501: shared attribute written without
its guarding lock.

`BrokenMeter.snapshot` reads `self.samples` under `self._lock`, so the
reader believes the lock excludes the writer — but the monitor thread's
`_loop` bumps the counter with no lock at all: the classic mixed-guard
race. The audited twin publishes a single GIL-atomic float heartbeat on
purpose and says so next to the pragma.
"""

import threading
import time


class BrokenMeter:
    def __init__(self):
        self._lock = threading.Lock()
        self.samples = 0
        self._monitor = threading.Thread(target=self._loop, daemon=True)

    def _loop(self, stop):
        while not stop.is_set():
            self.samples = self.samples + 1  # EXPECT: DP501
            time.sleep(0.01)

    def snapshot(self):
        with self._lock:
            return {"samples": self.samples}


class AuditedMeter:
    def __init__(self):
        self._lock = threading.Lock()
        self.last_beat = 0.0
        self._monitor = threading.Thread(target=self._beat, daemon=True)

    def _beat(self, stop):
        while not stop.is_set():
            # Deliberate benign publish: one GIL-atomic float store; the
            # guarded reader needs A consistent value, not THE latest.
            self.last_beat = time.monotonic()  # dplint: allow(DP501)
            time.sleep(0.01)

    def read(self):
        with self._lock:
            return self.last_beat
