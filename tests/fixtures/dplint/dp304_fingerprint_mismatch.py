"""Adversarial dplint fixture — DP304: collective-schedule fingerprint drift.

The program pins the collective-schedule fingerprint it was deployed with
(``expect_fingerprint``) — the digest of the ordered collective sequence +
replica groups `tpu_dp.analysis.hlo` computes and
`artifacts/collective_fingerprint.json` records. The binary now compiles a
*different* schedule than the pinned one: on a real pod, ranks running
mismatched schedules deadlock mid-step with no error. The analyzer catches
the drift at lint time; `tpu_dp.parallel.dist.verify_collective_fingerprint`
is the runtime cross-rank half of the same contract.
"""

import jax.numpy as jnp


def DPLINT_HLO_PROGRAM():
    def step(x):  # EXPECT: DP304
        return x * 2.0

    return {
        "fn": step,
        "args": (jnp.zeros((8,), jnp.float32),),
        # Pinned at deploy time; the schedule this binary compiles no
        # longer digests to it.
        "expect_fingerprint": "0" * 64,
    }
