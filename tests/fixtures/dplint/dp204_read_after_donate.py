"""Adversarial dplint fixture — DP204: donated buffer read after donation.

`make_train_step` compiles with ``donate_argnums=(0,)``: the `state`
passed in is handed to XLA for buffer reuse, and the Python object left
behind is dead. Reading it after the call returns garbage (or raises a
deleted-buffer error) on real backends.
"""

from tpu_dp.train.step import make_train_step


def broken_loop(model, optimizer, mesh, schedule, state, batches):
    train_step = make_train_step(model, optimizer, mesh, schedule)
    losses = []
    for batch in batches:
        new_state, metrics = train_step(state, batch)
        losses.append(metrics["loss"])
        # BUG: `state` was donated above and never rebound.
        print("step", state.step)  # EXPECT: DP204
    return losses


def audited_loop(model, optimizer, mesh, schedule, state, batches):
    train_step = make_train_step(model, optimizer, mesh, schedule)
    losses = []
    for batch in batches:
        new_state, metrics = train_step(state, batch)
        losses.append(metrics["loss"])
        # CPU-only harness: donation is a no-op on this backend and the
        # stale handle is the cheapest progress print available.
        print("step", state.step)  # dplint: allow(DP204)
    return losses
