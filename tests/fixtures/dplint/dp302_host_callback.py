"""Adversarial dplint fixture — DP302: host transfer in the compiled step.

A leftover `jax.debug.print` inside the jitted step body compiles into a
host-callback custom-call: every step round-trips to Python, serializing
dispatch against execution — the async-dispatch pipeline the whole hot loop
is built on collapses. The AST rules can't see it (debug.print is not a
collective, not a sync primitive); the compiled module shows the
custom-call.
"""

import jax
import jax.numpy as jnp


def DPLINT_HLO_PROGRAM():
    def step(x):  # EXPECT: DP302
        # BUG: a debug print left in the hot step — compiles to a
        # host-callback custom-call executed every single step.
        jax.debug.print("loss={v}", v=x.sum())
        return x + 1.0

    return {"fn": step, "args": (jnp.zeros((8,), jnp.float32),)}
