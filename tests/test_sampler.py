"""Sampler tests — `DistributedSampler` contract parity.

SURVEY.md §4 Unit: "sampler sharding (disjointness, coverage, pad policy,
`set_epoch` reshuffle per `cifar_example_ddp.py:70,92`)". Includes a direct
cross-check against `torch.utils.data.distributed.DistributedSampler` (torch
CPU is available in the build env), pinning the pad/stride contract to the
exact library the reference uses.
"""

import numpy as np
import pytest

from tpu_dp.data.sampler import ShardedSampler


def test_coverage_and_disjointness():
    n, world = 103, 4
    shards = [
        ShardedSampler(n, world, r, shuffle=True, seed=7).shard_indices()
        for r in range(world)
    ]
    # Equal sizes (padded): ceil(103/4) = 26 each.
    assert all(len(s) == 26 for s in shards)
    combined = np.concatenate(shards)
    # Every example appears at least once (pad repeats a few).
    assert set(combined.tolist()) == set(range(n))
    assert len(combined) == 26 * world


def test_drop_remainder():
    n, world = 103, 4
    shards = [
        ShardedSampler(n, world, r, shuffle=False, drop_remainder=True)
        .shard_indices()
        for r in range(world)
    ]
    assert all(len(s) == 25 for s in shards)
    combined = set(np.concatenate(shards).tolist())
    assert len(combined) == 100  # 3 dropped, none duplicated


def test_set_epoch_reshuffles_deterministically():
    s = ShardedSampler(1000, 4, 2, shuffle=True, seed=3)
    s.set_epoch(0)
    e0 = s.shard_indices()
    s.set_epoch(1)
    e1 = s.shard_indices()
    s.set_epoch(0)
    again = s.shard_indices()
    assert not np.array_equal(e0, e1)  # reshuffle happened
    assert np.array_equal(e0, again)  # and is deterministic in epoch


def test_no_shuffle_is_identity_order():
    s = ShardedSampler(12, 3, 1, shuffle=False)
    assert np.array_equal(s.shard_indices(), np.arange(12)[1::3])


def test_all_shards_agree_on_global_permutation():
    """Determinism by shared seed, not communication (SURVEY.md §3.3)."""
    n, world = 50, 5
    perms = []
    for r in range(world):
        s = ShardedSampler(n, world, r, shuffle=True, seed=11)
        s.set_epoch(4)
        perms.append(s.shard_indices())
    # Reconstruct the global permutation by interleaving rank::world.
    glob = np.empty(world * len(perms[0]), dtype=np.int64)
    for r in range(world):
        glob[r::world] = perms[r]
    assert set(glob.tolist()) == set(range(n))


def test_matches_torch_distributed_sampler_contract():
    torch = pytest.importorskip("torch")
    from torch.utils.data.distributed import DistributedSampler

    n, world = 103, 4

    class _DS(torch.utils.data.Dataset):
        def __len__(self):
            return n

        def __getitem__(self, i):
            return i

    for epoch in (0, 1):
        for rank in range(world):
            ts = DistributedSampler(
                _DS(), num_replicas=world, rank=rank, shuffle=False
            )
            ts.set_epoch(epoch)
            ours = ShardedSampler(n, world, rank, shuffle=False)
            ours.set_epoch(epoch)
            # Unshuffled contract must match torch exactly: pad-by-wraparound
            # then rank::world stride. (Shuffled orders differ by RNG, which
            # is fine — the *contract* under test is pad+stride.)
            assert list(ts) == ours.shard_indices().tolist()


def test_pad_exceeding_dataset_size_keeps_shards_equal():
    """More shards than examples: wraparound must tile, not underfill —
    unequal shard lengths would desync SPMD step counts (deadlock)."""
    n, world = 2, 8
    shards = [
        ShardedSampler(n, world, r, shuffle=False).shard_indices()
        for r in range(world)
    ]
    assert all(len(s) == 1 for s in shards)
    assert set(np.concatenate(shards).tolist()) == {0, 1}
