"""Fault-tolerance suite (`tpu_dp/resilience/`, docs/RESILIENCE.md).

The headline property: a training run killed mid-epoch by deterministic
fault injection auto-resumes from its latest async snapshot and reaches
final params **bitwise-identical** to an uninterrupted run — proved both
in-process (SIGTERM preemption through `Trainer.fit`) and across real
process boundaries (`train.py` subprocesses: `os._exit(137)` kill, exit
143 preemption, `--resume=auto` restart). Around it, unit coverage of each
resilience piece: fault-spec parsing, snapshot cadence/double-buffering/GC,
retry backoff, typed peer failure, and the mid-epoch sampler fast-forward.

All CPU (`tests/conftest.py` forces the backend); spawned subprocesses run
a single virtual device so their trajectories are self-consistent.
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import jax
import numpy as np
import pytest

from tpu_dp.checkpoint import CheckpointManager, load_checkpoint, save_checkpoint
from tpu_dp.resilience import (
    KILL_EXIT_CODE,
    PREEMPTED_EXIT_CODE,
    FaultInjector,
    FaultPlan,
    PeerFailedError,
    PreemptedError,
    PreemptionHandler,
    ResilientRing,
    SnapshotManager,
    backoff_delays,
    find_latest,
    resume_latest,
    retry_call,
)

pytestmark = pytest.mark.resilience


# --------------------------------------------------------------------------
# faultinject
# --------------------------------------------------------------------------

def test_fault_plan_parse():
    p = FaultPlan.parse("kill:step=13")
    assert (p.kind, p.step, p.rank) == ("kill", 13, -1)
    p = FaultPlan.parse("kill:step=13,rank=1")
    assert (p.kind, p.step, p.rank) == ("kill", 13, 1)
    p = FaultPlan.parse("delay:step=5,ms=250")
    assert (p.kind, p.step, p.delay_ms) == ("delay", 5, 250.0)
    assert FaultPlan.parse("") is None
    assert FaultPlan.parse("  ") is None
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse("explode:step=1")
    with pytest.raises(ValueError, match="needs step"):
        FaultPlan.parse("kill:rank=1")
    with pytest.raises(ValueError, match="bad fault field"):
        FaultPlan.parse("kill:step=1,when=now")


def test_fault_injector_rank_filter_and_one_shot():
    # A kill plan for rank 1 must never fire on rank 0 (or this test dies).
    inj = FaultInjector(FaultPlan(kind="kill", step=0, rank=1), rank=0)
    inj.on_step(100)
    assert not inj.fired

    inj = FaultInjector(FaultPlan(kind="delay", step=5, delay_ms=1), rank=0)
    inj.on_step(4)
    assert not inj.fired  # boundary not reached
    inj.on_step(6)        # first boundary past step 5
    assert inj.fired
    inj.on_step(7)        # exactly once: no second fire
    assert inj.fired


def test_fault_injector_drop_arms_once():
    inj = FaultInjector(FaultPlan(kind="drop", step=1), rank=0)
    assert not inj.take_drop()
    inj.on_step(1)
    assert inj.take_drop()      # consume the armed drop
    assert not inj.take_drop()  # one-shot


def test_fault_injector_from_spec_env(monkeypatch):
    assert FaultInjector.from_spec("", rank=0) is None
    monkeypatch.setenv("TPU_DP_FAULT", "delay:step=3,ms=1")
    inj = FaultInjector.from_spec("", rank=2)
    assert inj is not None and inj.plan.kind == "delay" and inj.rank == 2


# --------------------------------------------------------------------------
# retry
# --------------------------------------------------------------------------

def test_backoff_delays_deterministic_and_capped():
    assert backoff_delays(4, 0.05, 2.0) == [0.05, 0.1, 0.2, 0.4]
    assert backoff_delays(8, 0.05, 2.0)[-1] == 2.0  # capped
    assert backoff_delays(0) == []


def test_retry_call_retries_then_succeeds():
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert retry_call(flaky, retries=3, base_delay=0.05,
                      sleep=slept.append) == "ok"
    assert len(calls) == 3
    assert slept == [0.05, 0.1]  # deterministic schedule, no jitter


def test_retry_call_exhaustion_reraises_last():
    slept = []

    def dead():
        raise RuntimeError("peer gone")

    with pytest.raises(RuntimeError, match="peer gone"):
        retry_call(dead, retries=2, base_delay=0.01, sleep=slept.append)
    assert len(slept) == 2  # retries, not attempts


def test_retry_call_terminal_errors_propagate_immediately():
    calls = []

    def typed():
        calls.append(1)
        raise PeerFailedError("already attributed", rank=0, world=2)

    with pytest.raises(PeerFailedError):
        retry_call(typed, retries=5, sleep=lambda s: None)
    assert len(calls) == 1  # no re-wrapping of a terminal error

    def unexpected():
        calls.append(1)
        raise ValueError("not retryable")

    calls.clear()
    with pytest.raises(ValueError):
        retry_call(unexpected, retries=5, sleep=lambda s: None)
    assert len(calls) == 1


class _FakeRing:
    """hostlib.Ring stand-in: scriptable rendezvous/collective failures."""

    rendezvous_failures = 0
    collective_failures = 0
    instances = 0

    def __init__(self, host, base_port, rank, world, timeout_ms):
        type(self).instances += 1
        if type(self).rendezvous_failures > 0:
            type(self).rendezvous_failures -= 1
            raise RuntimeError("connection refused")
        self.calls = 0

    def allreduce(self, x):
        self.calls += 1
        if type(self).collective_failures > 0:
            type(self).collective_failures -= 1
            raise RuntimeError("recv failed: peer closed")
        return x

    def close(self):
        pass


@pytest.fixture()
def fake_ring(monkeypatch):
    from tpu_dp.ops.native import hostlib

    _FakeRing.rendezvous_failures = 0
    _FakeRing.collective_failures = 0
    _FakeRing.instances = 0
    monkeypatch.setattr(hostlib, "Ring", _FakeRing)
    return _FakeRing


def test_resilient_ring_retries_rendezvous(fake_ring):
    fake_ring.rendezvous_failures = 2  # ranks of a preempted pod restart late
    ring = ResilientRing("127.0.0.1", 9000, rank=0, world=2, retries=2,
                         base_delay=0.0)
    assert fake_ring.instances == 3
    ring.close()


def test_resilient_ring_rendezvous_exhaustion_is_typed(fake_ring):
    fake_ring.rendezvous_failures = 99
    with pytest.raises(PeerFailedError) as ei:
        ResilientRing("127.0.0.1", 9000, rank=0, world=2, retries=1,
                      base_delay=0.0)
    assert ei.value.rank == 0 and ei.value.world == 2
    assert ei.value.suspect_ranks == (1,)  # 2-rank ring: one neighbor


def test_resilient_ring_collective_retry_and_attribution(fake_ring):
    ring = ResilientRing("127.0.0.1", 9000, rank=1, world=4, retries=2,
                         base_delay=0.0)
    fake_ring.collective_failures = 1  # transient: retried, then succeeds
    assert ring.allreduce("payload") == "payload"

    fake_ring.collective_failures = 99  # persistent: typed terminal failure
    with pytest.raises(PeerFailedError) as ei:
        ring.allreduce("payload")
    assert ei.value.rank == 1 and ei.value.world == 4
    assert ei.value.suspect_ranks == (0, 2)  # the ring neighbors
    assert "allreduce" in str(ei.value)


def test_resilient_ring_injected_drop_is_retried(fake_ring):
    inj = FaultInjector(FaultPlan(kind="drop", step=1), rank=0)
    inj.on_step(1)  # arm the one-shot drop
    ring = ResilientRing("127.0.0.1", 9000, rank=0, world=2, retries=2,
                         base_delay=0.0, injector=inj)
    assert ring.allreduce("x") == "x"
    # First attempt was dropped before reaching the transport; the retry
    # went through — exactly one real collective call.
    assert ring._ring.calls == 1


def test_fault_tolerant_barrier(mesh8, monkeypatch):
    from tpu_dp.parallel import dist

    dist.fault_tolerant_barrier(mesh8)  # healthy mesh: plain success

    def broken(mesh=None):
        raise RuntimeError("coordination service unavailable")

    monkeypatch.setattr(dist, "barrier", broken)
    with pytest.raises(PeerFailedError) as ei:
        dist.fault_tolerant_barrier(mesh8, retries=1, base_delay=0.0)
    assert ei.value.rank == 0


# --------------------------------------------------------------------------
# snapshot
# --------------------------------------------------------------------------

def _state(v: float):
    return {"w": np.full((4, 4), v, np.float32),
            "m": np.full((4, 4), -v, np.float32)}


def test_snapshot_cadence_crossing_semantics(tmp_path):
    snap = SnapshotManager(tmp_path, every_steps=50)
    assert not snap.due(49)
    assert snap.due(50)
    assert snap.due(72)  # multi-step windows: boundary crossing, not equality
    snap.snapshot(_state(1.0), 72)
    assert not snap.due(99)   # still inside the same cadence interval
    assert snap.due(100)
    snap.close()

    off = SnapshotManager(tmp_path / "off", every_steps=0)
    assert not off.due(10_000)  # cadence off...
    assert off.maybe(_state(1.0), 10_000) is None
    assert off.snapshot(_state(1.0), 7) is not None  # ...explicit still works
    off.close()


def test_snapshot_double_buffer_isolation_and_gc(tmp_path):
    src = _state(1.0)
    with SnapshotManager(tmp_path, every_steps=1, keep=2) as snap:
        snap.snapshot(src, 1)
        src["w"][:] = 2.0  # mutate AFTER the snapshot: buffer must not alias
        snap.snapshot(src, 2)
        snap.wait()
        s1, _ = load_checkpoint(tmp_path / "step_0000000001", _state(0.0))
        s2, meta2 = load_checkpoint(tmp_path / "step_0000000002", _state(0.0))
        assert s1["w"][0, 0] == 1.0  # pre-mutation value: a real copy
        assert s2["w"][0, 0] == 2.0
        assert meta2["kind"] == "snapshot" and meta2["global_step"] == 2

        # Retention: keep=2 prunes the oldest after a third save.
        snap.snapshot(src, 3)
        snap.wait()
        names = sorted(p.name for p in tmp_path.glob("step_*"))
        assert names == ["step_0000000002", "step_0000000003"]
        assert snap.latest_dir().name == "step_0000000003"

        restored = snap.restore(_state(0.0))[0]
        np.testing.assert_array_equal(restored["w"], src["w"])


# --------------------------------------------------------------------------
# preempt
# --------------------------------------------------------------------------

def test_preemption_handler_flag_and_restore():
    prev = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as h:
        assert not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not h.requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h.requested
        assert h.last_signal == signal.SIGTERM
        os.kill(os.getpid(), signal.SIGTERM)  # repeated signal: still a flag
        assert h.requested
    assert signal.getsignal(signal.SIGTERM) is prev  # restored on exit


def test_find_latest_across_layouts(tmp_path):
    assert find_latest(tmp_path / "nothing") is None
    with pytest.raises(FileNotFoundError):
        resume_latest(_state(0.0), tmp_path / "nothing")

    ck_dir, snap_dir = tmp_path / "ck", tmp_path / "ck" / "snapshots"
    ck = CheckpointManager(ck_dir, async_save=False)
    ck.save(_state(8.0), {"epoch": 0}, step=8)
    with SnapshotManager(snap_dir) as snap:
        snap.snapshot(_state(9.0), 9)
        snap.wait()
        # Snapshot at step 9 beats the epoch checkpoint at step 8.
        found, step = find_latest(ck_dir, snap_dir)
        assert step == 9 and found == snap.latest_dir()

        state, meta, src = resume_latest(_state(0.0), ck_dir, snap_dir)
        assert meta["kind"] == "snapshot" and state["w"][0, 0] == 9.0

        # Ties go to the epoch checkpoint (clean epoch-start resume).
        ck.save(_state(9.5), {"epoch": 1}, step=9)
        found, step = find_latest(ck_dir, snap_dir)
        assert step == 9 and found == ck.latest_dir()

    # Flat pre-manager layout: the fallback of last resort.
    flat = tmp_path / "flat"
    save_checkpoint(flat, _state(3.0), {"epoch": 0})
    found, step = find_latest(flat)
    assert found == flat and step == -1


# --------------------------------------------------------------------------
# mid-epoch fast-forward (data pipeline)
# --------------------------------------------------------------------------

def test_pipeline_skip_steps_no_replay_no_skip(mesh8):
    from tpu_dp.data.cifar import make_synthetic
    from tpu_dp.data.pipeline import DataPipeline

    ds = make_synthetic(64, 10, seed=0, name="skiptest")
    pipe = DataPipeline(ds, batch_size=8, mesh=mesh8, shuffle=True, seed=3,
                        prefetch=0)
    pipe.set_epoch(1)
    full = [np.asarray(item["image"]) for _, item in pipe.windows(1)]
    assert len(full) == 8
    pipe.set_epoch(1)
    tail = [np.asarray(item["image"])
            for _, item in pipe.windows(1, skip_steps=3)]
    assert len(tail) == 5
    for a, b in zip(full[3:], tail):
        np.testing.assert_array_equal(a, b)  # step s drew the same examples

    # The resident twin: same invariant on the index stream.
    def steps_of(windows):
        out = []
        for n, idx in windows:
            arr = np.asarray(idx).reshape(n, -1)
            out.extend(arr[i] for i in range(n))
        return out

    pipe.set_epoch(1)
    full_idx = steps_of(pipe.index_windows(2))
    pipe.set_epoch(1)
    tail_idx = steps_of(pipe.index_windows(2, skip_steps=3))
    assert len(full_idx) == 8 and len(tail_idx) == 5
    for a, b in zip(full_idx[3:], tail_idx):
        np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------------------
# Trainer integration: preempt → snapshot → resume, bitwise (in-process)
# --------------------------------------------------------------------------

def _tiny_cfg(tmp_path, **overrides):
    from tpu_dp.config import Config

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 64
    c.data.synthetic_test_size = 16
    c.data.batch_size = 8  # 8 steps/epoch over the 8-device mesh
    c.data.prefetch = 1
    c.train.epochs = 2
    c.train.log_every = 100
    c.train.eval_at_end = False
    c.train.ckpt_dir = str(tmp_path / "ck")
    c.optim.lr = 0.05
    for k, v in overrides.items():
        section, name = k.split(".")
        setattr(getattr(c, section), name, v)
    return c


def _leaves_bytes(tree):
    return [(np.asarray(x).dtype.str, np.asarray(x).tobytes())
            for x in jax.tree_util.tree_leaves(tree)]


def test_preempt_mid_epoch_resume_bitwise_identical(tmp_path):
    """SIGTERM mid-epoch-1 → PreemptedError + final snapshot; a resumed
    Trainer fast-forwards the sampler and finishes with the full TrainState
    (params, momentum, step) bitwise-equal to an uninterrupted run."""
    from tpu_dp.train.trainer import Trainer

    control = Trainer(_tiny_cfg(tmp_path / "control"))
    control.fit()
    assert int(control.state.step) == 16

    cfg = _tiny_cfg(tmp_path / "run")
    cfg.resilience.snapshot_every_steps = 3
    cfg.resilience.fault = "preempt:step=11"  # SIGTERM to self, mid-epoch 1
    with pytest.raises(PreemptedError):
        Trainer(cfg).fit()
    snap_dirs = list((tmp_path / "run" / "ck" / "snapshots").glob("step_*"))
    assert snap_dirs, "preemption left no final snapshot"

    cfg2 = _tiny_cfg(tmp_path / "run")
    cfg2.resilience.snapshot_every_steps = 3
    cfg2.train.resume = True
    resumed = Trainer(cfg2)
    # Resumed mid-epoch from the snapshot, not at the epoch-0 boundary.
    assert resumed.start_epoch == 1 and resumed.start_step >= 3
    resumed.fit()
    assert int(resumed.state.step) == 16
    assert _leaves_bytes(resumed.state) == _leaves_bytes(control.state)


@pytest.mark.shard_update
def test_sharded_opt_state_snapshot_roundtrip(tmp_path):
    """SnapshotManager round-trips a TrainState whose optimizer state is
    sharded over the 8-device mesh (`train.update_sharding=sharded`): the
    double-buffered host copy assembles the global layout and a restore
    into a fresh sharded target is bitwise-complete."""
    from tpu_dp.models import Net
    from tpu_dp.train import SGD, create_train_state, shard_optimizer
    from tpu_dp.train.step import make_train_step_shard_map
    from tpu_dp.train.schedule import constant_lr
    from tpu_dp.parallel import dist
    from tpu_dp.data.cifar import make_synthetic, normalize

    mesh = dist.data_mesh()
    sopt = shard_optimizer(SGD(momentum=0.9), 8)
    state = create_train_state(
        Net(), jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
        sopt,
    )
    step = make_train_step_shard_map(Net(), sopt, mesh, constant_lr(0.05),
                                     update_sharding="sharded")
    ds = make_synthetic(16, 10, seed=0, name="snap")
    # One real step so the momentum shards are nonzero and device-committed
    # in their sharded layout.
    state, _ = step(state, {"image": normalize(ds.images),
                            "label": ds.labels})
    with SnapshotManager(tmp_path, every_steps=1) as snap:
        snap.snapshot(state, 1)
        snap.wait()
        target = create_train_state(
            Net(), jax.random.PRNGKey(1),
            np.zeros((1, 32, 32, 3), np.float32), sopt,
        )
        restored, meta = snap.restore(target)
    assert meta["global_step"] == 1
    assert _leaves_bytes(restored) == _leaves_bytes(state)


@pytest.mark.shard_update
def test_preempt_resume_with_sharded_opt_state(tmp_path):
    """Kill + auto-resume with the sharded weight update: a preempted
    sharded-mode run resumes from its snapshot (sharded opt state included)
    and finishes bitwise-identical to an uninterrupted sharded run."""
    from tpu_dp.train.trainer import Trainer

    def sharded_cfg(sub, **kw):
        c = _tiny_cfg(tmp_path / sub, **kw)
        c.train.update_sharding = "sharded"
        return c

    control = Trainer(sharded_cfg("control"))
    control.fit()
    assert int(control.state.step) == 16

    cfg = sharded_cfg("run")
    cfg.resilience.snapshot_every_steps = 3
    cfg.resilience.fault = "preempt:step=11"
    with pytest.raises(PreemptedError):
        Trainer(cfg).fit()
    assert list((tmp_path / "run" / "ck" / "snapshots").glob("step_*"))

    cfg2 = sharded_cfg("run")
    cfg2.resilience.snapshot_every_steps = 3
    cfg2.train.resume = True
    resumed = Trainer(cfg2)
    assert resumed.start_epoch == 1 and resumed.start_step >= 3
    resumed.fit()
    assert int(resumed.state.step) == 16
    assert _leaves_bytes(resumed.state) == _leaves_bytes(control.state)


# --------------------------------------------------------------------------
# End-to-end over real process boundaries: train.py + fault injection
# --------------------------------------------------------------------------

_CLI_COMMON = [
    "--data.dataset=synthetic",
    "--data.synthetic_train_size=64",
    "--data.synthetic_test_size=16",
    "--data.batch_size=8",
    "--train.epochs=2",
    "--train.log_every=100",
    "--train.eval_at_end=false",
    "--optim.lr=0.05",
    "--resilience.snapshot_every_steps=3",
]


def _run_train(ckpt_dir, *extra, timeout=240):
    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.pop("TPU_DP_FAULT", None)
    env["PYTHONPATH"] = (f"{repo}{os.pathsep}{env['PYTHONPATH']}"
                         if env.get("PYTHONPATH") else str(repo))
    proc = subprocess.run(
        [sys.executable, str(repo / "train.py"),
         f"--train.ckpt_dir={ckpt_dir}", *_CLI_COMMON, *extra],
        cwd=repo, env=env, capture_output=True, text=True, timeout=timeout,
    )
    return proc


@pytest.fixture(scope="module")
def control_run(tmp_path_factory):
    """One uninterrupted train.py run; returns its final params bytes."""
    ckpt_dir = tmp_path_factory.mktemp("resilience_control") / "ck"
    proc = _run_train(ckpt_dir)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return (ckpt_dir / "final_params.msgpack").read_bytes()


def test_kill_and_auto_resume_bitwise_identical(tmp_path, control_run):
    """The acceptance property: a worker hard-killed (`os._exit(137)`) at a
    mid-epoch step auto-resumes via `--resume=auto` from the latest async
    snapshot and reaches final params bitwise-identical to an uninterrupted
    run."""
    ckpt_dir = tmp_path / "ck"
    killed = _run_train(ckpt_dir, "--resilience.fault=kill:step=11")
    assert killed.returncode == KILL_EXIT_CODE, killed.stdout + killed.stderr
    assert not (ckpt_dir / "final_params.msgpack").exists()
    # The async snapshots survived the hard kill (cadence 3: step 9 landed).
    assert list((ckpt_dir / "snapshots").glob("step_*"))

    resumed = _run_train(ckpt_dir, "--resume=auto")
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    assert "resumed from" in resumed.stdout
    assert "snapshots" in resumed.stdout  # resumed from the snapshot layout
    assert (ckpt_dir / "final_params.msgpack").read_bytes() == control_run


def test_preempt_exits_143_and_resume_matches(tmp_path, control_run):
    """The preemption contract end-to-end: SIGTERM (injected to self) →
    final snapshot → exit 143; the supervisor's restart command
    (`--resume=auto`) completes bitwise-identical to uninterrupted."""
    ckpt_dir = tmp_path / "ck"
    preempted = _run_train(ckpt_dir, "--resilience.fault=preempt:step=5",
                           "--resilience.snapshot_every_steps=0")
    assert preempted.returncode == PREEMPTED_EXIT_CODE, (
        preempted.stdout + preempted.stderr)
    assert "preempted" in preempted.stdout
    # Even with periodic snapshotting off, the final snapshot landed.
    assert list((ckpt_dir / "snapshots").glob("step_*"))

    resumed = _run_train(ckpt_dir, "--resume=auto")
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    assert (ckpt_dir / "final_params.msgpack").read_bytes() == control_run


def test_resume_cli_flag():
    from tpu_dp.config import parse_cli

    cfg = parse_cli(["--resume=auto", "--data.dataset=synthetic"])
    assert cfg.train.resume is True
    assert parse_cli(["--data.dataset=synthetic"]).train.resume is False
    with pytest.raises(ValueError, match="--resume"):
        parse_cli(["--resume=never"])
