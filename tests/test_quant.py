"""Quantized collectives (`train.collective_dtype=int8`; docs/PERF.md
"Quantized collectives").

The correctness story of the blockwise-scaled int8 wire codec
(`tpu_dp/parallel/quant.py` + `collectives.psum_scatter_quant`), proven on
the 8-device CPU mesh:

1. **Codec units** — blockwise absmax round-trip error bound, zero blocks,
   NaN/Inf propagation through the scales (a corrupt gradient can never be
   laundered into a finite int8 value), overflow/clip accounting, layout
   math, wire-byte accounting.
2. **Collective level** — quantized reduce-scatter ≈ f32 reduce-scatter
   within the codec bound; small-leaf fallback bitwise; shard layout
   aligned with `shard_slice` (the sharded optimizer's contract); the
   codec-enabled `all_gather`.
3. **The wire-dtype parity harness** — ONE fixed-seed short-run A/B
   comparing every wire format (f32 / bf16 / int8) against the replicated
   f32 reference: f32 bitwise, bf16 and int8 within their documented
   tolerances and provably NOT bitwise (the compressed path really ran).
   This backfills the bf16 accuracy A/B that PR 4 left at bitwise-f32-only.
4. **Error feedback does real work** — the no-EF ablation lands measurably
   farther from the f32 trajectory than the EF run.
5. **Guardrails interaction** — the sentinel's health summary reads the
   *dequantized post-reduce* gradients; an injected NaN propagates through
   the codec, triggers the on-device skip, and the reverted state includes
   the residuals (a quarantined batch's rounding error is forgotten with
   the batch). Plus the Trainer-level `TPU_DP_FAULT` nan smoke.
6. **Checkpoint/resume** — residual round trip, resharding across world
   sizes (pending-correction preserving) and mode flips, pre-codec
   checkpoints loading with zero residuals, and the kill+auto-resume
   contract with int8 + residuals (bitwise vs an uninterrupted run).
7. **Analyzer** — gradsync counts the int8 payload exchange as THE
   reduction (scales uncounted), and a double exchange still fires DP202.
8. **obs** — quant.overflow / quant.clip_blocks counters flow from the
   per-window fetch into schema-3 records and gate through `obsctl diff`.

Fast lane: ``pytest -m quant``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dp.data.cifar import make_synthetic, normalize
from tpu_dp.models import Net
from tpu_dp.parallel import collectives, dist, quant
from tpu_dp.train import (
    SGD,
    constant_lr,
    create_train_state,
    make_train_step_shard_map,
    shard_optimizer,
)

pytestmark = pytest.mark.quant

WORLD = 8
BLOCK = 256


def _sample():
    return np.zeros((1, 32, 32, 3), np.float32)


def _make_batch(seed, n=16):
    ds = make_synthetic(n, 10, seed=seed, name="synthetic")
    return {"image": normalize(ds.images), "label": ds.labels}


def _copy(state):
    return jax.tree_util.tree_map(jnp.array, state)


def _states(momentum=0.9, block=BLOCK):
    model = Net()
    opt = SGD(momentum=momentum)
    sopt = shard_optimizer(SGD(momentum=momentum), WORLD)
    rng = jax.random.PRNGKey(0)
    state_r = create_train_state(model, rng, _sample(), opt)
    state_s = create_train_state(model, rng, _sample(), sopt)
    state_q = state_s.replace(
        residuals=quant.init_residuals(state_s.params, WORLD, block)
    )
    return model, opt, sopt, state_r, state_q


def _leaves_bytes(tree):
    return [(np.asarray(x).dtype.str, np.asarray(x).tobytes())
            for x in jax.tree_util.tree_leaves(tree)]


def _l2(a, b):
    return float(np.sqrt(sum(
        float(np.sum((np.asarray(x) - np.asarray(y)) ** 2))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b))
    )))


# --------------------------------------------------------------------------
# 1. codec units
# --------------------------------------------------------------------------

def test_quantize_roundtrip_error_bound(rng):
    """Dequantize(quantize(x)) is within half a quantization step of x for
    every element: |err| <= absmax/254 per block (absmax scaling, round to
    nearest)."""
    x = jnp.asarray(rng.normal(size=(4 * BLOCK,)).astype(np.float32))
    q, scales = quant.quantize_blocks(x, BLOCK)
    back = quant.dequantize_blocks(q, scales, BLOCK)
    err = np.abs(np.asarray(back) - np.asarray(x)).reshape(4, BLOCK)
    bound = np.abs(np.asarray(x)).reshape(4, BLOCK).max(axis=1) / 254.0
    assert (err.max(axis=1) <= bound + 1e-7).all()
    assert q.dtype == jnp.int8 and scales.dtype == jnp.float32


def test_quantize_zero_block_exact():
    x = jnp.zeros((BLOCK,), jnp.float32)
    q, scales = quant.quantize_blocks(x, BLOCK)
    back = quant.dequantize_blocks(q, scales, BLOCK)
    np.testing.assert_array_equal(np.asarray(back), 0.0)
    assert not np.isnan(np.asarray(back)).any()


@pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
def test_codec_never_launders_nonfinite(bad):
    """A non-finite value anywhere in a block makes the whole dequantized
    block non-finite (the scale carries the corruption) — the guard's
    finiteness sentinel sees it exactly as on the uncompressed path."""
    x = np.ones((2 * BLOCK,), np.float32)
    x[BLOCK + 3] = bad
    q, scales = quant.quantize_blocks(jnp.asarray(x), BLOCK)
    back = np.asarray(quant.dequantize_blocks(q, scales, BLOCK))
    assert np.isfinite(back[:BLOCK]).all()          # clean block untouched
    assert not np.isfinite(back[BLOCK:]).all()      # corrupt block flagged
    overflow, _ = quant.block_stats(q, scales)
    assert int(overflow) == 1


def test_block_stats_clip_counts_rail_crowding():
    # One value at absmax per block is structural (count 0); a second
    # value at the rail makes the block "clipping". Non-max values stay
    # well below 126.5/127 of the max so rounding cannot graze the rail.
    x = np.full((BLOCK,), 0.5, np.float32)
    x[-1] = 1.0
    q, s = quant.quantize_blocks(jnp.asarray(x), BLOCK)
    _, clip0 = quant.block_stats(q, s)
    x2 = x.copy()
    x2[:4] = 1.0  # five values at the rail
    q2, s2 = quant.quantize_blocks(jnp.asarray(x2), BLOCK)
    _, clip1 = quant.block_stats(q2, s2)
    assert int(clip0) == 0 and int(clip1) == 1


def test_layout_math_and_leaf_selection():
    assert quant.quant_padded_size(48000, 8, 256) == 49152
    assert quant.quant_padded_size(2048, 8, 256) == 2048
    # chunk-alignment identity: world * padded-chunk == quant_padded_size
    for n in (1, 450, 2400, 6001, 48000):
        pchunk = collectives.shard_size(n, 8)
        cpad = pchunk + (-pchunk) % 256
        assert 8 * cpad == quant.quant_padded_size(n, 8, 256), n
    assert quant.leaf_quantizes(2048, 8, 256)
    assert not quant.leaf_quantizes(2047, 8, 256)


def test_residual_init_covers_only_quantizable_leaves():
    _, _, _, _, state_q = _states()
    # Net on 8 devices at block 256: conv2/fc1/fc2 kernels quantize
    # (2400/48000/10080 elements); conv1 (450), fc3 (840) and all biases
    # ride the f32 fallback.
    assert set(state_q.residuals) == {
        "conv2/kernel", "fc1/kernel", "fc2/kernel",
    }
    for key, leaf in state_q.residuals.items():
        assert leaf.shape[0] == WORLD and leaf.dtype == jnp.float32
        assert leaf.shape[1] % (WORLD * BLOCK) == 0


def test_wire_report_compression():
    _, _, _, state_r, _ = _states()
    rep = quant.wire_report(state_r.params, WORLD, BLOCK)
    b = rep["wire_bytes_per_step"]
    assert b["bf16"] * 2 == b["f32"]
    assert b["int8"] < b["bf16"] < b["f32"]
    # Net is small-leaf-heavy; still >2.5x vs f32. ResNet-18 (all big
    # conv kernels) clears ~3.8x.
    assert rep["compression_vs_f32"] > 2.5
    assert rep["quantized_leaves"] == 3 and rep["leaves"] == 10


def test_make_wire_codec_parsing():
    assert quant.make_wire_codec("") is None
    assert quant.make_wire_codec("f32") is None
    assert isinstance(quant.make_wire_codec("bf16"), quant.CastCodec)
    c = quant.make_wire_codec("int8", block_size=64, error_feedback=False)
    assert isinstance(c, quant.Int8BlockCodec)
    assert c.block_size == 64 and not c.error_feedback
    with pytest.raises(ValueError, match="collective_dtype"):
        quant.make_wire_codec("int4")
    with pytest.raises(ValueError, match="quant_block_size"):
        quant.make_wire_codec("int8", block_size=0)


# --------------------------------------------------------------------------
# 2. collective level
# --------------------------------------------------------------------------

def _quant_roundtrip_fns(mesh8, mean=True, error_feedback=True):
    from jax.sharding import PartitionSpec as P

    from tpu_dp.train.step import _shard_map

    def via_quant(t, r):
        shards, new_r, stats = collectives.psum_scatter_quant(
            t, r, dist.DATA_AXIS, world=WORLD, mean=mean,
            block_size=BLOCK, error_feedback=error_feedback,
        )
        full = collectives.all_gather(shards, t, dist.DATA_AXIS)
        stats = {k: collectives.psum(v, dist.DATA_AXIS)
                 for k, v in stats.items()}
        return full, new_r, stats

    def via_f32(t):
        return collectives.all_gather(
            collectives.psum_scatter(t, dist.DATA_AXIS, world=WORLD,
                                     mean=mean), t, dist.DATA_AXIS)

    fq = jax.jit(_shard_map(via_quant, mesh8,
                            (P(dist.DATA_AXIS), P(dist.DATA_AXIS)),
                            (P(), P(dist.DATA_AXIS), P())))
    ff = jax.jit(_shard_map(via_f32, mesh8, (P(dist.DATA_AXIS),), P()))
    return fq, ff


def _per_replica_tree(rng):
    tree = {
        "big": jnp.asarray(rng.normal(size=(400, 120)).astype(np.float32)),
        "small": jnp.asarray(rng.normal(size=(5, 5, 3, 6)).astype(np.float32)),
    }
    return tree, jax.tree_util.tree_map(
        lambda x: jnp.stack([x * (i + 1) for i in range(WORLD)]), tree
    )


def test_quantized_scatter_tracks_f32_within_codec_bound(mesh8, rng):
    tree, args = _per_replica_tree(rng)
    res = quant.init_residuals(tree, WORLD, BLOCK)
    fq, ff = _quant_roundtrip_fns(mesh8)
    (out_q, new_res, stats), out_f = fq(args, res), ff(args)
    a, b = np.asarray(out_q["big"]), np.asarray(out_f["big"])
    assert np.abs(a - b).max() / np.abs(b).max() < 0.01
    assert not np.array_equal(a, b), "int8 wire produced bitwise f32?"
    # Small leaf took the f32 fallback: bitwise.
    np.testing.assert_array_equal(np.asarray(out_q["small"]),
                                  np.asarray(out_f["small"]))
    assert int(stats["overflow"]) == 0
    # The residual is exactly the rounding error of what went on the wire:
    # bounded by one quantization step of the largest block.
    step_bound = np.abs(np.asarray(args["big"])).max() / 126.0
    assert 0 < np.abs(np.asarray(new_res["big"])).max() < step_bound


def test_quantized_scatter_shard_layout_matches_shard_slice(mesh8, rng):
    """Replica i's quantized-reduced shard covers EXACTLY the elements
    `shard_slice` hands it for the params — the positional contract the
    sharded optimizer pairs them by. Proven by gathering the shards and
    comparing to the full quantized mean (already ≈f32): any chunk
    misalignment would garble the reassembled leaf entirely."""
    tree, args = _per_replica_tree(rng)
    res = quant.init_residuals(tree, WORLD, BLOCK)
    fq, ff = _quant_roundtrip_fns(mesh8)
    (out_q, _, _), out_f = fq(args, res), ff(args)
    # Alignment error would show as O(|x|) garbage, not O(absmax/254).
    for k in tree:
        a, b = np.asarray(out_q[k]), np.asarray(out_f[k])
        assert np.abs(a - b).max() <= np.abs(b).max() * 0.01 + 1e-6


def test_nan_propagates_through_quantized_scatter(mesh8, rng):
    tree, args = _per_replica_tree(rng)
    bad = dict(args)
    bad["big"] = bad["big"].at[3, 7, 7].set(np.nan)
    res = quant.init_residuals(tree, WORLD, BLOCK)
    fq, _ = _quant_roundtrip_fns(mesh8)
    out, _, stats = fq(bad, res)
    assert np.isnan(np.asarray(out["big"])).any()
    assert int(stats["overflow"]) >= 1


def test_all_gather_codecs_roundtrip(mesh8, rng):
    from jax.sharding import PartitionSpec as P

    from tpu_dp.train.step import _shard_map

    x = jnp.asarray(rng.normal(size=(450,)).astype(np.float32))

    def roundtrip(codec):
        def f(v):
            shards = collectives.shard_slice(v, dist.DATA_AXIS, world=WORLD)
            return collectives.all_gather(shards, v, dist.DATA_AXIS,
                                          codec=codec)
        return jax.jit(_shard_map(f, mesh8, (P(),), P()))(x)

    np.testing.assert_array_equal(np.asarray(roundtrip(None)), np.asarray(x))
    bf = np.asarray(roundtrip(quant.CastCodec(jnp.bfloat16)))
    np.testing.assert_allclose(bf, np.asarray(x), rtol=0.01, atol=1e-2)
    q8 = np.asarray(roundtrip(quant.Int8BlockCodec(block_size=64)))
    np.testing.assert_allclose(q8, np.asarray(x), rtol=0.02, atol=2e-2)
    assert not np.array_equal(q8, np.asarray(x))


# --------------------------------------------------------------------------
# 3. the wire-dtype parity harness (f32 / bf16 / int8 vs replicated f32)
# --------------------------------------------------------------------------

#: (collective_dtype, bucket_mb, bitwise, atol) — the documented accuracy
#: contract of each wire format over a 6-step fixed-seed run (docs/PERF.md
#: table), unbucketed AND under the bucketed overlap schedule
#: (`train.bucket_mb`): bucketing concatenates, it never changes the
#: per-element cross-replica arithmetic, so each wire dtype keeps its
#: unbucketed tolerance (bucketed f32 stays bitwise on this backend —
#: the documented cross-backend contract is reduction-order tolerance).
WIRE_CONTRACT = [
    ("", 0.0, True, 0.0),
    ("bf16", 0.0, False, 4e-3),
    ("int8", 0.0, False, 6e-3),
    ("", 0.05, True, 0.0),
    ("bf16", 0.05, False, 4e-3),
    ("int8", 0.05, False, 6e-3),
]


@pytest.mark.parametrize("wire,bucket_mb,bitwise,atol", WIRE_CONTRACT,
                         ids=lambda v: str(v) if v != "" else "f32")
def test_wire_dtype_parity_harness(mesh8, wire, bucket_mb, bitwise, atol):
    """One harness, all three wire dtypes (the PR-4 bf16 path gains the
    fixed-seed tolerance A/B it never had), bucketed × unbucketed: sharded
    update with the given wire format vs the replicated f32 reference. f32
    must be bitwise; the compressed formats must be within their documented
    tolerance AND not bitwise (proof they actually ran compressed)."""
    from tpu_dp.parallel import bucketing

    model, opt, sopt, state_r, state_q = _states()
    if bucket_mb and wire == "int8":
        state_q = state_q.replace(residuals=quant.init_residuals(
            state_q.params, WORLD, BLOCK,
            bucket_bytes=bucketing.parse_bucket_mb(bucket_mb)))
    step_r = make_train_step_shard_map(model, opt, mesh8, constant_lr(0.05))
    step_w = make_train_step_shard_map(
        model, sopt, mesh8, constant_lr(0.05), update_sharding="sharded",
        collective_dtype=wire or None, bucket_mb=bucket_mb,
    )
    sr = _copy(state_r)
    sw = _copy(state_q if wire == "int8" else
               state_q.replace(residuals={}))
    for i in range(6):
        batch = _make_batch(i)
        sr, _ = step_r(sr, batch)
        sw, _ = step_w(sw, batch)
    identical = True
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(sw.params)):
        a, b = np.asarray(a), np.asarray(b)
        if bitwise:
            np.testing.assert_array_equal(a, b)
        else:
            np.testing.assert_allclose(a, b, atol=atol)
        identical &= bool(np.array_equal(a, b))
    if not bitwise:
        assert not identical, f"{wire} wire produced bitwise-f32 results?"


def test_error_feedback_ablation_is_measurably_worse(mesh8):
    """The residual path does real work: over a 24-step fixed-seed run the
    no-error-feedback ablation drifts MORE than 2x farther from the f32
    trajectory than the EF run (measured margin ~6x; asserted at 2x so jax
    version drift cannot flake it). Deterministic — fixed seeds, CPU."""
    model, opt, sopt, state_r, state_q = _states()
    lr = constant_lr(0.01)
    step_r = make_train_step_shard_map(model, opt, mesh8, lr)
    step_ef = make_train_step_shard_map(
        model, sopt, mesh8, lr, update_sharding="sharded",
        collective_dtype="int8")
    step_no = make_train_step_shard_map(
        model, sopt, mesh8, lr, update_sharding="sharded",
        collective_dtype="int8", quant_error_feedback=False)
    sr, se, sn = _copy(state_r), _copy(state_q), _copy(state_q)
    for i in range(24):
        batch = _make_batch(i)
        sr, _ = step_r(sr, batch)
        se, _ = step_ef(se, batch)
        sn, _ = step_no(sn, batch)
    d_ef = _l2(se.params, sr.params)
    d_no = _l2(sn.params, sr.params)
    assert d_ef * 2 < d_no, (d_ef, d_no)
    # The ablation's residuals were never consumed nor updated.
    for leaf in jax.tree_util.tree_leaves(sn.residuals):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)
    for leaf in jax.tree_util.tree_leaves(se.residuals):
        assert np.abs(np.asarray(leaf)).max() > 0


def test_int8_multi_step_window_tracks_f32(mesh8):
    """The quantized wire composes with the windowed device-side loop."""
    from tpu_dp.train import make_multi_step

    model, opt, sopt, state_r, state_q = _states()
    K = 4
    loop_r = make_multi_step(model, opt, mesh8, constant_lr(0.05),
                             num_steps=K)
    loop_q = make_multi_step(model, sopt, mesh8, constant_lr(0.05),
                             num_steps=K, update_sharding="sharded",
                             collective_dtype="int8")
    batches = [_make_batch(100 + i) for i in range(K)]
    pool = {
        "image": np.stack([b["image"] for b in batches]),
        "label": np.stack([b["label"] for b in batches]),
    }
    sr, _ = loop_r(_copy(state_r), pool)
    sq, mq = loop_q(_copy(state_q), pool)
    assert int(sq.step) == K
    assert mq["quant_overflow"].shape == (K,)
    for a, b in zip(jax.tree_util.tree_leaves(sr.params),
                    jax.tree_util.tree_leaves(sq.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=6e-3)


def test_residual_memory_is_flat_sharded(mesh8):
    """Residuals live like the opt state: per-replica addressable shard =
    one [1, qpad] row per leaf — world-sharded, never replicated."""
    model, _, sopt, _, state_q = _states()
    step = make_train_step_shard_map(model, sopt, mesh8, constant_lr(0.05),
                                     update_sharding="sharded",
                                     collective_dtype="int8")
    new_state, _ = step(_copy(state_q), _make_batch(0))
    for key, leaf in new_state.residuals.items():
        shards = leaf.addressable_shards
        assert len(shards) == WORLD, key
        assert shards[0].data.shape == (1, leaf.shape[1]), key


def test_factory_validation():
    mesh = dist.data_mesh()
    sopt = shard_optimizer(SGD(momentum=0.9), WORLD)
    with pytest.raises(ValueError, match="quant_block_size"):
        make_train_step_shard_map(Net(), sopt, mesh, constant_lr(0.05),
                                  update_sharding="sharded",
                                  collective_dtype="int8",
                                  quant_block_size=0)
    with pytest.raises(ValueError, match="collective_dtype"):
        make_train_step_shard_map(Net(), SGD(momentum=0.9), mesh,
                                  constant_lr(0.05),
                                  collective_dtype="int8")


# --------------------------------------------------------------------------
# 5. guardrails interaction
# --------------------------------------------------------------------------

def test_sentinel_reads_dequantized_health_and_skips_nan(mesh8):
    """The sentinel's health summary sits AFTER dequantize-and-sum: a clean
    step reports a finite grad norm from the dequantized shards; an
    injected NaN survives the codec (scale propagation), the grad norm
    goes non-finite, the update is withheld, and the ENTIRE state —
    params, opt shards, step counter, AND the error-feedback residuals —
    is bitwise the pre-step state."""
    from tpu_dp.train.step import default_guard_in

    model, _, sopt, _, state_q = _states()
    step = make_train_step_shard_map(
        model, sopt, mesh8, constant_lr(0.05), update_sharding="sharded",
        collective_dtype="int8", sentinel=True,
    )
    s0 = _copy(state_q)
    before = _leaves_bytes(s0)

    clean, m_clean = step(s0, _make_batch(0), default_guard_in())
    assert int(m_clean["applied"]) == 1
    assert np.isfinite(float(m_clean["grad_norm"]))
    assert float(m_clean["grad_norm"]) > 0

    gi = default_guard_in()
    gi["fault_step"] = np.int32(1)  # clean step advanced the counter to 1
    gi["fault_scale"] = np.float32(np.nan)
    poisoned, m_bad = step(_copy(clean), _make_batch(1), gi)
    assert int(m_bad["applied"]) == 0
    assert not np.isfinite(float(m_bad["grad_norm"]))
    # Quarantine contract, residuals included: as if the batch never was.
    assert _leaves_bytes(poisoned) == _leaves_bytes(clean)
    assert _leaves_bytes(clean) != before  # ...and the clean step did apply


def test_trainer_nan_fault_skips_under_int8(tmp_path):
    """`TPU_DP_FAULT`-style nan injection through the full Trainer with
    int8 collectives + guard.action=skip behaves exactly like the
    uncompressed guard lane: one quarantine record, the run completes, the
    final params are finite."""
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 64
    c.data.synthetic_test_size = 16
    c.data.batch_size = 8
    c.data.prefetch = 1
    c.train.epochs = 1
    c.train.log_every = 100
    c.train.eval_at_end = False
    c.train.steps_per_call = 1
    c.train.ckpt_dir = str(tmp_path / "ck")
    c.train.update_sharding = "sharded"
    c.train.collective_dtype = "int8"
    c.optim.lr = 0.05
    c.guard.enabled = True
    c.guard.action = "skip"
    c.resilience.fault = "nan:step=3"

    t = Trainer(c)
    t.fit()
    recs = [json.loads(line) for line in
            t.quarantine_path.read_text().splitlines()]
    assert [r["kind"] for r in recs] == ["quarantine"]
    assert recs[0]["step"] in (3, 4)  # the armed fault's boundary step
    # The skipped step withheld its update: 8 planned, 7 applied.
    assert int(t.state.step) == 7
    for leaf in jax.tree_util.tree_leaves(t.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    # Codec counters flowed through the guard's per-window fetch.
    from tpu_dp.obs.counters import counters
    assert counters.get("quant.overflow") >= 1  # the nan-poisoned blocks


# --------------------------------------------------------------------------
# 6. checkpoint / resume
# --------------------------------------------------------------------------

def test_residuals_roundtrip_same_layout(tmp_path):
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model, _, sopt, _, state_q = _states()
    state_q = state_q.replace(residuals={
        k: v + np.float32(0.25) * (i + 1)
        for i, (k, v) in enumerate(sorted(state_q.residuals.items()))
    })
    save_checkpoint(tmp_path, state_q, {"epoch": 0})
    restored, _ = load_checkpoint(
        tmp_path, _states()[4])
    assert _leaves_bytes(restored.residuals) == _leaves_bytes(
        state_q.residuals)


def test_residuals_reshard_across_world_sizes(tmp_path):
    """World 8 → world 4: the TOTAL pending correction (sum of every
    replica's residual, in leaf element order) is preserved exactly —
    replica 0 of the new world owes the whole debt, everyone else zero."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model = Net()
    rng = jax.random.PRNGKey(0)
    opt8 = shard_optimizer(SGD(momentum=0.9), 8)
    opt4 = shard_optimizer(SGD(momentum=0.9), 4)
    state8 = create_train_state(model, rng, _sample(), opt8)
    res8 = quant.init_residuals(state8.params, 8, BLOCK)
    # Recognizable per-replica errors, zero in each chunk's pad region
    # (the invariant a real trajectory maintains).
    filled = {}
    gen = np.random.default_rng(3)
    for key, leaf in res8.items():
        n = {p: l for p, l in
             [("/".join(str(getattr(x, 'key', x)) for x in path), lf)
              for path, lf in
              jax.tree_util.tree_leaves_with_path(state8.params)]
             }[key].size
        pchunk = collectives.shard_size(n, 8)
        cpad = leaf.shape[1] // 8
        rows = gen.normal(size=(8, 8, cpad)).astype(np.float32) * 1e-3
        rows[:, :, pchunk:] = 0.0
        filled[key] = jnp.asarray(rows.reshape(8, -1))
    state8 = state8.replace(residuals=filled)
    save_checkpoint(tmp_path / "w8", state8, {"epoch": 0})

    state4 = create_train_state(model, rng, _sample(), opt4)
    state4 = state4.replace(
        residuals=quant.init_residuals(state4.params, 4, BLOCK))
    restored, _ = load_checkpoint(tmp_path / "w8", state4)
    param_sizes = {
        "/".join(str(getattr(x, "key", x)) for x in path): leaf.size
        for path, leaf in jax.tree_util.tree_leaves_with_path(state8.params)
    }
    # conv2 (2400 elems) stops quantizing at world 4 (needs >= 4*256*...?
    # 2400 >= 1024: still quantizes). Compare pending sums leaf-wise.
    for key, saved in filled.items():
        n = param_sizes[key]
        pchunk8 = collectives.shard_size(n, 8)
        pending = (np.asarray(saved).sum(axis=0)
                   .reshape(8, -1)[:, :pchunk8].reshape(-1)[:n])
        got = np.asarray(restored.residuals[key])
        pchunk4 = collectives.shard_size(n, 4)
        got_pending = (got.sum(axis=0)
                       .reshape(4, -1)[:, :pchunk4].reshape(-1)[:n])
        np.testing.assert_allclose(got_pending, pending, atol=1e-7)
        np.testing.assert_array_equal(got[1:], 0.0)


def test_precodec_checkpoint_loads_with_zero_residuals(tmp_path):
    """A checkpoint written with the codec OFF (residuals={} — byte-wise
    what every pre-codec checkpoint serializes to) restores into an
    int8-enabled target with zero-initialized residuals; and a quantized
    checkpoint restores into a codec-off target with residuals dropped."""
    from tpu_dp.checkpoint import load_checkpoint, save_checkpoint

    model, _, sopt, _, state_q = _states()
    plain = state_q.replace(residuals={})
    save_checkpoint(tmp_path / "plain", plain, {"epoch": 0})
    restored, _ = load_checkpoint(tmp_path / "plain", state_q)
    assert set(restored.residuals) == set(state_q.residuals)
    for leaf in jax.tree_util.tree_leaves(restored.residuals):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)

    state_q2 = state_q.replace(residuals={
        k: v + 1.0 for k, v in state_q.residuals.items()})
    save_checkpoint(tmp_path / "quant", state_q2, {"epoch": 0})
    dropped, _ = load_checkpoint(tmp_path / "quant", plain)
    assert dropped.residuals == {}

    # A GENUINELY old checkpoint (pre-codec msgpack: no "residuals" key at
    # all, the byte format every earlier PR wrote) restores the same way.
    from flax import serialization

    from tpu_dp.checkpoint import _to_host

    sd = serialization.to_state_dict(_to_host(plain))
    del sd["residuals"]
    old = tmp_path / "old"
    old.mkdir()
    (old / "state.msgpack").write_bytes(serialization.msgpack_serialize(sd))
    (old / "meta.json").write_text("{}")
    from_old, _ = load_checkpoint(old, state_q)
    assert set(from_old.residuals) == set(state_q.residuals)
    for leaf in jax.tree_util.tree_leaves(from_old.residuals):
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)


def test_preempt_resume_with_int8_residuals(tmp_path):
    """The kill+auto-resume contract with the quantized wire: a preempted
    int8 run resumes from its snapshot (error-feedback residuals included)
    and finishes bitwise-identical — residuals too — to an uninterrupted
    int8 run."""
    from tpu_dp.resilience import PreemptedError
    from tpu_dp.config import Config
    from tpu_dp.train.trainer import Trainer

    def int8_cfg(sub, **kw):
        c = Config()
        c.data.dataset = "synthetic"
        c.data.synthetic_train_size = 64
        c.data.synthetic_test_size = 16
        c.data.batch_size = 8
        c.data.prefetch = 1
        c.train.epochs = 2
        c.train.log_every = 100
        c.train.eval_at_end = False
        c.train.ckpt_dir = str(tmp_path / sub / "ck")
        c.train.update_sharding = "sharded"
        c.train.collective_dtype = "int8"
        c.optim.lr = 0.05
        for k, v in kw.items():
            section, name = k.split(".")
            setattr(getattr(c, section), name, v)
        return c

    control = Trainer(int8_cfg("control"))
    control.fit()
    assert int(control.state.step) == 16
    assert any(np.abs(np.asarray(v)).max() > 0
               for v in jax.tree_util.tree_leaves(control.state.residuals))

    cfg = int8_cfg("run")
    cfg.resilience.snapshot_every_steps = 3
    cfg.resilience.fault = "preempt:step=11"
    with pytest.raises(PreemptedError):
        Trainer(cfg).fit()

    cfg2 = int8_cfg("run")
    cfg2.resilience.snapshot_every_steps = 3
    cfg2.train.resume = True
    resumed = Trainer(cfg2)
    resumed.fit()
    assert int(resumed.state.step) == 16
    assert _leaves_bytes(resumed.state) == _leaves_bytes(control.state)


# --------------------------------------------------------------------------
# 7. analyzer (Level 2; Level 3 lives in test_hlo_analysis.py)
# --------------------------------------------------------------------------

@pytest.mark.analysis
def test_gradsync_counts_int8_exchange_exactly_once():
    from tpu_dp.analysis import gradsync

    for accum in (1, 2):
        findings, report = gradsync.verify_repo_step(
            accum_steps=accum, update_sharding="sharded",
            collective_dtype="int8",
        )
        assert findings == []
        assert report and all(c == 1 for c in report.values()), report


@pytest.mark.analysis
def test_gradsync_double_int8_exchange_fires_dp202():
    """A gradient routed through TWO int8 exchanges counts twice (DP202);
    the f32 scales exchange alongside a single payload exchange does NOT
    inflate the count (it is wire metadata, like the params all-gather)."""
    from jax import lax

    from tpu_dp.analysis.gradsync import verify_local_step

    def exchange(v):
        q = jnp.clip(jnp.round(v), -127, 127).astype(jnp.int8)
        scales = jnp.ones((8,), jnp.float32)
        qx = lax.all_to_all(q.reshape(8, -1), "data",
                            split_axis=0, concat_axis=0, tiled=True)
        sx = lax.all_to_all(scales.reshape(8, 1), "data",
                            split_axis=0, concat_axis=0, tiled=True)
        return (jnp.sum(qx.astype(jnp.float32), axis=0)
                * jnp.sum(sx) / jnp.sum(sx))

    def single(state, batch):
        g = state["params"]["w"]
        shard = exchange(g)
        return {"params": {"w": state["params"]["w"][: shard.size] - shard}}

    def double(state, batch):
        g = state["params"]["w"]
        shard = exchange(jnp.tile(exchange(g), 8))
        return {"params": {"w": state["params"]["w"][: shard.size] - shard}}

    state = {"params": {"w": jnp.zeros((64,), jnp.float32)}}
    ok, report = verify_local_step(single, (state, None), world=8)
    assert ok == [] and list(report.values()) == [1]
    bad, report2 = verify_local_step(double, (state, None), world=8)
    assert [f.rule for f in bad] == ["DP202"] and list(
        report2.values()) == [2]


# --------------------------------------------------------------------------
# 8. obs: counters → schema-3 records → obsctl diff
# --------------------------------------------------------------------------

@pytest.mark.obs
def test_trainer_publishes_quant_counters_into_metrics(tmp_path):
    """An obs=full int8 run stamps quant.overflow / quant.clip_blocks into
    its schema-3 records via the counter snapshots, and `obsctl diff`
    gates on them: identical baseline passes, a lower-count baseline makes
    the run a regression."""
    from tpu_dp.config import Config
    from tpu_dp.obs.counters import counters
    from tpu_dp.obs.obsctl import (
        RunArtifacts, diff_verdict, load_baseline, run_efficiency,
    )
    from tpu_dp.train.trainer import Trainer

    counters.reset()
    c = Config()
    c.data.dataset = "synthetic"
    c.data.synthetic_train_size = 32
    c.data.synthetic_test_size = 16
    c.data.batch_size = 8
    c.data.prefetch = 1
    c.train.epochs = 1
    c.train.log_every = 100
    c.train.eval_at_end = False
    c.train.obs = "full"
    c.train.ckpt_dir = str(tmp_path / "ck")
    c.train.update_sharding = "sharded"
    c.train.collective_dtype = "int8"
    t = Trainer(c)
    t.fit()

    records = [json.loads(line) for line in
               (tmp_path / "ck" / "metrics.jsonl").read_text().splitlines()]
    stamped = [r for r in records
               if isinstance(r.get("counters"), dict)
               and "quant.overflow" in r["counters"]]
    assert stamped, "no schema-3 record carries the quant counters"
    assert all(r.get("schema") == 3 for r in stamped)
    last = stamped[-1]["counters"]
    assert last["quant.overflow"] == 0  # clean run: explicit zero

    run = run_efficiency(RunArtifacts(tmp_path / "ck"))
    # Rates, not cumulative counts: a long healthy run must not read as a
    # regression against a short bench baseline (same unit both sides).
    assert run["quant_overflow_per_step"] == 0
    assert run["quant_clip_blocks_per_step"] is not None

    base_ok = {"mfu": None, "goodput": None, "p95_ms": None,
               "quant_overflow_per_step": run["quant_overflow_per_step"],
               "quant_clip_blocks_per_step":
                   run["quant_clip_blocks_per_step"]}
    v = diff_verdict(run, base_ok, tolerance=0.1)
    assert not v["regressed"]
    base_strict = dict(base_ok, quant_clip_blocks_per_step=-1)
    v2 = diff_verdict(run, base_strict, tolerance=0.1)
    if run["quant_clip_blocks_per_step"] > 0:
        assert v2["regressed"]

    # BENCH-record shape: the quant block's N-step totals normalize to
    # per-step rates in the baseline loader.
    bench = tmp_path / "bench.json"
    bench.write_text(json.dumps({
        "mfu": 0.5,
        "quant": {"overflow": 0, "clip_blocks": 8, "stats_steps": 4},
    }))
    loaded = load_baseline(bench)
    assert loaded["quant_overflow_per_step"] == 0
    assert loaded["quant_clip_blocks_per_step"] == 2.0


@pytest.mark.obs
def test_diff_verdict_skips_quant_for_unquantized_runs():
    from tpu_dp.obs.obsctl import diff_verdict

    run = {"mfu": 0.5, "goodput": 0.9, "p95_ms": 10.0,
           "quant_overflow_per_step": None,
           "quant_clip_blocks_per_step": None}
    base = {"mfu": 0.5, "goodput": 0.9, "p95_ms": 10.0}
    v = diff_verdict(run, base, tolerance=0.05)
    assert not v["regressed"]
    skipped = {c["signal"] for c in v["checks"]
               if c["verdict"] == "skipped"}
    # The comm-attribution signals follow the same contract: a run that
    # never profiled a comm window is skipped, never compared as 0 — as
    # is the throughput headline when neither side measured it.
    assert skipped == {"quant_overflow_per_step",
                       "quant_clip_blocks_per_step",
                       "comm_ms", "exposed_comm_ms", "overlap_frac",
                       "img_per_sec_per_chip"}
