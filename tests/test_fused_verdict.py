"""`tools/fused_verdict.py` renders the round's fused-vs-unfused decision
from the bench archive — the logic that picks which rows form each
comparison cell and what the verdict line says must not quietly drift.
"""

from __future__ import annotations

import json
import sys

from tools import fused_verdict


def _row(value, ts, batch=2048, window=30, fused="", bwd=False, xent="jnp",
         backend="tpu", mfu=0.5, smoke=False):
    r = {"metric": "cifar10_resnet18_train_images_per_sec_per_chip",
         "value": value, "ts": ts, "backend": backend, "mfu": mfu,
         "config": {"per_chip_batch": batch, "steps_per_call": window,
                    "fused_stages": fused, "fused_bwd": bwd, "xent": xent}}
    if smoke:
        r["smoke"] = True
    return r


def _write(monkeypatch, tmp_path, rows):
    p = tmp_path / "results.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    monkeypatch.setattr(fused_verdict, "RESULTS", p)
    monkeypatch.setattr(fused_verdict, "CAPTURE", tmp_path / "nocap")


def _verdict_line(capsys):
    out = capsys.readouterr().out
    return next(l for l in out.splitlines() if l.startswith("VERDICT:")), out


def test_smoke_cpu_and_pallas_xent_rows_excluded(monkeypatch, tmp_path,
                                                 capsys):
    _write(monkeypatch, tmp_path, [
        _row(34000, "2026-07-30T01:00:00Z"),
        # smoke on tpu backend and plain cpu backend pin the two filters
        # independently (a smoke row is not necessarily backend=cpu).
        _row(9.9, "2026-07-30T02:00:00Z", smoke=True),
        _row(7.7, "2026-07-30T02:30:00Z", backend="cpu"),
        _row(50000, "2026-07-30T03:00:00Z", xent="pallas"),
    ])
    monkeypatch.setattr(sys, "argv", ["fused_verdict.py"])
    fused_verdict.main()
    line, out = _verdict_line(capsys)
    # The unfused cell must be the tpu/jnp row — not the newer pallas-xent
    # row, not the smoke row.
    assert "34,000" in out and "50,000" not in out
    assert "9.9" not in out and "7.7" not in out


def test_winning_variant_flips_the_verdict(monkeypatch, tmp_path, capsys):
    _write(monkeypatch, tmp_path, [
        _row(34000, "2026-07-30T01:00:00Z"),
        _row(36000, "2026-07-30T01:00:00Z", fused="0"),
        _row(33000, "2026-07-30T01:00:00Z", fused="all"),
    ])
    monkeypatch.setattr(sys, "argv", ["fused_verdict.py"])
    fused_verdict.main()
    line, _ = _verdict_line(capsys)
    assert "BEATS" in line and "fused[0]" in line and "+5.9%" in line


def test_losing_variants_keep_default_off(monkeypatch, tmp_path, capsys):
    _write(monkeypatch, tmp_path, [
        _row(34000, "2026-07-30T01:00:00Z"),
        _row(31000, "2026-07-30T01:00:00Z", fused="all", bwd=True),
    ])
    monkeypatch.setattr(sys, "argv", ["fused_verdict.py"])
    fused_verdict.main()
    line, _ = _verdict_line(capsys)
    assert "no fused variant beats unfused" in line
    assert "fused[all]+bwd" in line and "-8.8%" in line


def test_newest_row_wins_a_cell(monkeypatch, tmp_path, capsys):
    _write(monkeypatch, tmp_path, [
        _row(30000, "2026-07-29T01:00:00Z"),
        _row(34000, "2026-07-30T01:00:00Z"),  # newer same cell
        _row(35000, "2026-07-30T02:00:00Z", fused="0"),
    ])
    monkeypatch.setattr(sys, "argv", ["fused_verdict.py"])
    fused_verdict.main()
    line, out = _verdict_line(capsys)
    assert "34,000" in line and "30,000" not in out


def test_pending_without_fused_measurements(monkeypatch, tmp_path, capsys):
    _write(monkeypatch, tmp_path, [_row(34000, "2026-07-30T01:00:00Z")])
    monkeypatch.setattr(sys, "argv", ["fused_verdict.py"])
    fused_verdict.main()
    line, _ = _verdict_line(capsys)
    assert "pending" in line
