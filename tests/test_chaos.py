"""Chaos-harness suite (`tpu_dp/chaos/`, docs/CHAOS.md).

Units for every leg of ISSUE 14's tentpole — composed-schedule parsing,
storage-fault shim placement at the checkpoint/snapshot/ledger seams,
the checksum manifest round trip with typed refusals, the unified IO
retry budget, skip-candidate attribution, and shrinker minimality — plus
the in-process half of the composed-fault acceptance trio: ``bitrot`` on
the newest snapshot before a spike rollback forces the older-candidate
fallback and still ends bitwise-equal to an oracle that never saw the
corrupt save. The multi-rank halves (SDC-during-grow, kill-mid-regroup)
are the ``slow``-marked subprocess tests at the bottom, run by the
``tools/run_tier1.sh --chaos`` lane.
"""

import json
import os
from pathlib import Path

import jax
import numpy as np
import pytest

from tpu_dp.chaos.storage import shim
from tpu_dp.resilience.faultinject import FaultInjector, FaultPlan

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_shim_and_budget():
    """The shim and the IO budget are process-global: every test starts
    and leaves them pristine."""
    from tpu_dp.resilience import retry

    shim.reset()
    retry.configure_io_retry(retry.DEFAULT_IO_RETRY_S)
    yield
    shim.reset()
    retry.configure_io_retry(retry.DEFAULT_IO_RETRY_S)


def _mini_state():
    from tpu_dp.models import Net
    from tpu_dp.train import SGD, create_train_state

    return create_train_state(Net(), jax.random.PRNGKey(0),
                              np.zeros((1, 32, 32, 3), np.float32),
                              SGD(0.9))


# ---------------------------------------------------------------------------
# composed-schedule parsing
# ---------------------------------------------------------------------------


def test_schedule_parse_composed_clauses():
    plans = FaultPlan.parse_schedule(
        "bitrot:step=4;spike:step=8,scale=1e6;kill:step=9,rank=1;")
    assert [p.kind for p in plans] == ["bitrot", "spike", "kill"]
    assert plans[1].scale == 1e6 and plans[2].rank == 1
    # Round trip: to_spec parses back to the same plans.
    again = FaultPlan.parse_schedule(";".join(p.to_spec() for p in plans))
    assert again == plans
    assert FaultPlan.parse_schedule("") == []
    assert FaultPlan.parse_schedule(" ; ") == []
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultPlan.parse_schedule("kill:step=1;explode:step=2")


def test_storage_kinds_parse_with_n_and_ms():
    p = FaultPlan.parse("ioerr:step=6,n=2")
    assert (p.kind, p.step, p.count) == ("ioerr", 6, 2)
    assert FaultPlan.parse("ioerr:step=6").count == 1  # default 1 write
    p = FaultPlan.parse("slowfs:step=3,ms=20,n=4")
    assert (p.delay_ms, p.count) == (20.0, 4)
    assert FaultPlan.parse("enospc:step=2").kind == "enospc"


def test_injector_composed_plans_arm_and_spend_independently():
    plans = FaultPlan.parse_schedule("drop:step=3;leave:step=5")
    inj = FaultInjector(plans, rank=0)
    inj.on_step(3)
    assert inj.take_drop() and not inj.leave_requested
    assert not inj.fired  # the leave plan is still pending
    inj.on_step(5)
    assert inj.leave_requested and inj.fired
    # Single-plan accessor + spend helper keep the legacy surface alive.
    inj2 = FaultInjector.from_spec("relaunch:step=2;drop:step=9", rank=0)
    assert inj2.plan.kind == "relaunch" and inj2.has_kind("drop")
    inj2.on_step(2)
    assert inj2.fired_kind("relaunch") and not inj2.fired_kind("drop")
    inj2.spend("drop")
    assert inj2.fired


def test_injector_same_boundary_clauses_all_land():
    # Two clauses at one boundary: both must fire in the same sweep (kill
    # would fire LAST — not testable without dying, but the ordering key
    # is pinned here via the sort the injector applies).
    inj = FaultInjector(FaultPlan.parse_schedule("drop:step=4;leave:step=4"),
                        rank=0)
    inj.on_step(4)
    assert inj.take_drop() and inj.leave_requested
    kill_last = sorted(
        [FaultPlan.parse("kill:step=4"), FaultPlan.parse("drop:step=4")],
        key=lambda p: p.kind == "kill")
    assert [p.kind for p in kill_last] == ["drop", "kill"]


def test_sdc_applies_before_same_boundary_kill(monkeypatch):
    """FaultHook contract: a kill never returns (`os._exit`), so a
    same-boundary `sdc:;kill:` composition must corrupt the params
    BEFORE the host dies — dropping the sdc would make the trial
    believe it tested SDC-composed-with-death when it only tested the
    death."""
    from types import SimpleNamespace

    import tpu_dp.resilience.faultinject as fi
    from tpu_dp.train.hooks import FaultHook, StepEvent

    order = []
    monkeypatch.setattr(fi.os, "_exit",
                        lambda code: order.append(("kill", code)))
    inj = fi.FaultInjector(
        fi.FaultPlan.parse_schedule("sdc:step=5,rank=0;kill:step=5"),
        rank=0)
    tr = SimpleNamespace(
        fault=inj, _host_step=5,
        _inject_sdc=lambda plan: order.append(("sdc", plan.kind)))
    FaultHook(tr).on_step_end(StepEvent(epoch=0, done=5, n=1, window=()))
    assert order == [("sdc", "sdc"), ("kill", fi.KILL_EXIT_CODE)]


def test_injector_rank_gated_storage_arm():
    inj = FaultInjector(FaultPlan.parse("bitrot:step=2,rank=1"), rank=0)
    inj.on_step(10)
    assert not shim.active  # bystander rank never arms the shim
    tgt = FaultInjector(FaultPlan.parse("bitrot:step=2,rank=1"), rank=1)
    tgt.on_step(2)
    assert shim.active and tgt.fired


# ---------------------------------------------------------------------------
# checksum manifest: round trip + typed refusals
# ---------------------------------------------------------------------------


def test_checkpoint_checksum_roundtrip_counts_verified(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib
    from tpu_dp.obs.counters import counters

    state = _mini_state()
    d = tmp_path / "ck"
    ckpt_lib.save_checkpoint(d, state, {"epoch": 0})
    meta = json.loads((d / "meta.json").read_text())
    assert meta["schema"] == ckpt_lib.CKPT_SCHEMA
    integ = meta["integrity"]
    assert integ["algo"] == "sha256" and integ["leaves"]
    assert all(len(h) == 64 for h in integ["leaves"].values())
    before = counters.get("ckpt.verified_loads")
    restored, meta2 = ckpt_lib.load_checkpoint(d, state)
    assert counters.get("ckpt.verified_loads") == before + 1
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_bitrot_is_typed_refusal(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib

    state = _mini_state()
    d = tmp_path / "ck"
    ckpt_lib.save_checkpoint(d, state, {})
    payload = d / "state.msgpack"
    blob = bytearray(payload.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    payload.write_bytes(bytes(blob))
    with pytest.raises(ckpt_lib.CorruptCheckpointError):
        ckpt_lib.load_checkpoint(d, state)
    # verify=False is the explicit forensic escape hatch.
    try:
        ckpt_lib.load_checkpoint(d, state, verify=False)
    except ckpt_lib.CorruptCheckpointError:
        pytest.fail("verify=False must not checksum")
    except Exception:
        pass  # the corrupt payload may legitimately fail to parse


def test_checkpoint_unknown_schema_is_typed_refusal(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib

    state = _mini_state()
    d = tmp_path / "ck"
    ckpt_lib.save_checkpoint(d, state, {})
    meta = json.loads((d / "meta.json").read_text())
    meta["schema"] = 99
    (d / "meta.json").write_text(json.dumps(meta))
    with pytest.raises(ckpt_lib.CheckpointSchemaError, match="schema 99"):
        ckpt_lib.load_checkpoint(d, state)


def test_pre_checksum_checkpoint_loads_unverified(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib
    from tpu_dp.obs.counters import counters

    state = _mini_state()
    d = tmp_path / "ck"
    ckpt_lib.save_checkpoint(d, state, {"epoch": 3})
    # Strip the schema + integrity block: the pre-PR-14 manifest layout.
    meta = json.loads((d / "meta.json").read_text())
    meta.pop("schema")
    meta.pop("integrity")
    (d / "meta.json").write_text(json.dumps(meta))
    before = counters.get("ckpt.unverified_loads")
    _, meta2 = ckpt_lib.load_checkpoint(d, state)
    assert counters.get("ckpt.unverified_loads") == before + 1
    assert meta2["epoch"] == 3


# ---------------------------------------------------------------------------
# storage shim at the real seams
# ---------------------------------------------------------------------------


def test_ioerr_on_checkpoint_write_is_retried(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib
    from tpu_dp.obs.counters import counters

    inj = FaultInjector(FaultPlan.parse("ioerr:step=1"), rank=0)
    inj.on_step(1)
    assert shim.active
    before = counters.get("retry.retries")
    out = ckpt_lib.save_checkpoint(tmp_path / "ck", _mini_state(), {})
    assert out is not None and out.exists()  # the save LANDED
    assert counters.get("retry.retries") >= before + 1
    assert not shim.active  # the transient fault is spent


def test_enospc_snapshot_write_degrades_not_kills(tmp_path):
    from tpu_dp.obs import flightrec
    from tpu_dp.obs.counters import counters
    from tpu_dp.resilience import SnapshotManager

    state = _mini_state()
    mgr = SnapshotManager(tmp_path / "snaps", every_steps=2,
                          async_save=False)
    assert mgr.snapshot(state, 2, {}) is not None  # clean baseline
    FaultInjector(FaultPlan.parse("enospc:step=4"), rank=0).on_step(4)
    before = counters.get("snapshot.write_errors")
    n_events = len(flightrec.recorder)
    out = mgr.snapshot(state, 4, {})
    assert out is None  # degraded, not raised
    assert counters.get("snapshot.write_errors") == before + 1
    kinds = [e["kind"] for e in flightrec.recorder.events()][n_events - 1:]
    assert "snapshot_write_error" in kinds
    # The cadence re-arms: the next crossing is due again.
    assert mgr.due(6)
    mgr.close()  # teardown degrades too — never raises on a full disk


def test_torn_defeats_per_file_atomicity_and_resume_falls_back(tmp_path):
    from tpu_dp import checkpoint as ckpt_lib
    from tpu_dp.obs.counters import counters
    from tpu_dp.resilience import find_candidates, resume_latest

    state = _mini_state()
    snaps = tmp_path / "snaps"
    mgr = ckpt_lib.CheckpointManager(snaps, async_save=False)
    mgr.save(state, {"kind": "snapshot", "epoch": 0, "steps_done": 2},
             step=5)
    FaultInjector(FaultPlan.parse("torn:step=7"), rank=0).on_step(7)
    mgr.save(state, {"kind": "snapshot", "epoch": 0, "steps_done": 4},
             step=9)
    # Both files exist in the torn dir: per-file atomicity says complete.
    assert (snaps / "step_0000000009" / "state.msgpack").exists()
    assert (snaps / "step_0000000009" / "meta.json").exists()
    # The checksum refusal marks it corrupt and falls back to step 5.
    restored, meta, source = resume_latest(state, tmp_path / "none", snaps)
    assert source.name == "step_0000000005"
    assert (snaps / "step_0000000009"
            / ckpt_lib.QUARANTINED_MARKER).exists()
    assert counters.get("ckpt.corrupt_candidates") >= 1
    # ... and the NEXT candidate scan attributes the skip, loudly.
    before = counters.get("ckpt.skipped_candidates")
    found = find_candidates(tmp_path / "none", snaps)
    assert [d.name for d, _ in found] == ["step_0000000005"]
    assert counters.get("ckpt.skipped_candidates") == before + 1


def test_slowfs_delays_ledger_reads(tmp_path):
    import time

    from tpu_dp.resilience.elastic import MembershipLedger

    led = MembershipLedger(tmp_path, 0)
    led.check_in(1, 7, leaving=False, flavor="graceful")
    FaultInjector(FaultPlan.parse("slowfs:step=2,ms=30,n=2"),
                  rank=0).on_step(2)
    t0 = time.perf_counter()
    assert led.check_ins(1)[0]["step"] == 7  # reads still WORK
    assert time.perf_counter() - t0 >= 0.025  # ... just slower
    led.check_ins(1)  # second slowed read spends the n=2 budget
    t0 = time.perf_counter()
    led.check_ins(1)
    assert time.perf_counter() - t0 < 0.025  # budget spent: fast again


def test_ioerr_on_ledger_write_rides_the_retry_budget(tmp_path):
    from tpu_dp.obs.counters import counters
    from tpu_dp.resilience.elastic import MembershipLedger

    FaultInjector(FaultPlan.parse("ioerr:step=1,n=2"), rank=0).on_step(1)
    before = counters.get("retry.retries")
    led = MembershipLedger(tmp_path, 0)
    led.check_in(1, 3, leaving=False, flavor="graceful")
    assert led.check_ins(1)[0]["step"] == 3  # the publish LANDED
    assert counters.get("retry.retries") >= before + 2


# ---------------------------------------------------------------------------
# unified IO retry budget (resilience.io_retry_s)
# ---------------------------------------------------------------------------


def test_io_retry_schedule_derivation():
    from tpu_dp.resilience.retry import backoff_delays, io_retry_schedule

    retries, base = io_retry_schedule(3.1)
    assert (retries, base) == (5, 0.1)  # the historical ledger schedule
    assert sum(backoff_delays(retries, base)) == pytest.approx(3.1)
    assert io_retry_schedule(0.01)[0] == 1  # never zero retries
    r10, _ = io_retry_schedule(10.0)
    assert sum(backoff_delays(r10, 0.1)) <= 10.0


def test_io_retry_exhaustion_stays_typed_elastic_error(tmp_path,
                                                       monkeypatch):
    """A tiny configured budget still exhausts into the TYPED ElasticError
    — and fast (the knob is what lets chaos runs stress exhaustion
    without 3s sleeps)."""
    import time

    from tpu_dp.resilience.elastic import ElasticError, MembershipLedger
    from tpu_dp.resilience.retry import configure_io_retry

    configure_io_retry(0.1)

    def always_fails(src, dst):
        raise OSError(5, "Input/output error (injected, permanent)")

    monkeypatch.setattr(os, "replace", always_fails)
    led = MembershipLedger(tmp_path, 0)
    t0 = time.perf_counter()
    with pytest.raises(ElasticError, match="failed after .* attempts"):
        led.check_in(1, 7, leaving=False, flavor="graceful")
    assert time.perf_counter() - t0 < 1.0


# ---------------------------------------------------------------------------
# skip-candidate attribution (satellite)
# ---------------------------------------------------------------------------


def test_find_candidates_attributes_every_skip(tmp_path):
    from tpu_dp.obs import flightrec
    from tpu_dp.obs.counters import counters
    from tpu_dp.resilience import find_candidates, quarantine_save_dir

    snaps = tmp_path / "snaps"
    good = snaps / "step_0000000005"
    good.mkdir(parents=True)
    (good / "state.msgpack").write_bytes(b"x")
    (good / "meta.json").write_text("{}")
    partial = snaps / "step_0000000010"
    partial.mkdir()
    (partial / "state.msgpack").write_bytes(b"y")  # meta never landed
    bad = snaps / "step_0000000015"
    bad.mkdir()
    (bad / "state.msgpack").write_bytes(b"z")
    (bad / "meta.json").write_text("{}")
    quarantine_save_dir(bad, "sdc mismatch at step 14")
    before = counters.get("ckpt.skipped_candidates")
    n_events = len(flightrec.recorder)
    found = find_candidates(tmp_path / "none", snaps)
    assert [d.name for d, _ in found] == ["step_0000000005"]
    assert counters.get("ckpt.skipped_candidates") == before + 2
    skips = [e for e in flightrec.recorder.events()[max(0, n_events - 1):]
             if e["kind"] == "ckpt_skipped_candidate"]
    reasons = {Path(e["dir"]).name: e["reason"] for e in skips}
    assert "torn write" in reasons["step_0000000010"]
    assert "sdc mismatch" in reasons["step_0000000015"]


def test_flat_layout_fallback_honors_quarantine_marker(tmp_path):
    """A corrupt FLAT checkpoint, once quarantined by the self-healing
    resume loop, must stop being offered — re-offering it hands
    `_load_rollback_state` the same rotten dir forever (a sleep-free
    wedge)."""
    from tpu_dp.resilience import find_candidates, quarantine_save_dir

    flat = tmp_path / "ck"
    flat.mkdir()
    (flat / "state.msgpack").write_bytes(b"rotten")
    (flat / "meta.json").write_text("{}")
    assert [d for d, _ in find_candidates(flat)] == [flat]
    quarantine_save_dir(flat, "checksum refusal: payload sha256 mismatch")
    assert find_candidates(flat) == []


# ---------------------------------------------------------------------------
# shrinker minimality
# ---------------------------------------------------------------------------


def test_shrinker_returns_one_minimal_schedule():
    from tpu_dp.chaos.runner import shrink_schedule

    a, b, c = FaultPlan.parse_schedule(
        "kill:step=2;delay:step=3,ms=50;bitrot:step=4")
    runs = []

    def fails_iff_a_and_c(clauses):
        runs.append(list(clauses))
        s = set(p.kind for p in clauses)
        return {"kill", "bitrot"} <= s

    minimal = shrink_schedule([a, b, c], fails_iff_a_and_c)
    assert [p.kind for p in minimal] == ["kill", "bitrot"]
    # 1-minimality: dropping either remaining clause stops the failure.
    for i in range(len(minimal)):
        assert not fails_iff_a_and_c(minimal[:i] + minimal[i + 1:])
    # Singleton schedules shrink to themselves without a single re-run.
    runs.clear()
    assert shrink_schedule([a], fails_iff_a_and_c) == [a]
    assert runs == []


def test_sample_schedule_is_seed_deterministic():
    import random

    from tpu_dp.chaos.runner import DEFAULT_PALETTE, sample_schedule

    kinds = {e.kind for e in DEFAULT_PALETTE}
    specs = set()
    for index in range(20):
        s1 = sample_schedule(random.Random(f"7:{index}"))
        s2 = sample_schedule(random.Random(f"7:{index}"))
        assert s1.spec == s2.spec  # replayable from (seed, index)
        assert all(c.kind in kinds for c in s1.clauses)
        if s1.guard_action:
            assert s1.guard_action in ("skip", "rollback")
        assert "slowfs" not in [c.kind for c in s1.clauses]  # world-1 pool
        specs.add(s1.spec)
    assert len(specs) > 5  # the generator actually explores


def test_sample_schedule_multi_rank_targets_non_writer_ranks():
    """At world>1 the sampler rank-targets death/straggler clauses away
    from rank 0 (the save/export writer) and slowfs joins the pool —
    the schedule shapes the 3-process acceptance compositions use."""
    import random

    from tpu_dp.chaos.runner import sample_schedule

    saw_slowfs = saw_targeted = False
    for index in range(40):
        sched = sample_schedule(random.Random(f"9:{index}"), world=3)
        for clause in sched.clauses:
            if clause.kind == "slowfs":
                saw_slowfs = True
            if clause.kind in ("kill", "preempt", "delay"):
                saw_targeted = True
                assert 1 <= clause.rank <= 2  # never the writer
    assert saw_slowfs and saw_targeted


# ---------------------------------------------------------------------------
# acceptance (c): bitrot before a spike rollback — in-process, tier-1
# ---------------------------------------------------------------------------


def _chaos_cfg(tmp_path, **over):
    from tpu_dp.config import Config

    cfg = Config()
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_train_size = 48
    cfg.data.synthetic_test_size = 16
    cfg.data.batch_size = 4
    cfg.train.epochs = 2
    cfg.train.log_every = 100
    cfg.train.eval_at_end = False
    cfg.train.steps_per_call = 1
    cfg.train.ckpt_dir = str(tmp_path / "ck")
    cfg.train.ckpt_async = False
    cfg.parallel.num_devices = 1
    cfg.resilience.snapshot_every_steps = 3
    cfg.guard.enabled = True
    cfg.guard.action = "rollback"
    cfg.guard.spike_min_steps = 4
    cfg.guard.spike_z = 12
    for key, val in over.items():
        cfg.override(key, str(val))
    return cfg


@pytest.mark.resilience
def test_bitrot_newest_snapshot_forces_older_candidate_fallback(tmp_path):
    """Acceptance (c): ``bitrot`` lands on the newest snapshot, then a
    spike rollback needs it — the run refuses the corrupt candidate
    (typed, counted, quarantine-marked), restores the older one, replays,
    and finishes with params BITWISE equal to an oracle that never saw
    the corrupt save."""
    from tpu_dp.obs.counters import counters
    from tpu_dp.train.trainer import Trainer

    before_fail = counters.get("ckpt.checksum_failures")
    before_fb = counters.get("ckpt.corrupt_candidates")
    cfg = _chaos_cfg(tmp_path, **{
        "resilience.fault": "bitrot:step=4;spike:step=8,scale=1e6"})
    tr = Trainer(cfg)
    tr.fit()
    shim.reset()
    assert counters.get("ckpt.checksum_failures") > before_fail
    assert counters.get("ckpt.corrupt_candidates") > before_fb
    # Diagnosable from artifacts alone: the black box carries the whole
    # story — injection, typed refusal, fallback. (The on-disk quarantine
    # marker is transient BY DESIGN: the replay re-saves clean state into
    # the same step dir, and a fresh complete write clears the
    # suspicion.)
    from tpu_dp.obs import flightrec

    dump = flightrec.read_dump(
        tmp_path / "ck" / "obs" / "flightrec_r00000.json")
    kinds = [e["kind"] for e in dump["events"]]
    for k in ("storage_fault", "ckpt_corrupt", "ckpt_corrupt_fallback",
              "guard_rollback"):
        assert k in kinds, (k, sorted(set(kinds)))
    rot = next(e for e in dump["events"] if e["kind"] == "storage_fault")
    assert rot["fault"] == "bitrot"
    # Bitwise identical to the never-faulted oracle: the rollback landed
    # on the older clean snapshot and replayed exactly.
    oracle = Trainer(_chaos_cfg(tmp_path / "oracle"))
    oracle.fit()
    for x, y in zip(jax.tree_util.tree_leaves(tr.state.params),
                    jax.tree_util.tree_leaves(oracle.state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.resilience
def test_enospc_training_completes_with_degraded_durability(tmp_path):
    """Satellite regression under the new injector: persistent write
    failure from mid-run on — training must complete (no raise anywhere
    in the cadence, the epoch saves, or teardown), with the losses loud
    in the counters."""
    from tpu_dp.obs.counters import counters
    from tpu_dp.train.trainer import Trainer

    before = counters.get("snapshot.write_errors")
    cfg = _chaos_cfg(tmp_path, **{"resilience.fault": "enospc:step=7"})
    cfg.guard.enabled = False
    tr = Trainer(cfg)
    result = tr.fit()  # completes; a raise here fails the test
    shim.reset()
    assert len(result["history"]) == 2
    assert counters.get("snapshot.write_errors") > before
    # Saves from before the fault survive as resume candidates.
    from tpu_dp.resilience import find_latest

    found = find_latest(tmp_path / "ck", tmp_path / "ck" / "snapshots")
    assert found is not None and found[1] <= 7


# ---------------------------------------------------------------------------
# acceptance (a) + (b): the multi-rank composed-fault trials
# ---------------------------------------------------------------------------


def _chaos_mp_audit(ckpt_dir, *, want_kinds=()):
    """The multi-rank half of the invariant auditor: artifacts parse,
    the obsctl timeline rebuilds the run, the wanted story kinds are
    present, and every optimizer step appears exactly once across all
    membership/rollback generations (the surviving attempt wins)."""
    from tpu_dp.obs import obsctl

    out = obsctl.build_timeline(obsctl.RunArtifacts(ckpt_dir),
                                include_steps=True)
    kinds = [e["kind"] for e in out["events"]]
    assert kinds, "obsctl timeline is empty"
    for k in want_kinds:
        assert k in kinds, (k, sorted(set(kinds)))
    steps = [e["step"] for e in out["events"] if e["kind"] == "step"]
    assert steps and len(steps) == len(set(steps)), \
        "a replayed optimizer step appears twice in the timeline"
    return out


def _assert_params_lockstep(results):
    """Every finishing rank holds bitwise-identical params."""
    import jax

    sids = sorted(results)
    ref = results[sids[0]]["params"]
    for sid in sids[1:]:
        for x, y in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(results[sid]["params"])):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
@pytest.mark.elastic
@pytest.mark.guard
def test_three_process_sdc_during_grow_handshake(tmp_path):
    """Acceptance (a): the SDC audit fires while an elastic grow
    handshake is in flight. Rank 2 departs at step 2 via ``relaunch:``
    and rejoins through the membership ledger; while its admission is
    pending, rank 1's params flip (``sdc:step=4,rank=1``). The audit
    must catch the divergence WITHOUT wedging the composed transition
    (the audit schedule stays boundary-synchronized even though quiesce
    entry is rank-local — the exact deadlock this trial found), the
    suppressed-snapshot rule keeps the corruption off disk, and the
    checksum-verified regroup reload purges it, so every rank finishes
    at the regrown world holding bitwise-identical params."""
    import pickle

    from test_multiprocess import _run_elastic_workers

    procs, outs = _run_elastic_workers(
        tmp_path, "relaunch:step=2,rank=2;sdc:step=4,rank=1",
        train_size=96, guard=True)
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=300)[0].decode())
    except Exception:
        for q in procs:
            if q.poll() is None:
                q.kill()
        drained = logs + [p.communicate()[0].decode()
                          for p in procs[len(logs):]]
        pytest.fail("WEDGE: composed-fault workers timed out; logs:\n"
                    + "\n--- next rank ---\n".join(t[-3000:]
                                                  for t in drained))
    # Legal exits only: the relaunch rank rejoins and finishes (0); the
    # corrupted rank either heals through the verified reload (0) or is
    # evicted (143) — both are legal, a wedge or a crash is not.
    assert procs[0].returncode == 0, logs[0][-3000:]
    assert procs[2].returncode == 0, logs[2][-3000:]
    assert procs[1].returncode in (0, 143), logs[1][-3000:]
    results = {r: pickle.loads(outs[r].read_bytes())
               for r in range(3) if procs[r].returncode == 0}

    # The audit caught the flip, on every surviving rank's counters.
    for r in results:
        assert results[r]["counters"]["guard.sdc_mismatches"] >= 1
    # The rejoiner's round trip is attributed.
    assert results[2]["counters"]["elastic.departures"] == 1
    assert results[2]["counters"]["elastic.joins"] == 1
    # The checksum manifest verified the regroup reloads (integrity leg).
    assert results[0]["counters"].get("ckpt.verified_loads", 0) >= 1

    # Ledger story: a graceful shrink losing sid 2, then a grow
    # readmitting it — the handshake the audit fired inside of.
    from test_multiprocess import _read_ledger_records

    records = _read_ledger_records(tmp_path / "ck")
    reasons = [r["reason"] for r in records]
    assert "grow" in reasons, reasons
    shrink = next(r for r in records if r["reason"] == "graceful")
    assert [d["sid"] for d in shrink["departed"]] == [2]
    grow = next(r for r in records if r["reason"] == "grow")
    assert [j["sid"] for j in grow["joined"]] == [2]
    final = records[-1]
    assert {0, 2} <= set(final["members"])
    assert (1 in final["members"]) == (procs[1].returncode == 0)
    for r in results:
        assert results[r]["world"] == len(final["members"])

    # Lockstep: every finishing rank holds bitwise-identical params —
    # the corruption did not survive the composed transitions.
    _assert_params_lockstep(results)

    # Black-box verdict: the whole story is in the artifacts.
    _chaos_mp_audit(tmp_path / "ck",
                    want_kinds=("guard_sdc", "elastic_departure",
                                "rank_joined", "elastic_grow"))


@pytest.mark.slow
@pytest.mark.elastic
@pytest.mark.guard
def test_three_process_preempt_mid_rollback_regroup(tmp_path):
    """Acceptance (b): a rank is killed in the middle of a rollback
    regroup. Rank 2's params flip at step 2 (SDC) and the audit's
    rollback eviction starts converging; rank 1 is preempted at step 3,
    inside that quiesce. Both departures must compose (one rollback
    transition or two back-to-back — either is legal, a wedge is not):
    the sole survivor resumes from a pre-corruption snapshot, replays,
    and finishes BOTH epochs matching the ledger-reconstructed
    single-device oracle."""
    import pickle

    from test_multiprocess import _read_ledger_records, _run_elastic_workers

    procs, outs = _run_elastic_workers(
        tmp_path, "sdc:step=2,rank=2;preempt:step=3,rank=1",
        train_size=96, guard=True)
    logs = []
    try:
        for p in procs:
            logs.append(p.communicate(timeout=300)[0].decode())
    except Exception:
        for q in procs:
            if q.poll() is None:
                q.kill()
        drained = logs + [p.communicate()[0].decode()
                          for p in procs[len(logs):]]
        pytest.fail("WEDGE: composed-fault workers timed out; logs:\n"
                    + "\n--- next rank ---\n".join(t[-3000:]
                                                  for t in drained))
    assert procs[0].returncode == 0, logs[0][-3000:]
    assert procs[1].returncode == 143, logs[1][-3000:]
    assert procs[2].returncode == 143, logs[2][-3000:]

    res = pickle.loads(outs[0].read_bytes())
    assert res["world"] == 1
    assert len(res["history"]) == 2  # both epochs finished, alone
    assert res["counters"]["guard.sdc_mismatches"] >= 1
    assert res["counters"]["elastic.lost_ranks"] == 2

    records = _read_ledger_records(tmp_path / "ck")
    assert records[-1]["members"] == [0]
    departed = {d["sid"] for r in records for d in r.get("departed", ())}
    assert departed == {1, 2}
    assert "rollback" in [r["reason"] for r in records]

    # Exactly-once + completion, from the artifacts alone.
    _chaos_mp_audit(tmp_path / "ck",
                    want_kinds=("guard_sdc", "elastic_regroup",
                                "epoch_complete"))

    # The one-composed-transition interleave (the pinned-seed outcome)
    # admits the strongest verdict: final params vs the single-device
    # oracle of the exact 2-steps-at-world-3 + rollback-remainder-at-
    # world-1 sample schedule, reconstructed from the membership record.
    if len(records) == 2 and len(records[1]["resume"]["lineage"]) == 1:
        import jax

        from test_multiprocess import _elastic_oracle_params

        oracle_state, _ = _elastic_oracle_params(records[1],
                                                 num_examples=96)
        for x, y in zip(jax.tree_util.tree_leaves(res["params"]),
                        jax.tree_util.tree_leaves(oracle_state.params)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=2e-5)

